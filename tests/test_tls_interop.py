"""LIVE mTLS interop: a reference aiocluster node and ours mutually
verify certificates and replicate over TLS (VERDICT r4 next item 7).

tests/test_reference_interop.py proves plaintext wire interop; the TLS
handshake + SAN/CN-vs-claimed-tls_name verification (reference
server.py:570-597) is the hairiest compatibility surface and was
previously tested only own-vs-own (tests/test_tls.py). Here the
reference's exact cert scheme (its tests/test_tls_mtls.py:45-163: one
CA, per-node SAN certs, CERT_REQUIRED both ways) carries a two-node
mixed-implementation cluster:

- positive: both nodes replicate each other's keys and see each other
  live over mTLS;
- negative: a node claiming a tls_name absent from its certificate is
  rejected by the OTHER implementation's verifier.
"""

import shutil
import ssl
import subprocess

import pytest
from conftest import wait_for

import test_reference_interop as ri
from aiocluster_tpu import Cluster, Config, NodeId

pytestmark = [
    pytest.mark.skipif(
        not ri.HAVE_REFERENCE,
        reason=f"reference aiocluster not importable: {ri._REF_IMPORT_ERROR}",
    ),
    pytest.mark.skipif(
        shutil.which("openssl") is None, reason="openssl not available"
    ),
]


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """One CA plus per-node SAN certs — the reference's own scheme
    (reference tests/test_tls_mtls.py:45-163)."""
    d = tmp_path_factory.mktemp("interop-certs")

    def run(*args):
        subprocess.run(args, check=True, capture_output=True, cwd=d)

    run("openssl", "genrsa", "-out", "ca.key", "2048")
    run(
        "openssl", "req", "-x509", "-new", "-key", "ca.key", "-sha256",
        "-days", "2", "-out", "ca.pem", "-subj", "/CN=interop-ca",
    )
    for name in ("refnode", "ournode"):
        run("openssl", "genrsa", "-out", f"{name}.key", "2048")
        run(
            "openssl", "req", "-new", "-key", f"{name}.key",
            "-out", f"{name}.csr", "-subj", f"/CN={name}",
        )
        ext = d / f"{name}.ext"
        ext.write_text(
            f"subjectAltName=DNS:{name},IP:127.0.0.1\n"
            "keyUsage=digitalSignature,keyEncipherment\n"
            "extendedKeyUsage=serverAuth,clientAuth\n"
        )
        run(
            "openssl", "x509", "-req", "-in", f"{name}.csr", "-CA", "ca.pem",
            "-CAkey", "ca.key", "-CAcreateserial", "-out", f"{name}.pem",
            "-days", "2", "-sha256", "-extfile", f"{name}.ext",
        )
    return d


def _contexts(certs, name: str) -> tuple[ssl.SSLContext, ssl.SSLContext]:
    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(certs / f"{name}.pem", certs / f"{name}.key")
    server.load_verify_locations(certs / "ca.pem")
    server.verify_mode = ssl.CERT_REQUIRED
    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client.load_cert_chain(certs / f"{name}.pem", certs / f"{name}.key")
    client.load_verify_locations(certs / "ca.pem")
    return server, client


def _ref_config(certs, port: int, seed_port: int, tls_name: str = "refnode"):
    server_ctx, client_ctx = _contexts(certs, "refnode")
    return ri.RefConfig(
        node_id=ri.RefNodeId(
            name="refnode",
            gossip_advertise_addr=("127.0.0.1", port),
            tls_name=tls_name,
        ),
        cluster_id="tls-interop",
        gossip_interval=0.05,
        seed_nodes=[("127.0.0.1", seed_port)],
        tls_server_context=server_ctx,
        tls_client_context=client_ctx,
        # Until tls_names have gossiped, connections go by address; the
        # cert's IP SAN covers the loopback connect, and this keeps SNI
        # deterministic either way (reference server.py:393-397).
        tls_server_hostname="ournode",
    )


def _our_config(certs, port: int, seed_port: int, tls_name: str = "ournode"):
    server_ctx, client_ctx = _contexts(certs, "ournode")
    return Config(
        node_id=NodeId(
            name="ournode",
            gossip_advertise_addr=("127.0.0.1", port),
            tls_name=tls_name,
        ),
        cluster_id="tls-interop",
        gossip_interval=0.05,
        seed_nodes=[("127.0.0.1", seed_port)],
        tls_server_context=server_ctx,
        tls_client_context=client_ctx,
        tls_server_hostname="refnode",
    )


async def test_mtls_interop_replicates_both_ways(certs, free_port_factory):
    p_ref, p_ours = free_port_factory(), free_port_factory()
    ref = ri.RefCluster(
        _ref_config(certs, p_ref, p_ours),
        initial_key_values={"from-ref": "sealed"},
    )
    ours = Cluster(
        _our_config(certs, p_ours, p_ref),
        initial_key_values={"from-ours": "delivered"},
    )
    async with ref, ours:
        await wait_for(
            lambda: ri._sees(
                ours.snapshot().node_states, "refnode", "from-ref", "sealed"
            ),
            timeout=8.0,
        )
        await wait_for(
            lambda: ri._sees(
                ref.snapshot().node_states, "ournode", "from-ours",
                "delivered",
            ),
            timeout=8.0,
        )
        # Mutual liveness through the verified channel.
        await wait_for(
            lambda: any(
                n.name == "refnode" for n in ours.snapshot().live_nodes
            ),
            timeout=8.0,
        )
        await wait_for(
            lambda: any(n.name == "ournode" for n in ref.live_nodes()),
            timeout=8.0,
        )


async def test_mtls_interop_rejects_wrong_claimed_name(
    certs, free_port_factory
):
    """Our node claims a tls_name its certificate does not carry; the
    reference must never mark it LIVE — the same observable its own
    negative test asserts (reference tests/test_tls_mtls.py:253-310).

    Mechanics (reference semantics, mirrored by ours): the responder
    verifier (server.py:585-597) rejects our Syns because the claimed
    name is not in our cert's SAN/CN set, and once the bogus tls_name
    has gossiped, every reference-initiated connection uses it as the
    TLS server_hostname (server.py:393-397) and fails the handshake —
    so at most one pre-gossip seed contact ever lands, one heartbeat
    observation is not liveness (state.py:280-287), and the imposter
    stays dark."""
    import asyncio

    p_ref, p_ours = free_port_factory(), free_port_factory()
    ref = ri.RefCluster(
        _ref_config(certs, p_ref, p_ours),
        initial_key_values={"from-ref": "sealed"},
    )
    ours = Cluster(
        _our_config(certs, p_ours, p_ref, tls_name="imposter"),
        initial_key_values={"from-ours": "forged"},
    )
    async with ref, ours:
        await asyncio.sleep(1.5)  # ~30 gossip intervals of opportunity
        assert not any(n.name == "ournode" for n in ref.live_nodes())
