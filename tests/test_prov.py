"""Propagation provenance, flight recorder, staleness tensor, twin drift.

The observability plane of docs/observability.md "Propagation &
provenance" + "Flight recorder" and docs/twin.md's drift monitor:

- the collector's join semantics on synthetic traces (direct
  ``from_peer`` edges, the send join for responder-side applies, hop
  depths, the shared nearest-rank percentiles);
- a REAL loopback fleet joined end to end (>= 99% of applies for a
  marked write — the prov-smoke gate at test scale) with byte-identical
  defaults (no trace attached => no prov events anywhere);
- the sim staleness tensor bit-matching a host numpy oracle on the
  int32 AND packed-u4r rungs, unsharded and under a 2-shard mesh;
- the flight recorder's ring discipline and its never-shed serve
  endpoint;
- histogram quantiles (bucket interpolation, snapshot p50/p99);
- ``twin.check_drift`` verdicts against a stored calibration.
"""

from __future__ import annotations

import asyncio
import json
import math
import time

import pytest

from aiocluster_tpu.obs import TraceWriter, join_propagation
from aiocluster_tpu.obs.flightrec import FlightRecorder
from aiocluster_tpu.obs.registry import (
    MetricsRegistry,
    percentile_of_sorted,
)

# -- collector unit tests -----------------------------------------------------


def _rec(event, **fields):
    return {"event": event, "ts": 0.0, **fields}


def test_join_direct_and_send_edges_build_the_spread_tree():
    records = [
        _rec("prov_write", node="a", key="k", version=3, t_mono=10.0),
        # b pulled from a (initiator side: from_peer named).
        _rec("prov_apply", node="b", owner="a", key="k", version=3,
             from_peer="a", t_mono=10.1),
        # b then initiated toward c and packed the kv into its Ack:
        # c's apply is responder-side (from_peer null) and joins to the
        # closest preceding matching send.
        _rec("prov_send", node="b", to_peer="c", owner="a", key="k",
             version=3, t_mono=10.2),
        _rec("prov_apply", node="c", owner="a", key="k", version=3,
             from_peer=None, t_mono=10.25),
        # d pulled from c.
        _rec("prov_apply", node="d", owner="a", key="k", version=3,
             from_peer="c", t_mono=10.4),
    ]
    report = join_propagation(records)
    tree = report.tree(owner="a", key="k")
    assert tree is not None and tree.version == 3
    assert tree.origin_t == 10.0
    assert tree.nodes["a"].hop == 0
    assert tree.nodes["b"].hop == 1 and tree.nodes["b"].join == "direct"
    assert tree.nodes["c"].hop == 2 and tree.nodes["c"].join == "send"
    assert tree.nodes["c"].from_peer == "b"
    assert tree.nodes["d"].hop == 3
    assert tree.unjoined_hops == 0
    assert tree.joined_fraction(4) == 1.0
    lats = tree.latencies()
    assert lats == sorted(lats)
    assert math.isclose(tree.visibility_percentile(1.0), 0.4, abs_tol=1e-9)
    assert tree.hop_histogram() == {0: 1, 1: 1, 2: 1, 3: 1}
    summary = tree.summary(4)
    assert summary["hops_p99"] == 3 and summary["joined_fraction"] == 1.0


def test_join_first_visibility_wins_and_unjoined_counted():
    records = [
        _rec("prov_write", node="a", key="k", version=1, t_mono=0.0),
        _rec("prov_apply", node="b", owner="a", key="k", version=1,
             from_peer="a", t_mono=1.0),
        # A later duplicate apply must not move b's first sighting.
        _rec("prov_apply", node="b", owner="a", key="k", version=1,
             from_peer="c", t_mono=5.0),
        # No from_peer and no matching send: joined for latency, but
        # its hop stays unresolved (counted, not invented).
        _rec("prov_apply", node="e", owner="a", key="k", version=1,
             from_peer=None, t_mono=2.0),
    ]
    tree = join_propagation(records).tree(owner="a", key="k")
    assert tree.nodes["b"].t_mono == 1.0 and tree.nodes["b"].from_peer == "a"
    assert tree.nodes["e"].join == "unjoined"
    assert tree.nodes["e"].hop is None
    assert tree.nodes["e"].latency_s == 2.0
    assert tree.unjoined_hops == 1


def test_join_key_filter_and_version_separation():
    records = [
        _rec("prov_write", node="a", key="k", version=1, t_mono=0.0),
        _rec("prov_write", node="a", key="k", version=2, t_mono=1.0),
        _rec("prov_write", node="a", key="other", version=1, t_mono=0.0),
        _rec("prov_apply", node="b", owner="a", key="k", version=2,
             from_peer="a", t_mono=1.5),
    ]
    report = join_propagation(records, key="k")
    assert all(k == "k" for (_o, k, _v) in report.trees)
    # tree() defaults to the highest version of the (owner, key) pair.
    assert report.tree(owner="a", key="k").version == 2
    assert report.tree(owner="a", key="k", version=1).version == 1


def test_join_send_horizon_rejects_stale_and_future_senders():
    records = [
        _rec("prov_write", node="a", key="k", version=1, t_mono=100.0),
        # A send far older than the horizon, and one AFTER the apply:
        # neither may claim the edge.
        _rec("prov_send", node="x", to_peer="b", owner="a", key="k",
             version=1, t_mono=10.0),
        _rec("prov_send", node="y", to_peer="b", owner="a", key="k",
             version=1, t_mono=101.0),
        _rec("prov_apply", node="b", owner="a", key="k", version=1,
             from_peer=None, t_mono=100.5),
    ]
    tree = join_propagation(records).tree(owner="a", key="k")
    assert tree.nodes["b"].join == "unjoined"
    assert tree.nodes["b"].from_peer is None


# -- nearest-rank + histogram quantiles ---------------------------------------


def test_percentile_of_sorted_convention():
    assert math.isnan(percentile_of_sorted([], 0.5))
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert percentile_of_sorted(vals, 0.0) == 1.0
    assert percentile_of_sorted(vals, 0.5) == 3.0
    assert percentile_of_sorted(vals, 0.99) == 5.0
    assert percentile_of_sorted(vals, 1.0) == 5.0


def test_histogram_quantile_interpolates_buckets():
    reg = MetricsRegistry()
    hist = reg.histogram("h_seconds", "t", buckets=(1.0, 2.0, 4.0))
    assert hist.quantile(0.5) is None  # empty
    for v in (0.5, 1.5, 1.5, 3.0):
        hist.observe(v)
    # rank 2 of 4 falls in the (1, 2] bucket: cum 1 -> 3 across it.
    assert hist.quantile(0.5) == pytest.approx(1.5)
    # rank 0.4 falls in the first bucket, interpolated from 0.
    assert hist.quantile(0.1) == pytest.approx(0.4)
    # +Inf landings clamp to the highest finite bound.
    hist.observe(100.0)
    assert hist.quantile(1.0) == 4.0
    with pytest.raises(ValueError):
        hist.quantile(1.5)


def test_histogram_quantile_negative_first_bucket_convention():
    """Prometheus convention: a non-positive first bound is returned
    as-is (0 is not a valid interpolation anchor below it) — the
    quantile can never exceed the bucket every sample sits in."""
    reg = MetricsRegistry()
    hist = reg.histogram("neg_units", "t", buckets=(-5.0, 5.0))
    for v in (-8.0, -7.0, -6.0):
        hist.observe(v)
    assert hist.quantile(0.5) == -5.0
    # Later buckets interpolate between REAL bounds, negative included.
    hist2 = reg.histogram("neg2_units", "t", buckets=(-10.0, -2.0))
    for v in (-9.0, -5.0, -5.0, -5.0):
        hist2.observe(v)
    assert -10.0 < hist2.quantile(0.75) <= -2.0


def test_snapshot_histograms_carry_p50_p99():
    reg = MetricsRegistry()
    hist = reg.histogram("h_seconds", "t", buckets=(1.0, 2.0))
    hist.labels()  # materialize the 0-label child (still empty)
    entry = reg.snapshot()["h_seconds"]
    assert entry["p50"] is None and entry["p99"] is None
    hist.observe(0.5)
    hist.observe(1.5)
    entry = reg.snapshot()["h_seconds"]
    assert 0.0 < entry["p50"] <= 2.0 and 0.0 < entry["p99"] <= 2.0
    assert entry["count"] == 2


# -- flight recorder ----------------------------------------------------------


def test_flight_recorder_ring_bounds_and_eviction_marker():
    rec = FlightRecorder(capacity=4)
    assert len(rec) == 0 and rec.dump() == []
    for i in range(10):
        rec.note("handshake", peer=f"p{i}", outcome="ok")
    assert len(rec) == 4
    dump = rec.dump()
    assert [d["peer"] for d in dump] == ["p6", "p7", "p8", "p9"]
    assert dump[0]["evicted_before"] == 6
    assert all(d["kind"] == "handshake" for d in dump)
    assert all("t_mono" in d and "ts" in d for d in dump)
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


# -- staleness tensor: device vs host oracle ----------------------------------


def _oracle(state, cfg):
    import numpy as np

    w = np.asarray(state.w)
    mv = np.asarray(state.max_version).astype(np.int64)
    alive = np.asarray(state.alive)
    n = alive.shape[0]
    if cfg.version_dtype == "u4r":
        residual = np.empty((n, n), np.int64)
        residual[:, 0::2] = (w & 0xF).astype(np.int64)
        residual[:, 1::2] = (w >> 4).astype(np.int64)
        wv = mv[None, :] - residual
    else:
        wv = w.astype(np.int64)
    pair = alive[:, None] & alive[None, :]
    lag = np.where(pair, mv[None, :] - wv, 0)
    per_node = np.maximum(lag.max(axis=1), 0)
    ordered = np.sort(per_node)
    picks = {
        f"staleness_p{label}": int(
            ordered[min(n - 1, int(q * (n - 1) + 0.5))]
        )
        for label, q in (("50", 0.50), ("99", 0.99), ("100", 1.0))
    }
    return per_node.astype(np.int64), picks


@pytest.mark.parametrize("rung", ["int32", "u4r"])
@pytest.mark.parametrize("shards", [1, 2])
def test_staleness_tensor_bitmatches_host_oracle(rung, shards):
    import jax
    import numpy as np

    from aiocluster_tpu.ops.gossip import staleness_tensor
    from aiocluster_tpu.parallel.mesh import make_mesh
    from aiocluster_tpu.sim import SimConfig
    from aiocluster_tpu.sim.simulator import Simulator

    cfg = SimConfig(
        n_nodes=32, keys_per_node=9, fanout=3, budget=3,
        version_dtype=rung, track_failure_detector=False,
        track_heartbeats=False,
    )
    mesh = None if shards == 1 else make_mesh(jax.devices()[:2])
    sim = Simulator(cfg, seed=11, chunk=1, mesh=mesh)
    sim.run(2)
    oracle_vec, oracle_picks = _oracle(jax.device_get(sim.state), cfg)
    assert oracle_picks["staleness_p100"] > 0  # non-trivial mid-flight
    m = sim.metrics()
    assert {k: int(m[k]) for k in oracle_picks} == oracle_picks
    if mesh is None:
        got = np.asarray(staleness_tensor(sim.state)).astype(np.int64)
        assert np.array_equal(got, oracle_vec)
    # p100 is version_spread by construction.
    assert int(m["staleness_p100"]) == int(m["version_spread"])


def test_staleness_gauges_exported_in_round_units():
    from aiocluster_tpu.obs.sim import SimMetrics

    reg = MetricsRegistry()
    sm = SimMetrics(reg, stride=1, writes_per_round=4)
    sm.record(1, {"staleness_p50": 8, "staleness_p99": 12,
                  "staleness_p100": 20})
    sm.flush()
    snap = reg.snapshot()
    assert snap["aiocluster_sim_staleness_rounds{engine=xla,pct=50}"] == 2.0
    assert snap["aiocluster_sim_staleness_rounds{engine=xla,pct=99}"] == 3.0
    assert snap["aiocluster_sim_staleness_rounds{engine=xla,pct=100}"] == 5.0


# -- wavefront ----------------------------------------------------------------


@pytest.mark.parametrize("rung", ["int32", "u4r"])
def test_marked_write_state_is_converged_except_the_marked_write(rung):
    import numpy as np

    from aiocluster_tpu.obs.sim import marked_write_state
    from aiocluster_tpu.sim import SimConfig
    from aiocluster_tpu.sim.packed import watermarks_i32

    cfg = SimConfig(
        n_nodes=16, keys_per_node=5, fanout=3, budget=8,
        version_dtype=rung, track_failure_detector=False,
        track_heartbeats=False,
    )
    state = marked_write_state(cfg, owner=3)
    wv = np.asarray(watermarks_i32(state))
    mv = np.asarray(state.max_version)
    assert mv[3] == 6 and (np.delete(mv, 3) == 5).all()
    assert wv[3, 3] == 6
    lag = mv[None, :] - wv
    assert lag[:, 3].sum() == 15  # everyone but the owner one behind
    assert np.delete(lag, 3, axis=1).sum() == 0


def test_wavefront_series_reaches_threshold_monotonically():
    from aiocluster_tpu.obs.sim import wavefront_series
    from aiocluster_tpu.sim import SimConfig

    cfg = SimConfig(
        n_nodes=16, keys_per_node=5, fanout=2, budget=8,
        track_failure_detector=False, track_heartbeats=False,
    )
    wf = wavefront_series(cfg, owner=0, seed=3, max_rounds=64)
    fr = wf["fractions"]
    assert fr[0] == pytest.approx(1 / 16)
    assert all(b >= a for a, b in zip(fr, fr[1:]))  # epidemic: no regress
    assert wf["rounds_to_threshold"] is not None
    assert fr[-1] >= 0.99


# -- end-to-end: real loopback fleet ------------------------------------------


async def _converged_marked_fleet(tmp_path, n=5, prov=True):
    from aiocluster_tpu.faults.runner import ChaosHarness

    prov_tw = TraceWriter(tmp_path / "prov.jsonl") if prov else None
    harness = ChaosHarness(
        n, gossip_interval=0.05, prov_trace=prov_tw
    )
    async with harness:
        await harness.wait_converged(20.0)
        harness.clusters["n00"].set("marked", "v")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            seen = sum(
                1
                for name, c in harness.clusters.items()
                if name != "n00"
                for nid, ns in c.node_states_view().items()
                if nid.name == "n00" and ns.get("marked") is not None
            )
            if seen == n - 1:
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError("marked write never fully visible")
        await asyncio.sleep(0.2)  # let trailing applies hit the trace
    if prov_tw is not None:
        prov_tw.close()
    return harness, prov_tw


async def test_runtime_fleet_provenance_joins_all_applies(tmp_path):
    harness, _tw = await _converged_marked_fleet(tmp_path, n=5)
    report = harness.propagation_report(key="marked")
    tree = report.tree(owner="n00", key="marked")
    assert tree is not None
    # The prov-smoke acceptance bar at test scale: every apply joined.
    assert tree.joined_fraction(5) >= 0.99
    assert tree.origin_t is not None
    for v in tree.applies():
        assert v.latency_s is not None and v.latency_s >= 0.0
        assert v.hop is not None and v.hop >= 1  # every hop resolved
    # Flight recorders saw the same life: every node has handshake
    # outcomes and applies in its ring.
    for cluster in harness.clusters.values():
        kinds = {e["kind"] for e in cluster.flight_record()}
        assert "lifecycle" in kinds and "handshake" in kinds
        assert "apply" in kinds


async def test_no_prov_trace_means_no_prov_events(tmp_path):
    """Defaults untouched: a fleet without prov_trace writes nothing
    provenance-shaped anywhere (the byte-identical-paths contract)."""
    harness, _ = await _converged_marked_fleet(tmp_path, n=3, prov=False)
    with pytest.raises(ValueError):
        harness.propagation_report()
    for cluster in harness.clusters.values():
        assert cluster._prov is None
        assert cluster._engine._prov is None


async def test_flightrec_serve_endpoint_never_shed(tmp_path):
    from aiocluster_tpu.core.config import Config
    from aiocluster_tpu.core.identity import NodeId
    from aiocluster_tpu.runtime.cluster import Cluster
    from aiocluster_tpu.serve.http import OverloadPolicy, ServeApp

    # Pick a free gossip port up front (NodeId wants a concrete addr).
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    config = Config(
        node_id=NodeId(name="solo", gossip_advertise_addr=("127.0.0.1", port)),
        cluster_id="t",
        gossip_interval=0.05,
        seed_nodes=[],
    )
    cluster = Cluster(config, metrics=MetricsRegistry())
    await cluster.start()
    # An overload posture that sheds EVERYTHING shed-able.
    app = ServeApp(
        cluster,
        overload=OverloadPolicy(enabled=True, max_inflight=0),
    )
    try:
        serve_port = await app.start()
        reader, writer = await asyncio.open_connection("127.0.0.1", serve_port)
        writer.write(b"GET /debug/flightrec HTTP/1.1\r\n\r\n")
        await writer.drain()
        status = await reader.readline()
        assert b"200" in status  # operator endpoint: never shed
        headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        body = await reader.readexactly(int(headers["content-length"]))
        events = json.loads(body)["events"]
        assert any(
            e["kind"] == "lifecycle" and e["event"] == "start"
            for e in events
        )
        # A plain endpoint IS shed under the same posture.
        writer.write(b"GET /state HTTP/1.1\r\n\r\n")
        await writer.drain()
        status = await reader.readline()
        assert b"429" in status
        writer.close()
        await writer.wait_closed()
    finally:
        await app.stop()
        await cluster.close()


# -- twin drift ---------------------------------------------------------------


def _synthetic_twin_trace(tmp_path, *, rate_hz: float, rounds: int = 40,
                          nodes: int = 3):
    """A hand-built twin-grade trace at a known per-node round rate."""
    path = tmp_path / "twin.jsonl"
    tw = TraceWriter(path)
    for i in range(nodes):
        tw.emit(
            "twin_node", node=f"n{i:02d}", generation=1,
            gossip_interval_s=1.0 / rate_hz, gossip_count=2,
            phi_threshold=8.0, max_payload_size=65507, n_own_keys=4,
        )
    for r in range(rounds):
        for i in range(nodes):
            tw.emit(
                "twin_round", node=f"n{i:02d}", round=r,
                duration_s=0.001, targets=2, live=nodes - 1, dead=0,
                kv_sent=0, kv_applied=0, heartbeat=r + 1, phi_max=0.1,
            )
    tw.close()
    # Rewrite ts fields to an exact cadence (TraceWriter stamps real
    # wall time; the drift check needs a controlled rate).
    lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
    out = []
    for rec in lines:
        if rec.get("event") == "twin_round":
            rec["ts"] = 1000.0 + rec["round"] / rate_hz
        out.append(json.dumps(rec))
    path.write_text("\n".join(out) + "\n")
    return path


def _calibration(rate_hz: float) -> "object":
    from aiocluster_tpu.twin import CALIBRATION_SCHEMA, CalibrationRecord

    return CalibrationRecord(
        schema=CALIBRATION_SCHEMA, source="stored.jsonl", n_nodes=3,
        trace_rounds=40, fit_rounds=20, holdout_rounds=20,
        rounds_per_sec=rate_hz, rounds_per_sec_std=0.1,
        round_duration_s=0.001, kv_scale=None, kv_scale_std=None,
        sim_converged_round=5, holdout_wall_rel_err=0.01,
        holdout_kv_rel_err=None, tolerance=0.25, holdout_ok=True,
    )


def test_check_drift_ok_when_rates_match(tmp_path):
    from aiocluster_tpu.twin import check_drift

    trace = _synthetic_twin_trace(tmp_path, rate_hz=20.0)
    reg = MetricsRegistry()
    verdict = check_drift(
        _calibration(20.0), str(trace), registry=reg
    )
    assert verdict.ok and not verdict.drifted_axes
    assert verdict.window_rounds == 20  # the stored fit window
    by_axis = {a.axis: a for a in verdict.axes}
    assert by_axis["rounds_per_sec"].rel_err < 0.05
    assert reg.snapshot()["aiocluster_twin_drift"] == 0.0


def test_check_drift_flags_a_slowed_deployment(tmp_path):
    from aiocluster_tpu.twin import check_drift

    # The fleet now runs at half the calibrated rate.
    trace = _synthetic_twin_trace(tmp_path, rate_hz=10.0)
    reg = MetricsRegistry()
    verdict = check_drift(_calibration(20.0), str(trace), registry=reg)
    assert not verdict.ok
    axes = {a.axis: a for a in verdict.drifted_axes}
    assert "rounds_per_sec" in axes
    assert axes["rounds_per_sec"].rel_err == pytest.approx(0.5, abs=0.05)
    snap = reg.snapshot()
    assert snap["aiocluster_twin_drift"] == 1.0
    assert snap[
        "aiocluster_twin_drift_rel_err{axis=rounds_per_sec}"
    ] == pytest.approx(0.5, abs=0.05)


def test_check_drift_skips_kv_axis_on_midflight_windows(tmp_path):
    from dataclasses import replace

    from aiocluster_tpu.twin import check_drift

    trace = _synthetic_twin_trace(tmp_path, rate_hz=20.0)
    cal = replace(_calibration(20.0), kv_scale=2.0, kv_scale_std=0.1)
    # Window covers only the tail: kv axis is not re-fittable against a
    # cold-start sim — reported skipped, never silently verdicted.
    verdict = check_drift(cal, str(trace), window=10)
    assert "kv_scale" in verdict.skipped_axes
    assert all(a.axis != "kv_scale" for a in verdict.axes)


def test_check_drift_refuses_an_empty_window(tmp_path):
    from aiocluster_tpu.twin import check_drift

    trace = _synthetic_twin_trace(tmp_path, rate_hz=20.0, rounds=3)
    with pytest.raises(ValueError):
        check_drift(_calibration(20.0), str(trace), window=1)
