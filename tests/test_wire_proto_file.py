"""The shipped .proto schema stays in sync with the hand-rolled codec.

``wire/messages.proto`` is the third-party codegen surface (reference
ships one at protos/messages.proto + a regeneration target, Makefile:19-22).
These tests compile it with protoc at test time and prove byte-for-byte
agreement both ways: codec bytes parse + re-serialize identically through
the generated classes, and generated-class bytes decode to the same
objects through the codec. If either side drifts (field number, presence
rule, new message), this fails.
"""

from __future__ import annotations

import importlib.util
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from aiocluster_tpu.core.identity import NodeId
from aiocluster_tpu.core.messages import (
    Ack,
    BadCluster,
    Delta,
    Digest,
    KeyValueUpdate,
    NodeDelta,
    NodeDigest,
    Packet,
    Syn,
    SynAck,
)
from aiocluster_tpu.core.values import VersionStatusEnum
from aiocluster_tpu.wire import decode_packet, encode_packet

PROTO = Path(__file__).parent.parent / "aiocluster_tpu" / "wire" / "messages.proto"


@pytest.fixture(scope="module")
def pb(tmp_path_factory):
    protoc = shutil.which("protoc")
    if protoc is None:
        pytest.skip("protoc not installed")
    out = tmp_path_factory.mktemp("protogen")
    subprocess.run(
        [protoc, f"--proto_path={PROTO.parent}", f"--python_out={out}",
         PROTO.name],
        check=True,
        capture_output=True,
    )
    spec = importlib.util.spec_from_file_location(
        "aiocluster_tpu_wire_messages_pb2", out / "messages_pb2.py"
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def _node(i: int, tls: str | None = None) -> NodeId:
    return NodeId(f"node-{i}", 1000 + i, (f"10.0.0.{i}", 7000 + i), tls)


def _digest() -> Digest:
    return Digest(
        {
            _node(1): NodeDigest(_node(1), 7, 2, 9),
            _node(2, "tls-2"): NodeDigest(_node(2, "tls-2"), 0, 0, 4),
        }
    )


def _delta() -> Delta:
    return Delta(
        [
            NodeDelta(
                _node(1),
                from_version_excluded=3,
                last_gc_version=1,
                key_values=[
                    KeyValueUpdate("k1", "v1", 4, VersionStatusEnum.SET),
                    KeyValueUpdate("k2", "", 5, VersionStatusEnum.DELETED),
                    KeyValueUpdate(
                        "k3", "ttl", 6, VersionStatusEnum.DELETE_AFTER_TTL
                    ),
                ],
                max_version=6,
            ),
            # max_version ABSENT (optional field): presence must survive
            # both directions.
            NodeDelta(_node(2, "tls-2"), 0, 0, [], None),
        ]
    )


PACKETS = [
    Packet("interop", Syn(_digest())),
    Packet("interop", SynAck(_digest(), _delta())),
    Packet("interop", Ack(_delta())),
    Packet("", BadCluster()),
]


@pytest.mark.parametrize("packet", PACKETS, ids=lambda p: type(p.msg).__name__)
def test_codec_bytes_parse_and_reserialize_identically(pb, packet):
    raw = encode_packet(packet)
    parsed = pb.Packet.FromString(raw)
    assert parsed.SerializeToString(deterministic=True) == raw
    assert parsed.cluster_id == packet.cluster_id


def test_generated_class_bytes_decode_through_codec(pb):
    msg = pb.Packet(cluster_id="gen")
    nd = msg.synack.digest.node_digests.add()
    nd.node_id.name = "gen-node"
    nd.node_id.generation_id = 42
    nd.node_id.gossip_advertise_addr.host = "h"
    nd.node_id.gossip_advertise_addr.port = 1234
    nd.heartbeat = 5  # noqa: ACT030 -- white-box: fabricating a codec fixture, never gossiped
    nd.max_version = 8  # noqa: ACT030 -- white-box: fabricating a codec fixture, never gossiped
    d = msg.synack.delta.node_deltas.add()
    d.node_id.name = "gen-node"
    d.node_id.generation_id = 42
    d.node_id.gossip_advertise_addr.host = "h"
    d.node_id.gossip_advertise_addr.port = 1234
    kv = d.key_values.add()
    kv.key = "k"
    kv.value = "v"
    kv.version = 8
    kv.status = pb.VersionStatus.DELETE_AFTER_TTL
    d.max_version = 8  # noqa: ACT030 -- white-box: fabricating a codec fixture, never gossiped

    decoded = decode_packet(msg.SerializeToString(deterministic=True))
    assert decoded.cluster_id == "gen"
    assert isinstance(decoded.msg, SynAck)
    node = NodeId("gen-node", 42, ("h", 1234))
    assert decoded.msg.digest.node_digests[node].max_version == 8
    (got,) = decoded.msg.delta.node_deltas
    assert got.node_id == node
    assert got.max_version == 8
    assert got.key_values == [
        KeyValueUpdate("k", "v", 8, VersionStatusEnum.DELETE_AFTER_TTL)
    ]


def test_optional_max_version_presence_is_preserved(pb):
    raw = encode_packet(Packet("p", Ack(_delta())))
    parsed = pb.Packet.FromString(raw)
    with_max, without_max = parsed.ack.delta.node_deltas
    assert with_max.HasField("max_version") and with_max.max_version == 6
    assert not without_max.HasField("max_version")
