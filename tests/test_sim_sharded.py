"""Sharded simulator: shard_map over the 8-virtual-device CPU mesh must be
bit-identical to the single-device run (the budget's global greedy order is
preserved via the block-offset all_gather)."""

import numpy as np
import jax
import pytest
from jax import random

from aiocluster_tpu.ops.gossip import sim_step
from aiocluster_tpu.parallel.mesh import (
    make_mesh,
    shard_state,
    sharded_metrics_fn,
    sharded_step_fn,
)
from aiocluster_tpu.sim import SimConfig, Simulator, init_state

# Interpret-mode kernels / multi-device mesh / subprocess suites:
# minutes on a 1-core CPU host. `make test` deselects slow; the
# full `make test-all` (and CI) runs everything.
pytestmark = pytest.mark.slow

KEY = random.key(11)


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8  # conftest forces the CPU mesh


def test_sharded_step_bit_identical_to_single_device():
    cfg = SimConfig(n_nodes=64, keys_per_node=16, budget=32)
    mesh = make_mesh()
    step = sharded_step_fn(cfg, mesh)

    sharded = shard_state(init_state(cfg), mesh)
    single = init_state(cfg)
    for _ in range(12):
        sharded = step(sharded, KEY)
        single = sim_step(single, KEY, cfg)

    assert np.array_equal(np.asarray(sharded.w), np.asarray(single.w))
    assert np.array_equal(np.asarray(sharded.hb_known), np.asarray(single.hb_known))
    assert np.array_equal(
        np.asarray(sharded.live_view), np.asarray(single.live_view)
    )
    assert int(sharded.tick) == int(single.tick) == 12


@pytest.mark.parametrize("extra", [
    # Default matching pairing under churn + lifecycle.
    {},
    # Benchmark config 3's FD-faithful combination: choice pairing and
    # view-mode peer draws (the Gumbel-max composes across shards).
    {"pairing": "choice", "peer_mode": "view"},
])
def test_sharded_lifecycle_bit_identical_to_single_device(extra):
    """The dead-node lifecycle (stamp / schedule / GC) is pure elementwise
    + shard-local row-gather math, so a churning sharded run must stay
    bit-identical through detection, digest exclusion and removal."""
    cfg = SimConfig(n_nodes=64, keys_per_node=8, budget=32,
                    death_rate=0.03, revival_rate=0.08,
                    dead_grace_ticks=16, **extra)
    mesh = make_mesh()
    step = sharded_step_fn(cfg, mesh)

    sharded = shard_state(init_state(cfg), mesh)
    single = init_state(cfg)
    for _ in range(40):
        sharded = step(sharded, KEY)
        single = sim_step(single, KEY, cfg)

    for field in ("w", "hb_known", "live_view", "dead_since"):
        assert np.array_equal(
            np.asarray(getattr(sharded, field)),
            np.asarray(getattr(single, field)),
        ), field
    # The churn actually exercised the lifecycle in this window.
    assert np.asarray(single.dead_since).any()


def test_sharded_metrics_match():
    cfg = SimConfig(n_nodes=64, keys_per_node=16, track_failure_detector=False)
    mesh = make_mesh()
    step = sharded_step_fn(cfg, mesh)
    metrics = sharded_metrics_fn(mesh)
    state = shard_state(init_state(cfg), mesh)
    for _ in range(30):
        state = step(state, KEY)
    m = metrics(state)
    assert bool(m["all_converged"])
    assert int(m["converged_owners"]) == 64
    assert float(m["min_fraction"]) == 1.0


def test_sharded_simulator_driver():
    cfg = SimConfig(n_nodes=96, keys_per_node=8, track_failure_detector=False)
    sim = Simulator(cfg, mesh=make_mesh(), seed=13)
    single = Simulator(cfg, seed=13)
    r_sharded = sim.run_until_converged(1000)
    r_single = single.run_until_converged(1000)
    assert r_sharded == r_single  # identical trajectory => identical rounds


def test_sharded_state_actually_sharded():
    cfg = SimConfig(n_nodes=64, keys_per_node=4, track_failure_detector=False)
    mesh = make_mesh()
    state = shard_state(init_state(cfg), mesh)
    sharding = state.w.sharding
    # Column (owner) axis split over 8 devices: each shard is (64, 8).
    shard_shapes = {s.data.shape for s in state.w.addressable_shards}
    assert shard_shapes == {(64, 8)}
    assert len(sharding.device_set) == 8


def test_sharded_topology_step_bit_identical_to_single_device():
    from aiocluster_tpu.models.topology import ring

    cfg = SimConfig(n_nodes=64, keys_per_node=8, budget=16)
    topo = ring(64, neighbors_each_side=2)
    adj = jax.numpy.asarray(topo.adjacency)
    deg = jax.numpy.asarray(topo.degrees)
    mesh = make_mesh()
    step = sharded_step_fn(cfg, mesh, topology=True)

    sharded = shard_state(init_state(cfg), mesh)
    single = init_state(cfg)
    for _ in range(10):
        sharded = step(sharded, KEY, adj, deg)
        single = sim_step(single, KEY, cfg, adjacency=adj, degrees=deg)

    assert np.array_equal(np.asarray(sharded.w), np.asarray(single.w))
    assert np.array_equal(
        np.asarray(sharded.live_view), np.asarray(single.live_view)
    )
    assert int(sharded.tick) == int(single.tick) == 10


def test_sharded_simulator_with_scale_free_topology():
    from aiocluster_tpu.models.topology import scale_free

    cfg = SimConfig(n_nodes=96, keys_per_node=8, track_failure_detector=False)
    topo = scale_free(96, attach=3, seed=5)
    sharded = Simulator(cfg, mesh=make_mesh(), seed=7, topology=topo)
    single = Simulator(cfg, seed=7, topology=topo)
    r_sharded = sharded.run_until_converged(2000)
    r_single = single.run_until_converged(2000)
    assert r_sharded is not None
    assert r_sharded == r_single


def test_sharded_matching_compact_dtypes_bit_identical():
    """The new matching pairing and int16/bfloat16 storage must stay
    shard-exact too (dither/draws key off GLOBAL indices only)."""
    cfg = SimConfig(
        n_nodes=64, keys_per_node=8, budget=24, pairing="matching",
        version_dtype="int16", heartbeat_dtype="int16", fd_dtype="bfloat16",
    )
    mesh = make_mesh()
    step = sharded_step_fn(cfg, mesh)
    sharded = shard_state(init_state(cfg), mesh)
    single = init_state(cfg)
    for _ in range(10):
        sharded = step(sharded, KEY)
        single = sim_step(single, KEY, cfg)
    assert np.array_equal(np.asarray(sharded.w), np.asarray(single.w))
    assert np.array_equal(
        np.asarray(sharded.imean), np.asarray(single.imean)
    )
    assert np.array_equal(
        np.asarray(sharded.live_view), np.asarray(single.live_view)
    )


def test_sharded_resume_matches_single_device_resume(tmp_path):
    cfg = SimConfig(n_nodes=32, keys_per_node=4, budget=16)
    a = Simulator(cfg, seed=6)
    a.run(5)
    ckpt = tmp_path / "s.npz"
    a.save(ckpt)
    b = Simulator.resume(ckpt, mesh=make_mesh())
    a.run(7)
    b.run(7)
    assert np.array_equal(np.asarray(a.state.w), np.asarray(b.state.w))

