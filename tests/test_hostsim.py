"""The native host fast-path must walk EXACTLY the Simulator's
trajectory on its domain — it exists to measure the 100k-node
rounds-to-convergence, so any divergence, however small, would poison
the headline number. Every round of w is compared bit-for-bit."""

from __future__ import annotations

import numpy as np
import pytest

from aiocluster_tpu.sim import SimConfig, Simulator
from aiocluster_tpu.sim.hostsim import HostSimulator, available, supported
from aiocluster_tpu.sim.memory import full_config, lean_config

pytestmark = pytest.mark.skipif(
    not available(), reason="native hostsim failed to build"
)


def _trajectories_equal(cfg, seed, max_rounds):
    sim = Simulator(cfg, seed=seed, chunk=1)
    host = HostSimulator(cfg, seed=seed)
    for r in range(1, max_rounds + 1):
        sim.run(1)
        host.run(1)
        np.testing.assert_array_equal(
            np.asarray(sim.state.w), host.w,
            err_msg=f"divergence at round {r}",
        )
    return sim, host


def test_trajectory_bit_identity_budget_bound():
    """Small budget keeps the run in the budget-bound regime (scale < 1,
    dithered rounding active) — the regime 100k convergence spends
    almost all its rounds in."""
    cfg = lean_config(256, budget=24)
    _trajectories_equal(cfg, seed=1, max_rounds=12)


def test_trajectory_bit_identity_saturating():
    """Large budget exercises the saturating fast path (scale == 1)."""
    cfg = lean_config(256, budget=4096)
    _trajectories_equal(cfg, seed=2, max_rounds=8)


def test_convergence_round_matches_simulator():
    """The headline quantity: exact first-converged round equal between
    the native path and the Simulator's in-chunk tracker."""
    cfg = lean_config(256, budget=64)
    r_sim = Simulator(cfg, seed=1, chunk=4).run_until_converged(
        max_rounds=512
    )
    r_host = HostSimulator(cfg, seed=1).run_until_converged(max_rounds=512)
    assert r_sim is not None
    assert r_host == r_sim


@pytest.mark.slow
def test_trajectory_bit_identity_vs_mesh():
    """Direct (not just transitive) closure of the certification chain:
    the native path equals the 8-device-mesh shard_map path — the exact
    program the 100k certify step replays — round by round."""
    import jax

    from aiocluster_tpu.parallel.mesh import make_mesh

    cfg = lean_config(256, budget=64)
    mesh = make_mesh(jax.devices()[:8])
    sim = Simulator(cfg, seed=4, mesh=mesh, chunk=1)
    host = HostSimulator(cfg, seed=4)
    for r in range(1, 9):
        sim.run(1)
        host.run(1)
        np.testing.assert_array_equal(
            np.asarray(sim.state.w), host.w,
            err_msg=f"mesh divergence at round {r}",
        )


@pytest.mark.slow
def test_trajectory_bit_identity_1024():
    """A bigger population (more groups, denser middle phase), full
    trajectory to convergence plus the convergence round itself."""
    cfg = lean_config(1024, budget=128)
    sim, host = _trajectories_equal(cfg, seed=3, max_rounds=30)
    r_host = HostSimulator(cfg, seed=3).run_until_converged(max_rounds=512)
    r_sim = Simulator(cfg, seed=3, chunk=8).run_until_converged(
        max_rounds=512
    )
    assert r_host == r_sim is not None


def test_checkpoint_resume_continues_exact(tmp_path):
    """save/resume is invisible to the trajectory (the 100k run
    checkpoints every few dozen rounds across battery pauses)."""
    cfg = lean_config(256, budget=64)
    a = HostSimulator(cfg, seed=5)
    a.run(6)
    a.save(str(tmp_path / "ck"))
    b = HostSimulator.resume(str(tmp_path / "ck"), cfg)
    assert b.tick == 6
    a.run(5)
    b.run(5)
    np.testing.assert_array_equal(a.w, b.w)
    # And the resumed run's future randomness matches a fresh
    # uninterrupted run (salts depend only on seed + tick).
    c = HostSimulator(cfg, seed=5)
    c.run(11)
    np.testing.assert_array_equal(a.w, c.w)


def test_supported_gate():
    assert supported(lean_config(1024))
    assert not supported(lean_config(1000))  # off the 128-lane domain
    assert not supported(
        lean_config(1024, version_dtype="int32")
    )
    # Full profile: on the domain at int16 heartbeat ticks (round 5),
    # but NOT at the default int32 (the kernel implements int16 only)
    # and NOT with the lifecycle/churn/writes branches.
    assert supported(full_config(1024))
    assert supported(full_config(1024, fd_dtype="float32"))
    assert not supported(
        SimConfig(n_nodes=1024, keys_per_node=16, fanout=3, budget=64)
    )  # default heartbeat_dtype=int32
    assert not supported(full_config(1024, dead_grace_ticks=64))
    assert not supported(full_config(1024, death_rate=0.05))
    assert not supported(full_config(1024, writes_per_round=1))
    with pytest.raises(ValueError):
        HostSimulator(lean_config(1000))


# -- 'choice' pairing (reference independent-sampling semantics) -------------


def test_choice_pairing_bit_identity():
    """The reference-faithful independent-sampling path (server.py:699
    semantics: every node samples a peer; inbound load varies; the
    responder side is a scatter-max) walks the XLA trajectory exactly.
    Small budget exercises the dithered regime in both directions."""
    cfg = lean_config(256, budget=24, pairing="choice")
    _trajectories_equal(cfg, seed=11, max_rounds=10)


def test_choice_pairing_convergence_round_matches():
    cfg = lean_config(256, budget=64, pairing="choice")
    r_sim = Simulator(cfg, seed=12, chunk=4).run_until_converged(
        max_rounds=512
    )
    r_host = HostSimulator(cfg, seed=12).run_until_converged(max_rounds=512)
    assert r_sim is not None
    assert r_host == r_sim


def test_choice_gate():
    assert supported(lean_config(256, pairing="choice"))
    # FD-faithful 'view' sampling and the hb scatter are outside the
    # native domain.
    assert not supported(full_config(256, pairing="choice"))
    assert not supported(
        lean_config(256, pairing="permutation")
    )


# -- full profile (heartbeats + failure detector), round 5 -------------------


def _full_state_equal(sim, host, r, fd_dtype):
    s = sim.state
    np.testing.assert_array_equal(
        np.asarray(s.w), host.w, err_msg=f"w divergence at round {r}"
    )
    np.testing.assert_array_equal(
        np.asarray(s.hb_known), host.hb, err_msg=f"hb divergence at round {r}"
    )
    np.testing.assert_array_equal(
        np.asarray(s.last_change), host.last_change,
        err_msg=f"last_change divergence at round {r}",
    )
    np.testing.assert_array_equal(
        np.asarray(s.icount), host.icount,
        err_msg=f"icount divergence at round {r}",
    )
    np.testing.assert_array_equal(
        np.asarray(s.live_view), host.live_view,
        err_msg=f"live_view divergence at round {r}",
    )
    a, b = np.asarray(s.imean), host.imean
    if fd_dtype == "bfloat16":
        a, b = a.view(np.uint16), b.view(np.uint16)
    np.testing.assert_array_equal(
        a, b, err_msg=f"imean divergence at round {r}"
    )


@pytest.mark.parametrize("fd_dtype", ["bfloat16", "float32"])
def test_full_profile_bit_identity(fd_dtype):
    """The FULL profile (heartbeats + phi-accrual FD — the reference's
    actual operating shape) walks the Simulator's exact trajectory in
    EVERY state matrix, at both stored-mean dtypes. Small budget keeps
    the watermark advance in the dithered budget-bound regime."""
    cfg = full_config(256, budget=24, fd_dtype=fd_dtype)
    sim = Simulator(cfg, seed=7, chunk=1)
    host = HostSimulator(cfg, seed=7)
    for r in range(1, 9):
        sim.run(1)
        host.run(1)
        _full_state_equal(sim, host, r, fd_dtype)


def test_full_profile_convergence_round_matches():
    cfg = full_config(256, budget=64)
    r_sim = Simulator(cfg, seed=8, chunk=4).run_until_converged(
        max_rounds=512
    )
    r_host = HostSimulator(cfg, seed=8).run_until_converged(max_rounds=512)
    assert r_sim is not None
    assert r_host == r_sim


def test_full_profile_matches_lean_w_trajectory():
    """On the no-churn/no-lifecycle domain the FD never feeds back into
    the watermark advance (validity masks are all-true, peer choice is
    the matching), so the full profile's w trajectory — and therefore
    its convergence round — must equal the lean profile's at the same
    seed. This is why the lean 100k R generalizes to the full profile."""
    lean = lean_config(256, budget=24)
    full = full_config(256, budget=24)
    a = HostSimulator(lean, seed=9)
    b = HostSimulator(full, seed=9)
    for _ in range(6):
        a.run(1)
        b.run(1)
        np.testing.assert_array_equal(a.w, b.w)


def test_full_profile_checkpoint_resume(tmp_path):
    """save/resume round-trips every full-profile matrix exactly."""
    cfg = full_config(256, budget=64)
    a = HostSimulator(cfg, seed=10)
    a.run(5)
    a.save(str(tmp_path / "ck"))
    b = HostSimulator.resume(str(tmp_path / "ck"), cfg)
    assert b.tick == 5
    a.run(4)
    b.run(4)
    np.testing.assert_array_equal(a.w, b.w)
    np.testing.assert_array_equal(a.hb, b.hb)
    np.testing.assert_array_equal(
        a.imean.view(np.uint16), b.imean.view(np.uint16)
    )
    np.testing.assert_array_equal(a.icount, b.icount)
    np.testing.assert_array_equal(a.live_view, b.live_view)
    # Lean checkpoints refuse to resume under a full-profile config
    # (missing matrices must not be silently zero-initialized).
    lean = lean_config(256, budget=64)
    c = HostSimulator(lean, seed=10)
    c.save(str(tmp_path / "lk"))
    with pytest.raises(ValueError):
        HostSimulator.resume(str(tmp_path / "lk"), cfg)
