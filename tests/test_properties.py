"""Property-style tests: randomized round-trips and CRDT convergence.

SURVEY.md §7 step 2 calls for property-testing the reconciliation core:
these drive randomized workloads through the public surfaces instead of
hand-picked cases — seeded for reproducibility.
"""

import random as pyrandom
import string
from datetime import UTC, datetime, timedelta

import pytest

from aiocluster_tpu.core import (
    ClusterState,
    Config,
    FailureDetector,
    FailureDetectorConfig,
    NodeId,
)
from aiocluster_tpu.core.messages import KeyValueUpdate, NodeDelta
from aiocluster_tpu.core.values import VersionStatusEnum
from aiocluster_tpu.runtime.engine import GossipEngine
from aiocluster_tpu.wire import decode_packet, encode_packet
from aiocluster_tpu.wire.proto import decode_node_delta, encode_node_delta

TS = datetime(2026, 1, 1, tzinfo=UTC)


def _rand_text(rng: pyrandom.Random, max_len: int = 24) -> str:
    alphabet = string.ascii_letters + string.digits + "/-_.é☃"
    return "".join(
        rng.choice(alphabet) for _ in range(rng.randint(0, max_len))
    )


@pytest.mark.parametrize("seed", range(5))
def test_node_delta_roundtrip_fuzz(seed):
    """encode -> decode is the identity for arbitrary well-formed deltas,
    through whichever codec path (native engages above the size gate)."""
    rng = pyrandom.Random(seed)
    kvs = [
        KeyValueUpdate(
            key=_rand_text(rng),
            value=_rand_text(rng, 60),
            version=rng.randint(0, 2**63),
            status=rng.choice(list(VersionStatusEnum)),
        )
        for _ in range(rng.randint(0, 120))
    ]
    nd = NodeDelta(
        node_id=NodeId(_rand_text(rng) or "n", rng.randint(0, 2**62),
                       ("host", rng.randint(1, 65535))),
        from_version_excluded=rng.randint(0, 2**40),
        last_gc_version=rng.randint(0, 2**40),
        key_values=kvs,
        max_version=rng.choice([None, rng.randint(0, 2**40)]),
    )
    body = encode_node_delta(nd)
    assert decode_node_delta(body) == nd


@pytest.mark.parametrize("seed", range(3))
def test_random_workload_converges_and_agrees(seed):
    """CRDT property: N engines applying random local writes + random
    pairwise handshakes end up with identical, complete cluster state
    once enough handshakes have run."""
    rng = pyrandom.Random(100 + seed)
    n = 4
    nodes = [NodeId(f"n{i}", i + 1, ("h", i + 1)) for i in range(n)]

    def mk(i: int) -> GossipEngine:
        cfg = Config(node_id=nodes[i], cluster_id="prop")
        cs = ClusterState()
        cs.node_state_or_default(nodes[i]).inc_heartbeat()
        return GossipEngine(cfg, cs, FailureDetector(FailureDetectorConfig()))

    engines = [mk(i) for i in range(n)]
    keys = [f"k{j}" for j in range(6)]

    def handshake(a: GossipEngine, b: GossipEngine) -> None:
        syn = decode_packet(encode_packet(a.make_syn()))
        synack = decode_packet(encode_packet(b.handle_syn(syn)))
        ack = decode_packet(encode_packet(a.handle_synack(synack)))
        b.handle_ack(ack)

    # Interleave random owner writes (sets, deletes, TTL) and handshakes.
    for _ in range(120):
        op = rng.random()
        i = rng.randrange(n)
        ns = engines[i]._state.node_state_or_default(nodes[i])
        if op < 0.5:
            ns.set(rng.choice(keys), _rand_text(rng, 8), ts=TS)
        elif op < 0.6:
            ns.delete(rng.choice(keys), ts=TS)
        elif op < 0.65:
            ns.set_with_ttl(rng.choice(keys), _rand_text(rng, 8), ts=TS)
        else:
            j = rng.randrange(n)
            if j != i:
                handshake(engines[i], engines[j])

    # Quiesce: enough all-pairs rounds for every delta to land.
    for _ in range(4):
        for i in range(n):
            for j in range(n):
                if i != j:
                    handshake(engines[i], engines[j])

    # Every engine holds the identical keyspace for every owner.
    for owner in range(n):
        truth = engines[owner]._state.node_state_or_default(nodes[owner])
        for other in range(n):
            replica = engines[other]._state.node_state_or_default(nodes[owner])
            assert replica.max_version == truth.max_version, (owner, other)
            assert {
                k: (v.value, v.version, v.status)
                for k, v in replica.key_values.items()
            } == {
                k: (v.value, v.version, v.status)
                for k, v in truth.key_values.items()
            }, (owner, other)


def test_gc_watermark_consistency_under_gossip():
    """Tombstones GC'd at the owner disappear from replicas via the
    watermark, never resurrecting."""
    nodes = [NodeId(f"g{i}", i + 1, ("h", 50 + i)) for i in range(2)]

    def mk(i):
        cfg = Config(node_id=nodes[i], cluster_id="gc")
        cs = ClusterState()
        cs.node_state_or_default(nodes[i]).inc_heartbeat()
        return GossipEngine(cfg, cs, FailureDetector(FailureDetectorConfig()))

    a, b = mk(0), mk(1)

    def handshake(x, y):
        syn = decode_packet(encode_packet(x.make_syn()))
        synack = decode_packet(encode_packet(y.handle_syn(syn)))
        ack = decode_packet(encode_packet(x.handle_synack(synack)))
        y.handle_ack(ack)

    ns = a._state.node_state_or_default(nodes[0])
    ns.set("keep", "v", ts=TS)
    ns.set("gone", "v", ts=TS)
    ns.delete("gone", ts=TS)
    handshake(a, b)
    replica = b._state.node_state_or_default(nodes[0])
    assert replica.get("keep") is not None and replica.get("gone") is None

    # Owner GCs the tombstone after the grace period. The watermark rides
    # deltas, and a fully caught-up replica gets no delta (reference
    # state.py:356-357 skips nodes with max_version <= digest's), so the
    # purge reaches replicas with the owner's NEXT write — same contract
    # as the reference.
    ns.gc_marked_for_deletion(timedelta(hours=2), ts=TS + timedelta(hours=3))
    assert "gone" not in ns.key_values
    ns.set("fresh", "w", ts=TS + timedelta(hours=3))
    handshake(a, b)
    assert "gone" not in replica.key_values
    assert replica.get("keep").value == "v"  # live key survives the watermark
    assert replica.get("fresh").value == "w"
    assert replica.last_gc_version == ns.last_gc_version
