"""Property-style tests: randomized round-trips and CRDT convergence.

SURVEY.md §7 step 2 calls for property-testing the reconciliation core:
these drive randomized workloads through the public surfaces instead of
hand-picked cases — seeded for reproducibility.
"""

import random as pyrandom
import string
from datetime import datetime, timedelta

from aiocluster_tpu.utils.clock import UTC

import pytest

from aiocluster_tpu.core import (
    ClusterState,
    Config,
    FailureDetector,
    FailureDetectorConfig,
    NodeId,
)
from aiocluster_tpu.core.messages import KeyValueUpdate, NodeDelta
from aiocluster_tpu.core.values import VersionStatusEnum
from aiocluster_tpu.runtime.engine import GossipEngine
from aiocluster_tpu.wire import decode_packet, encode_packet
from aiocluster_tpu.wire.proto import decode_node_delta, encode_node_delta

TS = datetime(2026, 1, 1, tzinfo=UTC)


def _rand_text(rng: pyrandom.Random, max_len: int = 24) -> str:
    alphabet = string.ascii_letters + string.digits + "/-_.é☃"
    return "".join(
        rng.choice(alphabet) for _ in range(rng.randint(0, max_len))
    )


@pytest.mark.parametrize("seed", range(5))
def test_node_delta_roundtrip_fuzz(seed):
    """encode -> decode is the identity for arbitrary well-formed deltas,
    through whichever codec path (native engages above the size gate)."""
    rng = pyrandom.Random(seed)
    kvs = [
        KeyValueUpdate(
            key=_rand_text(rng),
            value=_rand_text(rng, 60),
            version=rng.randint(0, 2**63),
            status=rng.choice(list(VersionStatusEnum)),
        )
        for _ in range(rng.randint(0, 120))
    ]
    nd = NodeDelta(
        node_id=NodeId(_rand_text(rng) or "n", rng.randint(0, 2**62),
                       ("host", rng.randint(1, 65535))),
        from_version_excluded=rng.randint(0, 2**40),
        last_gc_version=rng.randint(0, 2**40),
        key_values=kvs,
        max_version=rng.choice([None, rng.randint(0, 2**40)]),
    )
    body = encode_node_delta(nd)
    assert decode_node_delta(body) == nd


@pytest.mark.parametrize("seed", range(3))
def test_random_workload_converges_and_agrees(seed):
    """CRDT property: N engines applying random local writes + random
    pairwise handshakes end up with identical, complete cluster state
    once enough handshakes have run."""
    rng = pyrandom.Random(100 + seed)
    n = 4
    nodes = [NodeId(f"n{i}", i + 1, ("h", i + 1)) for i in range(n)]

    def mk(i: int) -> GossipEngine:
        cfg = Config(node_id=nodes[i], cluster_id="prop")
        cs = ClusterState()
        cs.node_state_or_default(nodes[i]).inc_heartbeat()  # noqa: ACT031 -- white-box: the property test plays each owner, issuing its own heartbeats
        return GossipEngine(cfg, cs, FailureDetector(FailureDetectorConfig()))

    engines = [mk(i) for i in range(n)]
    keys = [f"k{j}" for j in range(6)]

    def handshake(a: GossipEngine, b: GossipEngine) -> None:
        syn = decode_packet(encode_packet(a.make_syn()))
        synack = decode_packet(encode_packet(b.handle_syn(syn)))
        ack = decode_packet(encode_packet(a.handle_synack(synack)))
        b.handle_ack(ack)

    # Interleave random owner writes (sets, deletes, TTL) and handshakes.
    for _ in range(120):
        op = rng.random()
        i = rng.randrange(n)
        ns = engines[i]._state.node_state_or_default(nodes[i])
        if op < 0.5:
            ns.set(rng.choice(keys), _rand_text(rng, 8), ts=TS)
        elif op < 0.6:
            ns.delete(rng.choice(keys), ts=TS)
        elif op < 0.65:
            ns.set_with_ttl(rng.choice(keys), _rand_text(rng, 8), ts=TS)
        else:
            j = rng.randrange(n)
            if j != i:
                handshake(engines[i], engines[j])

    # Quiesce: enough all-pairs rounds for every delta to land.
    for _ in range(4):
        for i in range(n):
            for j in range(n):
                if i != j:
                    handshake(engines[i], engines[j])

    # Every engine holds the identical keyspace for every owner.
    for owner in range(n):
        truth = engines[owner]._state.node_state_or_default(nodes[owner])
        for other in range(n):
            replica = engines[other]._state.node_state_or_default(nodes[owner])
            assert replica.max_version == truth.max_version, (owner, other)
            assert {
                k: (v.value, v.version, v.status)
                for k, v in replica.key_values.items()
            } == {
                k: (v.value, v.version, v.status)
                for k, v in truth.key_values.items()
            }, (owner, other)


def test_gc_watermark_consistency_under_gossip():
    """Tombstones GC'd at the owner disappear from replicas via the
    watermark, never resurrecting."""
    nodes = [NodeId(f"g{i}", i + 1, ("h", 50 + i)) for i in range(2)]

    def mk(i):
        cfg = Config(node_id=nodes[i], cluster_id="gc")
        cs = ClusterState()
        cs.node_state_or_default(nodes[i]).inc_heartbeat()  # noqa: ACT031 -- white-box: the property test plays each owner, issuing its own heartbeats
        return GossipEngine(cfg, cs, FailureDetector(FailureDetectorConfig()))

    a, b = mk(0), mk(1)

    def handshake(x, y):
        syn = decode_packet(encode_packet(x.make_syn()))
        synack = decode_packet(encode_packet(y.handle_syn(syn)))
        ack = decode_packet(encode_packet(x.handle_synack(synack)))
        y.handle_ack(ack)

    ns = a._state.node_state_or_default(nodes[0])
    ns.set("keep", "v", ts=TS)
    ns.set("gone", "v", ts=TS)
    ns.delete("gone", ts=TS)
    handshake(a, b)
    replica = b._state.node_state_or_default(nodes[0])
    assert replica.get("keep") is not None and replica.get("gone") is None

    # Owner GCs the tombstone after the grace period. The watermark rides
    # deltas, and a fully caught-up replica gets no delta (reference
    # state.py:356-357 skips nodes with max_version <= digest's), so the
    # purge reaches replicas with the owner's NEXT write — same contract
    # as the reference.
    ns.gc_marked_for_deletion(timedelta(hours=2), ts=TS + timedelta(hours=3))
    assert "gone" not in ns.key_values
    ns.set("fresh", "w", ts=TS + timedelta(hours=3))
    handshake(a, b)
    assert "gone" not in replica.key_values
    assert replica.get("keep").value == "v"  # live key survives the watermark
    assert replica.get("fresh").value == "w"
    assert replica.last_gc_version == ns.last_gc_version


def test_restart_with_new_generation_replaces_old_incarnation():
    """A restarted node (same name/addr, fresh generation_id) is a NEW
    member: its keyspace replicates independently, and the old
    incarnation ages out through the FD's two-stage GC (reference
    entities.py:58, failure_detector.py:108-128)."""
    from datetime import timedelta

    b_id = NodeId("b", 1, ("h", 2))
    old = NodeId("r", 100, ("h", 9))
    new = NodeId("r", 200, ("h", 9))  # same name + address, new generation

    cfg = Config(node_id=b_id, cluster_id="gen")
    cs = ClusterState()
    cs.node_state_or_default(b_id).inc_heartbeat()  # noqa: ACT031 -- white-box: the test plays node b's owner to fabricate divergent history
    fd = FailureDetector(FailureDetectorConfig())
    b = GossipEngine(cfg, cs, fd)

    def handshake_from(peer_engine):
        syn = decode_packet(encode_packet(peer_engine.make_syn()))
        synack = decode_packet(encode_packet(b.handle_syn(syn)))
        ack = decode_packet(encode_packet(peer_engine.handle_synack(synack)))
        b.handle_ack(ack)

    def mk_peer(nid):
        pcfg = Config(node_id=nid, cluster_id="gen")
        pcs = ClusterState()
        ns = pcs.node_state_or_default(nid)
        ns.inc_heartbeat()
        return GossipEngine(pcfg, pcs, FailureDetector(FailureDetectorConfig()))

    old_engine = mk_peer(old)
    old_engine._state.node_state_or_default(old).set("epoch", "first", ts=TS)  # noqa: ACT031 -- white-box: the test plays the old generation's owner to seed its keyspace
    for _ in range(3):
        old_engine._state.node_state_or_default(old).inc_heartbeat()  # noqa: ACT031 -- white-box: the test plays the old generation's owner, issuing heartbeats
        handshake_from(old_engine)
    assert b._state.node_state_or_default(old).get("epoch").value == "first"

    # Restart: the new incarnation gossips; both NodeIds coexist at first.
    new_engine = mk_peer(new)
    new_engine._state.node_state_or_default(new).set("epoch", "second", ts=TS)  # noqa: ACT031 -- white-box: the test plays the new generation's owner to seed its keyspace
    for _ in range(3):
        new_engine._state.node_state_or_default(new).inc_heartbeat()  # noqa: ACT031 -- white-box: the test plays the new generation's owner, issuing heartbeats
        handshake_from(new_engine)
    assert b._state.node_state_or_default(new).get("epoch").value == "second"
    assert b._state.node_state_or_default(old).get("epoch").value == "first"

    # The old generation falls silent: dead after the phi threshold,
    # excluded from digests at half the grace period, GC'd at the full
    # 24h (time-travel through the injectable clocks; handshakes sampled
    # on the real clock, so travel starts from utc_now).
    from aiocluster_tpu.utils.clock import utc_now

    now = utc_now() + timedelta(seconds=60)
    fd.update_node_liveness(old, ts=now)
    assert old in fd.dead_nodes()
    later = now + timedelta(hours=13)
    assert old in fd.scheduled_for_deletion_nodes(ts=later)
    assert new not in fd.scheduled_for_deletion_nodes(ts=later)
    gone = fd.garbage_collect(ts=now + timedelta(hours=25))
    assert old in gone
    for nid in gone:
        b._state.remove_node(nid)
    assert b._state.node_state(old) is None
    assert b._state.node_state(new).get("epoch").value == "second"


@pytest.mark.parametrize("seed", range(6))
def test_budget_from_mtu_predicts_real_packer_capacity(seed):
    """Property: for uniform key/value sizes, budget_from_mtu's prediction
    equals what the REAL byte-exact packer fits into one delta at that
    MTU (within one key-version: a fresh receiver's zero
    from_version_excluded varint is omitted on the wire, which
    budget_from_mtu conservatively prices in)."""
    import random as pyrandom

    from aiocluster_tpu.core import ClusterState, Digest, NodeId
    from aiocluster_tpu.sim.bytes import budget_from_mtu

    rng = pyrandom.Random(seed)
    key_len = rng.randint(4, 16)
    value_len = rng.randint(1, 24)
    # MTUs small enough that every packed version fits a 1-byte varint
    # (<= 127), so version_scale=100 prices the wire exactly and the
    # only modelling slack left is the omitted zero from_version_excluded.
    mtu = rng.randint(300, 2000)
    k_total = 200  # more versions than any tested MTU can carry

    owner = NodeId("n" * 8, 1000, ("h" * 9, 65_000))
    cs = ClusterState()
    ns = cs.node_state_or_default(owner)
    for j in range(k_total):
        ns.set_with_version(
            f"{j:0{key_len}d}"[:key_len], "v" * value_len, j + 1
        )

    delta = cs.compute_partial_delta_respecting_mtu(Digest({}), mtu, set())
    packed = sum(len(nd.key_values) for nd in delta.node_deltas)

    predicted = budget_from_mtu(
        mtu, key_bytes=key_len, value_bytes=value_len,
        node_name_bytes=8, version_scale=100,
    )
    assert packed > 0
    assert packed <= 127  # inside the 1-byte varint regime priced above
    assert abs(packed - predicted) <= 1, (
        f"packer fit {packed}, budget_from_mtu said {predicted} "
        f"(key={key_len} value={value_len} mtu={mtu})"
    )
