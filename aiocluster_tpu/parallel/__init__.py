"""Device-mesh sharding for the simulator: owner-axis column sharding,
shard_map'd steps, collective convergence checks."""

from .mesh import (
    AXIS,
    make_mesh,
    shard_state,
    sharded_metrics_fn,
    sharded_step_fn,
    state_partition_spec,
)

__all__ = (
    "AXIS",
    "make_mesh",
    "shard_state",
    "sharded_metrics_fn",
    "sharded_step_fn",
    "state_partition_spec",
)
