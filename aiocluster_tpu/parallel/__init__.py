"""Device-mesh sharding for the simulator: owner-axis column sharding,
shard_map'd steps, collective convergence checks."""

from .mesh import (
    AXIS,
    make_mesh,
    shard_state,
    shard_sweep_state,
    sharded_metrics_fn,
    sharded_step_fn,
    sharded_sweep_chunk_fn,
    sharded_sweep_metrics_fn,
    state_partition_spec,
    sweep_state_partition_spec,
)

__all__ = (
    "AXIS",
    "make_mesh",
    "shard_state",
    "shard_sweep_state",
    "sharded_metrics_fn",
    "sharded_step_fn",
    "sharded_sweep_chunk_fn",
    "sharded_sweep_metrics_fn",
    "state_partition_spec",
    "sweep_state_partition_spec",
)
