"""Device-mesh plumbing for the sharded simulator.

One 1-D mesh axis ``"owners"`` shards every (N, N) knowledge matrix along
its column (owner) axis. Rows stay unsharded, so peer-row gathers inside
the gossip step are shard-local; the step's only ICI traffic is the
(N,)-per-shard all_gather for global budget order and the convergence
psum/pmin (ops/gossip.py docstring).

The same ``sim_step`` runs unsharded (axis_name=None) or under shard_map
(axis_name="owners") with bit-identical results — tested in
tests/test_sim_sharded.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.gossip import (
    convergence_metrics,
    fd_phase_engaged,
    pallas_path_engaged,
    sim_step,
    staleness_percentiles,
    version_spread,
)
from ..sim.config import SimConfig
from ..sim.state import SimState

AXIS = "owners"

# jax.shard_map (with its ``check_vma`` flag) only exists on newer JAX;
# older releases ship jax.experimental.shard_map.shard_map with the same
# semantics under ``check_rep``. One wrapper keeps every call site below
# version-agnostic.
if hasattr(jax, "shard_map"):

    def _shard_map(body, *, mesh, in_specs, out_specs, check=True):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map(body, *, mesh, in_specs, out_specs, check=True):
        # The legacy replication checker false-positives on fori_loop
        # carries whose replication is refined inside the loop (e.g. the
        # tracked chunk's psum'd convergence flag) — its own error text
        # prescribes check_rep=False as the workaround. Correctness is
        # held by the sharded-vs-single bit-identity tests instead
        # (tests/test_sim_sharded.py).
        del check
        return _legacy_shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def make_mesh(devices: list[Any] | None = None) -> Mesh:
    return Mesh(jax.devices() if devices is None else devices, (AXIS,))


# -- rule-based partition specs ------------------------------------------------
#
# One `match_partition_rules`-style table (SNIPPETS [2]'s exemplar,
# first-match-wins regex over FIELD NAMES) assigns every SimState leaf
# its PartitionSpec — for the single-run layout AND the lane-batched
# sweep layout, which merely prepends an unsharded lane axis. Rules are
# name-based, not shape-based, so the memory-ladder rungs (packed u4
# watermarks at half width, the live bitmap at eighth width) inherit the
# owner-column sharding without touching this file: packing is along the
# column axis, so a packed column block is still an owner block.
#
# There is deliberately NO catch-all: a new SimState field must be
# classified here — in ONE place — or spec construction fails loudly
# naming it (the alternative, silent replication of a new (N, N) matrix,
# is a 20 GB-at-100k mistake). Donation follows the same single rule:
# every chunk builder below donates the whole state pytree (argnums 0),
# so a field added to the table is donated too —
# tests/test_partition_rules.py audits the lowered aliasing.

PARTITION_RULES: tuple[tuple[str, P], ...] = (
    # (N, n_local)-class knowledge matrices (packed or wide): columns
    # are owners — shard them. Rows stay unsharded so peer-row gathers
    # are shard-local (module docstring).
    (r"^(w|hb_known|last_change|imean|icount|live_view|dead_since)$",
     P(None, AXIS)),
    # Scalars and (N,) per-owner vectors: replicated.
    (r"^(tick|max_version|heartbeat|alive)$", P()),
)


def match_partition_rules(
    rules: tuple[tuple[str, P], ...], names: list[str]
) -> dict[str, P]:
    """First-match-wins regex table over field names -> PartitionSpec.
    Unmatched names raise, naming both the field and the table."""
    import re

    out: dict[str, P] = {}
    for name in names:
        for pattern, spec in rules:
            if re.fullmatch(pattern, name):
                out[name] = spec
                break
        else:
            raise ValueError(
                f"SimState field {name!r} matches no partition rule; add "
                "it to parallel.mesh.PARTITION_RULES (the single place "
                "fields are classified for sharding)"
            )
    return out


def _spec_pytree(sweep: bool) -> SimState:
    import dataclasses

    names = [f.name for f in dataclasses.fields(SimState)]
    specs = match_partition_rules(PARTITION_RULES, names)
    if sweep:
        # Lane-batched layout: a leading unsharded scenario axis on
        # every leaf; replicated leaves stay fully replicated.
        specs = {
            k: (s if s == P() else P(None, *s)) for k, s in specs.items()
        }
    return SimState(**specs)


def state_partition_spec() -> SimState:
    """PartitionSpec pytree matching SimState: matrices column-sharded,
    vectors/scalars replicated — assigned by PARTITION_RULES."""
    return _spec_pytree(sweep=False)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    spec = state_partition_spec()
    return jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec)
    )


def _check_vma(
    cfg: SimConfig, mesh: Mesh, topology: bool, sweep: bool = False
) -> bool:
    """Keep shard_map's varying-manual-axes checker ON except when a
    Pallas kernel engages for this config: the checker cannot see
    through pallas_call's internal block slicing (interpret mode trips
    "dynamic_slice requires varying manual axes to match"; the JAX
    error text itself prescribes check_vma=False). Pure-XLA sharded
    runs keep the static safety net (ADVICE r2); kernel configs rely on
    the stronger bit-identity tests (tests/test_sim_sharded.py,
    tests/test_pallas_fd.py, tests/test_pallas_sharded.py,
    tests/test_fused_kernel.py). ``sweep`` mirrors sim_step's gate for
    BOTH kernel families: a sweep chunk whose shape falls off the pairs
    domain runs pure XLA (the standalone FD kernel has no lane axis
    either), so it KEEPS the static safety net — resolving the FD term
    through fd_phase_engaged with the same sweep flag sim_step uses."""
    n_local = cfg.n_nodes // mesh.size
    axis = None if mesh.size == 1 else AXIS
    return not (
        fd_phase_engaged(
            cfg, axis, n_local, has_topology=topology, sweep=sweep
        )
        in ("fused", "kernel")
        or pallas_path_engaged(
            cfg, AXIS, has_topology=topology, n_local=n_local, sweep=sweep
        )
    )


def sharded_chunk_fn(cfg: SimConfig, mesh: Mesh, *, topology: bool = False):
    """shard_map'd fn advancing ``m`` gossip rounds:
    (state, key, m[, adjacency, degrees]) -> state.

    ``m`` is a TRACED round count (a replicated scalar operand), so one
    compile serves every chunk length — a partial tail chunk
    (``min(chunk, remaining)``) no longer retraces; the fori_loop lowers
    to the same while loop either way.

    With ``topology=True`` adjacency/degrees are extra replicated args —
    their entries are global row indices, and peer-row gathers/scatters
    stay shard-local because rows of the column-sharded matrices are
    unsharded.
    """
    from jax import lax

    spec = state_partition_spec()
    extra_specs = (P(None, None), P(None)) if topology else ()

    def body(state: SimState, key: jax.Array, m: jax.Array, *topo) -> SimState:
        adj, deg = topo if topology else (None, None)
        return lax.fori_loop(
            0,
            m,
            lambda _, st: sim_step(
                st, key, cfg, axis_name=AXIS, adjacency=adj, degrees=deg
            ),
            state,
        )

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(), P(), *extra_specs),
        out_specs=spec,
        check=_check_vma(cfg, mesh, topology),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_step_fn(cfg: SimConfig, mesh: Mesh, *, topology: bool = False):
    """shard_map'd single-round step: (state, key[, adj, deg]) -> state."""
    fn = sharded_chunk_fn(cfg, mesh, topology=topology)

    def step(state: SimState, key: jax.Array, *topo) -> SimState:
        return fn(state, key, 1, *topo)

    return step


def sharded_tracked_chunk_fn(
    cfg: SimConfig, mesh: Mesh, *, topology: bool = False
):
    """Like sharded_chunk_fn, but the chunk also returns the EXACT tick
    at which full convergence was first observed inside it (0 = not in
    this chunk) — the sharded half of the chunk-invariant
    rounds-to-convergence contract (Simulator.run_until_converged).
    The per-round check is one fused read of w plus a scalar pmin.
    ``m`` is traced, exactly as in sharded_chunk_fn."""
    from jax import lax

    import jax.numpy as jnp

    spec = state_partition_spec()
    extra_specs = (P(None, None), P(None)) if topology else ()

    def body(state: SimState, key: jax.Array, m: jax.Array, *topo):
        adj, deg = topo if topology else (None, None)

        def one(_, carry):
            st, first = carry
            # Pairs-kernel configs get the flag from the round's last
            # sub-exchange (pmin'd inside sim_step); others run the
            # same separate all_converged_flag check as before.
            st, conv = sim_step(
                st, key, cfg, axis_name=AXIS, adjacency=adj, degrees=deg,
                return_converged=True,
            )
            first = jnp.where((first == 0) & conv, st.tick, first)
            return st, first

        return lax.fori_loop(
            0, m, one, (state, jnp.zeros((), jnp.int32))
        )

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(), P(), *extra_specs),
        out_specs=(spec, P()),
        check=_check_vma(cfg, mesh, topology),
    )
    return jax.jit(fn, donate_argnums=(0,))


# -- sweep lanes (sim/sweep.py): a leading scenario axis ----------------------
#
# Sweep state is the SimState pytree with a leading lane axis: matrices
# are (S, N, n_local) — lanes and rows unsharded, owners column-sharded
# exactly as before — and vectors/scalars are (S, ...) replicated. The
# body vmaps the per-lane chunk over the lane axis INSIDE shard_map, so
# each collective (deficit psums, convergence pmins) becomes one batched
# (S,)-wide collective instead of S separate dispatches.


def sweep_state_partition_spec() -> SimState:
    """PartitionSpec pytree for lane-batched SimState: (S, N, n_local)
    matrices column-sharded on the owner axis, everything else
    replicated — the same PARTITION_RULES table with a lane axis
    prepended."""
    return _spec_pytree(sweep=True)


def shard_sweep_state(states: SimState, mesh: Mesh) -> SimState:
    spec = sweep_state_partition_spec()
    return jax.device_put(
        states, jax.tree.map(lambda s: NamedSharding(mesh, s), spec)
    )


def sharded_sweep_chunk_fn(cfg: SimConfig, mesh: Mesh, *, tracked: bool = False):
    """shard_map'd lane-batched chunk. Untracked:
    (states, keys, sweep, m) -> states. Tracked:
    (states, keys, sweep, first, m) -> (states, first), where ``first``
    is the per-lane (S,) int32 first-converged tick accumulator (0 = not
    yet) carried ON DEVICE across chunks — lanes retire without
    per-chunk host syncs. ``m`` is traced (one compile per cfg)."""
    from jax import lax

    import jax.numpy as jnp

    spec = sweep_state_partition_spec()
    # Sweeps engage the lane-lifted pairs kernels when the shape allows
    # (sim_step's sweep-aware gate), so the vma checker must stand down
    # for exactly those configs.
    check = _check_vma(cfg, mesh, False, sweep=True)

    if not tracked:

        def body(states, keys, sweep, m):
            def one_lane(state, key, sw):
                return lax.fori_loop(
                    0,
                    m,
                    lambda _, st: sim_step(
                        st, key, cfg, axis_name=AXIS, sweep=sw
                    ),
                    state,
                )

            return jax.vmap(one_lane)(states, keys, sweep)

        fn = _shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, P(), P(), P()),
            out_specs=spec,
            check=check,
        )
        return jax.jit(fn, donate_argnums=(0,))

    def body(states, keys, sweep, first, m):
        def one_lane(state, key, sw, f0):
            def one(_, carry):
                st, f = carry
                st, conv = sim_step(
                    st, key, cfg, axis_name=AXIS, sweep=sw,
                    return_converged=True,
                )
                f = jnp.where((f == 0) & conv, st.tick, f)
                return st, f

            return lax.fori_loop(0, m, one, (state, f0))

        return jax.vmap(one_lane)(states, keys, sweep, first)

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(), P(), P(), P()),
        out_specs=(spec, P()),
        check=check,
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_sweep_metrics_fn(mesh: Mesh):
    """Per-lane convergence metrics for lane-batched sharded state:
    states -> dict of (S,) arrays."""
    spec = sweep_state_partition_spec()

    @partial(_shard_map, mesh=mesh, in_specs=(spec,), out_specs=P())
    def metrics(states: SimState):
        def one(state: SimState):
            out = convergence_metrics(state, axis_name=AXIS)
            out["version_spread"] = version_spread(state, axis_name=AXIS)
            return out

        return jax.vmap(one)(states)

    return jax.jit(metrics)


def sharded_metrics_fn(mesh: Mesh):
    spec = state_partition_spec()

    @partial(_shard_map, mesh=mesh, in_specs=(spec,), out_specs=P())
    def metrics(state: SimState):
        out = convergence_metrics(state, axis_name=AXIS)
        out["version_spread"] = version_spread(state, axis_name=AXIS)
        # Per-node staleness percentiles: each shard maxes its local
        # owner columns, pmax makes the tensor global, and the sort +
        # static rank picks replicate — bit-identical to the unsharded
        # sample (the propagation bench's oracle gate).
        out.update(staleness_percentiles(state, axis_name=AXIS))
        return out

    return jax.jit(metrics)
