"""Device-mesh plumbing for the sharded simulator.

One 1-D mesh axis ``"owners"`` shards every (N, N) knowledge matrix along
its column (owner) axis. Rows stay unsharded, so peer-row gathers inside
the gossip step are shard-local; the step's only ICI traffic is the
(N,)-per-shard all_gather for global budget order and the convergence
psum/pmin (ops/gossip.py docstring).

The same ``sim_step`` runs unsharded (axis_name=None) or under shard_map
(axis_name="owners") with bit-identical results — tested in
tests/test_sim_sharded.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.gossip import (
    convergence_metrics,
    pallas_fd_engaged,
    pallas_path_engaged,
    sim_step,
    version_spread,
)
from ..sim.config import SimConfig
from ..sim.state import SimState

AXIS = "owners"

# jax.shard_map (with its ``check_vma`` flag) only exists on newer JAX;
# older releases ship jax.experimental.shard_map.shard_map with the same
# semantics under ``check_rep``. One wrapper keeps every call site below
# version-agnostic.
if hasattr(jax, "shard_map"):

    def _shard_map(body, *, mesh, in_specs, out_specs, check=True):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map(body, *, mesh, in_specs, out_specs, check=True):
        # The legacy replication checker false-positives on fori_loop
        # carries whose replication is refined inside the loop (e.g. the
        # tracked chunk's psum'd convergence flag) — its own error text
        # prescribes check_rep=False as the workaround. Correctness is
        # held by the sharded-vs-single bit-identity tests instead
        # (tests/test_sim_sharded.py).
        del check
        return _legacy_shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def make_mesh(devices: list[Any] | None = None) -> Mesh:
    return Mesh(jax.devices() if devices is None else devices, (AXIS,))


def state_partition_spec() -> SimState:
    """PartitionSpec pytree matching SimState: matrices column-sharded,
    vectors/scalars replicated."""
    mat = P(None, AXIS)
    rep = P()
    return SimState(
        tick=rep,
        max_version=rep,
        heartbeat=rep,
        alive=rep,
        w=mat,
        hb_known=mat,
        last_change=mat,
        imean=mat,
        icount=mat,
        live_view=mat,
        dead_since=mat,
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    spec = state_partition_spec()
    return jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec)
    )


def _check_vma(cfg: SimConfig, mesh: Mesh, topology: bool) -> bool:
    """Keep shard_map's varying-manual-axes checker ON except when a
    Pallas kernel engages for this config: the checker cannot see
    through pallas_call's internal block slicing (interpret mode trips
    "dynamic_slice requires varying manual axes to match"; the JAX
    error text itself prescribes check_vma=False). Pure-XLA sharded
    runs keep the static safety net (ADVICE r2); kernel configs rely on
    the stronger bit-identity tests (tests/test_sim_sharded.py,
    tests/test_pallas_fd.py, tests/test_pallas_sharded.py)."""
    n_local = cfg.n_nodes // mesh.size
    return not (
        pallas_fd_engaged(cfg, n_local)
        or pallas_path_engaged(
            cfg, AXIS, has_topology=topology, n_local=n_local
        )
    )


def sharded_chunk_fn(
    cfg: SimConfig, mesh: Mesh, rounds: int = 1, *, topology: bool = False
):
    """shard_map'd fn advancing ``rounds`` gossip rounds:
    (state, key[, adjacency, degrees]) -> state.

    With ``topology=True`` adjacency/degrees are extra replicated args —
    their entries are global row indices, and peer-row gathers/scatters
    stay shard-local because rows of the column-sharded matrices are
    unsharded.
    """
    from jax import lax

    spec = state_partition_spec()
    extra_specs = (P(None, None), P(None)) if topology else ()

    def body(state: SimState, key: jax.Array, *topo) -> SimState:
        adj, deg = topo if topology else (None, None)
        return lax.fori_loop(
            0,
            rounds,
            lambda _, st: sim_step(
                st, key, cfg, axis_name=AXIS, adjacency=adj, degrees=deg
            ),
            state,
            unroll=False,
        )

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(), *extra_specs),
        out_specs=spec,
        check=_check_vma(cfg, mesh, topology),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_step_fn(cfg: SimConfig, mesh: Mesh, *, topology: bool = False):
    """shard_map'd single-round step: (state, key[, adj, deg]) -> state."""
    return sharded_chunk_fn(cfg, mesh, 1, topology=topology)


def sharded_tracked_chunk_fn(
    cfg: SimConfig, mesh: Mesh, rounds: int = 1, *, topology: bool = False
):
    """Like sharded_chunk_fn, but the chunk also returns the EXACT tick
    at which full convergence was first observed inside it (0 = not in
    this chunk) — the sharded half of the chunk-invariant
    rounds-to-convergence contract (Simulator.run_until_converged).
    The per-round check is one fused read of w plus a scalar pmin."""
    from jax import lax

    import jax.numpy as jnp

    spec = state_partition_spec()
    extra_specs = (P(None, None), P(None)) if topology else ()

    def body(state: SimState, key: jax.Array, *topo):
        adj, deg = topo if topology else (None, None)

        def one(_, carry):
            st, first = carry
            # Pairs-kernel configs get the flag from the round's last
            # sub-exchange (pmin'd inside sim_step); others run the
            # same separate all_converged_flag check as before.
            st, conv = sim_step(
                st, key, cfg, axis_name=AXIS, adjacency=adj, degrees=deg,
                return_converged=True,
            )
            first = jnp.where((first == 0) & conv, st.tick, first)
            return st, first

        return lax.fori_loop(
            0, rounds, one, (state, jnp.zeros((), jnp.int32)), unroll=False
        )

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, P(), *extra_specs),
        out_specs=(spec, P()),
        check=_check_vma(cfg, mesh, topology),
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_metrics_fn(mesh: Mesh):
    spec = state_partition_spec()

    @partial(_shard_map, mesh=mesh, in_specs=(spec,), out_specs=P())
    def metrics(state: SimState):
        out = convergence_metrics(state, axis_name=AXIS)
        out["version_spread"] = version_spread(state, axis_name=AXIS)
        return out

    return jax.jit(metrics)
