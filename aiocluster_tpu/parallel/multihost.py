"""Multi-host (multi-process) simulation meshes.

The asyncio backend scales across hosts the way the reference does — one
process per node over TCP (DCN). The sim backend scales differently: one
process per TPU host, all of them executing the SAME jit'd gossip step
over a global mesh, with XLA moving cross-shard traffic over ICI/DCN
collectives. This module is the small amount of glue that turns the
single-process mesh code in parallel/mesh.py into a multi-host run; the
kernels themselves are unchanged (they only ever see a named axis).

Usage, on every participating process:

    from aiocluster_tpu.parallel import multihost
    multihost.initialize("host0:1234", num_processes=2, process_id=rank)
    sim = Simulator(cfg, mesh=multihost.global_mesh(), seed=0)
    sim.run_until_converged()        # SPMD: every process steps together

Verified end-to-end by tests/test_multihost.py, which launches two real
processes over a localhost coordinator and checks the trajectory is
bit-identical to a single-process run — and MEASURED by
benchmarks/multihost_bench.py (``make multihost-smoke``, part of
``make check``), which stamps a parity-asserted 2-process rounds/s
figure into every BENCH/MULTICHIP record. Capacity planning treats the
host spread as a first-class dimension: ``sim.memory.plan(...,
hosts=)`` and ``fits_verdict(..., hosts=)`` key their models and
measured-boundary evidence per (rung, shards, hosts).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from .mesh import AXIS


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Join the distributed runtime. Call once, before any device use.

    On CPU platforms this also selects jaxlib's gloo cross-process
    collectives (when the installed jax exposes the knob and the caller
    hasn't pinned one): without it, XLA:CPU rejects every multiprocess
    computation outright ("Multiprocess computations aren't implemented
    on the CPU backend") — which silently reduced the 2-process CPU
    path to a smoke claim. TPU jobs are unaffected (collectives ride
    ICI/DCN through the plugin)."""
    values = getattr(jax.config, "values", {})
    platforms = str(values.get("jax_platforms") or "")
    if (
        "jax_cpu_collectives_implementation" in values
        and values.get("jax_cpu_collectives_implementation")
        in (None, "", "none")
        # Unset platforms may still resolve to CPU (the default on a
        # CPU-only host — exactly the case that used to break), so only
        # an EXPLICIT non-cpu pin skips the knob; the option configures
        # the CPU client alone, so accelerator jobs are unaffected by
        # setting it.
        and (platforms == "" or "cpu" in platforms.split(","))
    ):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh() -> Mesh:
    """One-axis mesh over every device in the job (all processes)."""
    return Mesh(jax.devices(), (AXIS,))


def is_primary() -> bool:
    """True on the process that should do host-side reporting."""
    return jax.process_index() == 0


def process_count() -> int:
    """How many processes (hosts) the job spans — the ``hosts=``
    argument capacity planning wants (sim.memory.plan/fits_verdict)."""
    return jax.process_count()
