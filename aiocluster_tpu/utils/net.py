"""Loopback port allocation shared by benchmarks and test harnesses.

All sockets are held open until every port is picked so the kernel
cannot hand the same ephemeral port out twice within one call — the
usual bind-then-close race when ports are allocated one at a time.
"""

from __future__ import annotations

import socket


def free_ports(n: int) -> list[int]:
    """``n`` distinct free loopback TCP ports."""
    socks: list[socket.socket] = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
