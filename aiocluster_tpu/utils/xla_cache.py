"""Persistent XLA compilation cache wiring, in one place.

The library/bench pays ~30 s of XLA compile before the first 10k-node
chunk runs (BENCH_r05); the persistent compilation cache makes every
rerun of the same program skip straight to execution. Until this
module, only ad-hoc scripts under benchmarks/records/ set it up, each
with its own copy of the three config lines — now bench.py, the sim
CLI and those scripts all call :func:`enable_persistent_cache`.

Resolution order for the cache directory:

1. the explicit ``cache_dir`` argument (the records scripts pass their
   ``NORTHSTAR_CACHE`` location through it);
2. the ``AIOCLUSTER_XLA_CACHE`` environment variable — set it to ``off``
   (or ``0`` / ``none``) to disable the cache entirely;
3. ``<repo>/build/xla_cache`` (the repo's build dir, next to the other
   generated artifacts), falling back to a per-user temp dir when the
   package is installed somewhere read-only.

Failures are non-fatal by design: a bench or sim run must never die
because a cache directory could not be created.
"""

from __future__ import annotations

import os
import tempfile

ENV_VAR = "AIOCLUSTER_XLA_CACHE"
_DISABLED = ("off", "0", "none", "disabled")


def default_cache_dir() -> str | None:
    """The directory :func:`enable_persistent_cache` would use, or None
    when the env var disables caching."""
    env = os.environ.get(ENV_VAR)
    if env is not None:
        if env.strip().lower() in _DISABLED:
            return None
        return env
    # aiocluster_tpu/utils/xla_cache.py -> <repo>/build/xla_cache
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, "build", "xla_cache")


def enable_persistent_cache(
    cache_dir: str | None = None,
    *,
    min_compile_seconds: float = 1.0,
    log=None,
) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (resolved per the module docstring). Returns the directory actually
    enabled, or None when caching is disabled/unavailable. Idempotent;
    safe to call before or after backend initialization."""

    def say(msg: str) -> None:
        if log is not None:
            log(msg)

    path = cache_dir if cache_dir is not None else default_cache_dir()
    if path is None:
        say("persistent XLA cache disabled via " + ENV_VAR)
        return None
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".writable")
        with open(probe, "w"):
            pass
        os.remove(probe)
    except OSError:
        fallback = os.path.join(
            tempfile.gettempdir(), f"aiocluster_xla_cache_{os.getuid()}"
        )
        say(f"cache dir {path!r} unwritable; falling back to {fallback!r}")
        try:
            os.makedirs(fallback, exist_ok=True)
            path = fallback
        except OSError as exc:
            say(f"persistent XLA cache unavailable: {exc!r}")
            return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_seconds
        )
    except Exception as exc:  # old jax without the knob, etc.
        say(f"persistent XLA cache not enabled: {exc!r}")
        return None
    say(f"persistent XLA cache: {path}")
    return path


def entry_count(cache_dir: str | None) -> int:
    """Number of cache entries currently on disk (0 for a missing or
    disabled cache) — the cheap hit/miss probe bench.py records."""
    if not cache_dir:
        return 0
    try:
        return sum(
            1
            for name in os.listdir(cache_dir)
            if not name.startswith(".") and not name.endswith(".tmp")
        )
    except OSError:
        return 0
