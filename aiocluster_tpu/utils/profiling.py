"""Compatibility shim: profiling moved to ``aiocluster_tpu.obs.profiling``
when the unified telemetry layer landed. Import from ``obs`` directly in
new code; this module keeps old import paths working.
"""

from __future__ import annotations

from ..obs.profiling import SectionTimer, device_trace

__all__ = ("SectionTimer", "device_trace")
