"""Stream framing for the socket transport.

Frames are a 4-byte big-endian unsigned length followed by the payload —
wire-compatible with the reference transport (reference utils.py:9-20,
server.py:502-521) so mixed clusters interoperate.
"""

from __future__ import annotations

HEADER_SIZE = 4
_MAX_FRAME = 0xFFFFFFFF


def frame_header(n: int) -> bytes:
    """The 4-byte big-endian length prefix alone — the scatter-gather
    write path sends ``[header, *payload_parts]`` without ever
    concatenating the payload."""
    if n > _MAX_FRAME:
        raise ValueError(f"payload too large to frame: {n} bytes")
    return n.to_bytes(HEADER_SIZE, "big")


def frame(payload: bytes) -> bytes:
    """Prefix ``payload`` with its 4-byte big-endian length."""
    return frame_header(len(payload)) + payload


def read_frame_size(header: bytes) -> int:
    """Decode the length prefix from the first 4 bytes of ``header``."""
    if len(header) < HEADER_SIZE:
        raise ValueError(f"short frame header: {len(header)} bytes")
    return int.from_bytes(header[:HEADER_SIZE], "big")


def unframe(data: bytes) -> bytes:
    """Strip and validate the length prefix of a complete in-memory frame."""
    size = read_frame_size(data)
    body = data[HEADER_SIZE : HEADER_SIZE + size]
    if len(body) != size:
        raise ValueError(f"truncated frame: expected {size}, got {len(body)}")
    return body
