"""Shared utilities: clock, logging, wire framing."""

from .clock import utc_now
from .framing import frame, read_frame_size, unframe
from .logging import logger, node_logger

__all__ = (
    "frame",
    "logger",
    "node_logger",
    "read_frame_size",
    "unframe",
    "utc_now",
)
