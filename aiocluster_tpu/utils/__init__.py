"""Shared utilities: clock, logging, wire framing, profiling."""

from .clock import utc_now
from .framing import frame, read_frame_size, unframe
from .logging import logger, node_logger
from .profiling import SectionTimer, device_trace

__all__ = (
    "SectionTimer",
    "device_trace",
    "frame",
    "logger",
    "node_logger",
    "read_frame_size",
    "unframe",
    "utc_now",
)
