"""Shared utilities: clock, logging, wire framing, profiling."""

from .clock import (
    CONTEXT_CLOCK,
    SYSTEM_CLOCK,
    Clock,
    ManualClock,
    SystemClock,
    current_clock,
    resolve_clock,
    utc_now,
)
from .framing import frame, read_frame_size, unframe
from .logging import logger, node_logger
from .profiling import SectionTimer, device_trace

__all__ = (
    "CONTEXT_CLOCK",
    "Clock",
    "ManualClock",
    "SYSTEM_CLOCK",
    "SectionTimer",
    "SystemClock",
    "current_clock",
    "device_trace",
    "frame",
    "logger",
    "node_logger",
    "read_frame_size",
    "resolve_clock",
    "unframe",
    "utc_now",
)
