"""Asyncio compatibility helpers.

The package runs on Python 3.10+, but ``asyncio.timeout`` only landed in
3.11. ``timeout_after`` is the portable spelling used by the tests and
benchmark harnesses: on 3.11+ it IS ``asyncio.timeout``; on 3.10 a small
shim reproduces the same contract (cancel the enclosing task at the
deadline, surface it as the builtin ``TimeoutError``).
"""

from __future__ import annotations

import asyncio
from contextlib import asynccontextmanager

if hasattr(asyncio, "timeout"):
    timeout_after = asyncio.timeout
else:

    @asynccontextmanager
    async def timeout_after(delay: float):
        # Shim limitation vs the real asyncio.timeout: an EXTERNAL cancel
        # racing the deadline timer cannot be told apart from the timeout
        # on 3.10 (no Task.uncancel), so it surfaces as TimeoutError.
        task = asyncio.current_task()
        assert task is not None
        loop = asyncio.get_running_loop()
        timed_out = False

        def _fire() -> None:
            nonlocal timed_out
            timed_out = True
            task.cancel()

        handle = loop.call_later(delay, _fire)
        try:
            yield
        except asyncio.CancelledError:
            if timed_out:
                raise TimeoutError from None
            raise
        else:
            if timed_out:
                # The timer fired as the body completed: absorb the
                # pending cancellation (it would otherwise surface at the
                # caller's next await) and report the elapsed deadline.
                try:
                    await asyncio.sleep(0)
                except asyncio.CancelledError:  # noqa: ACT013 -- deadline cancel converts to TimeoutError
                    # This cancellation is our own timer's (timed_out is
                    # True); converting it to TimeoutError below IS the
                    # asyncio.timeout contract being shimmed.
                    pass
                raise TimeoutError from None
        finally:
            handle.cancel()
