"""Shared compile-and-cache loader for the repo's native C++ modules.

One cache policy for every native component (wire codec, host-sim
kernel): g++-compile on first use into
``$XDG_CACHE_HOME/aiocluster_tpu`` (``~/.cache`` default), keyed by a
sha256 of the SOURCE + COMPILE FLAGS + HOST ISA. The ISA term matters
when ``-march=native`` is among the flags: a shared or network cache
directory must never hand an AVX-512 binary to a host without it
(SIGILL mid-run), so the host's cpuinfo flags line participates in the
key. Atomic tmp+rename keeps concurrent builders race-free.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path


def _host_isa_tag() -> str:
    """A short digest of this host's ISA surface (uname machine + the
    cpuinfo feature flags). Only affects the cache key."""
    bits = os.uname().machine
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    bits += line
                    break
    except OSError:
        pass
    return hashlib.sha256(bits.encode()).hexdigest()[:8]


def build_and_load(
    src: Path,
    flags: tuple[str, ...] = ("-O2",),
    timeout: float = 180.0,
) -> ctypes.CDLL | None:
    """Compile ``src`` with g++ (shared lib) and load it; None on any
    failure — callers degrade to their pure-Python/XLA fallbacks."""
    source = src.read_bytes()
    key = hashlib.sha256(
        source + " ".join(flags).encode() + _host_isa_tag().encode()
    ).hexdigest()[:16]
    cache_dir = Path(
        os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
    ) / "aiocluster_tpu"
    so_path = cache_dir / f"{src.stem}-{key}.so"
    if not so_path.exists():
        cache_dir.mkdir(parents=True, exist_ok=True)
        with tempfile.NamedTemporaryFile(
            dir=cache_dir, suffix=".so", delete=False
        ) as tmp:
            tmp_path = Path(tmp.name)
        try:
            subprocess.run(
                ["g++", *flags, "-shared", "-fPIC", "-std=c++17",
                 str(src), "-o", str(tmp_path)],
                check=True, capture_output=True, timeout=timeout,
            )
            tmp_path.replace(so_path)
        except Exception:
            tmp_path.unlink(missing_ok=True)
            return None
    try:
        return ctypes.CDLL(str(so_path))
    except OSError:
        return None
