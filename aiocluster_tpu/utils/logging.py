"""Package logging (parity: reference log.py:8, server.py:92-93)."""

from __future__ import annotations

import logging

logger = logging.getLogger("aiocluster_tpu")


class _NodeLoggerAdapter(logging.LoggerAdapter):
    """Adapter that merges per-call ``extra`` with the node tag (the 3.13
    ``merge_extra=True`` behavior, reimplemented for 3.12)."""

    def process(self, msg, kwargs):
        kwargs["extra"] = {**(self.extra or {}), **(kwargs.get("extra") or {})}
        return msg, kwargs


def node_logger(node_name: str) -> logging.LoggerAdapter:
    """Per-node adapter tagging records with the node's long name."""
    return _NodeLoggerAdapter(logger, extra={"node": node_name})
