"""Package logging (parity: reference log.py:8, server.py:92-93)."""

from __future__ import annotations

import logging

logger = logging.getLogger("aiocluster_tpu")


def node_logger(node_name: str) -> logging.LoggerAdapter:
    """Per-node adapter tagging records with the node's long name."""
    return logging.LoggerAdapter(logger, extra={"node": node_name}, merge_extra=True)
