"""Time helpers.

Every time-dependent method in the core takes an optional ``ts`` so tests can
time-travel instead of sleeping (parity with reference utils.py:5-6 and the
clock-injection seam described in SURVEY.md §4).
"""

from __future__ import annotations

from datetime import datetime, timezone

# ``datetime.UTC`` only exists on Python 3.11+; this alias keeps the whole
# package (and its tests) importable on 3.10, where it equals timezone.utc.
UTC = timezone.utc


def utc_now() -> datetime:
    """Current wall-clock time as an aware UTC datetime."""
    return datetime.now(UTC)
