"""The one clock seam (docs/virtual-time.md).

Every time-dependent method in the core takes an optional ``ts`` so tests
can time-travel instead of sleeping (parity with reference utils.py:5-6 and
the clock-injection seam described in SURVEY.md §4). This module widens
that seam into a single :class:`Clock` protocol shared by every runtime
clock consumer — phi windows, breaker backoff, adaptive timeouts, TTLs,
fault windows, pool idle eviction, flight-recorder and trace timestamps —
so that installing ONE virtual clock (``aiocluster_tpu.vtime``) compresses
all of them together.

Resolution order, per read:

1. an explicitly injected ``Clock`` (construction parameter), else
2. the running event loop's ``aiocluster_clock`` attribute (set by
   ``vtime.VirtualClockLoop``), else
3. :data:`SYSTEM_CLOCK` (real ``time.monotonic``/``time.time``).

Components that default their clock hold :data:`CONTEXT_CLOCK`, which
re-resolves on EVERY read — so an object built before the loop exists
(the common ``Cluster(config)``-then-``await start()`` shape) still picks
up the virtual clock once it runs under one, and the default real-clock
path stays byte-identical to the pre-seam code (same ``time.monotonic``
/ ``time.time`` reads, one dispatch away).

``sleep`` is the sanctioned suspension primitive for runtime/serve/faults
code (analyzer rule ACT044): it is loop-clock-driven, so it compresses
under virtual time with no code change at the call sites.
"""

from __future__ import annotations

import asyncio
import time
from datetime import datetime, timezone
from typing import Protocol, runtime_checkable

# ``datetime.UTC`` only exists on Python 3.11+; this alias keeps the whole
# package (and its tests) importable on 3.10, where it equals timezone.utc.
UTC = timezone.utc


@runtime_checkable
class Clock(Protocol):
    """Three views of one instant: a monotonic float for durations and
    deadlines, a wall float (epoch seconds) for trace records, and an
    aware UTC datetime for the core's ``ts=`` seams. Implementations
    must keep the three consistent (``now() == fromtimestamp(wall())``)
    so mixed consumers agree on ordering."""

    def monotonic(self) -> float:
        """Seconds on the monotonic axis (durations, deadlines)."""
        ...

    def wall(self) -> float:
        """Seconds since the epoch (trace ``ts`` fields)."""
        ...

    def now(self) -> datetime:
        """The wall instant as an aware UTC datetime."""
        ...


class SystemClock:
    """The real clocks, undecorated."""

    __slots__ = ()

    def monotonic(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()

    def now(self) -> datetime:
        return datetime.now(UTC)


SYSTEM_CLOCK = SystemClock()


class ManualClock:
    """The one hand-cranked test clock, replacing the ad-hoc
    ``lambda: now["t"]`` shims the breaker/pool/fault tests used to
    carry. Starts at ``start`` and only moves when told to; ``wall()``
    tracks ``monotonic()`` offset by ``wall_base`` so datetime-facing
    consumers stay consistent with float-facing ones."""

    __slots__ = ("_t", "wall_base")

    def __init__(self, start: float = 0.0, *, wall_base: float = 0.0) -> None:
        self._t = float(start)
        self.wall_base = float(wall_base)

    def monotonic(self) -> float:
        return self._t

    def wall(self) -> float:
        return self.wall_base + self._t

    def now(self) -> datetime:
        return datetime.fromtimestamp(self.wall(), UTC)

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` seconds; returns the new time."""
        if dt < 0:
            raise ValueError(f"clocks do not run backwards: advance({dt})")
        self._t += dt
        return self._t

    def set_time(self, t: float) -> None:
        """Jump to absolute monotonic time ``t`` (forward only)."""
        if t < self._t:
            raise ValueError(
                f"clocks do not run backwards: set_time({t}) < {self._t}"
            )
        self._t = float(t)


def current_clock() -> Clock:
    """The ambient clock: the running loop's ``aiocluster_clock`` if a
    loop is running and carries one (``vtime.VirtualClockLoop`` does),
    else the system clock. Callable from any thread; threads without a
    running loop read real time."""
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return SYSTEM_CLOCK
    return getattr(loop, "aiocluster_clock", None) or SYSTEM_CLOCK


class _ContextClock:
    """Defers resolution to the ambient clock on EVERY read, so one
    object built before any loop exists follows whichever loop it later
    runs under. This is what ``resolve_clock(None)`` hands out."""

    __slots__ = ()

    def monotonic(self) -> float:
        return current_clock().monotonic()

    def wall(self) -> float:
        return current_clock().wall()

    def now(self) -> datetime:
        return current_clock().now()


CONTEXT_CLOCK = _ContextClock()


def resolve_clock(clock: Clock | None) -> Clock:
    """The constructor-side half of the seam: an injected clock wins;
    ``None`` means "the ambient clock, re-resolved per read"."""
    return clock if clock is not None else CONTEXT_CLOCK


def utc_now() -> datetime:
    """Current wall-clock time as an aware UTC datetime — through the
    clock seam, so core TTLs/phi windows/GC grace periods compress
    under a virtual loop with no call-site changes."""
    return current_clock().now()


async def sleep(delay: float, result: object = None) -> object:
    """The sanctioned suspension primitive for runtime/serve/faults code
    (ACT044): identical to ``asyncio.sleep`` — and loop-clock-driven, so
    it compresses under ``vtime`` — but greppable as the seam."""
    return await asyncio.sleep(delay, result)
