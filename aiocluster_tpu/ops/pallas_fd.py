"""Standalone streaming Pallas kernel for the phi-accrual FD phase.

Since the fused round kernel landed, FD-enabled configs served by the
PAIRS pull variant run the whole FD phase inside the round's last
sub-exchange (ops/pallas_pull.py `fd=` epilogue — zero extra reads of
the heartbeat matrices); this kernel is the STANDALONE FALLBACK for
every other kernel-wanting path — the single-pass m8 variant,
choice/permutation pairing (the pull stays on XLA but the FD phase
still kernels), and ``use_pallas_fd=True`` forced without the pull
kernel. ``ops/gossip.py::fd_phase_engaged`` is the single dispatch
resolution ("fused" / "kernel" / "xla" / "off").

The XLA path of ops/gossip.py's failure-detection block is a chain of
elementwise ops over five (N, N) matrices (hb, round-start hb,
last_change, imean, icount) producing four (last_change', imean',
icount', live'). XLA fuses the chain but, measured on a v5e at
N=10,240, still spends ~5.4 ms against a ~2.3 ms analytic-traffic
floor. This kernel streams row blocks through VMEM once — every matrix
read exactly once, every output written exactly once, all math on
registers in between.

Bit-compatibility: the arithmetic is the same f32 ops in the same order
as the XLA block in gossip.sim_step (loads widen int16->int32 /
bfloat16->float32 exactly; stores round exactly once, at the end, as
the XLA path does), so flipping the kernel on never changes a
trajectory — asserted in tests/test_pallas_fd.py. Gated like the pull
kernel (ops/gossip.py::pallas_fd_engaged): real TPU, failure detector
on, dead-node lifecycle off (the lifecycle branch rewrites w/hb and is
XLA-only). Unlike the pull kernel the math is purely per-element, so
it also runs under shard_map: each shard streams its (N, n_local)
column block with its global owner offset (bit-identical to the
single-device run, tests/test_pallas_fd.py).

Reference anchor: this is failure_detector.py:43-106 (phi +
update_node_liveness over every observer) collapsed into one pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_pull import fd_update, largest_fitting_block


def _fd_kernel(
    meta_ref,  # scalar prefetch: (2,) int32 — [tick, owner offset]
    hb_ref,  # (block, n) heartbeat_dtype — post-exchange hb knowledge
    hb0_ref,  # (block, n) heartbeat_dtype — round-start hb knowledge
    hbv_ref,  # (1, n) int32 — owner heartbeats (diagonal refresh of hb0)
    lc_ref,  # (block, n) heartbeat_dtype — tick of last observed increase
    im_ref,  # (block, n) fd_dtype — running interval mean
    ic_ref,  # (block, n) int16 — interval sample count
    lc_out,
    im_out,
    ic_out,
    live_out,  # (block, n) bool
    *,
    block: int,
    max_interval: float,
    window: int,
    prior_weight: float,
    prior_mean: float,
    phi_threshold: float,
):
    tick = meta_ref[0]
    shape = hb_ref.shape
    rows = pl.program_id(0) * block + lax.broadcasted_iota(jnp.int32, shape, 0)
    # Column c of this (column-sharded) block is GLOBAL owner
    # offset + c; single-device callers pass offset 0.
    cols = meta_ref[1] + lax.broadcasted_iota(jnp.int32, shape, 1)
    diag = rows == cols
    hb = hb_ref[:].astype(jnp.int32)
    # Round-start knowledge carries the round's owner-diagonal refresh
    # (hb0[i, i] = heartbeat[i]); applied here from the broadcast row so
    # the caller never materializes a diagonal-select pass. Idempotent
    # when the caller already applied it (the XLA pull path does).
    hb0 = jnp.where(diag, hbv_ref[:], hb0_ref[:].astype(jnp.int32))
    # The arithmetic lives in pallas_pull.fd_update — one source shared
    # with the fused round kernel's FD epilogue, so the two kernels and
    # the XLA block can never drift (cross-multiplied phi test included:
    # two divides per element saved; the FD pass is VPU-bound).
    lc2, imean, icount, live = fd_update(
        tick,
        hb,
        hb0,
        lc_ref[:].astype(jnp.int32),
        im_ref[:].astype(jnp.float32),
        ic_ref[:].astype(jnp.int32),
        max_interval=max_interval,
        window=window,
        prior_weight=prior_weight,
        prior_mean=prior_mean,
        phi=phi_threshold,
    )
    # Self-belief diagonal (global row == global owner column — the
    # offset above makes this exact on every shard).
    live = live | diag
    # Death wipes the window (re-earn liveness with fresh samples).
    lc_out[:] = lc2.astype(lc_out.dtype)
    im_out[:] = jnp.where(live, imean, 0.0).astype(im_out.dtype)
    ic_out[:] = jnp.where(live, icount, 0).astype(ic_out.dtype)
    live_out[:] = live


def _per_row_bytes(n_cols: int, hb_size: int, fd_size: int) -> int:
    """Double-buffered VMEM bytes per block row: inputs hb + hb0 +
    last_change (heartbeat dtype) and imean (fd dtype) and icount
    (int16); outputs last_change + imean + icount and the bool live
    output — whose VMEM block Mosaic holds as s32 (4 B/elem; observed in
    the compiled custom-call layout), even though its HBM form is 1 B."""
    inputs = 3 * hb_size + fd_size + 2
    outputs = hb_size + fd_size + 2 + 4
    return 2 * (inputs + outputs) * n_cols


def _fixed_bytes(n_cols: int) -> int:
    """Block-size-independent VMEM: the (1, n_cols) int32 hbv broadcast
    row, double-buffered and sublane-padded to 8 rows (ADVICE r2 — at
    the boundary block size the budget must include it so the search
    stays strictly conservative). The scalar-prefetch meta lives in
    SMEM, not VMEM."""
    return 2 * 8 * 4 * n_cols


def _pick_block(
    n_rows: int, n_cols: int, hb_size: int, fd_size: int
) -> int | None:
    """Largest multiple-of-8 divisor of n_rows whose double-buffered
    block set fits the VMEM budget at the given element sizes (required
    — the compact int16/bfloat16 and default int32/float32 profiles
    differ ~1.9x in footprint, so there is no safe default). n_cols may
    be a column shard's width under shard_map."""
    return largest_fitting_block(
        n_rows,
        _per_row_bytes(n_cols, hb_size, fd_size),
        fixed_bytes=_fixed_bytes(n_cols),
    )


def supported(n_rows: int, n_cols: int, hb_size: int, fd_size: int) -> bool:
    """Whether the streaming FD kernel can run this shape and dtype mix
    (callers fall back to the XLA block when not). Lane-aligned columns
    keep the padded memref whole-tile (as in pallas_pull.supported)."""
    return (
        n_cols % 128 == 0
        and _pick_block(n_rows, n_cols, hb_size, fd_size) is not None
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_interval",
        "window",
        "prior_weight",
        "prior_mean",
        "phi_threshold",
        "interpret",
    ),
)
def fused_fd(
    tick: jax.Array,
    hb: jax.Array,
    hb0: jax.Array,
    hbv: jax.Array,
    last_change: jax.Array,
    imean: jax.Array,
    icount: jax.Array,
    *,
    max_interval: float,
    window: int,
    prior_weight: float,
    prior_mean: float,
    phi_threshold: float,
    interpret: bool = False,
    owner_offset: jax.Array | None = None,
):
    """One streaming FD pass. Returns (last_change', imean', icount',
    live'). Inputs are the post-exchange and round-start heartbeat
    matrices, the owner-heartbeat vector for the LOCAL columns (hb0's
    diagonal refresh — see _fd_kernel), and the FD bookkeeping;
    constants from SimConfig.

    Works under shard_map: matrices are (N, n_local) column shards, and
    ``owner_offset`` (default 0) is the global owner index of local
    column 0 — the FD math is purely per-element, so each shard runs the
    identical kernel on its block (unlike the pull kernel, whose global
    budget total would need a cross-shard psum between two passes —
    that one stays single-device)."""
    n_rows, n_cols = hb.shape
    block = _pick_block(
        n_rows, n_cols, hb.dtype.itemsize, imean.dtype.itemsize
    )
    if block is None or n_cols % 128 != 0:
        raise ValueError(f"no suitable row block for shape {hb.shape}")
    spec = pl.BlockSpec((block, n_cols), lambda i, *_: (i, 0))
    vec_spec = pl.BlockSpec((1, n_cols), lambda i, *_: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_rows // block,),
        in_specs=[spec, spec, vec_spec, spec, spec, spec],
        out_specs=[spec] * 4,
    )
    kernel = functools.partial(
        _fd_kernel,
        block=block,
        max_interval=float(max_interval),
        window=int(window),
        prior_weight=float(prior_weight),
        prior_mean=float(prior_mean),
        phi_threshold=float(phi_threshold),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(last_change.shape, last_change.dtype),
            jax.ShapeDtypeStruct(imean.shape, imean.dtype),
            jax.ShapeDtypeStruct(icount.shape, icount.dtype),
            jax.ShapeDtypeStruct(hb.shape, jnp.bool_),
        ],
        # In-place bookkeeping: each block of last_change/imean/icount is
        # read exactly once before its updated block is written, so the
        # outputs can alias the inputs. Without this, every round pays
        # three (N, N) copies re-homing the results into the fori_loop
        # carry buffers (~2 ms each at 10k on a v5e — the dominant FD
        # cost, found via the compiled HLO's copy instructions). Indices
        # are over the flattened operand list: 0 = the scalar-prefetch
        # meta, then hb, hb0, hbv, last_change (4), imean (5), icount (6).
        input_output_aliases={4: 0, 5: 1, 6: 2},
        interpret=interpret,
    )(
        jnp.stack(
            [
                tick.astype(jnp.int32),
                jnp.asarray(0, jnp.int32)
                if owner_offset is None
                else owner_offset.astype(jnp.int32),
            ]
        ),
        hb,
        hb0,
        hbv.astype(jnp.int32)[None, :],
        last_change,
        imean,
        icount,
    )
