"""The batched gossip round: one jit'd tensor step for the whole cluster.

This is the TPU recast of the object model's hot loop
(runtime/cluster.py::_gossip_round driving engine.py's 3-way handshake,
reference server.py:378-495): all N nodes execute one ScuttleButt round in
a single XLA computation.

Correspondence (object model → tensor op), with the default config:

- peer selection (runtime/peers.py)        → a random perfect matching
  per sub-exchange (pairing="matching", default: one bidirectional
  handshake per pair, a single involution pull — drawn from the
  8-row-group family on the fused kernel's domain), a random
  permutation (pairing="permutation": initiate to p[i], respond via the
  inverse permutation, still gather-only), or categorical/adjacency
  draws + responder scatter-max (pairing="choice", the reference's
  independent-sampling semantics)
- digest heartbeat observation             → row gather + max on hb_known
- MTU-bounded delta (core packer)          → budgeted watermark advance:
  deficits d[i,j] = max(0, w[peer,j] - w[i,j]); either proportional
  scaling with dithered rounding (budget_policy="proportional", default)
  or exact greedy in owner order via exclusive cumsum ("greedy", the
  reference packer's observable behavior)
- bidirectional SynAck/Ack application     → two budgeted pulls per pair
  (the CRDT join: versions only grow)
- phi-accrual liveness (core/failure.py)   → vectorized tick-time phi over
  the (N, N) heartbeat-knowledge matrix

Sharding contract: every (N, N) array is sharded on the OWNER axis
(columns). Peer-row gathers are shard-local; the only collectives are
(N,)-sized per-row reductions — deficit totals (psum; also between the
two passes of the sharded Pallas pull), greedy budget block offsets
(all_gather) and convergence reductions — they ride ICI, everything
else is local HBM traffic. Pass ``axis_name`` when calling under
shard_map; ``None`` runs the identical math on one device.
"""

from __future__ import annotations

import collections
import contextlib
import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax, random

from ..sim.config import SimConfig
from ..sim.packed import U4_MAX, imean_f32, is_packed_w, pack_bits, watermarks_i32
from ..sim.state import SimState, state_n_local

NEG_INF = -1e30

# Backend names that mean "a real TPU chip" (axon is the tunnel's PJRT
# plugin name). Single-sourced: the pallas gate, interpret-mode choice,
# and bench.py's CPU-fallback logic all consult this.
ACCEL_BACKENDS = ("tpu", "axon")


def on_accelerator() -> bool:
    return jax.default_backend() in ACCEL_BACKENDS


def _local_owner_ids(n_local: int, axis_name: str | None) -> jax.Array:
    """Global owner indices of this shard's columns."""
    base = 0 if axis_name is None else lax.axis_index(axis_name) * n_local
    return base + jnp.arange(n_local, dtype=jnp.int32)


def _random_matching(key: jax.Array, n: int) -> jax.Array:
    """A uniform random perfect matching as an involution p (p[p[i]] == i).

    Shuffle, then pair the first half with the second; with odd n one node
    is left self-paired (a no-op exchange). Cost is O(N) — negligible next
    to the (N, N) pulls it halves.
    """
    perm = random.permutation(key, n)
    half = n // 2
    a, b = perm[:half], perm[half : 2 * half]
    p = jnp.arange(n, dtype=perm.dtype)
    return p.at[a].set(b).at[b].set(a)


def _grouped_matching(
    key: jax.Array, n: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """A random involution from the 8-row-GROUP matching family:
    ``p[8g + r] = 8*gm[g] + (r - c[g]) % 8`` — groups of 8 rows matched
    uniformly (``gm`` an involution over n/8 groups), rows within a
    matched pair assigned by a per-pair rotation ``c``.

    This is the TPU-shaped matching: Mosaic can only DMA row slices
    aligned to the 8-sublane tile, so drawing the matching from this
    family makes every peer fetch in the fused Pallas kernel an aligned
    (8, n) copy, with the rotation applied in VMEM. Used for ALL matching
    sub-exchanges on the fused kernel's domain (n % 128 == 0) so the XLA
    and Pallas paths share one
    trajectory. Mixing quality: each node's peer is a uniformly random
    group times a uniform rotation — marginally uniform over non-self
    groups, fresh independent draw every sub-exchange; measured
    rounds-to-convergence matches the unrestricted family (see
    tests/test_sim.py::test_grouped_matching_convergence_parity).

    Involution: partners g < h get rotations c and (8-c) % 8; self-matched
    groups (odd group count) rotate by 0 or 4, the self-inverse rotations.
    Returns (gm, c, p) with p the row-level involution.
    """
    n_groups = n // 8
    kg, kc = random.split(key)
    gm = _random_matching(kg, n_groups)
    u = random.randint(kc, (n_groups,), 0, 8)
    gid = jnp.arange(n_groups)
    c = jnp.where(
        gid < gm, u, jnp.where(gid > gm, (8 - u[gm]) % 8, 4 * (u % 2))
    ).astype(jnp.int32)
    g = jnp.arange(n, dtype=jnp.int32) // 8
    r = jnp.arange(n, dtype=jnp.int32) % 8
    p = 8 * gm[g].astype(jnp.int32) + (r - c[g]) % 8
    return gm, c, p


def _global_cumsum_excl(d: jax.Array, axis_name: str | None) -> jax.Array:
    """Exclusive cumsum of per-owner deficits in GLOBAL owner order, given
    the local (N, n_local) block. Cross-shard part is one (N,)-per-shard
    all_gather — the exact global greedy order is preserved, so a sharded
    run advances watermarks identically to a single-device run."""
    local_excl = jnp.cumsum(d, axis=1) - d
    if axis_name is None:
        return local_excl
    block_totals = lax.all_gather(d.sum(axis=1), axis_name)  # (S, N)
    shard = lax.axis_index(axis_name)
    n_shards = block_totals.shape[0]
    before = jnp.arange(n_shards)[:, None] < shard
    offset = jnp.sum(jnp.where(before, block_totals, 0), axis=0)  # (N,)
    return local_excl + offset[:, None]


def hash_mix_u32(i: jax.Array, j: jax.Array, s: jax.Array) -> jax.Array:
    """The repo's one multiplicative-hash mix of two index streams and
    a salt (uint32 in, uint32 out). Single-sourced: the budget dither /
    view draws below and the fault masks (faults/sim.py) must stay in
    lockstep — the fused Pallas kernel reproduces this exact sequence,
    so a tweak here is a kernel change too."""
    h = (
        i * jnp.uint32(0x9E3779B1)
        ^ j * jnp.uint32(0x85EBCA77)
        ^ s * jnp.uint32(0xC2B2AE3D)
    )
    h = (h ^ (h >> 15)) * jnp.uint32(0x27D4EB2F)
    return h ^ (h >> 13)


def _hash_uniform(
    salt: jax.Array,
    n_rows: int,
    owner_ids: jax.Array,
    run_salt: jax.Array | None = None,
    bits: int = 24,
) -> jax.Array:
    """Deterministic (row, global-owner, salt) -> [0, 1) dither pattern.

    A multiplicative integer hash rather than jax PRNG so the value of
    every element depends only on GLOBAL indices — a column-sharded run
    therefore produces bit-identical advances to a single-device run
    (jax.random streams are shape-dependent and would diverge per shard).
    ``run_salt`` mixes the run's PRNG seed in so different seeds get
    different dither/draw patterns.

    ``bits=24`` (the dither default) maps the top 24 hash bits through an
    int32 cast — float32 holds 24-bit integers exactly, and Mosaic (the
    Pallas TPU compiler) has no uint32->float32 lowering, so this is the
    form the fused kernel reproduces bit-identically; its maximum is
    exactly 1 - 2^-24, making the upper clip a no-op kept only as a
    safety net. ``bits=32`` keeps the full-entropy mapping for consumers
    that never run in the kernel and care about tie probability (the
    Gumbel-max peer draw); there the upper clip is load-bearing — u ==
    1.0 (a ~2^-25 uint32->float32 rounding event) would make the Gumbel
    transform +inf and let a fallback peer outrank the live tier. The
    lower clip guards log(0) in both modes.
    """
    i = jnp.arange(n_rows, dtype=jnp.uint32)[:, None]
    j = owner_ids.astype(jnp.uint32)[None, :]
    s = salt.astype(jnp.uint32)
    if run_salt is not None:
        s = s ^ run_salt.astype(jnp.uint32)
    h = hash_mix_u32(i, j, s)
    if bits == 32:
        u = h.astype(jnp.float32) * (1.0 / 4294967296.0)
    else:
        u = (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return jnp.clip(u, 1e-12, 1.0 - 2.0**-24)


def _budgeted_advance(
    w_recv: jax.Array,
    w_send: jax.Array,
    budget: int,
    valid: jax.Array,
    axis_name: str | None,
    policy: str,
    salt: jax.Array,
    owner_ids: jax.Array,
    run_salt: jax.Array | None = None,
    col_ok: jax.Array | None = None,
) -> jax.Array:
    """How far each receiver row may advance toward the sender row under
    the per-exchange key-version budget (the MTU analogue).

    "greedy" reproduces the reference packer's prefix allocation in owner
    order; "proportional" scales every stale owner's deficit by the same
    factor so the total fits — cheaper (no scan) and spreads the MTU
    across owners instead of privileging low owner indices. Proportional
    advances are rounded with a dithered Bernoulli so the expected total
    matches the budget exactly and progress never stalls even when every
    scaled deficit is below one key-version.

    ``col_ok`` (N, n_local bool), when given, masks owner columns the
    SENDER has scheduled for deletion — the digest-exclusion analogue
    (reference state.py:346-348 skips scheduled nodes in the delta).
    """
    dt = w_recv.dtype
    d = jnp.maximum(w_send - w_recv, 0) * valid[:, None].astype(dt)
    if col_ok is not None:
        d = jnp.where(col_ok, d, 0)
    if policy == "greedy":
        # Row totals/cumsums run in int32 even for int16 watermarks — a
        # row's total deficit can exceed the element dtype's range.
        c = _global_cumsum_excl(d.astype(jnp.int32), axis_name)
        return jnp.clip(budget - c, 0, d.astype(jnp.int32)).astype(dt)
    total = d.sum(axis=1, dtype=jnp.float32)
    if axis_name is not None:
        total = lax.psum(total, axis_name)
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))
    x = d.astype(jnp.float32) * scale[:, None]
    floor = jnp.floor(x)
    bump = _hash_uniform(salt, d.shape[0], owner_ids, run_salt) < (x - floor)
    return jnp.minimum(floor.astype(jnp.int32) + bump, d.astype(jnp.int32)).astype(dt)


# -- packed u4 residual rung: byte-space gossip math --------------------------
#
# version_dtype="u4r" stores watermarks as saturating residuals below
# the owner's max_version, two per byte (sim/packed.py). The sub-
# exchange math is closed in residual space — the deficit of one
# handshake direction is max(r_recv - r_send, 0) because the per-owner
# max_version cancels out of (w_send - w_recv) — so the helpers below
# compute DIRECTLY on the nibbles: the packed (N, n_local/2) matrix is
# the only (N, N)-class array that ever exists in HBM; lo/hi halves are
# fusion intermediates. Every value reproduces _budgeted_advance's
# proportional path bit-for-bit (same f32 totals — deficit sums are
# exact integers < 2^24 in any association — same scale, same dither
# hash on the same (row, GLOBAL owner, salt) triples), which is what
# the rung's bit-parity merge gate pins (tests/test_memory_ladder.py).


def _pack_halves(lo: jax.Array, hi: jax.Array) -> jax.Array:
    return (lo | (hi << 4)).astype(jnp.uint8)


def _packed_adv_halves(
    r: jax.Array,
    r_peer: jax.Array,
    budget: int,
    valid: jax.Array,
    axis_name: str | None,
    salt: jax.Array,
    owners: jax.Array,
    run_salt: jax.Array | None,
) -> tuple[jax.Array, jax.Array]:
    """Budgeted advance of each receiver row toward its peer row for the
    packed rung: (a_lo, a_hi) int32 nibble advances (the receiver's
    residual shrinks by them). Proportional policy only — the config
    validates that; the greedy global cumsum would interleave nibbles."""
    lo = (r & 0xF).astype(jnp.int32)
    hi = (r >> 4).astype(jnp.int32)
    plo = (r_peer & 0xF).astype(jnp.int32)
    phi = (r_peer >> 4).astype(jnp.int32)
    v32 = valid[:, None].astype(jnp.int32)
    d_lo = jnp.maximum(lo - plo, 0) * v32
    d_hi = jnp.maximum(hi - phi, 0) * v32
    # f32 row totals: every partial sum is an exact integer (< 2^24), so
    # summing the halves separately equals the unpacked column-order sum.
    total = d_lo.sum(axis=1, dtype=jnp.float32) + d_hi.sum(
        axis=1, dtype=jnp.float32
    )
    if axis_name is not None:
        total = lax.psum(total, axis_name)
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))

    def half(d: jax.Array, owner_ids: jax.Array) -> jax.Array:
        x = d.astype(jnp.float32) * scale[:, None]
        floor = jnp.floor(x)
        bump = _hash_uniform(salt, d.shape[0], owner_ids, run_salt) < (
            x - floor
        )
        return jnp.minimum(floor.astype(jnp.int32) + bump, d)

    return half(d_lo, owners[0::2]), half(d_hi, owners[1::2])


def _packed_apply(r: jax.Array, a_lo: jax.Array, a_hi: jax.Array) -> jax.Array:
    """Apply nibble advances: the receiver's residual shrinks in place
    (w += adv in watermark space)."""
    lo = (r & 0xF).astype(jnp.int32) - a_lo
    hi = (r >> 4).astype(jnp.int32) - a_hi
    return _pack_halves(lo, hi)


def _packed_diag_zero(r: jax.Array, owners: jax.Array, n: int) -> jax.Array:
    """Owner-diagonal refresh in residual space: an owner's residual on
    itself is 0 by definition (w[j, j] = max_version[j])."""
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    r = jnp.where(rows == owners[0::2][None, :], r & 0xF0, r)
    return jnp.where(rows == owners[1::2][None, :], r & 0x0F, r)


def _packed_writes_shift(
    r: jax.Array, bump: jax.Array, owners: jax.Array
) -> jax.Array:
    """Owner-side writes raise max_version, which raises every stale
    observer's residual by the same amount (w unchanged). Saturating at
    the nibble ceiling — the horizon guard keeps valid runs below it,
    so in-domain trajectories never actually clip."""
    lo = jnp.minimum(
        (r & 0xF).astype(jnp.int32) + bump[owners[0::2]][None, :], U4_MAX
    )
    hi = jnp.minimum(
        (r >> 4).astype(jnp.int32) + bump[owners[1::2]][None, :], U4_MAX
    )
    return _pack_halves(lo, hi)


def _view_peer_choice(
    live_view: jax.Array,
    salt: jax.Array,
    owners: jax.Array,
    axis_name: str | None,
    run_salt: jax.Array | None = None,
) -> jax.Array:
    """One global peer index per row, sampled uniformly from the row's
    live view via deterministic Gumbel-max.

    live_view is the (N, n_local) column-sharded belief matrix; the noise
    is the global-index hash (not jax PRNG) so each shard's local argmax
    composes into the exact single-device draw: take the local best per
    row, then the best across shards (one small all_gather on ICI).
    """
    n = live_view.shape[0]
    # Full 32-bit entropy: this draw never runs in the Pallas kernel, and
    # the argmax tie probability (~n/2^bits per row) must stay negligible
    # — 24 bits would re-introduce a low-owner-index tie bias at large n.
    u = _hash_uniform(salt, n, owners, run_salt, bits=32)
    gumbel = -jnp.log(-jnp.log(u))
    # Two-tier draw: a live non-self peer always beats a fallback pick
    # (the +LIVE_BONUS tier), but when a row believes no one else is live
    # — cold start, or total isolation — it samples uniformly over all
    # other nodes instead, the reference's cold-start/forced-seed rule
    # (server.py:692-697,709-716). The clipped u keeps gumbel inside
    # (-3.4, 16.7), so a bonus of 64 separates the tiers with float32
    # ulp 7.6e-6 — no quantization-tie bias toward low owner indices.
    is_self = owners[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    LIVE_BONUS = 64.0
    score = jnp.where(
        live_view & ~is_self,
        gumbel + LIVE_BONUS,
        jnp.where(~is_self, gumbel, NEG_INF),
    )
    local_best = jnp.argmax(score, axis=1)  # (N,) local column
    local_score = jnp.max(score, axis=1)
    local_idx = owners[local_best]  # global owner index
    if axis_name is None:
        return local_idx
    scores = lax.all_gather(local_score, axis_name)  # (S, N)
    idxs = lax.all_gather(local_idx, axis_name)  # (S, N)
    shard_best = jnp.argmax(scores, axis=0)
    return jnp.take_along_axis(idxs, shard_best[None, :], axis=0)[0]


def select_peers(
    key: jax.Array,
    alive: jax.Array,
    live_view: jax.Array | None,
    cfg: SimConfig,
    adjacency: jax.Array | None = None,
    degrees: jax.Array | None = None,
    *,
    axis_name: str | None = None,
    view_salt: jax.Array | None = None,
    run_salt: jax.Array | None = None,
    force_masked: bool = False,
) -> jax.Array:
    """(N, fanout) peer indices for this round.

    - topology mode: uniform over each node's adjacency list;
    - "alive" mode: uniform over truly-alive nodes (scalable default);
    - "view" mode: each node samples from its own live_view row
      (FD-faithful) via the deterministic Gumbel-max, which is
      shard-exact under column sharding.

    Self/dead picks are legal — they degenerate to no-op exchanges, which
    also stands in for the reference's failed connections to dead peers.

    ``force_masked`` pins the masked categorical draw even on a
    statically churn-free config — the breaker-quarantine path
    (docs/robustness.md) passes an ``alive`` mask that excludes
    quarantined peers, which the uniform-integer fast path below would
    ignore.
    """
    n = cfg.n_nodes
    if adjacency is not None:
        assert degrees is not None
        slot = random.randint(key, (n, cfg.fanout), 0, degrees[:, None])
        return jnp.take_along_axis(adjacency, slot, axis=1)
    if cfg.peer_mode == "view":
        assert live_view is not None and view_salt is not None
        n_local = live_view.shape[1]
        owners = _local_owner_ids(n_local, axis_name)
        cols = [
            _view_peer_choice(live_view, view_salt + c, owners, axis_name, run_salt)
            for c in range(cfg.fanout)
        ]
        return jnp.stack(cols, axis=1)
    if cfg.death_rate == 0.0 and cfg.revival_rate == 0.0 and not force_masked:
        # Statically churn-free: the alive mask is all-true forever, so
        # the uniform categorical degenerates to a uniform integer draw
        # — same distribution (self-picks included, no-op exchanges),
        # one u32 per draw instead of a gumbel per CATEGORY per draw
        # (categorical materializes (n, fanout, n) noise: ~3.2e9
        # samples at 32k — minutes per round on a CPU host, and wasted
        # HBM traffic on chip).
        peers = random.randint(key, (n, cfg.fanout), 0, n)
    else:
        logits = jnp.where(alive, 0.0, NEG_INF)
        peers = random.categorical(key, logits, shape=(n, cfg.fanout))
    return _zone_biased(peers, key, cfg)


def _zone_biased(
    peers: jax.Array, key: jax.Array, cfg: SimConfig
) -> jax.Array:
    """Zone-aware peer bias (models/topology.Heterogeneity): with
    probability ``zone_bias`` a draw is replaced by a uniform pick from
    the node's OWN zone (contiguous coordinate blocks — the same
    bucketing the partition masks use). A biased pick may land on a
    dead node or the node itself: a no-op exchange, standing in for the
    reference's failed connection exactly like the unbiased draw's
    self-picks. Unchanged (same object) when the config carries no
    bias."""
    het = cfg.heterogeneity
    if het is None or het.zone_bias <= 0.0:
        return peers
    import numpy as np

    n, fanout = peers.shape
    z = (np.arange(n) * het.zones) // n
    starts = np.searchsorted(z, np.arange(het.zones))
    counts = np.bincount(z, minlength=het.zones)
    zstart = jnp.asarray(starts[z], jnp.int32)  # (N,) own-zone first index
    zcount = jnp.asarray(counts[z], jnp.int32)  # (N,) own-zone size
    kz, kb = random.split(random.fold_in(key, 0x5A))
    local = zstart[:, None] + random.randint(
        kz, (n, fanout), 0, zcount[:, None]
    )
    biased = random.bernoulli(kb, het.zone_bias, (n, fanout))
    return jnp.where(biased, local, peers)


def scheduled_for_deletion_mask(
    state: SimState, cfg: SimConfig, tick: jax.Array | None = None
) -> jax.Array | None:
    """(N, n_local) bool: observer i has had owner j scheduled for
    deletion for at least half the grace — the digest-exclusion stage.
    Single source of the formula for sim_step AND for tests/tooling that
    inspect lifecycle state; None when the lifecycle is disabled."""
    if not (cfg.track_failure_detector and cfg.dead_grace_ticks is not None):
        return None
    t = state.tick if tick is None else tick
    ds32 = state.dead_since.astype(jnp.int32)
    return (ds32 > 0) & ((t - ds32) >= cfg.dead_grace_ticks // 2)


def _pallas_wanted(cfg: SimConfig, assume_accelerator: bool = False) -> bool:
    """Resolution of ``use_pallas`` shared by both kernel gates:
    True forces the kernels (interpret mode off-TPU — tests), "auto"
    engages them on a real TPU backend only. ``assume_accelerator``
    resolves "auto" as if on TPU regardless of the current backend —
    for capacity planning (sim/memory.py), which must give the same
    answer on a CPU planning host as on the chip."""
    # assume_accelerator first: planner calls must not force JAX backend
    # initialization (on a planning host with a down tunnel, backend
    # init can block for minutes).
    return cfg.use_pallas is True or (
        cfg.use_pallas == "auto" and (assume_accelerator or on_accelerator())
    )


def _lifecycle_enabled(cfg: SimConfig) -> bool:
    return cfg.track_failure_detector and cfg.dead_grace_ticks is not None


def _fault_plan_active(cfg: SimConfig) -> bool:
    """Whether the config's EFFECTIVE fault plan — the configured plan
    plus heterogeneity's derived WAN LinkFaults — carries ANY behavior
    the masks would have to inject (link, crash or byzantine): the
    predicate sim_step itself branches on, so a no-op plan (empty, or
    all-zero probabilities) costs nothing and keeps the fused-kernel
    fast paths engaged. Cadence classes are deliberately NOT in this
    predicate: they fold into pair validity, which the kernels carry
    natively."""
    from ..faults.sim import (
        effective_fault_plan,
        plan_affects_byzantine,
        plan_affects_links,
        plan_affects_nodes,
    )

    plan = effective_fault_plan(cfg.fault_plan, cfg.heterogeneity)
    return (
        plan_affects_links(plan)
        or plan_affects_nodes(plan)
        or plan_affects_byzantine(plan)
    )


# Loud-fallback ledger: every sim_step TRACE whose config WANTED the
# fused kernels (use_pallas True, or "auto" on an accelerator) but was
# routed to XLA bumps a reason-keyed counter here — a metric, not a
# print, so tests and telemetry can pin "this config silently degraded"
# (tests/test_fused_kernel.py). Counted at trace time: one increment
# per compiled config, which is exactly the grain at which the decision
# is made.
pallas_fallbacks: collections.Counter = collections.Counter()

# Saved snapshots of scopes currently active (innermost last): the
# counter itself holds only in-scope deltas while a scope is open, so
# consumers that need the STABLE process-wide view (the obs delta
# export — its baseline/flush pair must not jump when a scope exits
# between them) read ``pallas_fallbacks_total`` instead of the raw
# counter.
_fallbacks_scope_stack: list[collections.Counter] = []


def pallas_fallbacks_total() -> collections.Counter:
    """The process-wide loud-fallback ledger INCLUDING any counts
    temporarily parked by active ``pallas_fallbacks_scope``s — the view
    that is invariant across scope entry/exit (inside a scope it equals
    saved + in-scope deltas, which is exactly what the exit restores).
    Telemetry baselines/exports read this; tests asserting deltas read
    the scoped counter itself."""
    total = collections.Counter(pallas_fallbacks)
    for saved in _fallbacks_scope_stack:
        total.update(saved)
    return total


@contextlib.contextmanager
def pallas_fallbacks_scope():
    """Scoped view of the loud-fallback ledger: on entry the ambient
    counts are snapshotted and the counter zeroed, so reads INSIDE the
    scope are exact deltas (``fb["packed_dtype"] == 1``, not
    ``before + 1`` against whatever test ran earlier); on exit the
    snapshot is restored WITH the in-scope counts folded back in, so
    the process-wide ledger (and its /metrics mirror) sees every
    fallback exactly once, scope or no scope. Counter-regression tests
    (tests/test_fused_kernel.py, tests/test_memory_ladder.py) use this
    instead of diffing ambient state, which bled across test ordering.

    Yields the live Counter (the module global — sim_step keeps
    bumping the same object inside the scope)."""
    saved = collections.Counter(pallas_fallbacks)
    pallas_fallbacks.clear()
    _fallbacks_scope_stack.append(saved)
    try:
        yield pallas_fallbacks
    finally:
        _fallbacks_scope_stack.pop()
        delta = collections.Counter(pallas_fallbacks)
        pallas_fallbacks.clear()
        pallas_fallbacks.update(saved + delta)


def pallas_fallback_reason(
    cfg: SimConfig,
    axis_name: str | None = None,
    *,
    has_topology: bool = False,
    n_local: int | None = None,
    sweep: bool = False,
) -> str | None:
    """Why a kernel-wanting config fell back to XLA — the FIRST failing
    gate, in the order ``pallas_path_engaged`` checks them (the shard
    -width precondition first, then the config gates in the boolean's
    written order, then variant/VMEM) — or None when the kernels
    engaged (or were never wanted). A rejection no named gate explains
    (a future gate added to pallas_path_engaged but not here) lands in
    the catch-all "vmem_or_width", so the counter can under-label but
    never miss a fallback; tests/test_fused_kernel.py pins one reason
    per named gate. Feeds the ``pallas_fallbacks`` counter."""
    if not _pallas_wanted(cfg):
        return None
    if axis_name is not None and n_local is None:
        return "unknown_shard_width"
    if has_topology:
        return "topology"
    if _fault_plan_active(cfg):
        return "fault_plan"
    if cfg.pairing != "matching":
        return "pairing"
    if cfg.version_dtype == "u4r" and (
        cfg.track_heartbeats or cfg.pallas_variant == "m8"
    ):
        # The pairs kernel carries the u4 nibble codec for the LEAN
        # (heartbeat-free) profile only, and the single-pass m8 kernel
        # carries no codec at all: a heartbeat-tracking packed config
        # or a pinned-m8 packed config runs the byte-space XLA path —
        # loudly. (Packed widths off the kernel domain fall through to
        # the vmem_or_width catch-all below, still counted.)
        return "packed_dtype"
    if cfg.fanout < 1:
        return "fanout"
    if cfg.n_nodes % 128 != 0:
        return "shape"
    if cfg.budget_policy != "proportional":
        return "budget_policy"
    if _lifecycle_enabled(cfg):
        return "lifecycle"
    if sweep and pallas_variant_engaged(cfg, axis_name, n_local) != "pairs":
        return "sweep_needs_pairs"
    if not pallas_path_engaged(
        cfg, axis_name, has_topology=has_topology, n_local=n_local,
        sweep=sweep,
    ):
        return "vmem_or_width"
    return None


def pallas_path_engaged(
    cfg: SimConfig,
    axis_name: str | None = None,
    *,
    has_topology: bool = False,
    n_local: int | None = None,
    assume_accelerator: bool = False,
    sweep: bool = False,
) -> bool:
    """Single source of truth for whether sim_step routes matching
    sub-exchanges through the fused Pallas kernel for this config —
    consumed by sim_step AND by bench.py's speedup/roofline labelling, so
    the two can never drift (the ADVICE.md r1 itemsize-gate bug class).

    "auto" resolves by backend: the compiled kernel on a real TPU, plain
    XLA elsewhere (interpret mode is for tests only — forcing
    use_pallas=True off-TPU runs it interpreted). The remaining terms
    mirror the kernel's hard requirements: grouped-matching domain
    (n % 128 == 0), proportional budget, no dead-node lifecycle (the
    kernel has no scheduled-for-deletion column mask), and a legal VMEM
    block for the widest matrix dtype (fused_pull_m8 sizes VMEM from the
    same). Both profiles qualify: with heartbeats the kernel fuses w and
    hb; the lean convergence-only profile runs the w-only variant.

    Column-sharded runs engage too (the north-star config): the kernel
    runs per shard on its (N, n_local) block — peer DMA is shard-local
    because rows are unsharded — with the rows' global deficit totals
    computed by a first streaming pass and one psum (sim_step wires the
    two passes). Callers under shard_map must pass the shard's
    ``n_local`` so the lane-width check sees the LOCAL column count.

    ``has_topology``: adjacency-constrained runs force the choice path,
    so callers labelling a Simulator(..., topology=...) run must pass
    True (sim_step itself never consults the gate on that path).

    ``sweep``: lane-batched steps (SweepSimulator vmaps sim_step over S
    scenarios) engage the kernels too — the pairs family carries a lane
    grid axis (pallas_pull.fused_pull_pairs_lanes) — but ONLY when the
    pairs variant serves the shape: the single-pass m8 kernel and the
    standalone FD kernel have no lane lift, so a sweep whose shape
    falls off the pairs domain runs plain XLA (loudly — see
    pallas_fallbacks)."""
    from . import pallas_pull

    if axis_name is not None and n_local is None:
        return False  # sharded callers must say how wide a shard is
    if not (
        _pallas_wanted(cfg, assume_accelerator)
        and not has_topology  # adjacency runs force the choice path
        # Fault-injecting runs stay on XLA: the fused kernels carry no
        # link/crash mask (docs/faults.md). A plan with no effective
        # behavior keeps the kernels — sim_step injects nothing then.
        and not _fault_plan_active(cfg)
        and cfg.pairing == "matching"
        # The packed u4 rung rides the pairs kernel's nibble codec —
        # but only in the lean (heartbeat-free) profile: a packed w
        # next to an unpacked hb would need two tile widths in one
        # stream table, which no kernel carries (pallas_fallback_reason
        # "packed_dtype" keeps that degradation loud).
        and not (cfg.version_dtype == "u4r" and cfg.track_heartbeats)
        # fanout >= 1 so the round's first kernel call exists to carry
        # the owner-diagonal refresh (a fanout=0 round must still
        # refresh diagonals, which the XLA path does unconditionally).
        and cfg.fanout >= 1
        and cfg.n_nodes % 128 == 0
        and cfg.budget_policy == "proportional"
        and not _lifecycle_enabled(cfg)
    ):
        return False
    # The VMEM-fit term follows the variant that would actually serve
    # the shape (evaluated only past the cheap gates, so an invalid
    # variant override cannot raise from configs whose kernel path is
    # off anyway): the pair-fused kernel's domain extends past the
    # single-pass kernel's (one in-place tile per matrix instead of
    # five streamed buffers), so a pairs-served width must not be
    # rejected by the m8 block search.
    if pallas_variant_engaged(cfg, axis_name, n_local) == "pairs":
        return True  # pairs_supported held inside the variant decision
    if cfg.version_dtype == "u4r":
        # Only the pairs family carries the u4 nibble codec: a packed
        # shape the pairs gate refuses (VMEM, a byte width off the
        # 128-lane domain, a pinned m8 variant) has no m8 fallback.
        return False
    if sweep:
        return False  # only the pairs family carries the lane axis
    itemsize = jnp.dtype(cfg.version_dtype).itemsize
    if cfg.track_heartbeats:
        itemsize = max(itemsize, jnp.dtype(cfg.heartbeat_dtype).itemsize)
    return pallas_pull.supported(
        cfg.n_nodes, itemsize, track_hb=cfg.track_heartbeats,
        n_local=cfg.n_nodes if axis_name is None else n_local,
    )


def resolve_variant_env(cfg: SimConfig) -> SimConfig:
    """Fold the AIOCLUSTER_TPU_PALLAS_VARIANT env override (the
    benchmark A/B / kill-switch knob) into the config ONCE, at
    construction time. Returns ``cfg`` unchanged unless the override
    applies.

    The override is resolved here — not inside the (jitted) decision
    functions — because cfg is the jit static argument: an env var read
    at trace time is invisible to the jit cache key, so flipping it
    between runs in one process would silently reuse the previously
    compiled kernel variant while Python-level provenance reported the
    new value (ADVICE r3). ``Simulator.__init__`` applies this, making
    the resolved variant part of every cache key.

    Precedence: an EXPLICIT cfg.pallas_variant ("m8"/"pairs") wins over
    the env var — the override steers configs that left the choice to
    "auto" (the battery's canary pin, bench's default config) without
    defeating code that deliberately pinned a variant (bench's warm-up
    fallback to the proven kernel, the canary's own A/B arms). The env
    value is validated loudly whenever set — a typo'd override must not
    silently measure the wrong kernel."""
    env = os.environ.get("AIOCLUSTER_TPU_PALLAS_VARIANT")
    if not env:
        return cfg
    if env not in ("auto", "m8", "pairs"):
        raise ValueError(
            "AIOCLUSTER_TPU_PALLAS_VARIANT must be auto/m8/pairs, "
            f"got {env!r}"
        )
    if env == "auto" or cfg.pallas_variant != "auto":
        return cfg
    import dataclasses

    return dataclasses.replace(cfg, pallas_variant=env)


def pallas_variant_engaged(
    cfg: SimConfig,
    axis_name: str | None = None,
    n_local: int | None = None,
) -> str:
    """Which pull-kernel implementation serves matching sub-exchanges
    when the Pallas path is engaged: "pairs" (the pair-fused kernel —
    2 passes per matrix per sub-exchange) or "m8" (the single-pass
    kernel — 3). Single source of truth consumed by sim_step's dispatch
    AND by bench.py's variant provenance + analytic bytes/round, so the
    recorded roofline can never drift from what the kernel actually did
    (same drift class pallas_path_engaged guards against). A pure
    function of cfg: the env override is folded into cfg up front by
    ``resolve_variant_env`` (Simulator construction), never read at
    trace time."""
    from . import pallas_pull

    variant = cfg.pallas_variant
    n = cfg.n_nodes
    if axis_name is not None and n_local is None:
        return "m8"  # sharded callers must say how wide a shard is
    width = n if axis_name is None else n_local
    packed = cfg.version_dtype == "u4r"
    itemsize = 1 if packed else jnp.dtype(cfg.version_dtype).itemsize
    if cfg.track_heartbeats:
        itemsize = max(itemsize, jnp.dtype(cfg.heartbeat_dtype).itemsize)
    # FD-fusing configs charge the epilogue's VMEM (last_change / imean
    # / icount / live tiles + the hb0 stream) in the pairs fit check:
    # the variant decision and the kernel that actually allocates must
    # read one accounting or a width could pass the gate and then fail
    # pairs_nbuf inside the wrapper. The shrunk bookkeeping rungs
    # charge their own widths (int8 counters, the 1-bit/pair bitmap).
    fd_sizes = (
        (
            jnp.dtype(cfg.heartbeat_dtype).itemsize,
            jnp.dtype(cfg.fd_dtype).itemsize,
            jnp.dtype(cfg.icount_dtype).itemsize,
            0.125 if cfg.live_bits else 4,
        )
        if _fd_fusion_candidate(cfg)
        else None
    )
    use_pairs = variant in ("auto", "pairs") and pallas_pull.pairs_supported(
        n, itemsize, cfg.track_heartbeats, n_local=width, fd_sizes=fd_sizes,
        packed=packed,
    )
    return "pairs" if use_pairs else "m8"


def _fd_bookkeeping_packed(cfg: SimConfig) -> bool:
    """Whether the FD bookkeeping sits below the r5 int16/bool profile
    (int8 sample counters / the live bitmap). The FUSED pairs epilogue
    models both shrunk forms natively (it widens per tile in VMEM and
    writes the bitmap — ops/pallas_pull.py); only the STANDALONE
    streaming kernel (ops/pallas_fd.py) remains unpacked-only, which is
    what fd_phase_engaged and the loud-fallback ledger key off this
    predicate for."""
    return cfg.icount_dtype != "int16" or cfg.live_bits


def fd_fallback_reason(cfg: SimConfig) -> str | None:
    """Why a config that WANTED the FD kernels runs the FD phase on
    XLA anyway — currently the one packed-bookkeeping cause (the
    STANDALONE FD kernel carries no int8 counters / live bitmap; the
    fused pairs epilogue does, so this fires only off the pairs path)
    — or None. The FD-phase analogue of pallas_fallback_reason;
    sim_step feeds the ``pallas_fallbacks`` ledger from this exactly
    when fd_phase_engaged resolved "xla", never from a re-derived
    predicate."""
    if (
        cfg.track_failure_detector
        and _pallas_wanted(cfg)
        and not _lifecycle_enabled(cfg)
        and cfg.use_pallas_fd is not False
        and _fd_bookkeeping_packed(cfg)
    ):
        return "fd_packed_bookkeeping"
    return None


def _fd_fusion_candidate(cfg: SimConfig) -> bool:
    """Whether a pairs-served round would carry the fused FD epilogue —
    the term the variant decision charges VMEM for. use_pallas_fd=False
    pins the FD phase to XLA (the A/B seam), so those configs don't pay
    the epilogue's footprint. The shrunk bookkeeping rungs (int8
    counters, the live bitmap) DO fuse — the epilogue widens per tile
    in VMEM via the sanctioned nibble/bit algebra and their tile
    widths are charged in the fit check."""
    return (
        cfg.track_failure_detector
        and not _lifecycle_enabled(cfg)
        and cfg.use_pallas_fd is not False
    )


def fd_phase_engaged(
    cfg: SimConfig,
    axis_name: str | None = None,
    n_local: int | None = None,
    *,
    has_topology: bool = False,
    assume_accelerator: bool = False,
    sweep: bool = False,
) -> str:
    """Which implementation serves the round's failure-detection phase:

    - "fused": the FD update rides the round's LAST pairs sub-exchange
      (one Pallas dispatch for pull + FD — the fused round kernel);
    - "kernel": the standalone streaming FD kernel (ops/pallas_fd.py),
      the fallback when the pull phase is not pairs-served (m8 shapes,
      choice/permutation pairing, use_pallas off with use_pallas_fd
      forced);
    - "xla": the plain XLA block (lifecycle configs, use_pallas_fd
      pinned False, unsupported shapes, sweeps off the pairs domain);
    - "off": no failure detector in this config.

    THE single resolution consumed by sim_step's dispatch AND by
    bench.py's ``fd_kernel`` stamp / bytes-per-round accounting
    (sim/bytes.py), so the recorded provenance can never drift from
    what the compiled step actually did."""
    if not cfg.track_failure_detector:
        return "off"
    if _lifecycle_enabled(cfg) or cfg.use_pallas_fd is False:
        return "xla"
    if pallas_path_engaged(
        cfg,
        axis_name,
        has_topology=has_topology,
        n_local=n_local,
        assume_accelerator=assume_accelerator,
        sweep=sweep,
    ) and pallas_variant_engaged(cfg, axis_name, n_local) == "pairs":
        return "fused"
    if sweep:
        return "xla"  # the standalone FD kernel has no lane axis
    if _fd_bookkeeping_packed(cfg):
        # Shrunk bookkeeping rungs off the pairs path: the STANDALONE
        # FD kernel models neither int8 counters nor the live bitmap
        # (only the fused pairs epilogue does) — the XLA block serves
        # them (sim_step bumps the loud-fallback counter via
        # fd_fallback_reason, the same predicate).
        return "xla"
    from . import pallas_fd

    wanted = cfg.use_pallas_fd is True or _pallas_wanted(
        cfg, assume_accelerator
    )
    if wanted and pallas_fd.supported(
        cfg.n_nodes,
        cfg.n_nodes if n_local is None else n_local,
        jnp.dtype(cfg.heartbeat_dtype).itemsize,
        jnp.dtype(cfg.fd_dtype).itemsize,
    ):
        return "kernel"
    return "xla"


def pallas_fd_engaged(cfg: SimConfig, n_local: int | None = None) -> bool:
    """Whether the FD phase runs in a Pallas kernel for this config —
    fused into the round's last pairs sub-exchange OR the standalone
    streaming kernel (``fd_phase_engaged`` says which; this is the
    boolean consumers like mesh._check_vma and bench's ``fd_kernel``
    stamp care about). Mirrors ``pallas_path_engaged``'s resolution of
    ``use_pallas`` ("auto" = on a real TPU; forcing True off-TPU runs
    interpreted, for tests). The dead-node lifecycle stays on XLA: its
    branch rewrites w/hb and carries dead_since, which no kernel
    models.

    The FD math is purely per-element, so it engages under shard_map
    too (each shard's (N, n_local) column block with its owner offset);
    pass the shard's ``n_local`` so the lane-width check sees the LOCAL
    column count (default: unsharded, n_local = n_nodes).

    ``cfg.use_pallas_fd`` refines the resolution independently of the
    pull kernel: False pins the FD phase to the XLA block (the on-chip
    A/B seam / kill switch), True forces the kernel, "auto" follows
    ``use_pallas``. Bit-identical every way."""
    axis = None if n_local is None or n_local == cfg.n_nodes else "owners"
    return fd_phase_engaged(cfg, axis, n_local) in ("fused", "kernel")


@partial(
    jax.jit,
    static_argnames=("cfg", "axis_name", "return_converged"),
    donate_argnums=(0,),
)
def sim_step(
    state: SimState,
    key: jax.Array,
    cfg: SimConfig,
    axis_name: str | None = None,
    adjacency: jax.Array | None = None,
    degrees: jax.Array | None = None,
    return_converged: bool = False,
    sweep=None,
) -> SimState | tuple[SimState, jax.Array]:
    """Advance the whole cluster by one gossip round.

    ``return_converged=True`` also returns the all-converged flag for
    the POST-round state (exactly ``all_converged_flag(new_state)``).
    On the pair-fused kernel path the flag rides the round's last
    sub-exchange for free — convergence-tracked runs pay no extra pass
    over w; other paths compute the separate (XLA-fused) check.

    ``sweep`` (a ``sim.state.SweepParams``) lifts the declared sweepable
    scalars — fanout, phi threshold, writes_per_round, fault-plan seed —
    from static config fields to traced operands, so ``SweepSimulator``
    can vmap one compiled step over a lane axis of scenarios. Each
    override reproduces EXACTLY the math of the corresponding static
    field (tests/test_sweep.py asserts lane-vs-sequential bit-identity).
    Sweep steps engage the fused Pallas path too whenever the pairs
    variant serves the shape: the pairs kernels carry a lane grid axis
    (a custom_vmap rule in ops/pallas_pull.py routes the vmapped call
    to it, per-lane scalars riding scalar prefetch), and a swept fanout
    folds into the kernel's alive-pair mask. Off the pairs domain a
    sweep runs plain XLA — and either way every lane stays bit-identical
    to the equivalent sequential run (tests/test_fused_kernel.py)."""
    n = cfg.n_nodes
    n_local = state_n_local(state)
    owners = _local_owner_ids(n_local, axis_name)
    # Packed u4 residual rung: the watermark matrix stays byte-packed in
    # HBM for the whole round — the branches below compute on nibbles
    # inside the fusion (sim/packed.py). The config validates the rung's
    # domain (matching/permutation, proportional, no lifecycle); a
    # topology run would force the choice path, which has no byte-space
    # form, so refuse it here where adjacency is visible.
    packed = is_packed_w(state.w)
    if packed and adjacency is not None:
        raise ValueError(
            "version_dtype='u4r' does not support topology runs (the "
            "adjacency path's scatter-max is unpacked-only)"
        )
    sw_fanout = None if sweep is None else sweep.fanout
    sw_phi = None if sweep is None else sweep.phi_threshold
    sw_wpr = None if sweep is None else sweep.writes_per_round
    sw_fault_seed = None if sweep is None else sweep.fault_seed
    if sw_fanout is not None and (
        cfg.pairing == "choice" or adjacency is not None
    ):
        # "choice" (and topology runs, which force the choice path)
        # draws all fanout columns in one shape-(n, fanout) PRNG call —
        # the draws are shape-dependent, so a masked wider draw cannot
        # reproduce a narrower sequential run bit-for-bit, and that
        # path carries no sub_active masking.
        raise ValueError(
            "per-lane fanout sweeps require pairing='matching' or "
            "'permutation' without a topology (choice-path peer draws "
            "are shape-dependent)"
        )
    tick = state.tick + 1
    round_key = random.fold_in(key, tick)
    churn_key, peer_key = random.split(round_key)
    # Per-run constant mixed into every hash salt so different seeds give
    # different dither and view-draw patterns (the key is replicated, so
    # this stays identical across shards).
    run_salt = random.bits(key, dtype=jnp.uint32)

    # -- churn (ground truth) -------------------------------------------------
    alive = state.alive
    if cfg.death_rate > 0 or cfg.revival_rate > 0:
        dk, rk = random.split(churn_key)
        dies = random.bernoulli(dk, cfg.death_rate, (n,))
        revives = random.bernoulli(rk, cfg.revival_rate, (n,))
        alive = jnp.where(alive, ~dies, revives)

    # -- fault plan + heterogeneity (docs/faults.md) -------------------------
    # Crash windows override EFFECTIVE liveness for the round — the
    # node's process isn't running, so its heartbeat/writes freeze and
    # its exchanges no-op — without touching the churn ground truth
    # (state.alive), so the window's end is the restart. Link faults
    # (including heterogeneity's derived WAN class faults) lower to
    # per-direction masks ANDed into exchange validity below; byzantine
    # kinds lower to owner-column blocks (the guarded-defense outcome —
    # faults/sim.py); cadence classes lower to a per-tick initiator
    # mask folded into pair validity.
    from ..faults.sim import (
        effective_fault_plan,
        link_ok,
        plan_affects_byzantine,
        plan_affects_links,
        plan_affects_nodes,
    )

    het = cfg.heterogeneity
    plan = effective_fault_plan(cfg.fault_plan, het)

    eff_alive = alive
    if plan_affects_nodes(plan):
        from ..faults.sim import (
            amnesia_restart_mask,
            crash_mask,
            plan_amnesia_restarts,
        )

        eff_alive = alive & ~crash_mask(plan, n, tick)
        if plan_amnesia_restarts(plan):
            # Amnesiac restart (docs/robustness.md): at the tick a
            # recovery="amnesia" crash window ends, the node reboots
            # EMPTY — its knowledge rows reset to the fresh-boot state
            # and the whole cluster re-replicates into it (the cost
            # recovery="warm" exists to avoid; restart_bench maps the
            # ratio). Owner ground truth (max_version/heartbeat)
            # persists: the sim has no generations, so only the replica
            # knowledge resets. Static predicate: plans without amnesia
            # restarts trace the exact pre-existing step. Config
            # validation excludes the packed rungs (u4r w / live_bits),
            # whose reset has no byte-space form.
            reset = amnesia_restart_mask(plan, n, tick)
            reset_col = reset[:, None]
            zeros_w = jnp.zeros((), state.w.dtype)
            new_w = jnp.where(reset_col, zeros_w, state.w)
            new_hb = state.hb_known
            if cfg.track_heartbeats:
                new_hb = jnp.where(
                    reset_col, jnp.zeros((), state.hb_known.dtype), state.hb_known
                )
            updates = {"w": new_w, "hb_known": new_hb}
            if cfg.track_failure_detector:
                self_col = owners[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
                updates["last_change"] = jnp.where(
                    reset_col, jnp.zeros((), state.last_change.dtype),
                    state.last_change,
                )
                updates["imean"] = jnp.where(
                    reset_col, jnp.zeros((), state.imean.dtype), state.imean
                )
                updates["icount"] = jnp.where(
                    reset_col, jnp.zeros((), state.icount.dtype), state.icount
                )
                updates["live_view"] = jnp.where(
                    reset_col, self_col, state.live_view
                )
                if state.dead_since.size:
                    updates["dead_since"] = jnp.where(
                        reset_col, jnp.zeros((), state.dead_since.dtype),
                        state.dead_since,
                    )
            state = state.replace(**updates)
    faulty_links = plan_affects_links(plan)
    byz_active = plan_affects_byzantine(plan)
    sw_byz = None if sweep is None else sweep.byz_frac
    if sw_byz is not None and not (plan is not None and plan.byzantine):
        raise ValueError(
            "byz_frac sweep lanes require a fault plan with byzantine "
            "entries (the lane value overrides their attacker windows)"
        )

    cad = None
    if het is not None and het.cadence_effective():
        from ..faults.sim import cadence_on

        cad = cadence_on(het, n, tick)

    def fault_ok(src: jax.Array, dst: jax.Array, sub) -> jax.Array | None:
        """(N,) permit mask for traffic src[i] -> dst[i] this round, or
        None when the plan carries no link behavior (keeps the
        fault-free trace byte-identical to before). A sweep lane's
        fault seed re-rolls the probabilistic draws exactly as
        ``replace(plan, seed=...)`` would."""
        if not faulty_links:
            return None
        return link_ok(plan, n, tick, src, dst, sub, seed=sw_fault_seed)

    # Receiver-side byzantine block (digest_inflation starves the
    # attacker) — peer-independent, so one mask serves the whole round.
    byz_in = None
    if byz_active:
        from ..faults.sim import byz_in_block

        byz_in = byz_in_block(
            plan, n, tick, owners, seed=sw_fault_seed, byz_frac=sw_byz
        )

    def byz_pull_block(peer: jax.Array, sub) -> jax.Array | None:
        """(N, n_local) owner-columns of this pull whose advances the
        receiver's guards reject (sender-side stale_replay /
        owner_violation plus the receiver-side inflation starvation),
        or None without byzantine behavior."""
        if not byz_active:
            return byz_in
        from ..faults.sim import byz_out_block

        ob = byz_out_block(
            plan, n, tick, peer, owners, sub,
            seed=sw_fault_seed, byz_frac=sw_byz,
        )
        if ob is None:
            return byz_in
        return ob if byz_in is None else ob | byz_in

    def byz_hb_mask(peer: jax.Array, sub) -> jax.Array | None:
        """Heartbeat-absorption block for this pull (stale_replay's
        stale digest adverts), or None."""
        if not byz_active:
            return None
        from ..faults.sim import byz_hb_block

        return byz_hb_block(
            plan, n, tick, peer, owners, sub,
            seed=sw_fault_seed, byz_frac=sw_byz,
        )

    # -- owner-side activity: heartbeat tick + workload writes ---------------
    wpr = cfg.writes_per_round if sw_wpr is None else sw_wpr
    heartbeat = state.heartbeat + eff_alive.astype(jnp.int32)
    max_version = state.max_version + wpr * eff_alive.astype(jnp.int32)

    # Owner diagonal refresh: w[j_owner, j] = max_version[j_owner] (and
    # the heartbeat analogue). On the fused-kernel path the refresh rides
    # the round's FIRST pull kernel and the FD kernel re-derives hb0's
    # diagonal, so nothing is materialized. Elsewhere it is a
    # broadcast-iota select, NOT a scatter: the where is elementwise, so
    # XLA fuses it into the adjacent passes, while the equivalent
    # ``w.at[owners, cols].set(...)`` lowers to a scatter costing a full
    # serialized pass over both matrices (~5 ms/round at 10k on a v5e —
    # measured, round 2).
    track_hb = cfg.track_heartbeats
    mv_vec = max_version[owners]
    hbv_vec = heartbeat[owners]
    # Sweeps engage the kernels too (the pairs family carries a lane
    # axis); the gate additionally requires the pairs variant then,
    # because m8 and the standalone FD kernel have no lane lift.
    use_pallas = pallas_path_engaged(
        cfg, axis_name, has_topology=adjacency is not None, n_local=n_local,
        sweep=sweep is not None,
    )
    # Which implementation serves the FD phase this trace — the SAME
    # resolution bench.py stamps into records (fd_kernel provenance).
    fd_phase = fd_phase_engaged(
        cfg, axis_name, n_local,
        has_topology=adjacency is not None, sweep=sweep is not None,
    )
    if not use_pallas:
        # Loud fallback: a config that WANTED the kernels but degraded
        # to XLA bumps the reason-keyed counter (trace-time — once per
        # compiled config), so silent-perf-loss regressions are visible
        # in a metric instead of a vibe.
        reason = pallas_fallback_reason(
            cfg, axis_name, has_topology=adjacency is not None,
            n_local=n_local, sweep=sweep is not None,
        )
        if reason is not None:
            pallas_fallbacks[reason] += 1
    if fd_phase == "xla":
        # The FD-phase analogue of the pull fallback above: a config
        # that wanted the FD kernels but shrank its bookkeeping below
        # what they model degrades to the XLA block — counted, not
        # silent (one predicate, shared with fd_phase_engaged).
        fd_reason = fd_fallback_reason(cfg)
        if fd_reason is not None:
            pallas_fallbacks[fd_reason] += 1
    if use_pallas:
        diag = None
        w, hb = state.w, state.hb_known
        # Packed rung on the kernel path: the first sub-exchange's
        # refresh operand is the per-owner WRITE BUMP — the kernel
        # applies gossip._packed_writes_shift (saturating) and
        # _packed_diag_zero on the nibbles in VMEM — instead of the
        # unpacked rungs' max_version row.
        kernel_refresh_vec = (
            (max_version - state.max_version)[owners] if packed else mv_vec
        )
    elif packed:
        diag = jnp.arange(n, dtype=jnp.int32)[:, None] == owners[None, :]
        w = state.w
        if cfg.writes_per_round != 0 or sw_wpr is not None:
            # Owner writes raised max_version above; every observer's
            # residual rises with it (its watermark didn't move).
            w = _packed_writes_shift(w, max_version - state.max_version,
                                     owners)
        w = _packed_diag_zero(w, owners, n)
        hb = (
            jnp.where(
                diag,
                hbv_vec[None, :].astype(state.hb_known.dtype),
                state.hb_known,
            )
            if track_hb
            else state.hb_known
        )
    else:
        diag = jnp.arange(n, dtype=jnp.int32)[:, None] == owners[None, :]
        w = jnp.where(diag, mv_vec[None, :].astype(state.w.dtype), state.w)
        hb = (
            jnp.where(
                diag,
                hbv_vec[None, :].astype(state.hb_known.dtype),
                state.hb_known,
            )
            if track_hb
            else state.hb_known
        )
    hb_round_start = hb

    # Scheduled-for-deletion mask from the PRE-round belief (the reference
    # recomputes it from the FD's dead set at syn time each round): rows
    # that have believed owner j dead for >= half the grace stop sending
    # j's state and stop advertising j's heartbeat in their digests.
    lifecycle = _lifecycle_enabled(cfg)
    sched = scheduled_for_deletion_mask(state, cfg, tick)
    kernel_flag = None  # set when the pairs kernel carries the check
    kernel_fd = None  # set when the fused FD rides the last sub-exchange

    rows = jnp.arange(n, dtype=jnp.int32)

    def peer_adv(w, peer, salt, active=None, pair_ok=None):
        """The budgeted watermark advance of each row toward its peer row
        (one handshake direction), masked to alive pairs, to the fault
        plan's link permits (traffic peer -> row), to cadence (an
        off-cadence pair skips the round), to owner columns the sender
        has not scheduled for deletion, and to the byzantine guard
        blocks (rejected poison advances nothing — but the budget was
        spent negotiating for it, so blocked columns still consume
        their share, exactly like a runtime MTU wasted on rejected
        key-values). ``active`` (scalar bool) voids the whole
        sub-exchange — how a lane whose swept fanout is below the
        static bound skips its excess sub-exchanges; ``pair_ok`` (N,)
        is the cadence gate."""
        valid = eff_alive & eff_alive[peer]
        if active is not None:
            valid = valid & active
        if pair_ok is not None:
            valid = valid & pair_ok
        f_ok = fault_ok(peer, rows, salt)
        if f_ok is not None:
            valid = valid & f_ok
        adv = _budgeted_advance(
            w, w[peer, :], cfg.budget, valid, axis_name,
            cfg.budget_policy, salt, owners, run_salt,
            col_ok=None if sched is None else ~sched[peer, :],
        )
        blk = byz_pull_block(peer, salt)
        if blk is not None:
            adv = jnp.where(blk, 0, adv)
        return adv, valid

    def packed_peer_adv(r, peer, salt, active=None, pair_ok=None):
        """peer_adv for the packed u4 residual rung: gathers the PEER'S
        PACKED rows (0.5 B/pair — the only per-sub-exchange HBM
        transient) and computes the budgeted advance on the nibbles.
        The lifecycle's column mask never applies, and neither do the
        byzantine blocks (the config excludes both from this rung)."""
        valid = eff_alive & eff_alive[peer]
        if active is not None:
            valid = valid & active
        if pair_ok is not None:
            valid = valid & pair_ok
        f_ok = fault_ok(peer, rows, salt)
        if f_ok is not None:
            valid = valid & f_ok
        a_lo, a_hi = _packed_adv_halves(
            r, r[peer, :], cfg.budget, valid, axis_name, salt, owners,
            run_salt,
        )
        return a_lo, a_hi, valid

    def hb_absorb(hb, peer, valid, salt=None):
        ok = valid[:, None]
        if sched is not None:
            ok = ok & ~sched[peer, :]
        if salt is not None:
            hblk = byz_hb_mask(peer, salt)
            if hblk is not None:
                ok = ok & ~hblk
        return jnp.maximum(hb, jnp.where(ok, hb[peer, :], 0))

    def sub_salt(c: int, direction: int) -> jax.Array:
        # A swept fanout feeds the lane's OWN value into the dither-salt
        # schedule, so the lane's salts equal a sequential run with that
        # static fanout (the salt spacing is 2 * fanout per tick).
        f = cfg.fanout if sw_fanout is None else sw_fanout
        return (tick * (2 * f) + 2 * c + direction).astype(jnp.int32)

    def sub_active(c: int) -> jax.Array | None:
        """Scalar bool: does sub-exchange ``c`` run for this lane?
        None (all run) unless the lane sweeps fanout below the static
        bound."""
        return None if sw_fanout is None else (c < sw_fanout)

    # -- fanout sub-exchanges (both handshake directions per pair) -----------
    if cfg.pairing in ("permutation", "matching") and adjacency is None:
        from . import pallas_pull

        dual = cfg.pairing == "permutation"
        # The grouped family is used exactly on the kernel's domain so
        # flipping use_pallas never changes a trajectory; off it (or at
        # tiny n, where few groups would throttle mixing — one
        # self-matched group's only involution rotations are 0 and 4,
        # which disconnect the pairs) matching stays unrestricted.
        grouped = cfg.pairing == "matching" and n % 128 == 0
        # Interpreter mode off-TPU so the same config runs (slowly) in
        # CPU tests; the axon platform is a TPU PJRT plugin.
        interpret = not on_accelerator()
        # Static FD constants for the fused epilogue (python scalars —
        # part of the kernel's jit key, hoisted out of the loop).
        fused_fd_params = (
            (
                float(cfg.max_interval_ticks),
                int(cfg.window_ticks),
                float(cfg.prior_weight),
                float(cfg.prior_mean_ticks),
                # Shrunk-rung liveness: the epilogue writes the column
                # BITMAP (sim/packed.pack_bits layout) straight from
                # VMEM — the bool matrix never lands in HBM.
                bool(cfg.live_bits),
            )
            if fd_phase == "fused"
            else None
        )
        for c in range(cfg.fanout):
            ck = random.fold_in(peer_key, c)
            gm8 = c8 = None
            if dual:
                # Initiator i talks to p[i]; the responder role is the
                # pull through the inverse permutation. Both exchanges
                # are computed from the pre-round state and joined with
                # an elementwise max — as in the reference handshake,
                # where both sides' deltas derive from the pre-handshake
                # digests — so they fuse into one pass over w.
                p = random.permutation(ck, n)
                inv = jnp.argsort(p)
            else:
                # Random perfect matching (p an involution): one
                # bidirectional handshake per node — i's pull from p[i]
                # IS the pair's full exchange, because row p[i] pulls
                # from i in the same vectorized op. Half the traffic of
                # "permutation" per sub-exchange. Drawn from the
                # 8-row-group family when shapes allow so the XLA and
                # Pallas paths share one trajectory.
                if grouped:
                    gm8, c8, p = _grouped_matching(ck, n)
                else:
                    p = _random_matching(ck, n)
                inv = p
            if use_pallas:
                # The first sub-exchange carries the diagonal refresh
                # (later ones see it in w/hb themselves).
                first = c == 0
                last = c == cfg.fanout - 1
                valid_pair = eff_alive & eff_alive[p]
                if cad is not None:
                    # Cadence gate: a matched pair exchanges when either
                    # side is on-cadence this tick (the quiet side still
                    # responds). Folds into the kernel's validity mask,
                    # so cadence classes keep the fused path engaged.
                    valid_pair = valid_pair & (cad | cad[p])
                # A lane sweeping fanout below the static bound voids
                # its excess sub-exchanges by zeroing the alive-pair
                # mask — the kernel then writes identical tiles back
                # (adv = 0, hb max against 0), exactly the XLA
                # sub_active no-op.
                act = sub_active(c)
                if act is not None:
                    valid_pair = valid_pair & act
                # shards is STATIC (both n and n_local are trace-time
                # shapes): a one-shard mesh runs the plain single-pass
                # kernel — its in-kernel row sum IS the global total —
                # so single-chip "sharded" runs pay no two-pass tax.
                shards = n // n_local
                # The pair-fused kernels visit both sides of each
                # matched pair in one pass — 2/3 the HBM traffic of the
                # single-pass form, bit-identical
                # (tests/test_pallas_pairs.py). One decision function
                # shared with bench's provenance labelling.
                use_pairs = (
                    pallas_variant_engaged(cfg, axis_name, n_local)
                    == "pairs"
                )
                # The fused round: the LAST pairs sub-exchange also
                # runs the whole FD phase on the tiles it already
                # holds (fd_phase_engaged said "fused"), so the
                # separate FD pass over the heartbeat matrices
                # disappears (ops/pallas_fd.py stays the standalone
                # fallback for non-pairs paths).
                fd_here = fd_phase == "fused" and last
                if axis_name is not None and shards > 1:
                    # Two-pass sharded form: local deficit totals
                    # (streaming pass, no writes), one psum — the only
                    # ICI traffic — then the apply pass with the global
                    # totals. Bit-identical to the XLA sharded path's
                    # psum(d.sum(axis=1)) pipeline.
                    if use_pairs:
                        tops = {
                            "w": w, "gm": gm8, "c": c8,
                            "valid": valid_pair,
                            "owner_offset": owners[0],
                        }
                        if first:
                            tops["mv"] = kernel_refresh_vec
                        tot = pallas_pull.pairs_totals(
                            tops, interpret=interpret
                        )
                    else:
                        tot = pallas_pull.fused_pull_totals_m8(
                            w, gm8, c8, valid_pair, interpret=interpret,
                            mv=mv_vec if first else None,
                            owner_offset=owners[0],
                        )
                    tot = lax.psum(tot, axis_name)
                else:
                    tot = None
                # The round's LAST pairs call can also evaluate the
                # convergence flag on its output tiles (w is final
                # after the sub-exchanges on this path — no lifecycle),
                # so tracked runs pay no separate full read of w.
                carry_check = use_pairs and return_converged and last
                if use_pairs:
                    ops = {
                        "w": w,
                        "gm": gm8,
                        "c": c8,
                        "valid": valid_pair,
                        "salt": sub_salt(c, 0),
                        "run_salt": run_salt,
                        "owner_offset": owners[0],
                    }
                    if track_hb:
                        ops["hb"] = hb
                    if first:
                        ops["mv"] = kernel_refresh_vec
                        if track_hb:
                            ops["hbv"] = hbv_vec
                    if tot is not None:
                        ops["totals"] = tot
                    if carry_check:
                        ops["need"] = mv_vec
                        ops["alive"] = eff_alive
                        ops["alive_owner"] = eff_alive[owners]
                    fd_params = None
                    if fd_here:
                        ops["tick"] = tick
                        ops["lc"] = state.last_change
                        ops["im"] = state.imean
                        ops["ic"] = state.icount
                        ops["hbv"] = hbv_vec  # hb0's diagonal refresh
                        ops["phi"] = (
                            jnp.asarray(cfg.phi_threshold, jnp.float32)
                            if sw_phi is None
                            else sw_phi
                        )
                        if cfg.fanout > 1:
                            # fanout == 1: the kernel's input hb IS the
                            # round-start matrix — no extra stream.
                            ops["hb0"] = hb_round_start
                        fd_params = fused_fd_params
                    # The FD phase reads the round-start hb after the
                    # loop unless it fused into this very call:
                    # aliasing hb on the first sub-exchange would make
                    # XLA copy the retained buffer — two extra hb
                    # passes, worse than the plain write. With fused FD
                    # at fanout == 1 nothing after this call reads the
                    # input hb, so it aliases like any other.
                    retain_start = cfg.track_failure_detector and not (
                        fd_phase == "fused" and cfg.fanout == 1
                    )
                    flat = pallas_pull.pairs_pull(
                        ops,
                        budget=cfg.budget,
                        interpret=interpret,
                        alias_hb=not (first and retain_start),
                        fd_params=fd_params,
                    )
                    i = 0
                    w = flat[i]
                    i += 1
                    if track_hb:
                        hb = flat[i]
                        i += 1
                    if fd_here:
                        kernel_fd = flat[i : i + 4]
                        i += 4
                    if carry_check:
                        kernel_flag = flat[i]
                else:
                    pulled = pallas_pull.fused_pull_m8(
                        w, hb if track_hb else None, gm8, c8,
                        valid_pair, sub_salt(c, 0), run_salt,
                        cfg.budget, interpret=interpret,
                        mv=mv_vec if first else None,
                        hbv=hbv_vec if first and track_hb else None,
                        owner_offset=owners[0],
                        totals=tot,
                    )
                    w, hb = pulled if track_hb else (pulled, hb)
            elif dual:
                # Cadence: the i -> p[i] handshake is INITIATED by row i,
                # the inverse pull belongs to the handshake initiated by
                # inv[i] — each direction is gated by its initiator's
                # cadence (responders always serve).
                cad_p = cad
                cad_i = None if cad is None else cad[inv]
                if packed:
                    pl, ph, valid_p = packed_peer_adv(
                        w, p, sub_salt(c, 0), sub_active(c), cad_p
                    )
                    il, ih, valid_i = packed_peer_adv(
                        w, inv, sub_salt(c, 1), sub_active(c), cad_i
                    )
                    w = _packed_apply(
                        w, jnp.maximum(pl, il), jnp.maximum(ph, ih)
                    )
                else:
                    adv_p, valid_p = peer_adv(
                        w, p, sub_salt(c, 0), sub_active(c), cad_p
                    )
                    adv_i, valid_i = peer_adv(
                        w, inv, sub_salt(c, 1), sub_active(c), cad_i
                    )
                    w = w + jnp.maximum(adv_p, adv_i)
                if track_hb:
                    hb = jnp.maximum(
                        hb_absorb(hb, p, valid_p, sub_salt(c, 0)),
                        hb_absorb(hb, inv, valid_i, sub_salt(c, 1)),
                    )
            else:
                # Matching: one bidirectional handshake per pair — it
                # runs when either side is on-cadence.
                cad_pair = None if cad is None else cad | cad[p]
                if packed:
                    a_lo, a_hi, valid = packed_peer_adv(
                        w, p, sub_salt(c, 0), sub_active(c), cad_pair
                    )
                    w = _packed_apply(w, a_lo, a_hi)
                else:
                    adv, valid = peer_adv(
                        w, p, sub_salt(c, 0), sub_active(c), cad_pair
                    )
                    w = w + adv
                if track_hb:
                    hb = hb_absorb(hb, p, valid, sub_salt(c, 0))
    else:
        # Independent choice (reference semantics: inbound load varies) or
        # adjacency-constrained topology; responder side needs scatter-max.
        live_view = state.live_view if cfg.track_failure_detector else None
        # View-mode salts live in the negatives so they never collide with
        # the budget dither's non-negative sub_salt space.
        view_salt = (-(tick + 1) * cfg.fanout).astype(jnp.int32)
        # Breaker quarantine (docs/robustness.md): the runtime circuit
        # breaker lowered to a peer-selection mask — quarantined peers
        # leave the target draw instead of burning a no-op exchange,
        # exactly like runtime/peers.py under the same plan. Static
        # predicate: a plan with nothing to quarantine keeps the
        # unmasked draw (and its exact bit-stream).
        sel_alive = eff_alive
        quarantine_active = False
        if cfg.quarantine:
            from ..faults.sim import plan_quarantines, quarantine_mask

            if adjacency is not None:
                raise ValueError(
                    "quarantine is not supported with a topology (the "
                    "adjacency draw carries no per-peer mask)"
                )
            if plan_quarantines(plan):
                quarantine_active = True
                sel_alive = eff_alive & ~quarantine_mask(
                    plan, n, tick, open_after=cfg.quarantine_open_after
                )
        peers = select_peers(
            peer_key, sel_alive, live_view, cfg, adjacency, degrees,
            axis_name=axis_name, view_salt=view_salt, run_salt=run_salt,
            force_masked=quarantine_active,
        )

        def exchange(c, carry: tuple[jax.Array, jax.Array]):
            w, hb = carry
            p = peers[:, c]
            valid = eff_alive & eff_alive[p]
            if cad is not None:
                # Choice pairing: row i initiates this handshake, so its
                # cadence gates BOTH directions (responders always serve
                # but never initiate).
                valid = valid & cad
            salt_in = sub_salt(0, 0) + 2 * c
            salt_out = sub_salt(0, 1) + 2 * c
            # Per-direction fault permits: the two halves of one
            # handshake can fail independently (asymmetric links).
            f_in = fault_ok(p, rows, salt_in)
            f_out = fault_ok(rows, p, salt_out)
            valid_in = valid if f_in is None else valid & f_in
            valid_out = valid if f_out is None else valid & f_out
            w_peer = w[p, :]
            ok_from_peer = None if sched is None else ~sched[p, :]
            adv_in = _budgeted_advance(
                w, w_peer, cfg.budget, valid_in, axis_name,
                cfg.budget_policy, salt_in, owners, run_salt,
                col_ok=ok_from_peer,
            )
            adv_out = _budgeted_advance(
                w_peer, w, cfg.budget, valid_out, axis_name,
                cfg.budget_policy, salt_out, owners, run_salt,
                col_ok=None if sched is None else ~sched,
            )
            hb_blk_in = hb_blk_out = None
            if byz_active:
                from ..faults.sim import byz_hb_block, byz_out_block

                # Inbound direction: row i receives from sender p[i].
                blk_in = byz_pull_block(p, salt_in)
                if blk_in is not None:
                    adv_in = jnp.where(blk_in, 0, adv_in)
                # Outbound direction: p[i] receives from sender i — the
                # sender-side blocks index by row i (the delta is built
                # there and scattered to p), the receiver-side
                # inflation starvation gathers the receiver's rows.
                blk_out = byz_out_block(
                    plan, n, tick, rows, owners, salt_out,
                    seed=sw_fault_seed, byz_frac=sw_byz,
                )
                if byz_in is not None:
                    at_p = byz_in[p, :]
                    blk_out = at_p if blk_out is None else blk_out | at_p
                if blk_out is not None:
                    adv_out = jnp.where(blk_out, 0, adv_out)
                hb_blk_in = byz_hb_mask(p, salt_in)
                hb_blk_out = byz_hb_block(
                    plan, n, tick, rows, owners, salt_out,
                    seed=sw_fault_seed, byz_frac=sw_byz,
                )
            w_next = w + adv_in  # initiator applies the responder's delta
            w_next = w_next.at[p].max(w_peer + adv_out)  # responder applies ours
            if track_hb:
                hb_peer = hb[p, :]
                in_col = valid_in[:, None]
                out_col = valid_out[:, None]
                in_ok = in_col if sched is None else in_col & ok_from_peer
                out_ok = out_col if sched is None else out_col & ~sched
                if hb_blk_in is not None:
                    in_ok = in_ok & ~hb_blk_in
                if hb_blk_out is not None:
                    out_ok = out_ok & ~hb_blk_out
                hb_next = jnp.maximum(hb, jnp.where(in_ok, hb_peer, 0))
                hb_next = hb_next.at[p].max(jnp.where(out_ok, hb, 0))
            else:
                hb_next = hb
            return w_next, hb_next

        w, hb = lax.fori_loop(0, cfg.fanout, exchange, (w, hb), unroll=True)

    # -- vectorized phi-accrual failure detection ----------------------------
    if fd_phase == "fused":
        # The FD phase already rode the round's last pairs sub-exchange
        # (one Pallas dispatch for pull + FD — the fused round): the
        # kernel updated last_change/imean/icount in place and wrote
        # the live matrix while it still held every post-exchange hb
        # tile in VMEM, so the separate pass over the heartbeat
        # matrices never runs (tests/test_fused_kernel.py pins
        # bit-identity to the XLA block).
        assert kernel_fd is not None
        last_change, imean, icount, live = kernel_fd
        dead_since = state.dead_since
    elif fd_phase == "kernel":
        # Standalone streaming FD kernel — the fallback when the pull
        # phase is not pairs-served (bit-identical to the XLA block
        # below — tests/test_pallas_fd.py). Runs per shard under
        # shard_map, with this shard's owner offset.
        from . import pallas_fd

        last_change, imean, icount, live = pallas_fd.fused_fd(
            tick,
            hb,
            hb_round_start,
            hbv_vec,
            state.last_change,
            state.imean,
            state.icount,
            max_interval=cfg.max_interval_ticks,
            window=cfg.window_ticks,
            prior_weight=cfg.prior_weight,
            prior_mean=cfg.prior_mean_ticks,
            phi_threshold=cfg.phi_threshold,
            interpret=not on_accelerator(),
            owner_offset=owners[0],
        )
        dead_since = state.dead_since
    elif cfg.track_failure_detector:
        if diag is None:
            # The pull kernel carried the diagonal refresh, so the saved
            # round-start matrix is missing it — re-derive here (the
            # where fuses into this block's elementwise chain).
            diag = jnp.arange(n, dtype=jnp.int32)[:, None] == owners[None, :]
            hb_round_start = jnp.where(
                diag, hbv_vec[None, :].astype(hb.dtype), hb_round_start
            )
        increased = hb > hb_round_start
        never_seen = state.last_change == 0
        interval = (tick - state.last_change).astype(jnp.float32)
        sampled = increased & ~never_seen & (interval <= cfg.max_interval_ticks)
        # Running (mean, count) form of the ring-buffer window
        # (core/failure.py BoundedWindow): below the cap this is the exact
        # running mean; at the cap the update mean += (x - mean)/window is
        # exactly the old sum-form with one window-mean's worth of mass
        # evicted per new sample.
        icount = jnp.minimum(
            state.icount + sampled.astype(state.icount.dtype),
            jnp.asarray(cfg.window_ticks, state.icount.dtype),
        )
        mean_f32 = imean_f32(state.imean)
        denom = jnp.maximum(icount.astype(jnp.float32), 1.0)
        imean = jnp.where(
            sampled, mean_f32 + (interval - mean_f32) / denom, mean_f32
        )
        last_change = jnp.where(
            increased, tick.astype(state.last_change.dtype), state.last_change
        )
        count_f32 = icount.astype(jnp.float32)
        # live ⟺ phi = elapsed / prior_mean <= threshold, tested in
        # cross-multiplied form (prior_mean > 0 always): two f32 divides
        # per element become multiplies — the FD phase is VPU-bound, and
        # divides are its dominant cost (measured on v5e, round 2). The
        # ~1-ulp boundary shift vs the divide form is inside the noise of
        # an 8.0 heuristic threshold.
        elapsed = (tick - last_change).astype(jnp.float32)
        # A swept phi threshold replaces the static scalar in the same
        # f32 product — a python float and a traced float32 of the same
        # value promote identically, so lanes match sequential runs.
        phi = cfg.phi_threshold if sw_phi is None else sw_phi
        live = (icount >= 1) & (
            elapsed * (count_f32 + cfg.prior_weight)
            <= phi
            * (imean * count_f32 + cfg.prior_weight * cfg.prior_mean_ticks)
        )
        live = live | diag  # self-belief (elementwise, not a scatter)
        # Going (or staying) dead wipes the window: a returning node must
        # re-earn liveness with fresh samples (core/failure.py reset rule).
        imean = jnp.where(live, imean, 0.0).astype(state.imean.dtype)
        icount = jnp.where(live, icount, jnp.asarray(0, state.icount.dtype))
        if lifecycle:
            # Dead-stamp on the live->dead transition, but only for KNOWN
            # nodes (present in the observer's "cluster state", i.e. some
            # watermark or heartbeat observed) — the reference only runs
            # liveness over nodes it has state for — and only for ALIVE
            # observer rows: a dead node's process isn't running its FD,
            # so its bookkeeping freezes until revival (otherwise a dead
            # row would watch every heartbeat stall, stamp the whole
            # cluster and garbage-collect its own state). Re-earning
            # liveness discards the stamp (FD dead-set pop).
            # eff_alive: a node inside a fault-plan crash window isn't
            # running its FD either, so its bookkeeping freezes too.
            row_alive = eff_alive[:, None]
            known = ((w > 0) | (hb > 0)) & row_alive
            ds = jnp.where(
                live,
                0,
                jnp.where(
                    (state.dead_since == 0) & known,
                    tick,
                    state.dead_since.astype(jnp.int32),
                ),
            )
            # Full grace elapsed: forget the node — remove_node analogue.
            # Watermark, heartbeat knowledge and FD bookkeeping all reset;
            # if some straggler row later re-sends the state, the node is
            # re-created from scratch, exactly like the reference.
            gc_now = (ds > 0) & ((tick - ds) >= cfg.dead_grace_ticks) & row_alive
            w = jnp.where(gc_now, 0, w)
            hb = jnp.where(gc_now, 0, hb)
            last_change = jnp.where(gc_now, 0, last_change)
            dead_since = jnp.where(gc_now, 0, ds).astype(state.dead_since.dtype)
        else:
            dead_since = state.dead_since
        if cfg.live_bits:
            # Bit-packed liveness storage (the shrunk-FD rung): the bool
            # matrix above is a fusion intermediate; only the bitmap
            # lands in HBM (1 bit/pair).
            live = pack_bits(live)
    else:
        last_change, imean, icount, live, dead_since = (
            state.last_change,
            state.imean,
            state.icount,
            state.live_view,
            state.dead_since,
        )

    new_state = SimState(
        tick=tick,
        max_version=max_version,
        heartbeat=heartbeat,
        alive=alive,
        w=w,
        hb_known=hb,
        last_change=last_change,
        imean=imean,
        icount=icount,
        live_view=live,
        dead_since=dead_since,
    )
    if not return_converged:
        return new_state
    if kernel_flag is not None:
        # The pairs kernel evaluated the check on its output tiles
        # (nothing after the sub-exchanges touches w/alive/max_version
        # on that path); reduce across shards exactly like
        # all_converged_flag.
        f = kernel_flag
        if axis_name is not None:
            f = lax.pmin(f, axis_name)
        return new_state, f > 0
    return new_state, all_converged_flag(new_state, axis_name)


def all_converged_flag(
    state: SimState, axis_name: str | None = None
) -> jax.Array:
    """Scalar bool: every alive node's watermark has reached every alive
    owner's max_version — the cheap single-pass form of
    ``convergence_metrics()["all_converged"]`` (same excusals: dead
    observers and dead owners). Used by the in-chunk exact convergence
    tracker, where it runs once per ROUND, so it must stay one fused
    read of w (no fraction/mean reductions). On the packed u4 rung the
    check is nibble == 0 (a zero residual IS "caught up"), read straight
    off the bytes — no widening."""
    n_local = state_n_local(state)
    owners = _local_owner_ids(n_local, axis_name)
    if is_packed_w(state.w):
        row_dead = ~state.alive[:, None]
        lo_ok = (
            ((state.w & 0xF) == 0)
            | row_dead
            | ~state.alive[owners[0::2]][None, :]
        )
        hi_ok = (
            ((state.w >> 4) == 0)
            | row_dead
            | ~state.alive[owners[1::2]][None, :]
        )
        flag = lo_ok.all() & hi_ok.all()
    else:
        needed = state.max_version[owners][None, :]
        ok = (
            (state.w >= needed)
            | ~state.alive[:, None]
            | ~state.alive[owners][None, :]
        )
        flag = ok.all()
    if axis_name is not None:
        flag = lax.pmin(flag.astype(jnp.int32), axis_name) > 0
    return flag


def convergence_metrics(
    state: SimState, axis_name: str | None = None
) -> dict[str, jax.Array]:
    """How replicated the cluster is right now.

    An owner counts as converged when every alive node's watermark has
    reached the owner's max_version (dead observers and dead owners are
    excused). ``min_fraction`` is the worst watermark/max_version ratio
    over alive pairs — the sim's staleness_score analogue.

    Rung-agnostic: the packed u4 residual rung decodes through the
    sanctioned widen helper (sim/packed.py) — this is a metrics pass,
    sampled at the obs stride, not the hot loop.
    """
    n_local = state_n_local(state)
    owners = _local_owner_ids(n_local, axis_name)
    wv = watermarks_i32(state, owners)
    needed = state.max_version[owners][None, :]
    alive_rows = state.alive[:, None]
    caught_up = (wv >= needed) | ~alive_rows
    owner_ok = caught_up.all(axis=0) | ~state.alive[owners]
    frac = jnp.where(
        alive_rows & state.alive[owners][None, :],
        wv / jnp.maximum(needed, 1),
        1.0,
    )
    pair_mask = alive_rows & state.alive[owners][None, :]
    frac_sum = jnp.sum(jnp.where(pair_mask, jnp.minimum(frac, 1.0), 0.0))
    pair_count = jnp.sum(pair_mask)
    n_converged = owner_ok.sum()
    min_frac = frac.min()
    # Total key-versions replicated across alive pairs (capped at each
    # owner's max): differencing consecutive samples gives the
    # key-versions the gossip moved per window — the sim analogue of the
    # runtime's delta_key_values counter. f32 sum: an ESTIMATE above
    # ~2^24 total (fine for telemetry; convergence decisions never read
    # this).
    kv_known = jnp.sum(
        jnp.where(
            pair_mask,
            jnp.minimum(wv, needed).astype(jnp.float32),
            0.0,
        )
    )
    # Failure-detector false positives: alive (observer, owner) pairs
    # the observer currently believes dead (self excused). THE liveness
    # quality datum the byzantine tolerance atlas maps against
    # phi_threshold (stale heartbeat adverts starve the FD) — zero at a
    # quiet steady state, elevated under attack or an aggressive
    # threshold. Keys present only when the config tracks the FD (the
    # zero-sized live_view makes the branch trace-static).
    fd_fp = fd_denom = None
    if state.live_view.size:
        from ..sim.packed import live_view_bool

        lv = live_view_bool(state)
        rows_idx = jnp.arange(state.alive.shape[0], dtype=jnp.int32)[:, None]
        off_diag = rows_idx != owners[None, :]
        fp_pairs = pair_mask & off_diag & ~lv
        fd_fp = jnp.sum(fp_pairs)
        fd_denom = jnp.sum(pair_mask & off_diag)
    if axis_name is not None:
        n_converged = lax.psum(n_converged, axis_name)
        min_frac = lax.pmin(min_frac, axis_name)
        frac_sum = lax.psum(frac_sum, axis_name)
        pair_count = lax.psum(pair_count, axis_name)
        kv_known = lax.psum(kv_known, axis_name)
        if fd_fp is not None:
            fd_fp = lax.psum(fd_fp, axis_name)
            fd_denom = lax.psum(fd_denom, axis_name)
    total = state.alive.shape[0]
    out = {
        "converged_owners": n_converged,
        "all_converged": n_converged == total,
        "min_fraction": jnp.minimum(min_frac, 1.0),
        "mean_fraction": frac_sum / jnp.maximum(pair_count, 1),
        "alive_count": state.alive.sum(),
        "kv_known": kv_known,
    }
    if fd_fp is not None:
        out["fd_false_positives"] = fd_fp
        out["fd_false_positive_fraction"] = fd_fp / jnp.maximum(fd_denom, 1)
    return out


def version_spread(
    state: SimState, axis_name: str | None = None
) -> jax.Array:
    """Worst version lag over alive (observer, owner) pairs: how many
    key-versions the most stale alive replica still misses. 0 at full
    convergence; the obs layer samples it as the sim's staleness-depth
    gauge (companion to convergence_metrics' fractions, which normalise
    this away).

    One lag computation serves both staleness views: this is the max of
    the per-node :func:`staleness_tensor` (a masked-lag fix lands in
    one place, not two)."""
    return staleness_tensor(state, axis_name).max()


def staleness_tensor(
    state: SimState, axis_name: str | None = None
) -> jax.Array:
    """Per-node staleness: how many key-versions node ``i`` lags behind
    the alive owner it is MOST behind on — ``max_j alive
    (max_version[j] - w[i, j])`` — as an (N,) int32 vector (0 for dead
    observers and at full convergence). The per-node refinement of
    :func:`version_spread` (whose value is this tensor's max): the
    fleet-staleness distribution an operator alerts on, not just its
    worst point.

    Rung-agnostic: the packed u4 residual rung decodes through the
    sanctioned widen helper (sim/packed.py) — a metrics pass sampled at
    the obs stride, not the hot loop. Sharded meshes reduce each
    observer row's max over local owner columns, then ``pmax`` across
    shards — the tensor is bit-identical to the unsharded one
    (benchmarks/propagation_bench.py pins it against a host oracle)."""
    n_local = state_n_local(state)
    owners = _local_owner_ids(n_local, axis_name)
    needed = state.max_version[owners][None, :]
    pair_mask = state.alive[:, None] & state.alive[owners][None, :]
    lag = jnp.where(pair_mask, needed - watermarks_i32(state, owners), 0)
    per_node = jnp.maximum(lag.max(axis=1), 0)
    if axis_name is not None:
        per_node = lax.pmax(per_node, axis_name)
    return per_node


def staleness_percentiles(
    state: SimState, axis_name: str | None = None
) -> dict[str, jax.Array]:
    """The staleness tensor compressed to nearest-rank percentile
    scalars (``staleness_p50``/``p99``/``p100``) — the stride-sample
    bundle's keys, still device values (no host sync). Rank indices are
    host arithmetic on the STATIC node count, and the picks index one
    device sort — so the values bit-match a host oracle doing
    ``np.sort`` + the same nearest-rank formula
    (obs.registry.percentile_of_sorted) on the widened state. The
    percentile set is single-sourced with the gauge exporter
    (obs.sim.STALENESS_PCTS)."""
    from ..obs.sim import STALENESS_PCTS

    per_node = staleness_tensor(state, axis_name)
    ordered = jnp.sort(per_node)
    n = int(per_node.shape[0])
    return {
        f"staleness_p{label}": ordered[_nearest_rank(n, q)]
        for label, q in STALENESS_PCTS
    }


def _nearest_rank(n: int, q: float) -> int:
    """Nearest-rank pick index over n sorted values — the same formula
    as obs.registry.percentile_of_sorted, on pure host ints (n is the
    STATIC node count; no device value is touched)."""
    return min(n - 1, int(q * (n - 1) + 0.5))
