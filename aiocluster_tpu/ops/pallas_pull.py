"""Fused Pallas TPU kernel for one gossip sub-exchange (grouped matching).

The XLA path of ops/gossip.py executes a matching sub-exchange as several
separate passes over the (N, N) matrices: peer-row gathers for w and hb
(each a full-matrix read AND write of the materialized gather), a
deficit-total reduction, the dithered advance, and the heartbeat absorb.
This kernel performs the whole sub-exchange in ONE pass over HBM per
matrix: each row block is read once, peer rows arrive by direct
HBM->VMEM DMA (never materialized in HBM), and the budget math runs
entirely in VMEM.

Why GROUPED matching: Mosaic (the Pallas TPU compiler) can only DMA row
slices aligned to the 8-sublane tile — a single random row of an int16
matrix is not a legal copy. So the matching is drawn from the
8-row-group family (gossip._grouped_matching): groups of 8 rows are
matched uniformly, and rows within a matched group pair are assigned by
a per-pair rotation. Every peer fetch is then an aligned (8, n) slice,
and the rotation is applied in VMEM with the TPU's dynamic sublane
rotate. 8-row alignment suffices for BOTH int32 and int16: narrow
dtypes pack pairs within the 8-sublane tile ((8,128)(2,1) tiling), so
any multiple-of-8 row offset is a whole-tile boundary — verified on
hardware with odd multiples of 8 into int16 memrefs, exact results. The XLA path uses the same family on the kernel's whole domain
(n % 128 == 0), so the
kernel's output is exactly equal to the XLA path's (asserted in
tests/test_pallas_pull.py) and flipping use_pallas never changes a
trajectory.

Bit-compatibility: the advance formula and the (row, owner, salt) dither
hash are the same arithmetic as gossip._budgeted_advance /
gossip._hash_uniform. Single-device, proportional-budget, matching
pairing, no dead-node lifecycle — other configs stay on XLA (the
sim_step gate enforces this). Both storage profiles qualify: with
heartbeats the kernel fuses w and hb in one pass; the lean
convergence-only profile (hb=None) runs the w-only variant with half
the VMEM footprint.

Reference anchor: this is the hot loop of server.py:378-495 (the 3-way
handshake fan-out) collapsed into one tensor pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dither_base(shape, salt, run_salt) -> tuple[jax.Array, jax.Array]:
    """The group-invariant parts of gossip._hash_uniform's input mix,
    computed ONCE per kernel invocation and shared by every group (the
    uint32 multiplies are the expensive part of the hash on the VPU):
    ``r_k1 = r * K1`` for within-group row r, and ``js = j * K2 ^ s *
    K3`` for global column j. They stay separate because the global-row
    term folds in by ADDITION (``(row0 + r) * K1 = row0 * K1 + r * K1``
    mod 2^32) which does not distribute over the xor with ``js``."""
    s = salt.astype(jnp.uint32) ^ run_salt.astype(jnp.uint32)
    i = lax.broadcasted_iota(jnp.uint32, shape, 0)
    j = lax.broadcasted_iota(jnp.uint32, shape, 1)
    return (
        i * jnp.uint32(0x9E3779B1),
        j * jnp.uint32(0x85EBCA77) ^ s * jnp.uint32(0xC2B2AE3D),
    )


def _dither(r_k1: jax.Array, js: jax.Array, row0: jax.Array) -> jax.Array:
    """Same bits as gossip._hash_uniform for rows ``row0..row0+7``: one
    wrapping add + one xor per element recovers the full input mix from
    the precomputed parts; the avalanche + 24-bit mapping run per
    element as in the XLA path."""
    h = (r_k1 + row0.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)) ^ js
    h = (h ^ (h >> 15)) * jnp.uint32(0x27D4EB2F)
    h = h ^ (h >> 13)
    # Top 24 bits through int32: Mosaic has no uint32->float32 cast, and
    # float32 represents 24-bit integers exactly (same math as
    # gossip._hash_uniform — the paths must stay bit-identical).
    u = (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return jnp.clip(u, 1e-12, 1.0 - 2.0**-24)


def _advance(w_self32, w_peer32, valid_col, budget, r_k1, js, row0):
    """gossip._budgeted_advance, proportional policy, in int32/f32."""
    d = jnp.maximum(w_peer32 - w_self32, 0) * valid_col
    total = jnp.sum(d.astype(jnp.float32), axis=1, keepdims=True)
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))
    x = d.astype(jnp.float32) * scale
    floor = jnp.floor(x)
    bump = _dither(r_k1, js, row0) < (x - floor)
    return jnp.minimum(floor.astype(jnp.int32) + bump, d)


def _m8_kernel(
    # scalar prefetch
    gm_ref,  # (n/8,) partner group per group (involution)
    c_ref,  # (n/8,) within-pair row rotation
    meta_ref,  # [salt, run_salt, budget]
    # block inputs
    w_ref,
    hb_ref,
    valid_ref,  # (block, 1) int8 alive-pair mask per row
    mv_ref,  # (1, n) int32 owner max_version (diag refresh; dummy if off)
    hbv_ref,  # (1, n) int32 owner heartbeat (diag refresh; dummy if off)
    # HBM gather sources
    w_hbm,
    hb_hbm,
    # outputs
    wout_ref,
    hbout_ref,
    # scratch
    wp,
    hbp,
    sems,
    *,
    block: int,
    n: int,
    track_hb: bool,
    apply_diag: bool,
):
    gpb = block // 8  # groups per block
    g0 = pl.program_id(0) * gpb

    def gather(g, _):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[0, g]
        ).start()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(src, 8), :], hbp.at[pl.ds(g * 8, 8), :],
                sems.at[1, g],
            ).start()
        return 0

    def wait(g, _):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[0, g]
        ).wait()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(src, 8), :], hbp.at[pl.ds(g * 8, 8), :],
                sems.at[1, g],
            ).wait()
        return 0

    lax.fori_loop(0, gpb, gather, 0)

    salt = meta_ref[0]
    run_salt = meta_ref[1]
    budget = meta_ref[2].astype(jnp.float32)
    r_k1, js = _dither_base((8, n), salt, run_salt)
    col = lax.broadcasted_iota(jnp.int32, (8, n), 1)
    r8 = lax.broadcasted_iota(jnp.int32, (8, n), 0)

    # Per 8-row group: wait for its DMA just-in-time (later groups'
    # copies keep streaming behind this group's compute), rotate the
    # fetched partner group into row-pair order (w_peer[r] =
    # fetched[(r - c) % 8], i.e. roll by +c), then the row-independent
    # advance/absorb math on the (8, n) tile.
    for g in range(gpb):
        wait(g, 0)
        sl = slice(g * 8, (g + 1) * 8)
        cg = c_ref[g0 + g]
        row0 = pl.program_id(0) * block + g * 8
        vcol = valid_ref[sl, :].astype(jnp.int32)  # (8, 1)
        w_self = w_ref[sl, :].astype(jnp.int32)
        w_peer = pltpu.roll(wp[sl, :].astype(jnp.int32), cg, 0)
        if apply_diag:
            # Owner diagonal refresh, applied in VMEM instead of as a
            # separate materialized pass over HBM (the first sub-exchange
            # of the round carries it): at any (row, col=c) the diagonal
            # value IS mv[c], so one broadcast row + a column-iota
            # compare fixes the self tile; the peer tile's rows are
            # global rows 8*gm + (r - c) % 8, fixed the same way.
            self_rows = row0 + r8
            peer_rows = 8 * gm_ref[g0 + g] + ((r8 + 8 - cg) & 7)
            mv_b = mv_ref[:]
            w_self = jnp.where(col == self_rows, mv_b, w_self)
            w_peer = jnp.where(col == peer_rows, mv_b, w_peer)
        adv = _advance(w_self, w_peer, vcol, budget, r_k1, js, row0)
        wout_ref[sl, :] = (w_self + adv).astype(wout_ref.dtype)
        if track_hb:
            hb_self = hb_ref[sl, :].astype(jnp.int32)
            hb_peer = pltpu.roll(hbp[sl, :].astype(jnp.int32), cg, 0)
            if apply_diag:
                hbv_b = hbv_ref[:]
                hb_self = jnp.where(col == self_rows, hbv_b, hb_self)
                hb_peer = jnp.where(col == peer_rows, hbv_b, hb_peer)
            hbout_ref[sl, :] = jnp.maximum(hb_self, hb_peer * vcol).astype(
                hbout_ref.dtype
            )
    if not track_hb:
        hbout_ref[:] = hb_ref[:]  # dummy tile; outputs must be written


VMEM_BUDGET = 12 * 1024 * 1024  # ~16 MB/core, minus headroom for Mosaic

# (block, n)-sized VMEM buffers per matrix: pipelined in + out blocks
# (double-buffered, x2 each) plus one gather scratch -> 5; the lean
# (w-only) mode halves the total.
def _buffers(track_hb: bool) -> int:
    return 10 if track_hb else 5


def largest_fitting_block(n: int, per_row_bytes: int, cap: int = 512) -> int | None:
    """Largest multiple-of-8 divisor of n whose row count times
    ``per_row_bytes`` fits the VMEM budget. Shared block-search scaffold
    for every streaming kernel (this one and pallas_fd)."""
    limit = min(cap, VMEM_BUDGET // max(per_row_bytes, 1))
    best = None
    for b in range(8, limit + 1, 8):
        if n % b == 0:
            best = b
    return best


def _pick_block(
    n: int, itemsize: int = 4, cap: int = 512, track_hb: bool = True
) -> int | None:
    """Largest multiple-of-8 divisor of n such that every VMEM-resident
    buffer set fits the per-core budget."""
    return largest_fitting_block(n, _buffers(track_hb) * n * itemsize, cap)


def supported(n: int, itemsize: int, track_hb: bool = True) -> bool:
    """Whether the fused kernel can run this shape (callers fall back to
    the XLA path when not). Requires the grouped-matching family
    (n % 8 == 0 rows), lane-aligned manual DMA (n % 128 == 0 columns —
    Mosaic rejects copies of partial 128-lane tiles, and a non-multiple
    column count is a partial tile of the padded memref), and a legal
    VMEM block."""
    return n % 128 == 0 and _pick_block(n, itemsize, track_hb=track_hb) is not None


@functools.partial(jax.jit, static_argnames=("budget", "interpret"))
def fused_pull_m8(
    w: jax.Array,
    hb: jax.Array | None,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    salt: jax.Array,
    run_salt: jax.Array,
    budget: int,
    interpret: bool = False,
    mv: jax.Array | None = None,
    hbv: jax.Array | None = None,
):
    """One fused grouped-matching sub-exchange. Returns (w', hb'), or
    just w' when ``hb`` is None (the lean convergence-only profile: no
    heartbeat matrix exists, and the halved VMEM footprint buys larger
    row blocks).

    ``gm``/``c`` come from gossip._grouped_matching; ``valid`` is the
    per-row alive-pair mask (alive & alive[p]). Passing ``mv`` (owner
    max_version, (N,) int32; plus ``hbv``, owner heartbeats, when hb is
    tracked) folds the round's owner-diagonal refresh into this call —
    the caller must then NOT pre-apply the diagonal select, and should
    pass the vectors only on the round's FIRST sub-exchange (later ones
    see the refreshed diagonal in w itself).
    """
    track_hb = hb is not None
    apply_diag = mv is not None
    if apply_diag and track_hb and hbv is None:
        raise ValueError("hbv required when mv is given and hb is tracked")
    if hbv is not None and not track_hb:
        raise ValueError("hbv given but no hb matrix to refresh (lean mode)")
    if hbv is not None and mv is None:
        raise ValueError("hbv given without mv: the diagonal refresh is all-or-none")
    n = w.shape[0]
    itemsize = w.dtype.itemsize
    if track_hb:
        itemsize = max(itemsize, hb.dtype.itemsize)
    block = _pick_block(n, itemsize, track_hb=track_hb)
    if block is None or n % 128 != 0:
        raise ValueError(f"no suitable row block for n={n}")
    if not track_hb:
        # Minimal-tile dummies keep the kernel signature fixed without
        # spending VMEM (same trick the round-1 kernel used).
        hb = jnp.zeros((16, 128), w.dtype)
    hb_spec = (
        pl.BlockSpec((block, n), lambda i, *_: (i, 0))
        if track_hb
        else pl.BlockSpec((16, 128), lambda i, *_: (0, 0))
    )
    meta = jnp.stack(
        [
            salt.astype(jnp.int32),
            run_salt.astype(jnp.int32),
            jnp.asarray(budget, jnp.int32),
        ]
    )
    if apply_diag:
        mv = mv.astype(jnp.int32)[None, :]
        hbv = (
            hbv.astype(jnp.int32)[None, :]
            if track_hb
            else jnp.zeros((1, 128), jnp.int32)
        )
        vec_spec = pl.BlockSpec((1, n), lambda i, *_: (0, 0))
        hbv_spec = vec_spec if track_hb else pl.BlockSpec(
            (1, 128), lambda i, *_: (0, 0)
        )
    else:
        mv = jnp.zeros((1, 128), jnp.int32)
        hbv = jnp.zeros((1, 128), jnp.int32)
        vec_spec = hbv_spec = pl.BlockSpec((1, 128), lambda i, *_: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),  # w block
            hb_spec,  # hb block (dummy tile when lean)
            pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),  # valid col
            vec_spec,  # mv row (dummy tile when diag off)
            hbv_spec,  # heartbeat row (dummy tile when diag off / lean)
            pl.BlockSpec(memory_space=pl.ANY),  # w HBM (gather source)
            pl.BlockSpec(memory_space=pl.ANY),  # hb HBM
        ],
        out_specs=[
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),
            hb_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((block, n), w.dtype),
            pltpu.VMEM((block, n) if track_hb else (16, 128), hb.dtype),
            pltpu.SemaphoreType.DMA((2, block // 8)),
        ],
    )
    kernel = functools.partial(
        _m8_kernel, block=block, n=n, track_hb=track_hb, apply_diag=apply_diag
    )
    w_new, hb_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(w.shape, w.dtype),
            jax.ShapeDtypeStruct(hb.shape, hb.dtype),
        ],
        interpret=interpret,
    )(
        gm.astype(jnp.int32),
        c.astype(jnp.int32),
        meta,
        w,
        hb,
        valid.astype(jnp.int8)[:, None],
        mv,
        hbv,
        w,
        hb,
    )
    return (w_new, hb_new) if track_hb else w_new
