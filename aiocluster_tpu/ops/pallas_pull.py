"""Fused Pallas TPU kernel for one gossip sub-exchange.

The XLA path of ops/gossip.py executes a sub-exchange as several separate
passes over the (N, N) matrices: peer-row gathers for w and hb, a
deficit-total reduction, the dithered advance, and the heartbeat absorb.
This kernel performs the whole sub-exchange — both handshake directions —
in ONE pass over HBM per matrix: each row block is read once, its peer
rows are fetched by per-row DMA (sharing the same index for w and hb),
and the budget math runs entirely in VMEM.

Bit-compatibility: the advance formula and the (row, owner, salt) dither
hash are the same arithmetic as gossip._budgeted_advance /
gossip._hash_uniform, so the kernel's output is exactly equal to the XLA
path's (asserted in tests/test_pallas_pull.py). Single-device,
proportional-budget, permutation/matching pairing only — the sharded and
greedy paths stay on XLA.

Reference anchor: this is the hot loop of server.py:378-495 (the 3-way
handshake fan-out) collapsed into one tensor pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dither(rows: jax.Array, owners: jax.Array, salt, run_salt) -> jax.Array:
    """Same hash as gossip._hash_uniform, on explicit index grids."""
    i = rows.astype(jnp.uint32)
    j = owners.astype(jnp.uint32)
    s = salt.astype(jnp.uint32) ^ run_salt.astype(jnp.uint32)
    h = (
        i * jnp.uint32(0x9E3779B1)
        ^ j * jnp.uint32(0x85EBCA77)
        ^ s * jnp.uint32(0xC2B2AE3D)
    )
    h = (h ^ (h >> 15)) * jnp.uint32(0x27D4EB2F)
    h = h ^ (h >> 13)
    # Top 24 bits through int32: Mosaic has no uint32->float32 cast, and
    # float32 represents 24-bit integers exactly (same math as
    # gossip._hash_uniform — the paths must stay bit-identical).
    u = (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return jnp.clip(u, 1e-12, 1.0 - 2.0**-24)


def _advance(w_self32, w_peer32, valid_col, budget, rows, owners, salt, run_salt):
    """gossip._budgeted_advance, proportional policy, in int32/f32."""
    d = jnp.maximum(w_peer32 - w_self32, 0) * valid_col
    total = jnp.sum(d.astype(jnp.float32), axis=1, keepdims=True)
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))
    x = d.astype(jnp.float32) * scale
    floor = jnp.floor(x)
    bump = _dither(rows, owners, salt, run_salt) < (x - floor)
    return jnp.minimum(floor.astype(jnp.int32) + bump, d)


def _pull_kernel(
    # scalar prefetch
    p_ref,
    inv_ref,
    meta_ref,  # [salt_p, salt_i, run_salt, budget]
    # block inputs
    w_ref,
    hb_ref,
    validp_ref,
    validi_ref,
    # HBM inputs for gathers
    w_hbm,
    hb_hbm,
    # outputs
    wout_ref,
    hbout_ref,
    # scratch
    wp,
    wi,
    hbp,
    hbi,
    sems,
    *,
    block: int,
    n: int,
    track_hb: bool,
    dual: bool,
):
    b0 = pl.program_id(0) * block

    def gather(r, _):
        pr = p_ref[b0 + r]
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(pr, 1), :], wp.at[pl.ds(r, 1), :], sems.at[0, r]
        ).start()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(pr, 1), :], hbp.at[pl.ds(r, 1), :], sems.at[1, r]
            ).start()
        if dual:
            ir = inv_ref[b0 + r]
            pltpu.make_async_copy(
                w_hbm.at[pl.ds(ir, 1), :], wi.at[pl.ds(r, 1), :], sems.at[2, r]
            ).start()
            if track_hb:
                pltpu.make_async_copy(
                    hb_hbm.at[pl.ds(ir, 1), :],
                    hbi.at[pl.ds(r, 1), :],
                    sems.at[3, r],
                ).start()
        return 0

    def wait(r, _):
        pr = p_ref[b0 + r]
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(pr, 1), :], wp.at[pl.ds(r, 1), :], sems.at[0, r]
        ).wait()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(pr, 1), :], hbp.at[pl.ds(r, 1), :], sems.at[1, r]
            ).wait()
        if dual:
            ir = inv_ref[b0 + r]
            pltpu.make_async_copy(
                w_hbm.at[pl.ds(ir, 1), :], wi.at[pl.ds(r, 1), :], sems.at[2, r]
            ).wait()
            if track_hb:
                pltpu.make_async_copy(
                    hb_hbm.at[pl.ds(ir, 1), :],
                    hbi.at[pl.ds(r, 1), :],
                    sems.at[3, r],
                ).wait()
        return 0

    lax.fori_loop(0, block, gather, 0)
    lax.fori_loop(0, block, wait, 0)

    salt_p = meta_ref[0]
    salt_i = meta_ref[1]
    run_salt = meta_ref[2]
    budget = meta_ref[3].astype(jnp.float32)

    rows = b0 + lax.broadcasted_iota(jnp.int32, (block, n), 0)
    owners = lax.broadcasted_iota(jnp.int32, (block, n), 1)

    w_self = w_ref[:].astype(jnp.int32)
    vp = validp_ref[:].astype(jnp.int32)  # (block, 1)
    adv = _advance(
        w_self, wp[:].astype(jnp.int32), vp, budget, rows, owners,
        salt_p, run_salt,
    )
    if dual:
        vi = validi_ref[:].astype(jnp.int32)
        adv_i = _advance(
            w_self, wi[:].astype(jnp.int32), vi, budget, rows, owners,
            salt_i, run_salt,
        )
        adv = jnp.maximum(adv, adv_i)
    wout_ref[:] = (w_self + adv).astype(wout_ref.dtype)

    if track_hb:
        hb_self = hb_ref[:].astype(jnp.int32)
        hb_new = jnp.maximum(hb_self, hbp[:].astype(jnp.int32) * vp)
        if dual:
            hb_new = jnp.maximum(hb_new, hbi[:].astype(jnp.int32) * vi)
        hbout_ref[:] = hb_new.astype(hbout_ref.dtype)
    else:
        hbout_ref[:] = hb_ref[:]


VMEM_BUDGET = 12 * 1024 * 1024  # ~16 MB/core, minus headroom for Mosaic


def _buffer_count(dual: bool, track_hb: bool) -> int:
    """(block, n)-sized VMEM buffers the kernel needs: pipelined in/out
    blocks are double-buffered (x2), gather scratch is single."""
    per_matrix = 2 + 2 + 1 + (1 if dual else 0)  # in x2, out x2, peer scratch
    return per_matrix * (2 if track_hb else 1)


def _pick_block(
    n: int,
    itemsize: int = 4,
    dual: bool = True,
    track_hb: bool = True,
    cap: int = 512,
) -> int | None:
    """Largest multiple-of-8 divisor of n such that every VMEM-resident
    buffer set fits the per-core budget."""
    per_row = _buffer_count(dual, track_hb) * n * itemsize
    limit = min(cap, VMEM_BUDGET // max(per_row, 1))
    best = None
    for b in range(8, limit + 1, 8):
        if n % b == 0:
            best = b
    return best


def supported(n: int, itemsize: int, dual: bool, track_hb: bool) -> bool:
    """Whether the fused kernel can run this shape (callers fall back to
    the XLA path when not)."""
    return _pick_block(n, itemsize, dual, track_hb) is not None


@functools.partial(
    jax.jit,
    static_argnames=("budget", "track_hb", "dual", "interpret"),
)
def fused_pull(
    w: jax.Array,
    hb: jax.Array,
    p: jax.Array,
    inv: jax.Array,
    valid_p: jax.Array,
    valid_i: jax.Array,
    salt_p: jax.Array,
    salt_i: jax.Array,
    run_salt: jax.Array,
    budget: int,
    track_hb: bool = True,
    dual: bool = True,
    interpret: bool = False,
):
    """One fused sub-exchange. Returns (w', hb').

    ``dual=True`` is permutation pairing (initiator via p + responder via
    inv, joined by max); ``dual=False`` is matching pairing (p is an
    involution). ``valid_*`` are per-row alive-pair masks.
    """
    n = w.shape[0]
    itemsize = max(w.dtype.itemsize, hb.dtype.itemsize)
    block = _pick_block(n, itemsize, dual, track_hb)
    if block is None:
        raise ValueError(f"no suitable row block for n={n}")
    meta = jnp.stack(
        [
            salt_p.astype(jnp.int32),
            salt_i.astype(jnp.int32),
            run_salt.astype(jnp.int32),
            jnp.asarray(budget, jnp.int32),
        ]
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),  # w block
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),  # hb block
            pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),  # valid_p col
            pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),  # valid_i col
            pl.BlockSpec(memory_space=pl.ANY),  # w HBM (gather source)
            pl.BlockSpec(memory_space=pl.ANY),  # hb HBM
        ],
        out_specs=[
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),
            pl.BlockSpec((block, n), lambda i, *_: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, n), w.dtype),
            # Unused directions/matrices get minimal-tile dummies so the
            # kernel signature stays fixed without wasting VMEM.
            pltpu.VMEM((block, n) if dual else (16, 128), w.dtype),
            pltpu.VMEM((block, n) if track_hb else (16, 128), hb.dtype),
            pltpu.VMEM(
                (block, n) if (dual and track_hb) else (16, 128), hb.dtype
            ),
            pltpu.SemaphoreType.DMA((4, block)),
        ],
    )
    kernel = functools.partial(
        _pull_kernel, block=block, n=n, track_hb=track_hb, dual=dual
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(w.shape, w.dtype),
            jax.ShapeDtypeStruct(hb.shape, hb.dtype),
        ],
        interpret=interpret,
    )(
        p.astype(jnp.int32),
        inv.astype(jnp.int32),
        meta,
        w,
        hb,
        valid_p.astype(jnp.int8)[:, None],
        valid_i.astype(jnp.int8)[:, None],
        w,
        hb,
    )
