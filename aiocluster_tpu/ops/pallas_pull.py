"""Fused Pallas TPU kernels for gossip sub-exchanges (grouped matching)
— including the FUSED ROUND: pull + phi-accrual FD in one dispatch,
with a lane axis for multi-scenario sweeps (tests/test_fused_kernel.py
is the interpret-mode differential gate, `make kernel-parity`).

The XLA path of ops/gossip.py executes a matching sub-exchange as several
separate passes over the (N, N) matrices: peer-row gathers for w and hb
(each a full-matrix read AND write of the materialized gather), a
deficit-total reduction, the dithered advance, and the heartbeat absorb.
This kernel performs the whole sub-exchange in ONE pass over HBM per
matrix: each row block is read once, peer rows arrive by direct
HBM->VMEM DMA (never materialized in HBM), and the budget math runs
entirely in VMEM.

Why GROUPED matching: Mosaic (the Pallas TPU compiler) can only DMA row
slices aligned to the 8-sublane tile — a single random row of an int16
matrix is not a legal copy. So the matching is drawn from the
8-row-group family (gossip._grouped_matching): groups of 8 rows are
matched uniformly, and rows within a matched group pair are assigned by
a per-pair rotation. Every peer fetch is then an aligned (8, n) slice,
and the rotation is applied in VMEM with the TPU's dynamic sublane
rotate. 8-row alignment suffices for BOTH int32 and int16: narrow
dtypes pack pairs within the 8-sublane tile ((8,128)(2,1) tiling), so
any multiple-of-8 row offset is a whole-tile boundary — verified on
hardware with odd multiples of 8 into int16 memrefs, exact results. The XLA path uses the same family on the kernel's whole domain
(n % 128 == 0), so the
kernel's output is exactly equal to the XLA path's (asserted in
tests/test_pallas_pull.py) and flipping use_pallas never changes a
trajectory.

Bit-compatibility: the advance formula and the (row, owner, salt) dither
hash are the same arithmetic as gossip._budgeted_advance /
gossip._hash_uniform. Proportional-budget, matching pairing, no
dead-node lifecycle — other configs stay on XLA (the sim_step gate
enforces this). Both storage profiles qualify: with heartbeats the
kernel fuses w and hb in one pass; the lean convergence-only profile
(hb=None) runs the w-only variant with half the VMEM footprint.

Packed rungs (PR 12): a uint8 w is the u4 residual rung
(sim/packed.py, two saturating watermark residuals per byte) — the
pairs family DMAs the PACKED rows, widens the two nibbles transiently
in VMEM, runs the same budgeted advance in residual space
(gossip._packed_adv_halves' arithmetic — one row total spans both
halves, each half dithered against its true global owner id), applies
the round-start refresh (saturating write-bump + diagonal zero) on the
first sub-exchange's tiles, and repacks before the out DMA — with
input_output_aliases on the packed buffers, so the wide matrix never
exists in HBM. The fused FD epilogue likewise accepts the shrunk
bookkeeping rungs: int8 icount widens per tile, and live_bits streams
the column BITMAP straight from VMEM (the bool matrix is a kernel
transient). tests/test_fused_kernel.py + tests/test_memory_ladder.py
pin all of it bit-identical to the byte-space XLA path.

Column sharding (the BASELINE config-5 north star): rows are unsharded,
so each shard's peer DMA stays local to its (N, n_local) block; the one
cross-shard quantity is each row's global deficit total. The sharded
form is two passes — fused_pull_totals_m8 streams the block once for
LOCAL row totals, the caller psums them over ICI, and fused_pull_m8
applies the advance with the global totals (skipping its in-kernel
sum). A one-shard mesh short-circuits to the single-pass form
(ops/gossip.py sim_step wires both).

Reference anchor: this is the hot loop of server.py:378-495 (the 3-way
handshake fan-out) collapsed into one tensor pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dither_base(shape, salt, run_salt, col0) -> tuple[jax.Array, jax.Array]:
    """The group-invariant parts of gossip._hash_uniform's input mix,
    computed ONCE per kernel invocation and shared by every group (the
    uint32 multiplies are the expensive part of the hash on the VPU):
    ``r_k1 = r * K1`` for within-group row r, and ``js = j * K2 ^ s *
    K3`` for GLOBAL column j (``col0`` is this shard's owner offset —
    the hash must key off global indices so a column-sharded run
    reproduces the single-device dither bits). They stay separate
    because the global-row term folds in by ADDITION
    (``(row0 + r) * K1 = row0 * K1 + r * K1`` mod 2^32) which does not
    distribute over the xor with ``js``."""
    s = salt.astype(jnp.uint32) ^ run_salt.astype(jnp.uint32)
    i = lax.broadcasted_iota(jnp.uint32, shape, 0)
    j = lax.broadcasted_iota(jnp.uint32, shape, 1) + col0.astype(jnp.uint32)
    return (
        i * jnp.uint32(0x9E3779B1),
        j * jnp.uint32(0x85EBCA77) ^ s * jnp.uint32(0xC2B2AE3D),
    )


def _dither(r_k1: jax.Array, js: jax.Array, row0: jax.Array) -> jax.Array:
    """Same bits as gossip._hash_uniform for rows ``row0..row0+7``: one
    wrapping add + one xor per element recovers the full input mix from
    the precomputed parts; the avalanche + 24-bit mapping run per
    element as in the XLA path."""
    h = (r_k1 + row0.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)) ^ js
    h = (h ^ (h >> 15)) * jnp.uint32(0x27D4EB2F)
    h = h ^ (h >> 13)
    # Top 24 bits through int32: Mosaic has no uint32->float32 cast, and
    # float32 represents 24-bit integers exactly (same math as
    # gossip._hash_uniform — the paths must stay bit-identical).
    u = (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return jnp.clip(u, 1e-12, 1.0 - 2.0**-24)


def fd_update(
    tick, hb, hb0, lc, im32, ic,
    *, max_interval, window, prior_weight, prior_mean, phi,
):
    """The phi-accrual FD update on widened int32/f32 tiles — THE single
    source of the arithmetic shared by the standalone streaming FD
    kernel (ops/pallas_fd.py) and the fused epilogue the pairs kernel
    runs on the round's last sub-exchange. Same ops in the same order as
    the XLA block in gossip.sim_step (loads widen exactly, stores round
    once at the end), so every consumer stays bit-identical to the XLA
    path. ``phi`` may be a static float or a traced f32 scalar (a sweep
    lane's value) — both promote identically in the f32 product.

    Returns (last_change', imean', icount', live') PRE death-wipe and
    PRE self-diagonal: callers apply ``live |= diag`` and the
    where(live, ...) resets themselves (their diagonal bases differ)."""
    increased = hb > hb0
    never_seen = lc == 0
    interval = (tick - lc).astype(jnp.float32)
    sampled = increased & ~never_seen & (interval <= max_interval)
    icount = jnp.minimum(ic + sampled.astype(jnp.int32), window)
    denom = jnp.maximum(icount.astype(jnp.float32), 1.0)
    imean = jnp.where(sampled, im32 + (interval - im32) / denom, im32)
    lc2 = jnp.where(increased, tick, lc)
    count_f32 = icount.astype(jnp.float32)
    elapsed = (tick - lc2).astype(jnp.float32)
    live = (icount >= 1) & (
        elapsed * (count_f32 + prior_weight)
        <= phi * (imean * count_f32 + prior_weight * prior_mean)
    )
    return lc2, imean, icount, live


def _advance(w_self32, w_peer32, valid_col, budget, r_k1, js, row0, totals=None):
    """gossip._budgeted_advance, proportional policy, in int32/f32.

    ``totals`` ((8, 1) f32), when given, is the rows' GLOBAL deficit
    total (psum'd across shards between the two kernel passes of the
    sharded path); None means the local row sum IS the global total
    (single device, or a one-shard mesh)."""
    d = jnp.maximum(w_peer32 - w_self32, 0) * valid_col
    if totals is None:
        total = jnp.sum(d.astype(jnp.float32), axis=1, keepdims=True)
    else:
        total = totals
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))
    x = d.astype(jnp.float32) * scale
    floor = jnp.floor(x)
    bump = _dither(r_k1, js, row0) < (x - floor)
    return jnp.minimum(floor.astype(jnp.int32) + bump, d)


# -- packed u4 residual rung (version_dtype="u4r"): the VMEM nibble codec -----
#
# The packed rung stores two saturating watermark RESIDUALS per byte
# (sim/packed.py); residual space is closed under the gossip math, so
# the kernel DMAs the packed uint8 rows, widens the two nibbles
# transiently in VMEM, runs the same budgeted-advance arithmetic as
# gossip._packed_adv_halves on them, and repacks before the out DMA —
# the wide matrix never exists in HBM (input_output_aliases keeps even
# the packed copy single). Byte column j of a block whose first owner
# is ``owner_off`` holds owners owner_off + 2j (low nibble) and
# owner_off + 2j + 1 (high nibble), which is what the dither bases and
# diagonal compares below key off.

def _dither_base_packed(shape, salt, run_salt, col0):
    """The packed analogue of ``_dither_base``: returns (r_k1, jm, sk)
    where ``jm = j_lo * K2`` is keyed off the LOW-nibble owner ids
    (col0 + 2j) and ``sk`` is the scalar salt mix. The two halves'
    ``js`` inputs are derived per use — ``js_lo = jm ^ sk`` and
    ``js_hi = (jm + K2) ^ sk`` (the high owner is j_lo + 1 and the
    j-multiply distributes over +1 as one wrapping add) — so only two
    (8, width) uint32 bases stay resident, same as the unpacked path."""
    s = salt.astype(jnp.uint32) ^ run_salt.astype(jnp.uint32)
    i = lax.broadcasted_iota(jnp.uint32, shape, 0)
    j_lo = 2 * lax.broadcasted_iota(jnp.uint32, shape, 1) + col0.astype(
        jnp.uint32
    )
    return (
        i * jnp.uint32(0x9E3779B1),
        j_lo * jnp.uint32(0x85EBCA77),
        s * jnp.uint32(0xC2B2AE3D),
    )


def _advance_packed(
    lo_self, hi_self, lo_peer, hi_peer, valid_col, budget,
    r_k1, jm, sk, row0, totals=None,
):
    """gossip._packed_adv_halves in VMEM: ONE row total (and scale)
    spans both nibble halves — f32 sums of integer deficits are exact
    below 2^24, so summing the halves separately equals the unpacked
    column-order total — then each half runs the dithered proportional
    round against its own global-owner hash stream. Returns the
    (a_lo, a_hi) int32 nibble advances (the receiver's residual
    shrinks by them)."""
    d_lo = jnp.maximum(lo_self - lo_peer, 0) * valid_col
    d_hi = jnp.maximum(hi_self - hi_peer, 0) * valid_col
    if totals is None:
        total = jnp.sum(
            d_lo.astype(jnp.float32), axis=1, keepdims=True
        ) + jnp.sum(d_hi.astype(jnp.float32), axis=1, keepdims=True)
    else:
        total = totals
    scale = jnp.minimum(1.0, budget / jnp.maximum(total, 1.0))

    def half(d, js):
        x = d.astype(jnp.float32) * scale
        floor = jnp.floor(x)
        bump = _dither(r_k1, js, row0) < (x - floor)
        return jnp.minimum(floor.astype(jnp.int32) + bump, d)

    return half(d_lo, jm ^ sk), half(d_hi, (jm + jnp.uint32(0x85EBCA77)) ^ sk)


def _unpack_tile(t32):
    """(8, width) widened uint8 tile -> (lo, hi) int32 nibble halves.
    A VMEM-transient decode (the kernel repacks before the out DMA) —
    NOT the sanctioned HBM widen (that is sim/packed.unpack_u4)."""
    return t32 & 0xF, t32 >> 4


def _pack_bump_nibbles(bump):
    """(…, n_local) int32 per-owner write bump -> (…, n_local // 2)
    packed nibble row, THE one packing both the apply pass and the
    sharded totals pass feed their ``mv`` operand through (they must
    see identical refreshed tiles or the psum'd budgets diverge from
    single-device runs). Each half clips to [0, 15], which preserves
    the kernel's saturating min(r + bump, 15) exactly: r >= 0, so any
    bump >= 15 saturates either way."""
    bq = jnp.clip(bump, 0, 15).astype(jnp.int32)
    return bq[..., 0::2] | (bq[..., 1::2] << 4)


def _refresh_packed(lo, hi, bump_ref, col_lo, rows, r8):
    """The packed round-start refresh on one side's nibble halves:
    owner writes raise every observer's residual (saturating at the
    nibble ceiling — gossip._packed_writes_shift), then the owner
    diagonal resets to 0 (gossip._packed_diag_zero). ``bump_ref`` is
    the (1, width) per-owner write bump packed as nibbles (each half
    pre-clipped to [0, 15], which preserves the saturating min)."""
    b = bump_ref[:]
    lo = jnp.minimum(lo + (b & 0xF), 15)
    hi = jnp.minimum(hi + (b >> 4), 15)
    self_rows = rows + r8
    lo = jnp.where(col_lo == self_rows, 0, lo)
    hi = jnp.where(col_lo + 1 == self_rows, 0, hi)
    return lo, hi


def _m8_kernel(
    # scalar prefetch
    gm_ref,  # (n/8,) partner group per group (involution)
    c_ref,  # (n/8,) within-pair row rotation
    meta_ref,  # [salt, run_salt, budget, owner_offset]
    # block inputs
    w_ref,
    hb_ref,
    valid_ref,  # (block, 1) int8 alive-pair mask per row
    totals_ref,  # (block, 1) f32 global deficit totals (dummy if unused)
    mv_ref,  # (1, n) int32 owner max_version (diag refresh; dummy if off)
    hbv_ref,  # (1, n) int32 owner heartbeat (diag refresh; dummy if off)
    # HBM gather sources
    w_hbm,
    hb_hbm,
    # outputs
    wout_ref,
    hbout_ref,
    # scratch
    wp,
    hbp,
    sems,
    *,
    block: int,
    n: int,
    track_hb: bool,
    apply_diag: bool,
    use_totals: bool,
):
    gpb = block // 8  # groups per block
    g0 = pl.program_id(0) * gpb

    def gather(g, _):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[0, g]
        ).start()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(src, 8), :], hbp.at[pl.ds(g * 8, 8), :],
                sems.at[1, g],
            ).start()
        return 0

    def wait(g, _):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[0, g]
        ).wait()
        if track_hb:
            pltpu.make_async_copy(
                hb_hbm.at[pl.ds(src, 8), :], hbp.at[pl.ds(g * 8, 8), :],
                sems.at[1, g],
            ).wait()
        return 0

    lax.fori_loop(0, gpb, gather, 0)

    salt = meta_ref[0]
    run_salt = meta_ref[1]
    budget = meta_ref[2].astype(jnp.float32)
    owner_off = meta_ref[3]
    r_k1, js = _dither_base((8, n), salt, run_salt, owner_off)
    # Global column (owner) ids: the diag compares and the dither hash
    # both key off global indices, so a column-sharded block (owner_off
    # = shard * n_local) reproduces the single-device bits exactly.
    col = lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    r8 = lax.broadcasted_iota(jnp.int32, (8, n), 0)

    # Per 8-row group: wait for its DMA just-in-time (later groups'
    # copies keep streaming behind this group's compute), rotate the
    # fetched partner group into row-pair order (w_peer[r] =
    # fetched[(r - c) % 8], i.e. roll by +c), then the row-independent
    # advance/absorb math on the (8, n) tile.
    for g in range(gpb):
        wait(g, 0)
        sl = slice(g * 8, (g + 1) * 8)
        cg = c_ref[g0 + g]
        row0 = pl.program_id(0) * block + g * 8
        vcol = valid_ref[sl, :].astype(jnp.int32)  # (8, 1)
        w_self = w_ref[sl, :].astype(jnp.int32)
        w_peer = pltpu.roll(wp[sl, :].astype(jnp.int32), cg, 0)
        if apply_diag:
            # Owner diagonal refresh, applied in VMEM instead of as a
            # separate materialized pass over HBM (the first sub-exchange
            # of the round carries it): at any (row, col=c) the diagonal
            # value IS mv[c], so one broadcast row + a column-iota
            # compare fixes the self tile; the peer tile's rows are
            # global rows 8*gm + (r - c) % 8, fixed the same way.
            self_rows = row0 + r8
            peer_rows = 8 * gm_ref[g0 + g] + ((r8 + 8 - cg) & 7)
            mv_b = mv_ref[:]
            w_self = jnp.where(col == self_rows, mv_b, w_self)
            w_peer = jnp.where(col == peer_rows, mv_b, w_peer)
        tot = totals_ref[sl, :] if use_totals else None
        adv = _advance(w_self, w_peer, vcol, budget, r_k1, js, row0, tot)
        wout_ref[sl, :] = (w_self + adv).astype(wout_ref.dtype)
        if track_hb:
            hb_self = hb_ref[sl, :].astype(jnp.int32)
            hb_peer = pltpu.roll(hbp[sl, :].astype(jnp.int32), cg, 0)
            if apply_diag:
                hbv_b = hbv_ref[:]
                hb_self = jnp.where(col == self_rows, hbv_b, hb_self)
                hb_peer = jnp.where(col == peer_rows, hbv_b, hb_peer)
            hbout_ref[sl, :] = jnp.maximum(hb_self, hb_peer * vcol).astype(
                hbout_ref.dtype
            )
    if not track_hb:
        hbout_ref[:] = hb_ref[:]  # dummy tile; outputs must be written


def _m8_totals_kernel(
    # scalar prefetch
    gm_ref,
    c_ref,
    meta_ref,  # [owner_offset]
    # block inputs
    w_ref,
    valid_ref,  # (block, 1) int8
    mv_ref,  # (1, n) int32 (diag refresh; dummy if off)
    # HBM gather source
    w_hbm,
    # output
    tot_ref,  # (block, 1) f32 local deficit row totals
    # scratch
    wp,
    sems,
    *,
    block: int,
    n: int,
    apply_diag: bool,
):
    """Pass A of the sharded fused pull: each row's LOCAL deficit total,
    one streamed read of w + its peer rows, no writes of either. The
    caller psums the (N,) result across shards and feeds it back to
    _m8_kernel as ``totals`` — the only cross-shard quantity in a
    matching sub-exchange (rows are unsharded, so peer DMA stays
    shard-local)."""
    gpb = block // 8
    g0 = pl.program_id(0) * gpb

    def gather(g, _):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[g]
        ).start()
        return 0

    lax.fori_loop(0, gpb, gather, 0)

    owner_off = meta_ref[0]
    col = lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    r8 = lax.broadcasted_iota(jnp.int32, (8, n), 0)
    for g in range(gpb):
        src = gm_ref[g0 + g] * 8
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(src, 8), :], wp.at[pl.ds(g * 8, 8), :], sems.at[g]
        ).wait()
        sl = slice(g * 8, (g + 1) * 8)
        cg = c_ref[g0 + g]
        row0 = pl.program_id(0) * block + g * 8
        vcol = valid_ref[sl, :].astype(jnp.int32)
        w_self = w_ref[sl, :].astype(jnp.int32)
        w_peer = pltpu.roll(wp[sl, :].astype(jnp.int32), cg, 0)
        if apply_diag:
            self_rows = row0 + r8
            peer_rows = 8 * gm_ref[g0 + g] + ((r8 + 8 - cg) & 7)
            mv_b = mv_ref[:]
            w_self = jnp.where(col == self_rows, mv_b, w_self)
            w_peer = jnp.where(col == peer_rows, mv_b, w_peer)
        d = jnp.maximum(w_peer - w_self, 0) * vcol
        tot_ref[sl, :] = jnp.sum(d.astype(jnp.float32), axis=1, keepdims=True)


def _pairs_ref_names(
    track_hb: bool, use_totals: bool, fd: bool, fd_hb0: bool
) -> tuple[str, ...]:
    """Positional ref layout of ``_pairs_kernel`` for one static config:
    scalar prefetch, then inputs, outputs, scratch — in pallas_call
    order. The wrapper builds its operand/spec/scratch lists from this
    same table (``_pairs_call``), so kernel signature and call can never
    skew as the optional FD block comes and goes."""
    names = [
        "ld",  # (n/8,) slot -> leader group (padded past `count`)
        "gm",  # (n/8,) partner group per group (involution)
        "c",  # (n/8,) within-pair row rotation
        "vb",  # (n/8,) alive-pair mask, one bit per row, packed per group
        "ab",  # (n/8,) alive mask bits (convergence; dummy if check off)
        "meta",  # [salt, run_salt, budget, count, owner_offset, tick]
        # VMEM inputs (whole-array blocks, loaded once):
        "mv",  # (1, n) int32 owner max_version (diag refresh; dummy if off)
        "hbv",  # (1, n) int32 owner heartbeat (diag refresh / FD hb0 diag)
        "need",  # (1, n) int32 convergence target (dummy if check off)
        "fdp",  # (1, 128) f32 [phi_threshold, ...] (dummy if fd off)
        # HBM operands:
        "w_hbm",
        "hb_hbm",
        "tot_hbm",  # (n_rows, 1) f32 global deficit totals (dummy if unused)
    ]
    if fd:
        names += ["lc_hbm", "im_hbm", "ic_hbm"]  # FD bookkeeping
        if fd_hb0:
            names.append("hb0_hbm")  # round-start hb (fanout > 1)
    names += [
        "wout",
        "hbout",
        "flag_out",  # (1, 1) int32 all-converged flag (1 if check off)
    ]
    if fd:
        names += ["lcout", "imout", "icout", "liveout"]
    names += [
        "win",  # (16*nbuf, n): [buf] x [side 0/1] x 8 rows; outputs OVERWRITE
        "hbin",
        "tscr",  # (16*nbuf, 1) f32 totals rows (dummy if unused)
        "fscr",  # (1, 1) int32 running converged flag
    ]
    if fd:
        names += ["lcin", "imin", "icin", "livescr"]
        if fd_hb0:
            names.append("hb0in")
    names += [
        "insems",  # (nbuf, 2, n_in_streams): [buf, side, stream]
        "outsems",  # (nbuf, 2, n_out_streams)
    ]
    return tuple(names)


def _pairs_kernel(
    *refs,
    n: int,
    track_hb: bool,
    apply_diag: bool,
    use_totals: bool,
    check: bool,
    nbuf: int,
    lanes: bool,
    fd: bool,
    fd_hb0: bool,
    fd_consts: tuple | None,
    packed: bool = False,
    fd_live_bits: bool = False,
):
    """Both sides of every matched group pair in ONE visit (the
    pair-fused pull). The matching is an involution, so the single-pass
    kernel (_m8_kernel) touches each row of w three times per
    sub-exchange: the in-spec stream reads it as "self", a gather DMA
    reads it again as its partner's peer, and the out stream writes it.
    Processing the pair (g, gm[g]) together needs each row only twice —
    one read, one write — cutting the sub-exchange's HBM traffic by a
    third. Both directions compute from the pre-sub-exchange tiles, which
    is exactly the XLA matching path's semantics (one vectorized pull
    through the involution covers both sides), so the bits are identical.

    Single program (grid=(1,)): all streaming is manual double-buffered
    DMA over a fori_loop of pair slots; scratch persists across the loop.
    Slots [0, count) hold the leader groups (g <= gm[g]); self-matched
    groups fetch their own tile into the peer slot (one redundant 8-row
    read for at most one group per matching) and skip the side-1 write.
    The compute OVERWRITES the input tiles in VMEM and the out DMA
    streams from the same buffer — no separate out scratch. With
    ``nbuf=3`` (the default whenever VMEM allows) a slot's out DMA has
    a FULL later slot's compute to land before its buffer's next
    occupant streams in — the classic overlap schedule; ``nbuf=2``
    (the fallback that buys the widest shapes) must wait each out DMA
    immediately before the next prefetch, serializing ~1 row-pair DMA
    against each slot's compute.

    Column sharding: w may be an (N, n_local) block — rows stay global
    (the pairing is over rows, and peer rows are shard-local), columns
    are this shard's owners. ``owner_offset`` keys the dither hash and
    the diagonal compares off GLOBAL column ids, and ``use_totals``
    feeds the rows' global deficit totals (psum'd between the kernel
    passes) in place of the in-kernel local sum — together they make
    the sharded bits exactly the single-device bits.

    ``check``: the round's LAST sub-exchange can carry the convergence
    test (w' >= max_version[owner], dead rows and dead owners excused)
    on the output tiles it already holds, so convergence-tracked runs
    pay ZERO extra HBM traffic for the check (the separate
    all_converged_flag pass reads the whole matrix again).

    ``fd``: the round's LAST sub-exchange can also carry the whole
    phi-accrual FD phase (the fused round). Each side's freshly
    computed hb tile IS the post-exchange heartbeat knowledge, so the
    epilogue streams only the FD bookkeeping (last_change / imean /
    icount, updated IN PLACE via input_output_aliases) plus the
    round-start hb0 when fanout > 1 (``fd_hb0``; at fanout == 1 the
    input hb tile is the round-start matrix and hb0 costs nothing),
    and writes the live matrix — the separate ops/pallas_fd.py pass
    (which would re-read both heartbeat matrices) disappears. The
    arithmetic is ``fd_update`` — shared with the standalone kernel —
    with the phi threshold folded in from the ``fdp`` row (a traced
    per-lane scalar under sweeps, the static config value otherwise).

    ``lanes``: the grid is lifted over a leading sweep-lane dimension S
    (one grid step per lane). Every scalar-prefetch operand gains a
    lane row — per-lane matchings, salts, budgets-dither state, counts
    and FD phi — and the HBM operands a leading S axis indexed by
    ``program_id``; scratch is reused serially across lanes. This is
    how SweepSimulator's vmapped ``sim_step`` engages the kernel (the
    custom_vmap rule in ``_pairs_dispatcher`` routes batched calls
    here)."""
    r = dict(zip(_pairs_ref_names(track_hb, use_totals, fd, fd_hb0), refs))
    assert len(refs) == len(
        _pairs_ref_names(track_hb, use_totals, fd, fd_hb0)
    )
    lane = pl.program_id(0) if lanes else None

    def at(ref, i):
        # Scalar-prefetch access: lane-batched arrays carry a leading
        # lane axis; single-lane arrays are as before.
        return ref[lane, i] if lanes else ref[i]

    def lhbm(ref):
        # HBM operands: this lane's (n, n_cols) plane.
        return ref.at[lane] if lanes else ref

    ld_ref, gm_ref, c_ref = r["ld"], r["gm"], r["c"]
    vb_ref, ab_ref, meta_ref = r["vb"], r["ab"], r["meta"]
    mv_ref, hbv_ref, need_ref = r["mv"], r["hbv"], r["need"]
    win, hbin, tscr, fscr = r["win"], r["hbin"], r["tscr"], r["fscr"]
    insems, outsems = r["insems"], r["outsems"]
    flag_out = r["flag_out"]

    salt = at(meta_ref, 0)
    run_salt = at(meta_ref, 1)
    budget = at(meta_ref, 2).astype(jnp.float32)
    count = at(meta_ref, 3)
    owner_off = at(meta_ref, 4)
    if packed:
        # u4 residual rung: ``n`` is the BYTE width (two owners per
        # column); ``col`` carries the LOW-nibble owner ids (the high
        # owner is col + 1) and the dither bases key both halves off
        # their true global owners.
        r_k1, jm_p, sk_p = _dither_base_packed(
            (8, n), salt, run_salt, owner_off
        )
        js = None
        col = 2 * lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    else:
        r_k1, js = _dither_base((8, n), salt, run_salt, owner_off)
        col = lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    r8 = lax.broadcasted_iota(jnp.int32, (8, n), 0)
    # The per-row alive-pair mask arrives as one PACKED int32 per group
    # (bit r = row 8g+r): a (n, 1) VMEM column would lane-pad to 128
    # bytes/row; a vectorized shift rebuilds the (8, 1) column from the
    # scalar for free on the VPU.
    sub8 = lax.broadcasted_iota(jnp.int32, (8, 1), 0)

    def vmask(g):
        return (at(vb_ref, g) >> sub8) & 1

    # DMA stream tables (static per config): every in/out stream shares
    # the one slot/side -> 8-row addressing, so adding the FD matrices
    # is a table entry, not new plumbing.
    in_streams = [(lhbm(r["w_hbm"]), win)]
    if track_hb:
        in_streams.append((lhbm(r["hb_hbm"]), hbin))
    if use_totals:
        in_streams.append((lhbm(r["tot_hbm"]), tscr))
    if fd:
        in_streams += [
            (lhbm(r["lc_hbm"]), r["lcin"]),
            (lhbm(r["im_hbm"]), r["imin"]),
            (lhbm(r["ic_hbm"]), r["icin"]),
        ]
        if fd_hb0:
            in_streams.append((lhbm(r["hb0_hbm"]), r["hb0in"]))
    out_streams = [(win, lhbm(r["wout"]))]
    if track_hb:
        out_streams.append((hbin, lhbm(r["hbout"])))
    if fd:
        out_streams += [
            (r["lcin"], lhbm(r["lcout"])),
            (r["imin"], lhbm(r["imout"])),
            (r["icin"], lhbm(r["icout"])),
            (r["livescr"], lhbm(r["liveout"])),
        ]

    def in_copy(slot, side, k):
        src_hbm, scr = in_streams[k]
        g = at(ld_ref, slot)
        src = (g if side == 0 else at(gm_ref, g)) * 8
        row = (slot % nbuf) * 16 + side * 8
        return pltpu.make_async_copy(
            src_hbm.at[pl.ds(src, 8), :],
            scr.at[pl.ds(row, 8), :],
            insems.at[slot % nbuf, side, k],
        )

    def out_copy(slot, side, k):
        scr, dst_hbm = out_streams[k]
        g = at(ld_ref, slot)
        dst = (g if side == 0 else at(gm_ref, g)) * 8
        row = (slot % nbuf) * 16 + side * 8
        return pltpu.make_async_copy(
            scr.at[pl.ds(row, 8), :],
            dst_hbm.at[pl.ds(dst, 8), :],
            outsems.at[slot % nbuf, side, k],
        )

    def start_in(slot):
        for k in range(len(in_streams)):
            in_copy(slot, 0, k).start()
            in_copy(slot, 1, k).start()

    def wait_in(slot):
        for k in range(len(in_streams)):
            in_copy(slot, 0, k).wait()
            in_copy(slot, 1, k).wait()

    def start_out(slot):
        for k in range(len(out_streams)):
            out_copy(slot, 0, k).start()

        @pl.when(at(gm_ref, at(ld_ref, slot)) != at(ld_ref, slot))
        def _():
            for k in range(len(out_streams)):
                out_copy(slot, 1, k).start()

    def wait_out(slot):
        for k in range(len(out_streams)):
            out_copy(slot, 0, k).wait()

        @pl.when(at(gm_ref, at(ld_ref, slot)) != at(ld_ref, slot))
        def _():
            for k in range(len(out_streams)):
                out_copy(slot, 1, k).wait()

    if fd:
        tick = at(meta_ref, 5)
        phi = r["fdp"][0, 0]
        fd_max_interval, fd_window, fd_pw, fd_pm = fd_consts
        lcin, imin, icin = r["lcin"], r["imin"], r["icin"]
        livescr = r["livescr"]

        def fd_side(base_row, grp, hb_old, hb_new):
            """The FD phase for one side's 8-row tile: hb_new is the
            freshly computed post-exchange knowledge (int32, pre-cast —
            same values the stored matrix will hold), hb_old the
            diag-refreshed input tile. Death wipes the window and the
            self diagonal stays live, exactly as the XLA block."""
            sl = pl.ds(base_row, 8)
            diag_side = col == 8 * grp + r8
            if fd_hb0:
                hb0_t = jnp.where(
                    diag_side,
                    hbv_ref[:],
                    r["hb0in"][sl, :].astype(jnp.int32),
                )
            else:
                # fanout == 1: the input hb tile IS the round-start
                # matrix (owner diagonal already refreshed above).
                hb0_t = hb_old
            lc2, imean, icount, live = fd_update(
                tick,
                hb_new,
                hb0_t,
                lcin[sl, :].astype(jnp.int32),
                imin[sl, :].astype(jnp.float32),
                icin[sl, :].astype(jnp.int32),
                max_interval=fd_max_interval,
                window=fd_window,
                prior_weight=fd_pw,
                prior_mean=fd_pm,
                phi=phi,
            )
            live = live | diag_side
            lcin[sl, :] = lc2.astype(lcin.dtype)
            imin[sl, :] = jnp.where(live, imean, 0.0).astype(imin.dtype)
            icin[sl, :] = jnp.where(live, icount, 0).astype(icin.dtype)
            if fd_live_bits:
                # Bit-packed liveness (the shrunk-FD rung): the bool
                # tile is a VMEM transient; only the column bitmap
                # (sim/packed.pack_bits layout — column j in bit j % 8
                # of byte j // 8) streams out. NOTE for the tunnel
                # window: the bitmap rows are n/8 bytes wide — at
                # narrow shards the out copy is a partial 128-lane
                # tile, to be verified on chip like the odd-multiple
                # int16 copies were (certification owed either way).
                lw = live.astype(jnp.int32).reshape(8, n // 8, 8)
                weights = 1 << lax.broadcasted_iota(
                    jnp.int32, (8, n // 8, 8), 2
                )
                livescr[sl, :] = jnp.sum(lw * weights, axis=2).astype(
                    livescr.dtype
                )
            else:
                livescr[sl, :] = live

    def body(s, _):
        base = (s % nbuf) * 16

        # Slot s+1 streams into the buffer slot s-(nbuf-1) computed AND
        # wrote from: its out DMA must land first (in-place VMEM
        # reuse). With nbuf=3 that DMA had all of slot s-1's compute to
        # land — no stall; nbuf=2 waits it here, hot.
        @pl.when(s >= nbuf - 1)
        def _():
            wait_out(s - (nbuf - 1))

        @pl.when(s + 1 < count)
        def _():
            start_in(s + 1)

        wait_in(s)
        g = at(ld_ref, s)
        h = at(gm_ref, g)
        cg = at(c_ref, g)
        ch = at(c_ref, h)
        vg = vmask(g)
        vh = vmask(h)
        tg = tscr[pl.ds(base, 8), :] if use_totals else None
        th = tscr[pl.ds(base + 8, 8), :] if use_totals else None
        if packed:
            # u4 residual rung: widen the nibbles transiently, run the
            # same budgeted advance in residual space (the deficit of a
            # pull is max(r_self - r_peer, 0); an advance SHRINKS the
            # receiver's residual), repack before the out DMA. The
            # round-start refresh (owner-write shift + diagonal zero)
            # rides the first sub-exchange via the packed bump row.
            lo_g, hi_g = _unpack_tile(win[pl.ds(base, 8), :].astype(jnp.int32))
            lo_h, hi_h = _unpack_tile(
                win[pl.ds(base + 8, 8), :].astype(jnp.int32)
            )
            if apply_diag:
                lo_g, hi_g = _refresh_packed(lo_g, hi_g, mv_ref, col, 8 * g, r8)
                lo_h, hi_h = _refresh_packed(lo_h, hi_h, mv_ref, col, 8 * h, r8)
            a_lo_g, a_hi_g = _advance_packed(
                lo_g, hi_g, pltpu.roll(lo_h, cg, 0), pltpu.roll(hi_h, cg, 0),
                vg, budget, r_k1, jm_p, sk_p, 8 * g, tg,
            )
            a_lo_h, a_hi_h = _advance_packed(
                lo_h, hi_h, pltpu.roll(lo_g, ch, 0), pltpu.roll(hi_g, ch, 0),
                vh, budget, r_k1, jm_p, sk_p, 8 * h, th,
            )
            new_lo_g, new_hi_g = lo_g - a_lo_g, hi_g - a_hi_g
            new_lo_h, new_hi_h = lo_h - a_lo_h, hi_h - a_hi_h
            win[pl.ds(base, 8), :] = (new_lo_g | (new_hi_g << 4)).astype(
                win.dtype
            )
            win[pl.ds(base + 8, 8), :] = (new_lo_h | (new_hi_h << 4)).astype(
                win.dtype
            )
            if check:
                # Packed convergence: a zero residual IS "caught up"
                # (all_converged_flag's byte-space arm); dead owners
                # are excused by a zeroed need nibble, dead rows by the
                # alive bits.
                need = need_ref[:]
                na_lo, na_hi = need & 0xF, need >> 4
                ag = (at(ab_ref, g) >> sub8) & 1
                ah = (at(ab_ref, h) >> sub8) & 1
                row_ok_g = ((new_lo_g == 0) | (na_lo == 0)) & (
                    (new_hi_g == 0) | (na_hi == 0)
                )
                row_ok_h = ((new_lo_h == 0) | (na_lo == 0)) & (
                    (new_hi_h == 0) | (na_hi == 0)
                )
                ok_g = jnp.all(row_ok_g | (ag == 0))
                ok_h = jnp.all(row_ok_h | (ah == 0))
                ok_h = jnp.where(g == h, True, ok_h)
                fscr[0, 0] = fscr[0, 0] * ok_g.astype(jnp.int32) * ok_h.astype(
                    jnp.int32
                )
        else:
            w_g = win[pl.ds(base, 8), :].astype(jnp.int32)
            w_h = win[pl.ds(base + 8, 8), :].astype(jnp.int32)
            if apply_diag:
                mv_b = mv_ref[:]
                w_g = jnp.where(col == 8 * g + r8, mv_b, w_g)
                w_h = jnp.where(col == 8 * h + r8, mv_b, w_h)
            adv_g = _advance(
                w_g, pltpu.roll(w_h, cg, 0), vg, budget, r_k1, js, 8 * g, tg
            )
            adv_h = _advance(
                w_h, pltpu.roll(w_g, ch, 0), vh, budget, r_k1, js, 8 * h, th
            )
            # w_g/w_h are loaded VALUES; overwriting their tiles is safe.
            win[pl.ds(base, 8), :] = (w_g + adv_g).astype(win.dtype)
            win[pl.ds(base + 8, 8), :] = (w_h + adv_h).astype(win.dtype)
        if check and not packed:
            # Convergence on the freshly-computed output tiles (int32,
            # pre-cast — same values): a row passes where it has caught
            # up to the owner's target or the row is dead; dead OWNERS
            # are excused by the wrapper zeroing their target
            # (watermarks are non-negative, so w >= 0 always holds).
            # AND-accumulated across slots; side 1 skipped for
            # self-matched pairs (those rows were side 0).
            need = need_ref[:]
            ag = (at(ab_ref, g) >> sub8) & 1
            ah = (at(ab_ref, h) >> sub8) & 1
            ok_g = jnp.all((w_g + adv_g >= need) | (ag == 0))
            ok_h = jnp.all((w_h + adv_h >= need) | (ah == 0))
            ok_h = jnp.where(g == h, True, ok_h)
            fscr[0, 0] = fscr[0, 0] * ok_g.astype(jnp.int32) * ok_h.astype(
                jnp.int32
            )
        if track_hb:
            hb_g = hbin[pl.ds(base, 8), :].astype(jnp.int32)
            hb_h = hbin[pl.ds(base + 8, 8), :].astype(jnp.int32)
            if apply_diag:
                hbv_b = hbv_ref[:]
                hb_g = jnp.where(col == 8 * g + r8, hbv_b, hb_g)
                hb_h = jnp.where(col == 8 * h + r8, hbv_b, hb_h)
            hb_new_g = jnp.maximum(hb_g, pltpu.roll(hb_h, cg, 0) * vg)
            hb_new_h = jnp.maximum(hb_h, pltpu.roll(hb_g, ch, 0) * vh)
            hbin[pl.ds(base, 8), :] = hb_new_g.astype(hbin.dtype)
            hbin[pl.ds(base + 8, 8), :] = hb_new_h.astype(hbin.dtype)
            if fd:
                # FD epilogue on the tiles this slot already holds —
                # self-matched pairs skip side 1's write (those rows
                # were side 0), exactly like the pull outputs.
                fd_side(base, g, hb_g, hb_new_g)
                fd_side(base + 8, h, hb_h, hb_new_h)
        start_out(s)
        return 0

    fscr[0, 0] = jnp.int32(1)
    start_in(0)
    lax.fori_loop(0, count, body, 0)
    # Drain: the last nbuf-1 slots' out DMAs can still be in flight
    # (the body waits out(s-(nbuf-1)), so slots count-nbuf+1..count-1
    # are outstanding) — derived from nbuf so a future depth cannot
    # silently under-drain.
    for j in range(nbuf - 1, 1, -1):
        @pl.when(count >= j)
        def _(j=j):
            wait_out(count - j)

    wait_out(count - 1)
    flag_out[0, 0] = fscr[0, 0]
    # Lean mode's dummy hb output needs no write: the wrapper aliases
    # hb in -> hb out, so the output bytes ARE the dummy input's.


def _pairs_totals_kernel(
    # scalar prefetch
    ld_ref,
    gm_ref,
    c_ref,
    vb_ref,
    meta_ref,  # [count, owner_offset]
    # VMEM input
    mv_ref,  # (1, n) int32 (diag refresh; dummy if off)
    # HBM operand
    w_hbm,
    # HBM output
    tot_hbm,  # (n_rows, 1) f32 local deficit row totals
    # scratch
    win,  # (32, n)
    tout,  # (32, 1) f32
    insems,  # (2, 2): [buf, side]
    outsems,  # (2, 2)
    *,
    n: int,
    apply_diag: bool,
    lanes: bool = False,
    packed: bool = False,
):
    """Pass A of the sharded pair-fused pull: LOCAL deficit row totals
    for this shard's (N, n_local) block, visiting each matched group
    pair once — every row read ONCE (the m8 totals pass reads each row
    twice: streamed as self, gathered as its partner's peer). The
    caller psums the (N,) result across shards and feeds it to
    fused_pull_pairs as ``totals``. ``lanes`` lifts the grid over the
    sweep's leading S dimension exactly as in _pairs_kernel. ``packed``
    runs the u4 residual decode: one row total spans both nibble
    halves (mv_ref then carries the packed write-bump row, exactly as
    the apply pass will see it)."""
    lane = pl.program_id(0) if lanes else None

    def at(ref, i):
        return ref[lane, i] if lanes else ref[i]

    w_src = w_hbm.at[lane] if lanes else w_hbm
    tot_dst = tot_hbm.at[lane] if lanes else tot_hbm
    count = at(meta_ref, 0)
    owner_off = at(meta_ref, 1)
    if packed:
        col = 2 * lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    else:
        col = lax.broadcasted_iota(jnp.int32, (8, n), 1) + owner_off
    r8 = lax.broadcasted_iota(jnp.int32, (8, n), 0)
    sub8 = lax.broadcasted_iota(jnp.int32, (8, 1), 0)

    def vmask(g):
        return (at(vb_ref, g) >> sub8) & 1

    def in_copy(slot, side):
        g = at(ld_ref, slot)
        src = (g if side == 0 else at(gm_ref, g)) * 8
        row = (slot % 2) * 16 + side * 8
        return pltpu.make_async_copy(
            w_src.at[pl.ds(src, 8), :],
            win.at[pl.ds(row, 8), :],
            insems.at[slot % 2, side],
        )

    def out_copy(slot, side):
        g = at(ld_ref, slot)
        dst = (g if side == 0 else at(gm_ref, g)) * 8
        row = (slot % 2) * 16 + side * 8
        return pltpu.make_async_copy(
            tout.at[pl.ds(row, 8), :],
            tot_dst.at[pl.ds(dst, 8), :],
            outsems.at[slot % 2, side],
        )

    def start_in(slot):
        in_copy(slot, 0).start()
        in_copy(slot, 1).start()

    def start_out(slot):
        out_copy(slot, 0).start()

        @pl.when(at(gm_ref, at(ld_ref, slot)) != at(ld_ref, slot))
        def _():
            out_copy(slot, 1).start()

    def wait_out(slot):
        out_copy(slot, 0).wait()

        @pl.when(at(gm_ref, at(ld_ref, slot)) != at(ld_ref, slot))
        def _():
            out_copy(slot, 1).wait()

    def body(s, _):
        base = (s % 2) * 16

        @pl.when(s + 1 < count)
        def _():
            start_in(s + 1)

        in_copy(s, 0).wait()
        in_copy(s, 1).wait()

        @pl.when(s >= 2)
        def _():
            wait_out(s - 2)

        g = at(ld_ref, s)
        h = at(gm_ref, g)
        cg = at(c_ref, g)
        ch = at(c_ref, h)
        if packed:
            lo_g, hi_g = _unpack_tile(win[pl.ds(base, 8), :].astype(jnp.int32))
            lo_h, hi_h = _unpack_tile(
                win[pl.ds(base + 8, 8), :].astype(jnp.int32)
            )
            if apply_diag:
                lo_g, hi_g = _refresh_packed(lo_g, hi_g, mv_ref, col, 8 * g, r8)
                lo_h, hi_h = _refresh_packed(lo_h, hi_h, mv_ref, col, 8 * h, r8)
            vg, vh = vmask(g), vmask(h)
            d_lo_g = jnp.maximum(lo_g - pltpu.roll(lo_h, cg, 0), 0) * vg
            d_hi_g = jnp.maximum(hi_g - pltpu.roll(hi_h, cg, 0), 0) * vg
            d_lo_h = jnp.maximum(lo_h - pltpu.roll(lo_g, ch, 0), 0) * vh
            d_hi_h = jnp.maximum(hi_h - pltpu.roll(hi_g, ch, 0), 0) * vh
            tout[pl.ds(base, 8), :] = jnp.sum(
                d_lo_g.astype(jnp.float32), axis=1, keepdims=True
            ) + jnp.sum(d_hi_g.astype(jnp.float32), axis=1, keepdims=True)
            tout[pl.ds(base + 8, 8), :] = jnp.sum(
                d_lo_h.astype(jnp.float32), axis=1, keepdims=True
            ) + jnp.sum(d_hi_h.astype(jnp.float32), axis=1, keepdims=True)
            start_out(s)
            return 0
        w_g = win[pl.ds(base, 8), :].astype(jnp.int32)
        w_h = win[pl.ds(base + 8, 8), :].astype(jnp.int32)
        if apply_diag:
            mv_b = mv_ref[:]
            w_g = jnp.where(col == 8 * g + r8, mv_b, w_g)
            w_h = jnp.where(col == 8 * h + r8, mv_b, w_h)
        d_g = jnp.maximum(pltpu.roll(w_h, cg, 0) - w_g, 0) * vmask(g)
        d_h = jnp.maximum(pltpu.roll(w_g, ch, 0) - w_h, 0) * vmask(h)
        tout[pl.ds(base, 8), :] = jnp.sum(
            d_g.astype(jnp.float32), axis=1, keepdims=True
        )
        tout[pl.ds(base + 8, 8), :] = jnp.sum(
            d_h.astype(jnp.float32), axis=1, keepdims=True
        )
        start_out(s)
        return 0

    start_in(0)
    lax.fori_loop(0, count, body, 0)

    @pl.when(count >= 2)
    def _():
        wait_out(count - 2)

    wait_out(count - 1)


VMEM_BUDGET = 12 * 1024 * 1024  # ~16 MB/core, minus headroom for Mosaic

# (block, n_cols)-sized VMEM buffers per matrix: pipelined in + out blocks
# (double-buffered, x2 each) plus one gather scratch -> 5; the lean
# (w-only) mode halves the total.
def _buffers(track_hb: bool) -> int:
    return 10 if track_hb else 5


def largest_fitting_block(
    n: int, per_row_bytes: int, cap: int = 512, fixed_bytes: int = 0
) -> int | None:
    """Largest multiple-of-8 divisor of n whose row count times
    ``per_row_bytes`` (plus block-size-independent ``fixed_bytes`` —
    broadcast vector rows and the like) fits the VMEM budget. Shared
    block-search scaffold for every streaming kernel (this one and
    pallas_fd)."""
    limit = min(cap, max(VMEM_BUDGET - fixed_bytes, 0) // max(per_row_bytes, 1))
    best = None
    for b in range(8, limit + 1, 8):
        if n % b == 0:
            best = b
    return best


def _pick_block(
    n: int,
    itemsize: int = 4,
    cap: int = 512,
    track_hb: bool = True,
    n_cols: int | None = None,
    n_buffers: int | None = None,
    diag_rows: bool = True,
) -> int | None:
    """Largest multiple-of-8 divisor of the ROW count ``n`` such that
    every VMEM-resident buffer set fits the per-core budget. ``n_cols``
    is the block width (the shard's local column count; defaults to the
    unsharded square case n_cols = n); ``n_buffers`` overrides the
    (block, n_cols)-sized buffer count for kernels with a different
    residency set (the totals pass holds 3: w-in x2 + gather scratch);
    ``diag_rows`` says whether this kernel variant carries the mv/hbv
    broadcast rows (the default True is the conservative worst case
    ``supported()`` gates on).

    Beyond the matrix buffers, the search budgets the small operands
    too (same strict-conservatism rule as pallas_fd._fixed_bytes): the
    valid and totals columns are lane-padded to (block, 128) — per-row
    bytes — and the mv/hbv broadcast rows are sublane-padded (1 -> 8
    rows) int32, a block-size-independent fixed cost. All
    double-buffered."""
    width = n if n_cols is None else n_cols
    buffers = _buffers(track_hb) if n_buffers is None else n_buffers
    # valid (int8) + totals (f32) columns, padded to 128 lanes, x2.
    per_row = buffers * width * itemsize + 2 * 128 * (1 + 4)
    # mv (+hbv when heartbeats ride along) broadcast rows, 8-sublane
    # padded int32, x2 — a real (and at 32k-wide, megabyte-scale) cost,
    # but only for the kernel variant that carries the diagonal refresh
    # (the round's FIRST sub-exchange); callers pass diag_rows=False
    # for the refresh-free variants so those keep the larger block.
    fixed = (2 if track_hb else 1) * 2 * 8 * 4 * width if diag_rows else 0
    return largest_fitting_block(n, per_row, cap, fixed)


def supported(
    n: int, itemsize: int, track_hb: bool = True, n_local: int | None = None
) -> bool:
    """Whether the fused kernel can run this shape (callers fall back to
    the XLA path when not). Requires the grouped-matching family
    (n % 8 == 0 rows), lane-aligned manual DMA on the LOCAL column count
    (n_local % 128 == 0 — Mosaic rejects copies of partial 128-lane
    tiles, and a non-multiple column count is a partial tile of the
    padded memref; n_local = n unsharded), and a legal VMEM block."""
    width = n if n_local is None else n_local
    return (
        n % 128 == 0
        and width % 128 == 0
        and _pick_block(n, itemsize, track_hb=track_hb, n_cols=width) is not None
    )


@functools.partial(jax.jit, static_argnames=("budget", "interpret"))
def fused_pull_m8(
    w: jax.Array,
    hb: jax.Array | None,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    salt: jax.Array,
    run_salt: jax.Array,
    budget: int,
    interpret: bool = False,
    mv: jax.Array | None = None,
    hbv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
    totals: jax.Array | None = None,
):
    """One fused grouped-matching sub-exchange. Returns (w', hb'), or
    just w' when ``hb`` is None (the lean convergence-only profile: no
    heartbeat matrix exists, and the halved VMEM footprint buys larger
    row blocks).

    ``gm``/``c`` come from gossip._grouped_matching; ``valid`` is the
    per-row alive-pair mask (alive & alive[p]). Passing ``mv`` (owner
    max_version, (n_local,) int32; plus ``hbv``, owner heartbeats, when
    hb is tracked) folds the round's owner-diagonal refresh into this
    call — the caller must then NOT pre-apply the diagonal select, and
    should pass the vectors only on the round's FIRST sub-exchange
    (later ones see the refreshed diagonal in w itself).

    Column sharding (the two-pass sharded path): ``w`` may be a
    (N, n_local) column block of the global matrix. Pass this shard's
    ``owner_offset`` (global owner id of local column 0) and ``totals``
    — the rows' GLOBAL deficit totals from fused_pull_totals_m8, psum'd
    across shards. Rows stay unsharded, so the peer DMA never leaves
    the shard.
    """
    track_hb = hb is not None
    apply_diag = mv is not None
    if apply_diag and track_hb and hbv is None:
        raise ValueError("hbv required when mv is given and hb is tracked")
    if hbv is not None and not track_hb:
        raise ValueError("hbv given but no hb matrix to refresh (lean mode)")
    if hbv is not None and mv is None:
        raise ValueError("hbv given without mv: the diagonal refresh is all-or-none")
    n_rows, n_cols = w.shape
    use_totals = totals is not None
    itemsize = w.dtype.itemsize
    if track_hb:
        itemsize = max(itemsize, hb.dtype.itemsize)
    block = _pick_block(
        n_rows, itemsize, track_hb=track_hb, n_cols=n_cols,
        diag_rows=apply_diag,
    )
    if block is None or n_rows % 128 != 0 or n_cols % 128 != 0:
        raise ValueError(f"no suitable row block for shape {w.shape}")
    if not track_hb:
        # Minimal-tile dummies keep the kernel signature fixed without
        # spending VMEM (same trick the round-1 kernel used).
        hb = jnp.zeros((16, 128), w.dtype)
    hb_spec = (
        pl.BlockSpec((block, n_cols), lambda i, *_: (i, 0))
        if track_hb
        else pl.BlockSpec((16, 128), lambda i, *_: (0, 0))
    )
    meta = jnp.stack(
        [
            salt.astype(jnp.int32),
            run_salt.astype(jnp.int32),
            jnp.asarray(budget, jnp.int32),
            jnp.asarray(owner_offset, jnp.int32),
        ]
    )
    if use_totals:
        totals = totals.astype(jnp.float32).reshape(n_rows, 1)
        tot_spec = pl.BlockSpec((block, 1), lambda i, *_: (i, 0))
    else:
        totals = jnp.zeros((16, 128), jnp.float32)
        tot_spec = pl.BlockSpec((16, 128), lambda i, *_: (0, 0))
    if apply_diag:
        mv = mv.astype(jnp.int32)[None, :]
        hbv = (
            hbv.astype(jnp.int32)[None, :]
            if track_hb
            else jnp.zeros((1, 128), jnp.int32)
        )
        vec_spec = pl.BlockSpec((1, n_cols), lambda i, *_: (0, 0))
        hbv_spec = vec_spec if track_hb else pl.BlockSpec(
            (1, 128), lambda i, *_: (0, 0)
        )
    else:
        mv = jnp.zeros((1, 128), jnp.int32)
        hbv = jnp.zeros((1, 128), jnp.int32)
        vec_spec = hbv_spec = pl.BlockSpec((1, 128), lambda i, *_: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_rows // block,),
        in_specs=[
            pl.BlockSpec((block, n_cols), lambda i, *_: (i, 0)),  # w block
            hb_spec,  # hb block (dummy tile when lean)
            pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),  # valid col
            tot_spec,  # global totals col (dummy tile when unused)
            vec_spec,  # mv row (dummy tile when diag off)
            hbv_spec,  # heartbeat row (dummy tile when diag off / lean)
            pl.BlockSpec(memory_space=pl.ANY),  # w HBM (gather source)
            pl.BlockSpec(memory_space=pl.ANY),  # hb HBM
        ],
        out_specs=[
            pl.BlockSpec((block, n_cols), lambda i, *_: (i, 0)),
            hb_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((block, n_cols), w.dtype),
            pltpu.VMEM((block, n_cols) if track_hb else (16, 128), hb.dtype),
            pltpu.SemaphoreType.DMA((2, block // 8)),
        ],
    )
    kernel = functools.partial(
        _m8_kernel,
        block=block,
        n=n_cols,
        track_hb=track_hb,
        apply_diag=apply_diag,
        use_totals=use_totals,
    )
    w_new, hb_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(w.shape, w.dtype),
            jax.ShapeDtypeStruct(hb.shape, hb.dtype),
        ],
        interpret=interpret,
    )(
        gm.astype(jnp.int32),
        c.astype(jnp.int32),
        meta,
        w,
        hb,
        valid.astype(jnp.int8)[:, None],
        totals,
        mv,
        hbv,
        w,
        hb,
    )
    return (w_new, hb_new) if track_hb else w_new


def pairs_nbuf(
    n: int,
    itemsize: int,
    track_hb: bool = True,
    n_local: int | None = None,
    fd_sizes: tuple | None = None,
    packed: bool = False,
) -> int | None:
    """Scratch-buffer rotation depth for the pair-fused kernel at this
    shape, or None when it cannot run. 3 whenever VMEM allows — each
    slot's out DMA then has a full later slot's compute to land before
    its buffer is reused (no stall); 2 buys the widest shapes at the
    price of one hot out-DMA wait per slot. One accounting shared by
    the wrapper and the dispatch gate.

    The VMEM residency (no in-spec streaming): nbuf (16, width) tile
    pairs per matrix (outputs overwrite them in place), the two
    (8, width) uint32 dither bases, and the sublane-padded broadcast
    rows — mv (+hbv) diag rows plus the convergence-target row a
    tracked run's last sub-exchange carries (worst case fanout=1: diag
    AND check ride the same call), charged unconditionally so the gate
    never admits a shape whose tracked instance exceeds VMEM. The
    sharded form adds only the tiny (16*nbuf, 1) totals scratch.

    ``fd_sizes`` = (heartbeat itemsize, fd-mean itemsize[, icount
    itemsize, live bytes/elem]) when the round's last sub-exchange
    carries the fused FD epilogue: it adds tile pairs for last_change,
    imean, icount, the live matrix (bool held as 4 B/elem in VMEM —
    see pallas_fd._per_row_bytes — or the 0.125 B/elem bitmap when the
    shrunk rung packs it) and the streamed round-start hb0 (charged
    unconditionally — only fanout > 1 streams it, but the gate must
    never admit a shape whose multi-sub-exchange instance exceeds
    VMEM). The legacy 2-tuple reads as the int16/bool bookkeeping.

    ``packed`` is the u4 residual rung (uint8 nibble pairs): the w
    tiles shrink to the byte width (n_local // 2 columns, lane-aligned
    — n_local % 256), the dither bases halve with them, and the
    resident rows are the packed write-bump + packed need nibbles.
    Lean-profile only (the packed kernel carries no hb/FD tiles)."""
    width = n if n_local is None else n_local
    if packed:
        if track_hb or fd_sizes is not None:
            return None  # the nibble codec serves the lean profile only
        if n % 128 != 0 or width % 256 != 0:
            return None  # byte columns must stay 128-lane aligned
        bw = width // 2
        bases = 2 * 8 * bw * 4  # r_k1 + jm (js derived per use)
        vecs = 2 * 8 * bw * 4  # packed bump row + packed need row
        for nbuf in (3, 2):
            tiles = 16 * nbuf * bw * 1  # uint8 tile pairs, in-place out
            if tiles + bases + vecs <= VMEM_BUDGET:
                return nbuf
        return None
    if n % 128 != 0 or width % 128 != 0:
        return None
    bases = 2 * 8 * width * 4
    vecs = ((2 if track_hb else 1) + 1) * 8 * width * 4
    if fd_sizes is not None:
        hb_sz, fd_sz, ic_sz, live_sz = _norm_fd_sizes(fd_sizes)
        # live scratch: lane-padded rows (the packed bitmap's n/8
        # bytes can sit under one 128-lane tile at narrow shards).
        live_row = max(int(width * live_sz), 128)
    for nbuf in (3, 2):
        per_tile = 16 * nbuf * width
        tiles = (2 if track_hb else 1) * per_tile * itemsize
        if fd_sizes is not None:
            tiles += per_tile * (hb_sz + fd_sz + ic_sz + hb_sz)
            tiles += 16 * nbuf * live_row
        if tiles + bases + vecs <= VMEM_BUDGET:
            return nbuf
    return None


def _norm_fd_sizes(fd_sizes: tuple) -> tuple[int, int, int, float]:
    """(hb, fd[, icount, live]) -> the full 4-tuple; the legacy 2-tuple
    reads as the int16 counter + bool live accounting it was minted
    for."""
    if len(fd_sizes) == 2:
        return (*fd_sizes, 2, 4.0)
    hb_sz, fd_sz, ic_sz, live_sz = fd_sizes
    return hb_sz, fd_sz, ic_sz, float(live_sz)


def pairs_supported(
    n: int,
    itemsize: int,
    track_hb: bool = True,
    n_local: int | None = None,
    fd_sizes: tuple | None = None,
    packed: bool = False,
) -> bool:
    """Whether the pair-fused kernel can run this shape (see
    pairs_nbuf for the accounting)."""
    return (
        pairs_nbuf(n, itemsize, track_hb, n_local, fd_sizes, packed)
        is not None
    )


def pairs_supported_for(
    n: int,
    w: jax.Array,
    hb: jax.Array | None,
    fd_sizes: tuple | None = None,
) -> bool:
    """pairs_supported with itemsize, packing and local width derived
    from the operands — the one eligibility rule shared by the sim_step
    dispatch and the fused_pull_pairs wrapper. A uint8 w IS the packed
    u4 residual rung (sim/packed.is_packed_w): its stored width is the
    byte width, so the logical local column count is doubled."""
    packed = w.dtype == jnp.uint8
    itemsize = w.dtype.itemsize
    if hb is not None:
        itemsize = max(itemsize, hb.dtype.itemsize)
    width = w.shape[-1] * 2 if packed else w.shape[-1]
    return pairs_supported(
        n, itemsize, track_hb=hb is not None, n_local=width,
        fd_sizes=fd_sizes, packed=packed,
    )


def _pairs_call(
    w,
    hb,
    gm,
    c,
    valid,
    salt,
    run_salt,
    budget,
    interpret,
    mv,
    hbv,
    owner_offset,
    totals,
    check,
    fd,
    fd_params,
    alias_hb,
    lanes,
):
    """Shared builder behind fused_pull_pairs (lanes=False) and
    fused_pull_pairs_lanes (lanes=True): constructs the operand list,
    specs and scratch from the same table the kernel unpacks
    (``_pairs_ref_names``) and invokes one pallas_call. In lane mode
    every array carries a leading S axis and the grid is (S,)."""
    track_hb = hb is not None
    apply_diag = mv is not None
    use_totals = totals is not None
    do_check = check is not None
    do_fd = fd is not None
    # A uint8 w IS the packed u4 residual rung (sim/packed.is_packed_w):
    # tiles stay byte-packed in VMEM, the compute widens the nibbles
    # transiently, and ``mv`` carries the per-owner WRITE BUMP (the
    # packed round-start refresh: saturating shift + diagonal zero)
    # instead of the owner max_version row.
    packed = w.dtype == jnp.uint8
    if packed and (track_hb or do_fd):
        raise ValueError(
            "packed u4 w is lean-only in the pairs kernel (no hb/FD tiles)"
        )
    if apply_diag and track_hb and hbv is None:
        raise ValueError("hbv required when mv is given and hb is tracked")
    if hbv is not None and not track_hb:
        raise ValueError("hbv given but no hb matrix to refresh (lean mode)")
    if hbv is not None and mv is None and not do_fd:
        raise ValueError("hbv given without mv: the diagonal refresh is all-or-none")
    fd_live_bits = False
    if do_fd:
        if not track_hb:
            raise ValueError("fused FD requires the heartbeat matrix")
        if hbv is None:
            raise ValueError("fused FD requires hbv (hb0's diagonal refresh)")
        if fd_params is None:
            raise ValueError("fused FD requires fd_params statics")
        fd_tick, fd_lc, fd_im, fd_ic, fd_hb0_mat, fd_phi = fd
        fd_hb0 = fd_hb0_mat is not None
        # The 5th fd_params slot (when present) says the live matrix
        # stores as the column bitmap (SimConfig.live_bits); the legacy
        # 4-tuple reads as the bool form.
        fd_live_bits = len(fd_params) > 4 and bool(fd_params[4])
    else:
        fd_hb0 = False
    if lanes:
        n_lanes, n, n_cols = w.shape
    else:
        n, n_cols = w.shape
    itemsize = w.dtype.itemsize
    if track_hb:
        itemsize = max(itemsize, hb.dtype.itemsize)
    fd_sizes = (
        (
            fd_lc.dtype.itemsize,
            fd_im.dtype.itemsize,
            fd_ic.dtype.itemsize,
            0.125 if fd_live_bits else 4,
        )
        if do_fd
        else None
    )
    nbuf = pairs_nbuf(
        n, itemsize, track_hb,
        n_local=n_cols * 2 if packed else n_cols,
        fd_sizes=fd_sizes, packed=packed,
    )
    if nbuf is None:
        raise ValueError(f"pair-fused kernel cannot run shape {w.shape}")
    gm = gm.astype(jnp.int32)
    if lanes:
        leaders, count, vbits = jax.vmap(
            lambda g, v: _pairs_slots(n, g, v)
        )(gm, valid)

        def lane_vec(x):
            return jnp.broadcast_to(
                jnp.asarray(x, jnp.int32), (n_lanes,)
            ).astype(jnp.int32)

        meta = jnp.stack(
            [
                lane_vec(salt),
                lane_vec(run_salt),
                lane_vec(budget),
                count,
                lane_vec(owner_offset),
                lane_vec(fd_tick if do_fd else 0),
            ],
            axis=1,
        )
    else:
        leaders, count, vbits = _pairs_slots(n, gm, valid)
        meta = jnp.stack(
            [
                salt.astype(jnp.int32),
                run_salt.astype(jnp.int32),
                jnp.asarray(budget, jnp.int32),
                count,
                jnp.asarray(owner_offset, jnp.int32),
                (
                    fd_tick.astype(jnp.int32)
                    if do_fd
                    else jnp.asarray(0, jnp.int32)
                ),
            ]
        )
    if not track_hb:
        hb = jnp.zeros((8, 128), w.dtype)
    if use_totals:
        totals = totals.astype(jnp.float32).reshape(
            (n_lanes, n, 1) if lanes else (n, 1)
        )
    else:
        totals = jnp.zeros((8, 128), jnp.float32)

    # Broadcast-row specs: one (1, width) row per call, or per LANE in
    # lane mode (a leading squeezed axis indexed by the grid step).
    def row_spec(width):
        if lanes:
            return pl.BlockSpec((None, 1, width), lambda s, *_: (s, 0, 0))
        return pl.BlockSpec((1, width), lambda *_: (0, 0))

    dummy_spec = pl.BlockSpec((1, 128), lambda *_: (0, 0))

    def row_operand(vec):
        # (n_cols,) [or (S, n_cols)] -> broadcast row in the call shape.
        v = vec.astype(jnp.int32)
        return v[:, None, :] if lanes else v[None, :]

    if do_check:
        needed, alive, alive_owner = check
        abits = (
            jax.vmap(lambda a: _pack_row_bits(a, n))(alive)
            if lanes
            else _pack_row_bits(alive, n)
        )
        if packed:
            # Packed convergence target: a zero residual IS "caught
            # up", so the need row carries only the owner-ALIVE bit per
            # nibble (0 excuses a dead owner); ``needed`` is unused.
            ao = alive_owner.astype(jnp.int32)
            need = row_operand(ao[..., 0::2] | (ao[..., 1::2] << 4))
        else:
            # Dead owners are excused by zeroing their target:
            # watermarks are non-negative, so w >= 0 holds everywhere —
            # one broadcast row instead of a separate alive-owner mask
            # row.
            need = row_operand(
                jnp.where(alive_owner, needed.astype(jnp.int32), 0)
            )
        need_spec = row_spec(n_cols)
    else:
        abits = jnp.zeros(
            ((n_lanes, n // 8) if lanes else (n // 8,)), jnp.int32
        )
        need = jnp.zeros((1, 128), jnp.int32)
        need_spec = dummy_spec
    use_hbv = (apply_diag and track_hb) or do_fd
    if apply_diag:
        if packed:
            mv = _pack_bump_nibbles(mv)  # the write-bump row
        mv = row_operand(mv)
        vec_spec = row_spec(n_cols)
    else:
        mv = jnp.zeros((1, 128), jnp.int32)
        vec_spec = dummy_spec
    if use_hbv:
        hbv = row_operand(hbv)
        hbv_spec = row_spec(n_cols)
    else:
        hbv = jnp.zeros((1, 128), jnp.int32)
        hbv_spec = dummy_spec
    if do_fd:
        phi32 = jnp.asarray(fd_phi, jnp.float32)
        if lanes:
            fdp = jnp.broadcast_to(
                jnp.broadcast_to(phi32, (n_lanes,))[:, None, None],
                (n_lanes, 1, 128),
            )
        else:
            fdp = jnp.full((1, 128), phi32, jnp.float32)
        fdp_spec = row_spec(128)
    else:
        fdp = jnp.zeros((1, 128), jnp.float32)
        fdp_spec = dummy_spec

    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [vec_spec, hbv_spec, need_spec, fdp_spec,
                any_spec, any_spec, any_spec]
    inputs = [mv, hbv, need, fdp, w, hb, totals]
    if do_fd:
        in_specs += [any_spec] * (4 if fd_hb0 else 3)
        inputs += [fd_lc, fd_im, fd_ic]
        if fd_hb0:
            inputs.append(fd_hb0_mat)
    flag_shape = (n_lanes, 1, 1) if lanes else (1, 1)
    flag_spec = (
        pl.BlockSpec((None, 1, 1), lambda s, *_: (s, 0, 0))
        if lanes
        else pl.BlockSpec((1, 1), lambda *_: (0, 0))
    )
    out_specs = [any_spec, any_spec, flag_spec]
    out_shapes = [
        jax.ShapeDtypeStruct(w.shape, w.dtype),
        jax.ShapeDtypeStruct(hb.shape, hb.dtype),
        jax.ShapeDtypeStruct(flag_shape, jnp.int32),
    ]
    if do_fd:
        out_specs += [any_spec] * 4
        live_cols = n_cols // 8 if fd_live_bits else n_cols
        live_dt = jnp.uint8 if fd_live_bits else jnp.bool_
        out_shapes += [
            jax.ShapeDtypeStruct(fd_lc.shape, fd_lc.dtype),
            jax.ShapeDtypeStruct(fd_im.shape, fd_im.dtype),
            jax.ShapeDtypeStruct(fd_ic.shape, fd_ic.dtype),
            jax.ShapeDtypeStruct(
                (n_lanes, n, live_cols) if lanes else (n, live_cols), live_dt
            ),
        ]
    n_in_streams = 1 + int(track_hb) + int(use_totals) + (
        (3 + int(fd_hb0)) if do_fd else 0
    )
    n_out_streams = 1 + int(track_hb) + (4 if do_fd else 0)
    hb_scr = (16 * nbuf, n_cols) if track_hb else (8, 128)
    scratch = [
        pltpu.VMEM((16 * nbuf, n_cols), w.dtype),  # win (in-place out)
        pltpu.VMEM(hb_scr, hb.dtype),  # hbin (ditto)
        pltpu.VMEM((16 * nbuf, 1), jnp.float32),  # tscr
        pltpu.VMEM((1, 1), jnp.int32),  # fscr
    ]
    if do_fd:
        scratch += [
            pltpu.VMEM((16 * nbuf, n_cols), fd_lc.dtype),  # lcin
            pltpu.VMEM((16 * nbuf, n_cols), fd_im.dtype),  # imin
            pltpu.VMEM((16 * nbuf, n_cols), fd_ic.dtype),  # icin
            pltpu.VMEM((16 * nbuf, live_cols), live_dt),  # livescr
        ]
        if fd_hb0:
            scratch.append(pltpu.VMEM((16 * nbuf, n_cols), hb.dtype))
    scratch += [
        pltpu.SemaphoreType.DMA((nbuf, 2, n_in_streams)),
        pltpu.SemaphoreType.DMA((nbuf, 2, n_out_streams)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(n_lanes,) if lanes else (1,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _pairs_kernel,
        n=n_cols,
        track_hb=track_hb,
        apply_diag=apply_diag,
        use_totals=use_totals,
        check=do_check,
        nbuf=nbuf,
        lanes=lanes,
        fd=do_fd,
        fd_hb0=fd_hb0,
        fd_consts=fd_params[:4] if fd_params is not None else None,
        packed=packed,
        fd_live_bits=fd_live_bits,
    )
    # w (and usually hb) update IN PLACE: every row is read exactly
    # once (wait_in of its own slot) strictly before its out DMA
    # starts, and rows across slots are disjoint, so the aliasing
    # has no read-after-write hazard — unlike the m8 kernel, whose
    # peer gather may read rows whose output block already streamed
    # out. Halves the path's peak HBM (one resident copy per
    # matrix). ``alias_hb=False`` exists for callers that RETAIN
    # the input hb (the FD's round-start matrix on the round's
    # first sub-exchange): aliasing a still-live operand makes XLA
    # insert a full copy — two extra hb passes, worse than the
    # unaliased write. The fused FD's bookkeeping (last_change /
    # imean / icount) always aliases: each tile is read exactly once
    # before its updated tile streams out, and sim_step donates the
    # state they come from. Indices are over the flattened operand
    # list: 0-4 scalar prefetch (leaders, gm, c, vbits, abits),
    # 5 meta is prefetch too, then 6 mv, 7 hbv, 8 need, 9 fdp,
    # 10 w, 11 hb, 12 totals, 13 lc, 14 im, 15 ic[, 16 hb0].
    aliases = {10: 0}
    if alias_hb:
        aliases[11] = 1
    if do_fd:
        aliases.update({13: 3, 14: 4, 15: 5})
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        input_output_aliases=aliases,
        interpret=interpret,
    )(
        leaders,
        gm,
        c.astype(jnp.int32),
        vbits,
        abits,
        meta,
        *inputs,
    )
    w_new, hb_new, flag = outs[0], outs[1], outs[2]
    if do_fd:
        out = (w_new, hb_new) + tuple(outs[3:7])
    else:
        out = (w_new, hb_new) if track_hb else w_new
    if do_check:
        return out, (flag[:, 0, 0] if lanes else flag[0, 0])
    return out


@functools.partial(
    jax.jit, static_argnames=("budget", "interpret", "alias_hb", "fd_params")
)
def fused_pull_pairs(
    w: jax.Array,
    hb: jax.Array | None,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    salt: jax.Array,
    run_salt: jax.Array,
    budget: int,
    interpret: bool = False,
    mv: jax.Array | None = None,
    hbv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
    totals: jax.Array | None = None,
    check: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    fd: tuple | None = None,
    fd_params: tuple | None = None,
    alias_hb: bool = True,
):
    """One fused grouped-matching sub-exchange, pair-at-a-time: 4 bytes
    of HBM traffic per pair per matrix instead of the single-pass
    kernel's 6 (each row read once and written once — the involution
    means visiting pair (g, gm[g]) covers both directions). Bit-identical
    to fused_pull_m8 and to the XLA matching path (asserted in
    tests/test_pallas_pairs.py).

    Column sharding: ``w`` may be an (N, n_local) block. Pass this
    shard's ``owner_offset`` and ``totals`` — the rows' GLOBAL deficit
    totals from fused_pull_pairs_totals, psum'd across shards — exactly
    the fused_pull_m8 two-pass contract.

    ``check`` = (needed, alive, alive_owner) asks the round's last
    sub-exchange to also evaluate the convergence flag on its output
    tiles — ``needed`` is this shard's (n_local,) target
    (max_version[owners]), ``alive`` the (N,) row liveness,
    ``alive_owner`` the (n_local,) owner liveness. The flag (0/1 int32
    scalar, local to this shard) is appended to the return value;
    ops/gossip.py::all_converged_flag is the semantics being reproduced
    — same excusals, zero extra HBM traffic.

    ``fd`` = (tick, last_change, imean, icount, hb0, phi_threshold)
    asks the round's LAST sub-exchange to also run the whole phi-accrual
    FD phase on its output tiles (the fused round): ``hb0`` is the
    round-start heartbeat matrix (None at fanout == 1, where the input
    hb IS round-start), ``phi_threshold`` a float or traced f32 scalar,
    and ``fd_params`` = (max_interval, window, prior_weight,
    prior_mean) the static FD constants. The return value grows by
    (last_change', imean', icount', live') — bit-identical to the XLA
    FD block and to ops/pallas_fd.py (tests/test_fused_kernel.py),
    which stays as the standalone fallback for non-pairs paths.

    Reference anchor: the same server.py:378-495 hot loop; the pairing
    insight is that the reference's Syn/SynAck/Ack already computes both
    directions from the pre-handshake digests, so one visit per pair is
    semantically exact."""
    return _pairs_call(
        w, hb, gm, c, valid, salt, run_salt, budget, interpret,
        mv, hbv, owner_offset, totals, check, fd, fd_params, alias_hb,
        lanes=False,
    )


@functools.partial(
    jax.jit, static_argnames=("budget", "interpret", "alias_hb", "fd_params")
)
def fused_pull_pairs_lanes(
    w: jax.Array,
    hb: jax.Array | None,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    salt: jax.Array,
    run_salt: jax.Array,
    budget: int,
    interpret: bool = False,
    mv: jax.Array | None = None,
    hbv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
    totals: jax.Array | None = None,
    check: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    fd: tuple | None = None,
    fd_params: tuple | None = None,
    alias_hb: bool = True,
):
    """fused_pull_pairs lifted over a leading sweep-lane axis S: every
    array operand carries the lane dimension ((S, N, n_local) matrices,
    (S,) scalars, (S, n/8) matchings) and the kernel grid becomes (S,)
    — per-lane salts, matchings, budget dither, fanout masks (folded
    into ``valid`` by the caller) and FD phi all ride scalar prefetch.
    Lane s's output is bit-identical to fused_pull_pairs on lane s's
    operands (tests/test_fused_kernel.py); this is the implementation
    the custom_vmap rule dispatches to when SweepSimulator vmaps
    sim_step over scenarios."""
    return _pairs_call(
        w, hb, gm, c, valid, salt, run_salt, budget, interpret,
        mv, hbv, owner_offset, totals, check, fd, fd_params, alias_hb,
        lanes=True,
    )


def _pack_row_bits(mask: jax.Array, n: int) -> jax.Array:
    """(n,) boolean row mask -> (n/8,) int32, bit r = row 8g+r. The one
    packing the kernels' (8, 1) shift-unpack decodes."""
    return jnp.sum(
        mask.astype(jnp.int32).reshape(n // 8, 8)
        * (1 << jnp.arange(8, dtype=jnp.int32))[None, :],
        axis=1,
    )


def _pairs_slots(n: int, gm: jax.Array, valid: jax.Array):
    """Slot table for the pair-fused kernels: leader groups (g <= gm[g],
    padded to n/8 with 0 past ``count`` — never executed), the slot
    count, and the per-group packed alive-pair bits."""
    n_groups = n // 8
    gm = gm.astype(jnp.int32)
    gid = jnp.arange(n_groups, dtype=jnp.int32)
    is_leader = gid <= gm
    count = jnp.sum(is_leader.astype(jnp.int32))
    (leaders,) = jnp.nonzero(is_leader, size=n_groups, fill_value=0)
    return leaders.astype(jnp.int32), count, _pack_row_bits(valid, n)


def _pairs_totals_call(w, gm, c, valid, interpret, mv, owner_offset, lanes):
    apply_diag = mv is not None
    packed = w.dtype == jnp.uint8
    if lanes:
        n_lanes, n, n_cols = w.shape
    else:
        n, n_cols = w.shape
    if not pairs_supported_for(n, w, None):
        raise ValueError(f"pair-fused totals cannot run shape {w.shape}")
    gm = gm.astype(jnp.int32)
    if apply_diag and packed:
        # Packed rung: mv is the per-owner write bump — one shared
        # packing with the apply pass (_pack_bump_nibbles).
        mv = _pack_bump_nibbles(mv)
    if lanes:
        leaders, count, vbits = jax.vmap(
            lambda g, v: _pairs_slots(n, g, v)
        )(gm, valid)
        off = jnp.broadcast_to(
            jnp.asarray(owner_offset, jnp.int32), (n_lanes,)
        ).astype(jnp.int32)
        meta = jnp.stack([count, off], axis=1)
    else:
        leaders, count, vbits = _pairs_slots(n, gm, valid)
        meta = jnp.stack([count, jnp.asarray(owner_offset, jnp.int32)])
    if apply_diag:
        mv = mv.astype(jnp.int32)
        mv = mv[:, None, :] if lanes else mv[None, :]
        vec_spec = (
            pl.BlockSpec((None, 1, n_cols), lambda s, *_: (s, 0, 0))
            if lanes
            else pl.BlockSpec((1, n_cols), lambda *_: (0, 0))
        )
    else:
        mv = jnp.zeros((1, 128), jnp.int32)
        vec_spec = pl.BlockSpec((1, 128), lambda *_: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_lanes,) if lanes else (1,),
        in_specs=[
            vec_spec,  # mv row (dummy tile when diag off)
            pl.BlockSpec(memory_space=pl.ANY),  # w (HBM operand)
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # totals out
        scratch_shapes=[
            pltpu.VMEM((32, n_cols), w.dtype),  # win
            pltpu.VMEM((32, 1), jnp.float32),  # tout
            pltpu.SemaphoreType.DMA((2, 2)),  # in [buf, side]
            pltpu.SemaphoreType.DMA((2, 2)),  # out
        ],
    )
    kernel = functools.partial(
        _pairs_totals_kernel, n=n_cols, apply_diag=apply_diag, lanes=lanes,
        packed=packed,
    )
    (tot,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(
                (n_lanes, n, 1) if lanes else (n, 1), jnp.float32
            )
        ],
        interpret=interpret,
    )(
        leaders,
        gm,
        c.astype(jnp.int32),
        vbits,
        meta,
        mv,
        w,
    )
    return tot[..., 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_pull_pairs_totals(
    w: jax.Array,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    interpret: bool = False,
    mv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
) -> jax.Array:
    """Pass A of the sharded pair-fused pull: (N,) f32 LOCAL deficit row
    totals for this shard's (N, n_local) block, every row read ONCE
    (fused_pull_totals_m8 reads each row twice). The caller psums the
    result across shards and passes it to fused_pull_pairs as
    ``totals``; f32 sums of integer deficits are exact below 2^24, so
    the two-pass result is bit-identical to the single-pass kernel's."""
    return _pairs_totals_call(
        w, gm, c, valid, interpret, mv, owner_offset, lanes=False
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_pull_pairs_totals_lanes(
    w: jax.Array,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    interpret: bool = False,
    mv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
) -> jax.Array:
    """fused_pull_pairs_totals over a leading lane axis: (S, N, n_local)
    w -> (S, N) local totals, one grid step per lane (the sharded sweep
    path's pass A)."""
    return _pairs_totals_call(
        w, gm, c, valid, interpret, mv, owner_offset, lanes=True
    )


def _bcast_lane(x, batched, axis_size):
    """Broadcast an unbatched operand up to the lane axis (custom_vmap
    rule helper); batched operands already carry it in front."""
    if batched:
        return x
    x = jnp.asarray(x)
    return jnp.broadcast_to(x[None, ...], (axis_size,) + x.shape)


@functools.lru_cache(maxsize=128)
def _pairs_dispatcher(op_keys, budget, interpret, alias_hb, fd_params):
    """custom_vmap entry for one static pairs-call configuration: the
    primal path is the single-lane kernel; a vmapped call (sim_step
    under SweepSimulator's lane vmap) broadcasts any unbatched operands
    to the lane axis and runs the lane-lifted kernel — the grid itself
    absorbs the batch dimension instead of falling back to XLA. Keyed
    by the operand-name set (which optional blocks exist) plus the
    static scalars, so the returned callable is stable across sim_step
    retraces and its jit cache keys."""
    op_keys = frozenset(op_keys)
    do_check = "need" in op_keys
    do_fd = "lc" in op_keys

    def build(ops, lanes):
        fn = fused_pull_pairs_lanes if lanes else fused_pull_pairs
        check = (
            (ops["need"], ops["alive"], ops["alive_owner"])
            if do_check
            else None
        )
        fd = (
            (ops["tick"], ops["lc"], ops["im"], ops["ic"],
             ops.get("hb0"), ops["phi"])
            if do_fd
            else None
        )
        out = fn(
            ops["w"],
            ops.get("hb"),
            ops["gm"],
            ops["c"],
            ops["valid"],
            ops["salt"],
            ops["run_salt"],
            budget,
            interpret=interpret,
            mv=ops.get("mv"),
            hbv=ops.get("hbv"),
            owner_offset=ops["owner_offset"],
            totals=ops.get("totals"),
            check=check,
            fd=fd,
            fd_params=fd_params,
            alias_hb=alias_hb,
        )
        # Flatten to one tuple so primal and vmap rule agree on the
        # output pytree: (w, hb?, lc, im, ic, live?; flag?).
        if do_check:
            out, flag = out
        flat = out if isinstance(out, tuple) else (out,)
        if do_check:
            flat = flat + (flag,)
        return flat

    @jax.custom_batching.custom_vmap
    def run(ops):
        return build(ops, lanes=False)

    @run.def_vmap
    def _rule(axis_size, in_batched, ops):
        batched = in_batched[0]  # one positional arg: the ops dict
        ops = {
            k: _bcast_lane(v, batched[k], axis_size)
            for k, v in ops.items()
        }
        out = build(ops, lanes=True)
        return out, tuple(True for _ in out)

    return run


def pairs_pull(ops: dict, *, budget, interpret, alias_hb, fd_params=None):
    """The sim_step-facing pairs entry: dict-of-operands in, flat tuple
    out — (w', hb'?, last_change'?, imean'?, icount'?, live'?, flag?)
    with the optional parts keyed off which operands are present. Under
    jax.vmap (a sweep's lane axis) the custom_vmap rule reroutes to the
    lane-lifted kernel; called unbatched it is exactly
    fused_pull_pairs."""
    return _pairs_dispatcher(
        frozenset(ops), budget, interpret, alias_hb, fd_params
    )(ops)


@functools.lru_cache(maxsize=32)
def _pairs_totals_dispatcher(op_keys, interpret):
    op_keys = frozenset(op_keys)

    def build(ops, lanes):
        fn = (
            fused_pull_pairs_totals_lanes if lanes else fused_pull_pairs_totals
        )
        return fn(
            ops["w"],
            ops["gm"],
            ops["c"],
            ops["valid"],
            interpret=interpret,
            mv=ops.get("mv"),
            owner_offset=ops["owner_offset"],
        )

    @jax.custom_batching.custom_vmap
    def run(ops):
        return build(ops, lanes=False)

    @run.def_vmap
    def _rule(axis_size, in_batched, ops):
        batched = in_batched[0]
        ops = {
            k: _bcast_lane(v, batched[k], axis_size)
            for k, v in ops.items()
        }
        return build(ops, lanes=True), True

    return run


def pairs_totals(ops: dict, *, interpret):
    """sim_step-facing totals pass A (sharded path): vmap-aware like
    ``pairs_pull`` — lanes hit the lane-lifted totals kernel."""
    return _pairs_totals_dispatcher(frozenset(ops), interpret)(ops)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_pull_totals_m8(
    w: jax.Array,
    gm: jax.Array,
    c: jax.Array,
    valid: jax.Array,
    interpret: bool = False,
    mv: jax.Array | None = None,
    owner_offset: jax.Array | int = 0,
) -> jax.Array:
    """Pass A of the sharded fused pull: (N,) f32 LOCAL deficit row
    totals for this shard's (N, n_local) column block, one streamed
    read. The caller psums the result across shards and passes it to
    fused_pull_m8 as ``totals``; between them they reproduce the XLA
    sharded path's ``psum(d.sum(axis=1))`` bit-for-bit (integer-valued
    f32 sums are exact below 2^24).

    Pass ``mv`` on the round's first sub-exchange so the totals see the
    owner-diagonal refresh, exactly as the apply pass will."""
    apply_diag = mv is not None
    n_rows, n_cols = w.shape
    # This pass holds only w-in (double-buffered) + the gather scratch
    # — 3 (block, n_cols) buffers, not the apply pass's 5 — plus the
    # tiny (block, 1) totals out and broadcast rows, so it can afford
    # larger row blocks (one shared accounting in _pick_block).
    block = _pick_block(
        n_rows, w.dtype.itemsize, track_hb=False, n_cols=n_cols,
        n_buffers=3, diag_rows=apply_diag,
    )
    if block is None or n_rows % 128 != 0 or n_cols % 128 != 0:
        raise ValueError(f"no suitable row block for shape {w.shape}")
    meta = jnp.asarray(owner_offset, jnp.int32)[None]
    if apply_diag:
        mv = mv.astype(jnp.int32)[None, :]
        vec_spec = pl.BlockSpec((1, n_cols), lambda i, *_: (0, 0))
    else:
        mv = jnp.zeros((1, 128), jnp.int32)
        vec_spec = pl.BlockSpec((1, 128), lambda i, *_: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_rows // block,),
        in_specs=[
            pl.BlockSpec((block, n_cols), lambda i, *_: (i, 0)),  # w block
            pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),  # valid col
            vec_spec,  # mv row (dummy tile when diag off)
            pl.BlockSpec(memory_space=pl.ANY),  # w HBM (gather source)
        ],
        out_specs=[pl.BlockSpec((block, 1), lambda i, *_: (i, 0))],
        scratch_shapes=[
            pltpu.VMEM((block, n_cols), w.dtype),
            pltpu.SemaphoreType.DMA((block // 8,)),
        ],
    )
    kernel = functools.partial(
        _m8_totals_kernel, block=block, n=n_cols, apply_diag=apply_diag
    )
    (tot,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((n_rows, 1), jnp.float32)],
        interpret=interpret,
    )(
        gm.astype(jnp.int32),
        c.astype(jnp.int32),
        meta,
        w,
        valid.astype(jnp.int8)[:, None],
        mv,
        w,
    )
    return tot[:, 0]
