"""JAX kernels: the batched gossip round and its convergence metrics."""

from .gossip import convergence_metrics, select_peers, sim_step

__all__ = ("convergence_metrics", "select_peers", "sim_step")
