"""FaultPlan compiled for the batched JAX sim: per-round link and crash
masks.

The sim's unit of network activity is the per-round sub-exchange, so the
plan lowers to two mask families, both pure functions of
``(plan, tick, global indices)``:

- :func:`crash_mask` — (N,) bool, nodes inside a crash window this tick.
  ``sim_step`` freezes their heartbeats/writes and invalidates their
  exchanges (the node's process isn't running), without touching the
  churn ground truth — the restart half of the window ends the freeze.
- :func:`link_ok` — (N,) bool per sub-exchange direction: whether
  traffic ``src[i] -> dst[i]`` is permitted. Partitions mask
  cross-group pairs exactly like the churn mask masks dead pairs;
  probabilistic faults (drop, mid-handshake EOF, delays of >= 1 tick —
  a delayed exchange misses its round deadline) combine into one
  per-direction failure probability and draw from the same
  global-index multiplicative hash family as the budget dither
  (ops/gossip._hash_uniform), so a column-sharded run produces the
  identical mask sequence as a single-device run.

Time is measured in ticks (1 tick = 1 reference second); node sets are
fraction-addressed (``FaultPlan.check_sim_compatible`` rejects
name-addressed plans at config time). Duplication is a modelled no-op:
the sim's max-merge is idempotent.

Determinism: nothing here reads the run's PRNG key — masks depend only
on ``(plan.seed, tick)``, so the same (seed, FaultPlan) yields the
identical link-mask sequence on every run and every shard layout
(tests/test_faults.py).

Sweep lanes: because the probabilistic draws depend on the plan only
through ``plan.seed``, a multi-scenario sweep (sim/sweep.py) lowers a
per-lane fault-plan *salt* for free — ``link_ok(..., seed=s)`` with a
traced uint32 ``s`` produces exactly the mask sequence of
``dataclasses.replace(plan, seed=s)``, so one compiled step serves a
whole ensemble of plan variants (crash/partition windows are pure
functions of tick and take no seed; only the probabilistic link draws
re-roll per lane).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .plan import FaultPlan, NodeSet


def _pair_uniform(
    i: jax.Array, j: jax.Array, salt: jax.Array
) -> jax.Array:
    """Deterministic (i, j, salt) -> [0, 1) draw, elementwise over two
    index vectors — ops.gossip's shared hash mix evaluated per
    (row, peer) pair instead of per (row, owner column), so the draw
    for a directed link depends only on GLOBAL indices and is
    shard-exact."""
    from ..ops.gossip import hash_mix_u32

    h = hash_mix_u32(
        i.astype(jnp.uint32), j.astype(jnp.uint32), salt.astype(jnp.uint32)
    )
    return (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)


def _fault_salt(
    plan: FaultPlan,
    tick: jax.Array,
    fault_idx: int,
    sub: jax.Array,
    seed: jax.Array | None = None,
):
    """One salt per (plan seed, tick, link-fault entry, sub-exchange
    direction): every fault entry and every direction of every
    sub-exchange draws independently, reproducibly. ``seed`` (traced
    uint32) overrides ``plan.seed`` — the sweep's per-lane fault salt;
    it must be pre-masked to 32 bits so the traced path computes the
    exact expression the static path does."""
    if seed is None:
        seed = jnp.uint32(plan.seed & 0xFFFFFFFF)
    else:
        seed = seed.astype(jnp.uint32)
    return (
        tick.astype(jnp.uint32) * jnp.uint32(0x51ED2701)
        ^ seed * jnp.uint32(0x9E3779B9)
        ^ jnp.uint32(fault_idx * 2 + 1) * jnp.uint32(0x7FEB3527)
        ^ jnp.asarray(sub).astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
    )


def _member_mask(ns: NodeSet, idx: jax.Array, n: int) -> jax.Array | None:
    """(len(idx),) bool — which of the given global indices fall in the
    fraction-addressed set (None = all; explicit names were rejected by
    check_sim_compatible)."""
    if ns.matches_all():
        return None
    lo, hi = ns.frac
    pos = idx.astype(jnp.float32) / n
    return (pos >= lo) & (pos < hi)


def crash_mask(plan: FaultPlan, n: int, tick: jax.Array) -> jax.Array:
    """(N,) bool: nodes down inside a crash window at this tick."""
    i = jnp.arange(n, dtype=jnp.int32)
    t = tick.astype(jnp.float32)
    down = jnp.zeros((n,), bool)
    for cr in plan.crashes:
        active = (t >= cr.at) & (t < cr.at + cr.down_for)
        members = _member_mask(cr.nodes, i, n)
        hit = active if members is None else active & members
        down = down | hit
    return down


def _link_failure_prob(lf) -> float:
    """Per-direction sub-exchange failure probability of one LinkFault:
    drop, mid-handshake EOF and a >= 1-tick delay each independently
    kill the exchange for the round (matching the runtime's independent
    per-check draws)."""
    p_ok = (1.0 - lf.drop) * (1.0 - lf.eof)
    if lf.delay >= 1.0:
        p_ok *= 1.0 - lf.delay_prob
    return 1.0 - p_ok


def link_ok(
    plan: FaultPlan,
    n: int,
    tick: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    sub: jax.Array | int = 0,
    *,
    seed: jax.Array | None = None,
) -> jax.Array:
    """(N,) bool: is traffic ``src[i] -> dst[i]`` permitted this tick?

    ``sub`` distinguishes the round's sub-exchange directions so each
    draws fresh fault randomness. Pass ``src=p, dst=arange(n)`` for the
    receive direction of a pull from peer ``p`` and ``src=arange(n),
    dst=p`` for the send direction. ``seed`` (traced uint32, pre-masked
    to 32 bits) overrides ``plan.seed`` for the probabilistic draws —
    bit-identical to ``replace(plan, seed=...)``, which is how sweep
    lanes run plan ensembles under one compile.
    """
    t = tick.astype(jnp.float32)
    ok = jnp.ones(src.shape, bool)
    for part in plan.partitions:
        end = jnp.inf if part.end is None else part.end
        active = (t >= part.start) & (t < end)
        g_src = (src * part.n_groups) // n
        g_dst = (dst * part.n_groups) // n
        ok = ok & ~(active & (g_src != g_dst))
    for idx, lf in enumerate(plan.links):
        p_fail = _link_failure_prob(lf)
        if p_fail <= 0.0:
            continue
        end = jnp.inf if lf.end is None else lf.end
        active = (t >= lf.start) & (t < end)
        applies = jnp.ones(src.shape, bool)
        src_m = _member_mask(lf.src, src, n)
        if src_m is not None:
            applies = applies & src_m
        dst_m = _member_mask(lf.dst, dst, n)
        if dst_m is not None:
            applies = applies & dst_m
        u = _pair_uniform(src, dst, _fault_salt(plan, tick, idx, sub, seed))
        ok = ok & ~(active & applies & (u < p_fail))
    return ok


def plan_affects_links(plan: FaultPlan | None) -> bool:
    """Whether the plan carries any link-level behavior the sim must
    mask (partitions, or link faults with a nonzero per-round failure
    probability)."""
    if plan is None:
        return False
    return bool(plan.partitions) or any(
        _link_failure_prob(lf) > 0.0 for lf in plan.links
    )


def plan_affects_nodes(plan: FaultPlan | None) -> bool:
    return plan is not None and bool(plan.crashes)
