"""FaultPlan compiled for the batched JAX sim: per-round link and crash
masks.

The sim's unit of network activity is the per-round sub-exchange, so the
plan lowers to two mask families, both pure functions of
``(plan, tick, global indices)``:

- :func:`crash_mask` — (N,) bool, nodes inside a crash window this tick.
  ``sim_step`` freezes their heartbeats/writes and invalidates their
  exchanges (the node's process isn't running), without touching the
  churn ground truth — the restart half of the window ends the freeze.
- :func:`link_ok` — (N,) bool per sub-exchange direction: whether
  traffic ``src[i] -> dst[i]`` is permitted. Partitions mask
  cross-group pairs exactly like the churn mask masks dead pairs;
  probabilistic faults (drop, mid-handshake EOF, delays of >= 1 tick —
  a delayed exchange misses its round deadline) combine into one
  per-direction failure probability and draw from the same
  global-index multiplicative hash family as the budget dither
  (ops/gossip._hash_uniform), so a column-sharded run produces the
  identical mask sequence as a single-device run.

Time is measured in ticks (1 tick = 1 reference second); node sets are
fraction-addressed (``FaultPlan.check_sim_compatible`` rejects
name-addressed plans at config time). Duplication is a modelled no-op:
the sim's max-merge is idempotent.

Determinism: nothing here reads the run's PRNG key — masks depend only
on ``(plan.seed, tick)``, so the same (seed, FaultPlan) yields the
identical link-mask sequence on every run and every shard layout
(tests/test_faults.py).

Sweep lanes: because the probabilistic draws depend on the plan only
through ``plan.seed``, a multi-scenario sweep (sim/sweep.py) lowers a
per-lane fault-plan *salt* for free — ``link_ok(..., seed=s)`` with a
traced uint32 ``s`` produces exactly the mask sequence of
``dataclasses.replace(plan, seed=s)``, so one compiled step serves a
whole ensemble of plan variants (crash/partition windows are pure
functions of tick and take no seed; only the probabilistic link draws
re-roll per lane).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .plan import FaultPlan, NodeSet


def _pair_uniform(
    i: jax.Array, j: jax.Array, salt: jax.Array
) -> jax.Array:
    """Deterministic (i, j, salt) -> [0, 1) draw, elementwise over two
    index vectors — ops.gossip's shared hash mix evaluated per
    (row, peer) pair instead of per (row, owner column), so the draw
    for a directed link depends only on GLOBAL indices and is
    shard-exact."""
    from ..ops.gossip import hash_mix_u32

    h = hash_mix_u32(
        i.astype(jnp.uint32), j.astype(jnp.uint32), salt.astype(jnp.uint32)
    )
    return (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)


def _fault_salt(
    plan: FaultPlan,
    tick: jax.Array,
    fault_idx: int,
    sub: jax.Array,
    seed: jax.Array | None = None,
):
    """One salt per (plan seed, tick, link-fault entry, sub-exchange
    direction): every fault entry and every direction of every
    sub-exchange draws independently, reproducibly. ``seed`` (traced
    uint32) overrides ``plan.seed`` — the sweep's per-lane fault salt;
    it must be pre-masked to 32 bits so the traced path computes the
    exact expression the static path does."""
    if seed is None:
        seed = jnp.uint32(plan.seed & 0xFFFFFFFF)
    else:
        seed = seed.astype(jnp.uint32)
    return (
        tick.astype(jnp.uint32) * jnp.uint32(0x51ED2701)
        ^ seed * jnp.uint32(0x9E3779B9)
        ^ jnp.uint32(fault_idx * 2 + 1) * jnp.uint32(0x7FEB3527)
        ^ jnp.asarray(sub).astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
    )


def _member_mask(ns: NodeSet, idx: jax.Array, n: int) -> jax.Array | None:
    """(len(idx),) bool — which of the given global indices fall in the
    fraction-addressed set (None = all; explicit names were rejected by
    check_sim_compatible)."""
    if ns.matches_all():
        return None
    lo, hi = ns.frac
    pos = idx.astype(jnp.float32) / n
    return (pos >= lo) & (pos < hi)


def crash_mask(plan: FaultPlan, n: int, tick: jax.Array) -> jax.Array:
    """(N,) bool: nodes down inside a crash window at this tick."""
    i = jnp.arange(n, dtype=jnp.int32)
    t = tick.astype(jnp.float32)
    down = jnp.zeros((n,), bool)
    for cr in plan.crashes:
        active = (t >= cr.at) & (t < cr.at + cr.down_for)
        members = _member_mask(cr.nodes, i, n)
        hit = active if members is None else active & members
        down = down | hit
    return down


def _link_failure_prob(lf) -> float:
    """Per-direction sub-exchange failure probability of one LinkFault:
    drop, mid-handshake EOF and a >= 1-tick delay each independently
    kill the exchange for the round (matching the runtime's independent
    per-check draws)."""
    p_ok = (1.0 - lf.drop) * (1.0 - lf.eof)
    if lf.delay >= 1.0:
        p_ok *= 1.0 - lf.delay_prob
    return 1.0 - p_ok


def link_ok(
    plan: FaultPlan,
    n: int,
    tick: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    sub: jax.Array | int = 0,
    *,
    seed: jax.Array | None = None,
) -> jax.Array:
    """(N,) bool: is traffic ``src[i] -> dst[i]`` permitted this tick?

    ``sub`` distinguishes the round's sub-exchange directions so each
    draws fresh fault randomness. Pass ``src=p, dst=arange(n)`` for the
    receive direction of a pull from peer ``p`` and ``src=arange(n),
    dst=p`` for the send direction. ``seed`` (traced uint32, pre-masked
    to 32 bits) overrides ``plan.seed`` for the probabilistic draws —
    bit-identical to ``replace(plan, seed=...)``, which is how sweep
    lanes run plan ensembles under one compile.
    """
    t = tick.astype(jnp.float32)
    ok = jnp.ones(src.shape, bool)
    for part in plan.partitions:
        end = jnp.inf if part.end is None else part.end
        active = (t >= part.start) & (t < end)
        g_src = (src * part.n_groups) // n
        g_dst = (dst * part.n_groups) // n
        ok = ok & ~(active & (g_src != g_dst))
    for idx, lf in enumerate(plan.links):
        p_fail = _link_failure_prob(lf)
        if p_fail <= 0.0:
            continue
        end = jnp.inf if lf.end is None else lf.end
        active = (t >= lf.start) & (t < end)
        applies = jnp.ones(src.shape, bool)
        src_m = _member_mask(lf.src, src, n)
        if src_m is not None:
            applies = applies & src_m
        dst_m = _member_mask(lf.dst, dst, n)
        if dst_m is not None:
            applies = applies & dst_m
        u = _pair_uniform(src, dst, _fault_salt(plan, tick, idx, sub, seed))
        ok = ok & ~(active & applies & (u < p_fail))
    return ok


def plan_affects_links(plan: FaultPlan | None) -> bool:
    """Whether the plan carries any link-level behavior the sim must
    mask (partitions, or link faults with a nonzero per-round failure
    probability)."""
    if plan is None:
        return False
    return bool(plan.partitions) or any(
        _link_failure_prob(lf) > 0.0 for lf in plan.links
    )


def plan_affects_nodes(plan: FaultPlan | None) -> bool:
    return plan is not None and bool(plan.crashes)


def plan_amnesia_restarts(plan: FaultPlan | None) -> bool:
    """Whether any crash window restarts with ``recovery="amnesia"`` —
    the static predicate gating the knowledge-row reset in ``sim_step``
    (plans without one keep the exact pre-existing trace)."""
    return plan is not None and any(
        cr.recovery == "amnesia" for cr in plan.crashes
    )


def amnesia_restart_mask(plan: FaultPlan, n: int, tick: jax.Array) -> jax.Array:
    """(N,) bool: nodes whose ``recovery="amnesia"`` crash window ended
    EXACTLY at this tick — the restart instant. ``sim_step`` resets
    their knowledge rows (w, hb_known, FD bookkeeping) to the
    fresh-boot state: an amnesiac reboot re-replicates the whole
    cluster from zero, which is precisely the recovery cost the
    restart benchmark maps against ``recovery="warm"`` (where the
    persisted watermarks survive and nothing resets). Owner ground
    truth (``max_version``) persists — the sim has no generations; see
    NodeCrash's docstring for what that abstracts away. Pure function
    of (plan, tick, global index): shard-exact, PRNG-independent."""
    i = jnp.arange(n, dtype=jnp.int32)
    t = tick.astype(jnp.float32)
    reset = jnp.zeros((n,), bool)
    for cr in plan.crashes:
        if cr.recovery != "amnesia":
            continue
        end = cr.at + cr.down_for
        # Integer ticks: the restart tick is the first with t >= end.
        just_restarted = (t >= end) & (t - 1 < end)
        members = _member_mask(cr.nodes, i, n)
        hit = (
            just_restarted
            if members is None
            else just_restarted & members
        )
        reset = reset | hit
    return reset


# -- breaker-quarantine lowering (docs/robustness.md) -------------------------
#
# The runtime's per-peer circuit breaker (runtime/health.py) quarantines
# a peer from the gossip target draw after a handful of consecutive
# failures. Its sim analogue is a per-round PEER-SELECTION mask, lowered
# from the fault plan the same way crash windows were: a link fault that
# makes a destination set effectively unreachable (per-direction failure
# probability ~1 — the deterministic regime where a breaker must open)
# quarantines those destinations for every initiator, starting
# ``open_after`` ticks into the fault window (the failures-to-open
# threshold at one contact per round) and ending when the window heals
# (the half-open probe then succeeds immediately at tick resolution).
# Pure function of (plan, tick, global index): shard-exact and
# PRNG-independent like every mask here.

# Only a near-certain per-round failure opens a breaker deterministically
# enough to lower as a mask; sub-threshold flakiness stays un-modelled
# (the runtime's breaker may or may not open there, and the sim must not
# guess).
QUARANTINE_MIN_PFAIL = 0.999


def plan_quarantines(plan: FaultPlan | None) -> bool:
    """Whether the plan carries any link fault the quarantine mask
    would act on (all-initiator src, dst-restricted, effectively-total
    failure)."""
    if plan is None:
        return False
    return any(
        lf.src.matches_all()
        and not lf.dst.matches_all()
        and _link_failure_prob(lf) >= QUARANTINE_MIN_PFAIL
        for lf in plan.links
    )


def quarantine_mask(
    plan: FaultPlan, n: int, tick: jax.Array, *, open_after: int = 3
) -> jax.Array:
    """(N,) bool: peers every breaker-equipped initiator has
    quarantined from its target draw this tick (see the block comment
    above). Entries whose ``dst`` matches all nodes contribute nothing:
    they degrade the *initiator's* own operations everywhere, which is
    not a per-peer breaker signal. Entries whose ``src`` is restricted
    contribute nothing either — only the affected initiators' breakers
    would open in the runtime, and this mask is applied to EVERY
    initiator's draw (a per-initiator mask has no expression in the
    single alive-vector categorical); the sim must not quarantine more
    than the runtime would."""
    i = jnp.arange(n, dtype=jnp.int32)
    t = tick.astype(jnp.float32)
    q = jnp.zeros((n,), bool)
    for lf in plan.links:
        if not lf.src.matches_all() or lf.dst.matches_all():
            continue
        if _link_failure_prob(lf) < QUARANTINE_MIN_PFAIL:
            continue
        end = jnp.inf if lf.end is None else lf.end
        active = (t >= lf.start + open_after) & (t < end)
        members = _member_mask(lf.dst, i, n)
        if members is not None:
            q = q | (active & members)
    return q


def plan_affects_byzantine(plan: FaultPlan | None) -> bool:
    return plan is not None and any(bf.rate > 0.0 for bf in plan.byzantine)


# -- byzantine lowering (defense-on semantics) --------------------------------
#
# The sim has no way to store a lie — its watermark matrix IS the truth
# — so byzantine kinds lower as the GUARDED outcome the runtime's
# apply-delta defenses produce (core/guards.py; docs/faults.md):
#
# - stale_replay / owner_violation destroy the attacker's adverts for
#   the victims' keyspaces (replayed below-floor versions and fabricated
#   over-stamp key-values are rejected at every receiver): advances
#   PULLED FROM an attacker on victim owner-columns are zeroed
#   (byz_out_block). stale_replay additionally re-advertises stale
#   heartbeats, so heartbeat absorption from the attacker is masked on
#   the same columns (byz_hb_block) — the phi-accrual attack surface.
#   owner_violation never blocks the attacker's OWN column (it owns it;
#   its self-keyspace adverts stay genuine); stale_replay does when the
#   victims set matches it (it can lie about itself).
# - digest_inflation starves the attacker: honest responders withhold
#   the victims' data from a peer whose digest already claims it, so
#   advances INTO an attacker row on victim columns are zeroed
#   (byz_in_block). The inflated delta stamps it ships are refused by
#   the receivers' support-invariant guard, so nothing else changes.
#
# All masks are pure functions of (plan, tick, GLOBAL indices) via the
# shared multiplicative hash — shard-exact, PRNG-independent; ``seed``
# (the sweep's per-lane fault salt) and ``byz_frac`` (a traced attacker
# fraction overriding every entry's ``nodes`` window with [0, frac))
# reproduce ``replace(plan, ...)`` tick-for-tick under one compile.

# Disjoint draw-stream id base so byzantine rate draws never collide
# with link-fault draws of the same plan (both feed _fault_salt).
_BYZ_SALT_BASE = 0x10000


def _byz_attackers(
    bf, idx: jax.Array, n: int, byz_frac: jax.Array | None
) -> jax.Array:
    """(len(idx),) bool: which global indices attack under this entry.
    ``byz_frac`` (traced f32) overrides the entry's ``nodes`` window
    with [0, byz_frac) — the sweepable attacker fraction."""
    if byz_frac is not None:
        return idx.astype(jnp.float32) / n < byz_frac
    m = _member_mask(bf.nodes, idx, n)
    return jnp.ones(idx.shape, bool) if m is None else m


def _byz_pair_mask(
    plan: FaultPlan,
    bf_idx: int,
    bf,
    n: int,
    tick: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    sub,
    seed: jax.Array | None,
    byz_frac: jax.Array | None,
) -> jax.Array:
    """(N,) bool: entry ``bf`` applies to the directed pair
    src[i] -> dst[i] this tick (window, attacker membership, rate)."""
    t = tick.astype(jnp.float32)
    end = jnp.inf if bf.end is None else bf.end
    hit = (t >= bf.start) & (t < end)
    hit = hit & _byz_attackers(bf, src, n, byz_frac)
    if bf.rate < 1.0:
        u = _pair_uniform(
            src, dst, _fault_salt(plan, tick, _BYZ_SALT_BASE + bf_idx, sub, seed)
        )
        hit = hit & (u < bf.rate)
    return hit


def _byz_block(
    plan: FaultPlan,
    kinds: tuple[str, ...],
    n: int,
    tick: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    owners: jax.Array,
    sub,
    seed: jax.Array | None,
    byz_frac: jax.Array | None,
    exclude_own_col_kinds: tuple[str, ...] = (),
) -> jax.Array | None:
    """(N, n_local) bool: owner-columns of the src[i] -> dst[i] transfer
    voided by the named byzantine kinds (None = nothing applies)."""
    block = None
    for bf_idx, bf in enumerate(plan.byzantine):
        if bf.kind not in kinds or bf.rate <= 0.0:
            continue
        pair = _byz_pair_mask(
            plan, bf_idx, bf, n, tick, src, dst, sub, seed, byz_frac
        )
        vic = _member_mask(bf.victims, owners, n)
        b = pair[:, None] & (
            jnp.ones((owners.shape[0],), bool)[None, :]
            if vic is None
            else vic[None, :]
        )
        if bf.kind in exclude_own_col_kinds:
            # The attacker owns its own column — adverts for it are
            # genuine, so the block never applies there.
            b = b & (owners[None, :] != src[:, None])
        block = b if block is None else block | b
    return block


def byz_out_block(
    plan: FaultPlan,
    n: int,
    tick: jax.Array,
    peer: jax.Array,
    owners: jax.Array,
    sub,
    *,
    seed: jax.Array | None = None,
    byz_frac: jax.Array | None = None,
) -> jax.Array | None:
    """Advances pulled FROM peer[i] (the sender) on owner column j that
    the receiver's guards reject — stale_replay + owner_violation."""
    rows = jnp.arange(peer.shape[0], dtype=jnp.int32)
    return _byz_block(
        plan,
        ("stale_replay", "owner_violation"),
        n, tick, peer, rows, owners, sub, seed, byz_frac,
        exclude_own_col_kinds=("owner_violation",),
    )


def byz_hb_block(
    plan: FaultPlan,
    n: int,
    tick: jax.Array,
    peer: jax.Array,
    owners: jax.Array,
    sub,
    *,
    seed: jax.Array | None = None,
    byz_frac: jax.Array | None = None,
) -> jax.Array | None:
    """Heartbeat knowledge absorbed from peer[i] on victim columns that
    the attacker's stale digests withhold — stale_replay only."""
    rows = jnp.arange(peer.shape[0], dtype=jnp.int32)
    return _byz_block(
        plan, ("stale_replay",), n, tick, peer, rows, owners, sub, seed,
        byz_frac,
    )


def byz_in_block(
    plan: FaultPlan,
    n: int,
    tick: jax.Array,
    owners: jax.Array,
    *,
    seed: jax.Array | None = None,
    byz_frac: jax.Array | None = None,
) -> jax.Array | None:
    """Advances INTO attacker row i on victim column j that honest
    responders withhold (the attacker's digest already claims them) —
    digest_inflation. Receiver-side, so it is peer-independent: one
    mask per round, ANDed into every pull."""
    rows = jnp.arange(n, dtype=jnp.int32)
    return _byz_block(
        plan, ("digest_inflation",), n, tick, rows, rows, owners, 0, seed,
        byz_frac,
    )


# -- heterogeneity lowering ---------------------------------------------------
#
# Heterogeneity (models/topology.Heterogeneity) rides the same mask
# machinery: WAN latency/loss classes compile to derived LinkFaults
# appended to the effective plan (effective_fault_plan), and cadence
# classes lower to a per-tick initiator mask folded into sub-exchange
# validity. Zone-aware peer bias is lowered inside select_peers
# (ops/gossip.py) — it shapes the draw, not the mask.


def effective_fault_plan(
    plan: FaultPlan | None, heterogeneity
) -> FaultPlan | None:
    """The plan the sim actually injects: the configured plan plus the
    heterogeneity model's derived WAN LinkFaults (None when neither
    contributes). Static — evaluated at trace time off the config."""
    from .plan import with_extra_links

    if heterogeneity is None:
        return plan
    return with_extra_links(plan, heterogeneity.wan_link_faults())


def cadence_on(heterogeneity, n: int, tick: jax.Array) -> jax.Array:
    """(N,) bool: nodes whose cadence class initiates gossip this tick
    (class-k nodes fire when tick % gossip_every[k] == 0). A pure
    function of (tick, global index) — shard-exact like every mask
    here."""
    pos = jnp.arange(n, dtype=jnp.float32) / n
    on = jnp.zeros((n,), bool)
    cum = 0.0
    for k, frac in enumerate(heterogeneity.class_frac):
        lo, cum = cum, cum + frac
        period = int(heterogeneity.gossip_every[k])
        fires = (tick % period) == 0
        member = (pos >= lo) & (pos < cum if k < len(
            heterogeneity.class_frac) - 1 else jnp.ones((n,), bool))
        on = on | (member & fires)
    return on
