"""Scenario runner for the asyncio backend: an in-process fleet of real
clusters under one FaultPlan.

The harness boots N loopback clusters whose transports all inject the
same plan against one synchronised epoch (so a partition heals
everywhere at the same instant), and drives the plan's ``crashes``
against reality: a crashed node's ``Cluster`` is actually closed (its
port stops accepting, its pooled channels die) and the restart boots a
**fresh Cluster with a bumped generation** — exercising the
newer-generation-wins rule end to end, not a simulation of it.

Used by the chaos soak (tests/test_chaos.py), the convergence-under-
fault benchmark (benchmarks/fault_bench.py) and ad-hoc scenario runs.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import socket
from random import Random

from ..core.config import Config
from ..core.identity import NodeId, next_generation_id
from ..obs.registry import MetricsRegistry
from ..runtime.cluster import Cluster
from ..utils.clock import resolve_clock
from ..utils.clock import sleep as clock_sleep
from .plan import FaultPlan

# Crash schedule granularity: how often the harness compares plan time
# against the crash windows. Fine enough for sub-second scenario steps;
# long-horizon plans (gossip intervals of seconds to minutes under
# virtual time) coarsen it to interval/4 so an hour-long soak does not
# spend its wall budget polling an empty schedule.
_CRASH_POLL_S = 0.02


class ChaosHarness:
    """N loopback clusters, one plan, one epoch (see module docstring)."""

    def __init__(
        self,
        n_nodes: int,
        plan=None,
        *,
        cluster_id: str = "chaos",
        gossip_interval: float = 0.05,
        config_overrides: dict | None = None,
        persist_root: str | None = None,
        trace=None,
        prov_trace=None,
        virtual_time: bool = False,
        seed: int = 0,
        ports: dict[str, int] | None = None,
    ) -> None:
        self.n_nodes = n_nodes
        self.names = [f"n{i:02d}" for i in range(n_nodes)]
        self._cluster_id = cluster_id
        self._interval = gossip_interval
        self._overrides = config_overrides or {}
        # Virtual-time mode (docs/virtual-time.md): the harness must be
        # running under a vtime.VirtualClockLoop (start() checks loudly)
        # and flips every run-to-run nondeterminism source it owns to a
        # seeded/deterministic variant: per-node cluster RNGs (jitter,
        # peer selection, breaker backoff) derive from ``seed``, restart
        # generations count up from the previous incarnation instead of
        # stamping wall-clock nanoseconds, and callers pin ``ports`` so
        # two runs advertise identical peer labels. The clock itself
        # resolves through the utils.clock seam either way — the
        # default real-time path is byte-identical to before.
        self._virtual = virtual_time
        self._seed = seed
        self._clock = resolve_clock(None)
        # Twin-grade fleet tracing (docs/twin.md): one shared TraceWriter
        # attached to every member (restarts re-attach) via
        # Cluster.trace_rounds — the recording side of the digital
        # twin's replay/calibrate loop. None traces nothing.
        self._trace = trace
        # Propagation provenance (obs/prov.py): one shared TraceWriter
        # attached to every member via Cluster.trace_provenance
        # (restarts re-attach), joined fleet-wide by
        # propagation_report(). None traces nothing — byte-identical
        # member hot paths.
        self._prov_trace = prov_trace
        # Durable-store root (docs/robustness.md): when set, every node
        # gets ``Config.persistence`` pointing at its own subdirectory,
        # and crash windows with ``recovery="warm"`` reboot FROM the
        # store (the crash is an ``abort()`` — no clean marker, so the
        # generation still bumps while the keyspace survives). Without
        # it, warm-recovery plans are refused at start.
        self._persist_root = persist_root
        self.clusters: dict[str, Cluster] = {}
        self.registries: dict[str, MetricsRegistry] = {}
        # Ports are allocated up front so plans can address nodes by
        # BOTH name and "host:port": before a peer's first handshake the
        # cluster state cannot resolve an address to a name, and a
        # name-only partition group would let bootstrap traffic leak
        # across the cut (see name_groups). Replay runs pass ``ports``
        # (e.g. a previous run's ``harness._ports``) so both runs emit
        # identical peer labels in flight-recorder/trace streams.
        if ports is not None:
            missing = [n for n in self.names if n not in ports]
            if missing:
                raise ValueError(f"ports= missing nodes: {missing}")
            self._ports: dict[str, int] = {n: ports[n] for n in self.names}
        else:
            self._ports = self._free_ports()
        # ``plan`` may be a factory taking the harness — the hook for
        # building explicit groups over the fleet's real labels:
        #   ChaosHarness(6, lambda h: split_brain(2, groups=h.name_groups(2)))
        self.plan: FaultPlan | None = plan(self) if callable(plan) else plan
        if (
            self.plan is not None
            and self._persist_root is None
            and any(cr.recovery == "warm" for cr in self.plan.crashes)
        ):
            raise ValueError(
                "recovery='warm' crash windows need a persist_root (the "
                "reboot restores the durable store; without one there is "
                "nothing to restore)"
            )
        self._epoch: float | None = None
        self._crash_task: asyncio.Task | None = None
        self._crashed: set[str] = set()
        # name -> the recovery mode of the crash window that took the
        # node down (drives how the restart reboots it).
        self._crash_recovery: dict[str, str] = {}
        self.generations: dict[str, list[int]] = {}

    def addr_label(self, name: str) -> str:
        """The pre-resolution fault label of a node (``host:port``)."""
        return f"127.0.0.1:{self._ports[name]}"

    def name_groups(self, n_groups: int) -> tuple[tuple[str, ...], ...]:
        """Balanced partition groups over the fleet
        (scenarios.round_robin_groups), each member listed under both
        its name and its address label so the cut holds from the first
        bootstrap connect onward."""
        from .scenarios import round_robin_groups

        return tuple(
            tuple(
                label
                for member in group
                for label in (member, self.addr_label(member))
            )
            for group in round_robin_groups(self.names, n_groups)
        )

    def node_set(self, *names: str):
        """A NodeSet matching the given fleet members under both their
        labels (for crash/link entries in harness-run plans)."""
        from .plan import NodeSet

        return NodeSet(
            names=tuple(
                label for n in names for label in (n, self.addr_label(n))
            )
        )

    # -- lifecycle ------------------------------------------------------------

    def _free_ports(self) -> dict[str, int]:
        socks = []
        try:
            for _ in self.names:
                s = socket.socket()
                s.bind(("127.0.0.1", 0))
                socks.append(s)
            return {
                name: s.getsockname()[1]
                for name, s in zip(self.names, socks)
            }
        finally:
            for s in socks:
                s.close()

    def _wipe_store(self, name: str) -> None:
        """An amnesiac reboot is a reimaged machine: the node's store
        directory is deleted, so a LATER warm restart cannot resurrect
        the pre-amnesia keyspace (stale keys re-advertising as current
        — and diverging from the sim, whose warm recovery keeps CURRENT
        watermarks)."""
        if self._persist_root is None:
            return
        import os
        import shutil

        shutil.rmtree(
            os.path.join(self._persist_root, name), ignore_errors=True
        )

    def _node_rng(self, name: str) -> Random | None:
        """Seeded per-node, per-incarnation RNG under virtual time
        (startup jitter, gossip target draws, breaker backoff all flow
        from it); None otherwise — the cluster keeps its own unseeded
        Random() and the default path is untouched."""
        if not self._virtual:
            return None
        incarnation = len(self.generations.get(name, []))
        h = hashlib.blake2b(
            f"{self._seed}|{name}|{incarnation}".encode(), digest_size=8
        )
        return Random(int.from_bytes(h.digest(), "big"))

    def _next_generation(self, name: str) -> int:
        """Generation for an amnesiac reboot: the wall-clock-ns stamp
        (the reference semantics) normally; under virtual time the
        previous incarnation plus one — newer-generation-wins needs
        only ordering, and a wall stamp would differ run to run."""
        if not self._virtual:
            return next_generation_id()
        return max(self.generations.get(name) or [0]) + 1

    def _make_cluster(
        self,
        name: str,
        generation: int | None = None,
        persisted: bool | None = None,
    ) -> Cluster:
        port = self._ports[name]
        seeds = [
            ("127.0.0.1", p) for n, p in self._ports.items() if n != name
        ]
        if generation is None and self._virtual and (
            self._persist_root is None or persisted is False
        ):
            # No store to decide it: stamp the deterministic incarnation
            # index (1, 2, ...) instead of identity.py's wall-clock ns.
            generation = len(self.generations.get(name, [])) + 1
        node_id = (
            NodeId(name=name, gossip_advertise_addr=("127.0.0.1", port))
            if generation is None
            else NodeId(
                name=name,
                generation_id=generation,
                gossip_advertise_addr=("127.0.0.1", port),
            )
        )
        persistence = None
        if self._persist_root is not None and persisted is not False:
            import os

            from ..core.config import PersistenceConfig

            persistence = PersistenceConfig(
                path=os.path.join(self._persist_root, name)
            )
        config = Config(
            node_id=node_id,
            cluster_id=self._cluster_id,
            gossip_interval=self._interval,
            seed_nodes=seeds,
            fault_plan=self.plan,
            persistence=persistence,
            **self._overrides,
        )
        registry = self.registries.setdefault(name, MetricsRegistry())
        cluster = Cluster(
            config,
            initial_key_values={f"from-{name}": name},
            rng=self._node_rng(name),
            metrics=registry,
        )
        # Static label table for the fault transport: fraction-addressed
        # NodeSets must mean the same nodes from the FIRST handshake.
        # The cluster's own resolver learns names only as identities
        # replicate, and an unresolved "host:port" fallback label hashes
        # into an arbitrary frac bucket — bootstrap traffic would then
        # leak through (or get caught by) the wrong set, making
        # runtime-vs-sim differential verdicts racy. The harness owns
        # the whole fleet's name<->port map up front, so it resolves
        # statically (unknown addresses keep the cluster's fallback).
        transport = cluster._transport
        if hasattr(transport, "_resolve"):
            addr_names = {
                ("127.0.0.1", p): n for n, p in self._ports.items()
            }
            fallback = cluster._peer_label
            transport._resolve = lambda host, port: (
                addr_names.get((host, port)) or fallback(host, port)
            )
        # Read the generation off the CLUSTER: the persistence layer may
        # have rewritten it (clean store keeps the previous one, unclean
        # bumps above the store's floor).
        self.generations.setdefault(name, []).append(
            cluster.self_node_id.generation_id
        )
        if self._trace is not None:
            cluster.trace_rounds(self._trace)
        if self._prov_trace is not None:
            cluster.trace_provenance(self._prov_trace)
        return cluster

    async def start(self) -> None:
        if self._virtual:
            loop = asyncio.get_running_loop()
            if not getattr(loop, "aiocluster_virtual", False):
                raise RuntimeError(
                    "ChaosHarness(virtual_time=True) must run under a "
                    "vtime.VirtualClockLoop — wrap the scenario in "
                    "aiocluster_tpu.vtime.run(coro, seed=...) "
                    "(docs/virtual-time.md)"
                )
        self.clusters = {name: self._make_cluster(name) for name in self.names}
        # One epoch for the whole fleet, latched BEFORE any boot traffic
        # can lazily start a controller's local clock: every
        # controller's t=0 is the same instant, so windows open and
        # heal simultaneously (explicit epochs also override any lazy
        # latch that sneaks in — see FaultController.start).
        self._epoch = self._clock.monotonic()
        for cluster in self.clusters.values():
            ctl = cluster.fault_controller
            if ctl is not None:
                ctl.start(self._epoch)
        await asyncio.gather(*(c.start() for c in self.clusters.values()))
        if self.plan is not None and self.plan.crashes:
            self._crash_task = asyncio.create_task(self._drive_crashes())

    async def stop(self) -> None:
        if self._crash_task is not None:
            self._crash_task.cancel()
            try:
                await self._crash_task
            except asyncio.CancelledError:  # noqa: ACT013 -- absorbing the cancel we just issued at harness teardown
                pass
            self._crash_task = None
        await asyncio.gather(
            *(c.close() for c in self.clusters.values()),
            return_exceptions=True,
        )

    async def __aenter__(self) -> "ChaosHarness":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # -- plan time ------------------------------------------------------------

    def elapsed(self) -> float:
        assert self._epoch is not None, "harness not started"
        return self._clock.monotonic() - self._epoch

    # -- crash/restart driver -------------------------------------------------

    def _down_now(self, name: str, t: float) -> str | None:
        """The recovery mode of a crash window covering ``name`` at plan
        time ``t``, or None when the node should be up. A node matched
        by several simultaneous windows crashes once; "warm" wins only
        if every covering window is warm (one amnesiac crash wipes the
        disk story regardless of the others)."""
        modes = [
            cr.recovery
            for cr in self.plan.crashes
            if cr.down(t) and cr.nodes.matches_name(name)
        ]
        if not modes:
            return None
        return "amnesia" if "amnesia" in modes else "warm"

    async def _drive_crashes(self) -> None:
        """Close clusters whose crash window opened; reboot them (bumped
        generation, same name/port) once it closes. The restarted node's
        higher generation makes its fresh state win over stale replicas
        of the old incarnation.

        A transient failure on one node (e.g. the old port not yet
        released at restart) is logged and retried on the next poll —
        the driver must outlive individual hiccups, or every later
        crash window silently stops being injected while the soak
        appears to pass."""
        log = logging.getLogger("aiocluster.chaos")
        while True:
            t = self.elapsed()
            for name in self.names:
                down = self._down_now(name, t)
                try:
                    if down is not None and name not in self._crashed:
                        # A crash is a crash: abort() skips the graceful
                        # persistence flush (no clean marker), so a warm
                        # reboot recovers from the journaled store, not
                        # from a tidy shutdown that never happened.
                        await self.clusters[name].abort()
                        self._crashed.add(name)
                        self._crash_recovery[name] = down
                    elif down is None and name in self._crashed:
                        warm = (
                            self._crash_recovery.get(name) == "warm"
                            and self._persist_root is not None
                        )
                        # Warm: the store decides the generation (unclean
                        # ⇒ bumped above its durable floor) and restores
                        # the keyspace. Amnesia: the reference reboot — a
                        # fresh cluster, explicitly bumped generation,
                        # and the on-disk store WIPED (a reimaged
                        # machine; a later warm window must not
                        # resurrect pre-amnesia state).
                        if not warm:
                            self._wipe_store(name)
                        cluster = (
                            self._make_cluster(name, generation=None)
                            if warm
                            else self._make_cluster(
                                name,
                                generation=self._next_generation(name),
                                persisted=False,
                            )
                        )
                        # Rejoin the fleet's ORIGINAL epoch before any
                        # boot traffic runs — the restarted node must
                        # not restart the plan clock at its own reboot.
                        ctl = cluster.fault_controller
                        if ctl is not None:
                            ctl.start(self._epoch)
                        await cluster.start()
                        self.clusters[name] = cluster
                        # Only a successful reboot leaves the crashed
                        # set (a failed one rolls back the generation
                        # record and retries next poll).
                        self._crashed.discard(name)
                except Exception as exc:
                    if not down and self.generations.get(name):
                        self.generations[name].pop()
                    log.warning(
                        f"chaos crash driver: {name} "
                        f"{'close' if down else 'restart'} failed "
                        f"(retrying next poll): {exc!r}"
                    )
            await clock_sleep(max(_CRASH_POLL_S, self._interval / 4))

    async def restart_node(
        self, name: str, recovery: str = "amnesia", *, graceful: bool = False
    ) -> None:
        """Take one node down and immediately reboot it — the
        rolling-restart building block ``benchmarks/restart_bench.py``
        drives directly (no plan windows to wait out). ``graceful=True``
        closes cleanly (with a store: clean marker ⇒ the reboot keeps
        its generation — the deploy path); False aborts (a crash: the
        generation bumps either way). ``recovery="warm"`` reboots from
        the durable store (requires ``persist_root``), ``"amnesia"``
        reboots empty with an explicitly bumped generation, the
        reference semantics."""
        if recovery == "warm" and self._persist_root is None:
            raise ValueError("recovery='warm' needs a persist_root")
        cluster = self.clusters[name]
        if graceful:
            await cluster.close()
        else:
            await cluster.abort()
        if recovery != "warm":
            self._wipe_store(name)  # amnesia = reimaged machine
        new = (
            self._make_cluster(name, generation=None)
            if recovery == "warm"
            else self._make_cluster(
                name, generation=self._next_generation(name), persisted=False
            )
        )
        ctl = new.fault_controller
        if ctl is not None and self._epoch is not None:
            ctl.start(self._epoch)
        await new.start()
        self.clusters[name] = new

    # -- observation ----------------------------------------------------------

    def running(self) -> list[str]:
        return [n for n in self.names if n not in self._crashed]

    def sees(self, observer: str, owner: str) -> bool:
        """Does ``observer`` hold ``owner``'s marker key? (Reads the
        live state view — convergence polls run O(fleet²) of these, and
        a detached ``snapshot()`` deep copy per probe would swamp the
        soak.)"""
        cluster = self.clusters[observer]
        key = f"from-{owner}"
        for node_id, ns in cluster.node_states_view().items():
            if node_id.name == owner and ns.get(key) is not None:
                return True
        return False

    def converged(self) -> bool:
        """Every running cluster holds every running node's marker key
        (full cross-fleet replication among the nodes that are up)."""
        running = self.running()
        return all(
            self.sees(observer, owner)
            for observer in running
            for owner in running
            if observer != owner
        )

    def cross_group_blind(self, groups: tuple[tuple[str, ...], ...]) -> bool:
        """True while no cluster holds a marker from another group —
        the partitioned-state probe for split-brain assertions.
        ``name_groups``-style address aliases are ignored (only node
        names carry marker keys)."""
        groups = tuple(
            tuple(m for m in g if ":" not in m) for g in groups
        )
        for gi, members in enumerate(groups):
            for observer in members:
                for gj, others in enumerate(groups):
                    if gi == gj:
                        continue
                    for owner in others:
                        if self.sees(observer, owner):
                            return False
        return True

    async def wait_converged(self, timeout: float = 30.0) -> float:
        """Poll until :meth:`converged`; returns how long it took.
        Raises TimeoutError when the deadline passes."""
        start = self._clock.monotonic()
        deadline = start + timeout
        while self._clock.monotonic() < deadline:
            if self.converged():
                return self._clock.monotonic() - start
            await clock_sleep(self._interval / 2)
        raise TimeoutError(f"fleet did not converge within {timeout}s")

    def propagation_report(self, *, key: str | None = None):
        """Join the fleet's shared provenance trace into epidemic
        spread trees (obs/prov.py, docs/observability.md "Propagation &
        provenance"). Requires the harness to have been constructed
        with ``prov_trace=``; ``key`` narrows the join to one key's
        trees (the marked-write study). Reads the trace file tolerantly
        — the writer flushes per line, so an in-flight fleet still
        joins every completed record."""
        if self._prov_trace is None:
            raise ValueError(
                "propagation_report() needs ChaosHarness(prov_trace=...) "
                "— no provenance was recorded for this fleet"
            )
        from ..obs.prov import join_propagation

        return join_propagation(self._prov_trace.path, key=key)

    def fault_counts(self) -> dict[str, int]:
        """Fleet-wide ``aiocluster_faults_injected_total`` by kind."""
        totals: dict[str, int] = {}
        for registry in self.registries.values():
            for key, value in registry.snapshot().items():
                if key.startswith("aiocluster_faults_injected_total{"):
                    kind = key.split("kind=")[1].rstrip("}")
                    totals[kind] = totals.get(kind, 0) + int(value)
        return totals

    def byzantine_counts(self) -> dict[str, dict[str, int]]:
        """Fleet-wide byzantine accounting: ``injected`` sums the
        attacker-side ``byz_*`` kinds of
        ``aiocluster_faults_injected_total``; ``rejected`` sums the
        receiver-side ``aiocluster_byzantine_rejected_total`` guards by
        kind. Under a single-kind plan on a loss-free loopback fleet
        the two sides match EXACTLY (tests/test_byzantine.py)."""
        injected: dict[str, int] = {}
        rejected: dict[str, int] = {}
        for registry in self.registries.values():
            for key, value in registry.snapshot().items():
                if key.startswith("aiocluster_faults_injected_total{"):
                    kind = key.split("kind=")[1].rstrip("}")
                    if kind.startswith("byz_"):
                        short = kind[len("byz_"):]
                        injected[short] = injected.get(short, 0) + int(value)
                elif key.startswith("aiocluster_byzantine_rejected_total{"):
                    kind = key.split("kind=")[1].rstrip("}")
                    rejected[kind] = rejected.get(kind, 0) + int(value)
        return {"injected": injected, "rejected": rejected}
