"""Runtime fault injection: a FaultPlan compiled against the asyncio
backend.

:class:`FaultController` turns a plan into a deterministic per-link
decision stream; :class:`FaultyTransport` wraps the real
``GossipTransport`` and consults the controller around every initiator
operation — connect attempts (injected refusals/delays), framed writes
(drops as connection resets, slow-peer delays, duplication) and framed
reads (drops, delays, **mid-handshake EOF**). The connection pool is
covered transitively: it dials through the wrapped ``connect``, so
pooled borrows, the reconnect-retry path and stale eviction all see the
same hostile network.

Injection is initiator-side: every link gets both endpoints' outbound
operations degraded, which fully cuts a partitioned link (neither side's
handshakes go out) without the responder needing to attribute inbound
connections. Crashed-node windows additionally refuse all of the down
node's own traffic in both roles.

Determinism: each probability draw is
``blake2b(seed | src | dst | op | op_index | check)`` — a pure function
of the plan and the per-link operation sequence, independent of
wall-clock, PRNG state, or scheduling (tests/test_faults.py asserts two
controllers replay identical schedules). Fault *windows* (start/end)
are evaluated against an injectable clock so tests can step time
explicitly.

With ``Config.fault_plan=None`` none of this is constructed: the
transport is the plain ``GossipTransport`` and every wrapped path is
byte-identical to the fault-free build.
"""

from __future__ import annotations

import asyncio
import hashlib
import weakref
from collections.abc import Callable
from dataclasses import dataclass

from ..obs.registry import MetricsRegistry
from ..utils.clock import Clock, resolve_clock
from ..utils.clock import sleep as clock_sleep
from .plan import FaultPlan

# Operation labels the transport wrapper reports; part of the hash
# domain, so renaming one would re-key its schedule.
OPS = ("connect", "read", "write")


@dataclass(frozen=True, slots=True)
class Decision:
    """One injected-fault verdict for one operation."""

    action: str  # "ok" | "drop" | "eof" | "down" | "partition"
    delay: float = 0.0
    duplicate: bool = False


class FaultController:
    """Deterministic fault schedule for one node (see module docstring).

    ``clock`` defaults to the ambient ``utils.clock`` seam (real
    monotonic, or the loop's virtual clock under ``vtime``); tests
    inject a ``ManualClock``. The epoch is latched by :meth:`start` (the
    ChaosHarness synchronises one epoch across a fleet so partitions
    heal simultaneously) or lazily on the first decision.
    """

    def __init__(
        self,
        plan: FaultPlan,
        self_name: str,
        *,
        metrics: MetricsRegistry | None = None,
        clock: Clock | None = None,
    ) -> None:
        self._plan = plan
        self._self = self_name
        self._clock = resolve_clock(clock)
        self._t0: float | None = None
        self._op_index: dict[tuple[str, str], int] = {}
        self._injected = self._partition_gauge = None
        if metrics is not None:
            self._injected = metrics.counter(
                "aiocluster_faults_injected_total",
                "Faults injected into the runtime transport, by kind",
                labels=("kind",),
            )
            self._partition_gauge = metrics.gauge(
                "aiocluster_fault_partition_active",
                "Fault-plan partitions currently active (0 = fully healed)",
            )

    @property
    def plan(self) -> FaultPlan:
        return self._plan

    # -- time -----------------------------------------------------------------

    def start(self, epoch: float | None = None) -> None:
        """Latch the plan's t=0. An EXPLICIT epoch always wins: the
        cluster's own boot traffic lazily latches a local t0 via
        elapsed() before a harness can reach the controller, and a
        restarted node must rejoin the fleet's ORIGINAL epoch — not
        restart the plan clock at its own reboot."""
        if epoch is not None:
            self._t0 = epoch
        elif self._t0 is None:
            self._t0 = self._clock.monotonic()

    def elapsed(self) -> float:
        self.start()
        return self._clock.monotonic() - self._t0

    # -- deterministic draws --------------------------------------------------

    def _u(self, dst: str, op: str, k: int, check: str) -> float:
        """Uniform [0, 1) draw for check ``check`` of the k-th ``op`` on
        link self->dst. blake2b, not ``hash()``: stable across processes
        and runs, so (seed, plan) fully determines the schedule."""
        key = f"{self._plan.seed}|{self._self}|{dst}|{op}|{k}|{check}"
        digest = hashlib.blake2b(key.encode(), digest_size=8).digest()
        return int.from_bytes(digest, "big") / 2**64

    # -- decision -------------------------------------------------------------

    def partitions_active(self, t: float | None = None) -> int:
        t = self.elapsed() if t is None else t
        n = sum(1 for p in self._plan.partitions if p.active(t))
        if self._partition_gauge is not None:
            self._partition_gauge.set(n)
        return n

    def _node_down(self, name: str, t: float) -> bool:
        return any(
            cr.down(t) and cr.nodes.matches_name(name)
            for cr in self._plan.crashes
        )

    def _partition_blocked(self, dst: str, t: float) -> bool:
        self.partitions_active(t)  # keep the gauge current
        for p in self._plan.partitions:
            if not p.active(t):
                continue
            g_self = p.group_of_name(self._self)
            g_dst = p.group_of_name(dst)
            # None = unlisted under explicit groups: fail-closed — an
            # unattributable peer is cut from every island rather than
            # hash-bucketed into (possibly) our own.
            if g_self is None or g_dst is None or g_self != g_dst:
                return True
        return False

    def decide(self, dst: str, op: str, t: float | None = None) -> Decision:
        """The verdict for the next ``op`` on link self->dst. Advances
        the link's operation counter; every probability check consumes
        its own named draw, so the schedule does not depend on which
        check short-circuits first."""
        t = self.elapsed() if t is None else t
        k = self._op_index[(dst, op)] = self._op_index.get((dst, op), 0) + 1
        if self._node_down(self._self, t) or self._node_down(dst, t):
            return Decision("down")
        if self._partition_blocked(dst, t):
            return Decision("partition")
        delay = 0.0
        duplicate = False
        for idx, lf in enumerate(self._plan.links):
            if not lf.active(t):
                continue
            if not (
                lf.src.matches_name(self._self) and lf.dst.matches_name(dst)
            ):
                continue
            if (
                op == "read"
                and lf.eof > 0
                and self._u(dst, op, k, f"{idx}:eof") < lf.eof
            ):
                return Decision("eof")
            if lf.drop > 0 and self._u(dst, op, k, f"{idx}:drop") < lf.drop:
                return Decision("drop")
            if (
                lf.delay_prob > 0
                and self._u(dst, op, k, f"{idx}:delay") < lf.delay_prob
            ):
                delay = max(delay, lf.delay)
            if (
                op == "write"
                and lf.duplicate > 0
                and self._u(dst, op, k, f"{idx}:dup") < lf.duplicate
            ):
                duplicate = True
        return Decision("ok", delay=delay, duplicate=duplicate)

    # -- application ----------------------------------------------------------

    def _count(self, kind: str) -> None:
        if self._injected is not None:
            self._injected.labels(kind).inc()

    # -- byzantine message rewriting (wrong-data faults) ----------------------
    #
    # When THIS node matches an active ByzantineFault's attacker set,
    # its outbound handshake messages are rewritten in flight
    # (FaultyTransport routes every write through rewrite_packet /
    # rewrite_syn_bytes). Injection units mirror the receiver guards'
    # rejection units (core/guards.py) so tests can assert EXACT
    # injected == rejected equality: per key-value for stale_replay,
    # per delta stamp for digest_inflation, per fabricated NodeDelta
    # (one key-value each) for owner_violation. Digest rewrites are
    # counted separately ("byz_digest_rewrite") — digests are observed,
    # not rejected.

    def byzantine_active(self, t: float | None = None) -> list:
        """(index, fault) pairs of byzantine entries whose window is
        open and whose attacker set matches THIS node."""
        t = self.elapsed() if t is None else t
        return [
            (i, bf)
            for i, bf in enumerate(self._plan.byzantine)
            if bf.active(t) and bf.nodes.matches_name(self._self)
        ]

    def _byz_rate_ok(self, idx: int, bf, dst: str, op: str) -> bool:
        """Per-message injection draw for entry ``idx`` — same blake2b
        stream as every other decision (deterministic given the
        per-link message order; rate=1.0 plans skip the draw and are
        order-independent)."""
        if bf.rate >= 1.0:
            return True
        key = (dst, f"byz{idx}:{op}")
        k = self._op_index[key] = self._op_index.get(key, 0) + 1
        return self._u(dst, f"byz{idx}:{op}", k, "rate") < bf.rate

    def _rewrite_digest(self, digest, active, dst: str):
        """Apply digest-visible kinds: stale_replay re-advertises
        ancient knowledge of the victims (heartbeat 1, max_version 0 —
        the stale heartbeat advert is the phi-accrual attack), and
        digest_inflation claims their max_versions ``amount`` ahead.
        Returns the ORIGINAL object when nothing applies (the engine's
        digest objects are shared caches and must never be mutated)."""
        from ..core.messages import Digest, NodeDigest

        entries = None
        for node_id, nd in digest.node_digests.items():
            replacement = None
            for idx, bf in active:
                if not bf.victims.matches_name(node_id.name):
                    continue
                if bf.kind == "stale_replay":
                    if self._byz_rate_ok(idx, bf, dst, "digest"):
                        replacement = NodeDigest(node_id, 1, 0, 0)
                elif bf.kind == "digest_inflation":
                    if self._byz_rate_ok(idx, bf, dst, "digest"):
                        cur = replacement or nd
                        replacement = NodeDigest(
                            node_id,
                            cur.heartbeat,
                            cur.last_gc_version,
                            cur.max_version + bf.amount,
                        )
            if replacement is not None:
                if entries is None:
                    entries = dict(digest.node_digests)
                entries[node_id] = replacement
                self._count("byz_digest_rewrite")
        if entries is None:
            return digest
        return Digest(entries)

    def _rewrite_delta(self, delta, active, dst: str, digest=None):
        """Apply delta-visible kinds to an outbound delta (original
        object when nothing applies — delta parts may be shared):

        - stale_replay: victims' key-values replayed at the delta's own
          floor (below-floor — guard 2 rejects each), stamp kept: the
          poison is the fast-forward past data never delivered.
        - digest_inflation: victims' ``max_version`` stamps inflated by
          ``amount`` (guard 4 refuses each); genuine key-values ride
          along untouched.
        - owner_violation: each victim NodeDelta's key-values replaced
          by ONE fabricated entry ``amount`` past the stamp (guard 3 —
          or guard 1 when the receiver IS the victim); a truncated
          relay's None stamp is pinned to the delta's floor so guard 3
          keeps a bound to catch the fabrication against. With a digest
          in hand (SynAck), victims the delta did not mention get a
          fabricated NodeDelta appended — including the receiver's own
          keyspace when it is a victim, the ACT03x attack proper. The
          attacker never fabricates its OWN keyspace (it owns it).
        """
        from ..core.messages import (
            Delta, KeyValueUpdate, NodeDelta,
        )
        from ..core.values import KeyStatus

        out = []
        dirty = False
        for nd in delta.node_deltas:
            cur = nd
            for idx, bf in active:
                if not bf.victims.matches_name(nd.node_id.name):
                    continue
                if bf.kind == "stale_replay":
                    if cur.key_values and self._byz_rate_ok(
                        idx, bf, dst, "delta"
                    ):
                        floor = cur.from_version_excluded
                        cur = NodeDelta(
                            node_id=cur.node_id,
                            from_version_excluded=floor,
                            last_gc_version=cur.last_gc_version,
                            key_values=[
                                KeyValueUpdate(
                                    kv.key, kv.value, floor, kv.status
                                )
                                for kv in cur.key_values
                            ],
                            max_version=cur.max_version,
                        )
                        for _ in cur.key_values:
                            self._count("byz_stale_replay")
                elif bf.kind == "digest_inflation":
                    if cur.max_version is not None and self._byz_rate_ok(
                        idx, bf, dst, "delta"
                    ):
                        cur = NodeDelta(
                            node_id=cur.node_id,
                            from_version_excluded=cur.from_version_excluded,
                            last_gc_version=cur.last_gc_version,
                            key_values=list(cur.key_values),
                            max_version=cur.max_version + bf.amount,
                        )
                        self._count("byz_digest_inflation")
                elif bf.kind == "owner_violation":
                    if nd.node_id.name == self._self:
                        continue  # we own our keyspace: not a violation
                    if self._byz_rate_ok(idx, bf, dst, "delta"):
                        stamp = cur.max_version
                        base = (
                            stamp
                            if stamp is not None
                            else cur.from_version_excluded
                        )
                        # The fabricated stamp is pinned to ``base``
                        # (not the original, possibly-None stamp): a
                        # truncated relay's stamp-less delta would
                        # otherwise carry the fabrication PAST guard 3's
                        # reach — a self-consistent future history, the
                        # documented residual surface, not the pure kind
                        # this injector pins injected == rejected for.
                        cur = NodeDelta(
                            node_id=cur.node_id,
                            from_version_excluded=cur.from_version_excluded,
                            last_gc_version=cur.last_gc_version,
                            key_values=[
                                KeyValueUpdate(
                                    "byz", "byzantine", base + bf.amount,
                                    KeyStatus.SET,
                                )
                            ],
                            max_version=base,
                        )
                        self._count("byz_owner_violation")
            if cur is not nd:
                dirty = True
            out.append(cur)
        if digest is not None:
            # SynAck: fabricate for victims the delta did not cover —
            # the receiver's own keyspace included, when it matches.
            present = {nd.node_id for nd in out}
            for node_id, dg in digest.node_digests.items():
                if node_id in present or node_id.name == self._self:
                    continue
                for idx, bf in active:
                    if bf.kind != "owner_violation":
                        continue
                    if not bf.victims.matches_name(node_id.name):
                        continue
                    if not self._byz_rate_ok(idx, bf, dst, "delta"):
                        continue
                    out.append(
                        NodeDelta(
                            node_id=node_id,
                            from_version_excluded=dg.max_version,
                            last_gc_version=dg.last_gc_version,
                            key_values=[
                                KeyValueUpdate(
                                    "byz", "byzantine",
                                    dg.max_version + bf.amount,
                                    KeyStatus.SET,
                                )
                            ],
                            max_version=dg.max_version,
                        )
                    )
                    dirty = True
                    self._count("byz_owner_violation")
                    break
        if not dirty:
            return delta
        return Delta(node_deltas=out)

    def rewrite_packet(self, packet, dst: str | None):
        """Outbound handshake packet through the active byzantine
        kinds. Returns the ORIGINAL packet when this node is honest (or
        no window is open) — the fault-free path stays byte-identical.
        ``dst`` may be None for responder-side writes (the inbound peer
        is unlabelled before its first Syn resolves); the draw stream
        then keys on "?" — rate < 1 responder schedules are
        deterministic given a deterministic arrival order."""
        from ..core.messages import Ack, Packet, Syn, SynAck

        active = self.byzantine_active()
        if not active:
            return packet
        dst = dst or "?"
        msg = packet.msg
        if isinstance(msg, Syn):
            dg = self._rewrite_digest(msg.digest, active, dst)
            if dg is msg.digest:
                return packet
            return Packet(packet.cluster_id, Syn(dg))
        if isinstance(msg, SynAck):
            dg = self._rewrite_digest(msg.digest, active, dst)
            dl = self._rewrite_delta(
                msg.delta, active, dst, digest=msg.digest
            )
            if dg is msg.digest and dl is msg.delta:
                return packet
            return Packet(packet.cluster_id, SynAck(dg, dl))
        if isinstance(msg, Ack):
            dl = self._rewrite_delta(msg.delta, active, dst)
            if dl is msg.delta:
                return packet
            return Packet(packet.cluster_id, Ack(dl))
        return packet

    def rewrite_syn_bytes(self, payload: bytes, dst: str | None) -> bytes:
        """The pre-encoded Syn fast path (GossipEngine.make_syn_bytes):
        decode, rewrite, re-encode — only when a byzantine window is
        actually open for this node; honest bytes pass through
        untouched."""
        if not self.byzantine_active():
            return payload
        from ..wire import decode_packet, encode_packet

        packet = decode_packet(payload)
        rewritten = self.rewrite_packet(packet, dst)
        if rewritten is packet:
            return payload
        return encode_packet(rewritten)

    def apply(self, dst: str, op: str) -> Decision:
        """Decide, count, and raise injected failures (as the exception
        the real network would produce). Returns the Decision; the
        transport wrapper owns delay composition, because an injected
        delay must consume the OPERATION'S own timeout budget — a
        slow-peer plan whose delay exceeds ``read_timeout`` has to
        surface as the TimeoutError the fault-free code paths handle,
        not silently stretch the round."""
        d = self.decide(dst, op)
        if d.action == "ok":
            if d.delay > 0:
                self._count("delay")
            if d.duplicate:
                self._count("duplicate")
            return d
        self._count(d.action)
        if d.action == "eof":
            raise asyncio.IncompleteReadError(partial=b"", expected=None)
        if op == "connect":
            raise ConnectionRefusedError(f"fault injected: {d.action}")
        raise ConnectionResetError(f"fault injected: {d.action}")


class FaultyTransport:
    """``GossipTransport`` wrapper consulting a FaultController around
    every initiator-side operation. Constructed only when
    ``Config.fault_plan`` is set; reads/writes on connections the
    wrapper did not dial (the responder role) pass through untouched.
    """

    def __init__(
        self,
        inner,
        controller: FaultController,
        resolve_label: Callable[[str, int], str],
    ) -> None:
        self._inner = inner
        self._ctl = controller
        self._resolve = resolve_label
        # Dialed streams -> peer label, so read/write ops can be
        # attributed without threading labels through the call sites.
        self._peer_of: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()

    @property
    def controller(self) -> FaultController:
        return self._ctl

    async def _with_delay(self, delay: float, make_coro, budget: float):
        """Run ``make_coro()`` after an injected delay, with delay +
        operation together bounded by the operation's OWN configured
        timeout — so a delay past the budget surfaces as the
        TimeoutError a real slow peer would produce (the code path the
        plan exists to exercise), never as a silently stretched round.
        ``make_coro`` is a factory (not a coroutine) so a timeout that
        lands inside the sleep leaves no never-awaited coroutine."""
        if delay <= 0:
            return await make_coro()
        async def delayed():
            await clock_sleep(delay)
            return await make_coro()
        return await asyncio.wait_for(delayed(), timeout=budget)

    async def connect(
        self,
        host: str,
        port: int,
        tls_name: str | None = None,
        *,
        timeout: float | None = None,
    ):
        label = self._resolve(host, port)
        d = self._ctl.apply(label, "connect")
        # An adaptive per-peer timeout (runtime/health.py) replaces the
        # configured constant as the delay + operation budget: a
        # slow-peer plan must exhaust the budget the caller is actually
        # waiting on.
        reader, writer = await self._with_delay(
            d.delay,
            lambda: self._inner.connect(host, port, tls_name, timeout=timeout),
            self._inner._connect_timeout if timeout is None else timeout,
        )
        self._peer_of[reader] = label
        self._peer_of[writer] = label
        return reader, writer

    async def read_packet(self, reader, timeout: float | None = None):
        label = self._peer_of.get(reader)
        if label is None:
            return await self._inner.read_packet(reader, timeout)
        d = self._ctl.apply(label, "read")
        budget = self._inner._read_timeout if timeout is None else timeout
        return await self._with_delay(
            d.delay, lambda: self._inner.read_packet(reader, timeout), budget
        )

    async def write_packet(
        self, writer, packet, *, timeout: float | None = None
    ) -> None:
        label = self._peer_of.get(writer)
        # Byzantine rewriting applies to EVERY outbound packet this
        # node writes — including the responder role's SynAck on
        # connections it did not dial (label None there: the initiator
        # fault ops below stay initiator-side, but an attacker lies in
        # both roles).
        packet = self._ctl.rewrite_packet(packet, label)
        if label is None:
            return await self._inner.write_packet(
                writer, packet, timeout=timeout
            )
        d = self._ctl.apply(label, "write")
        if d.duplicate:
            await self._inner.write_packet(writer, packet, timeout=timeout)
        await self._with_delay(
            d.delay,
            lambda: self._inner.write_packet(writer, packet, timeout=timeout),
            self._inner._write_timeout if timeout is None else timeout,
        )

    async def write_framed(
        self, writer, payload: bytes, kind: str, *, timeout: float | None = None
    ) -> None:
        label = self._peer_of.get(writer)
        if kind == "syn":
            # The engine's pre-encoded Syn bytes: a byzantine window
            # rewrites the digest in flight (decode/re-encode only
            # while a window is actually open).
            payload = self._ctl.rewrite_syn_bytes(payload, label)
        if label is None:
            return await self._inner.write_framed(
                writer, payload, kind, timeout=timeout
            )
        d = self._ctl.apply(label, "write")
        if d.duplicate:
            await self._inner.write_framed(writer, payload, kind, timeout=timeout)
        await self._with_delay(
            d.delay,
            lambda: self._inner.write_framed(
                writer, payload, kind, timeout=timeout
            ),
            self._inner._write_timeout if timeout is None else timeout,
        )

    def _rewrite_parts(self, parts, kind: str, label: str | None):
        """Byzantine rewriting over a scatter-gather parts list FORCES
        MATERIALIZATION: the attacker must see (and may replace) the
        whole packet, so the buffers are joined, decoded, rewritten and
        re-encoded as one buffer — the documented contract that keeps
        PR 8's wrong-data injection composing unchanged with the
        zero-copy write path (docs/robustness.md). Honest windows (the
        overwhelmingly common case) return the parts untouched — the
        fast path stays join-free."""
        if not self._ctl.byzantine_active():
            return parts
        from ..wire import decode_packet, encode_packet

        payload = b"".join(parts)
        packet = decode_packet(payload)
        rewritten = self._ctl.rewrite_packet(packet, label)
        if rewritten is packet:
            return parts
        return [encode_packet(rewritten)]

    async def write_framed_parts(
        self, writer, parts, kind: str, *, timeout: float | None = None
    ) -> None:
        label = self._peer_of.get(writer)
        # Rewrite before the label gate — an attacker lies in both
        # roles (the responder's SynAck parts carry label None), same
        # as write_packet above.
        parts = self._rewrite_parts(parts, kind, label)
        if label is None:
            return await self._inner.write_framed_parts(
                writer, parts, kind, timeout=timeout
            )
        d = self._ctl.apply(label, "write")
        if d.duplicate:
            await self._inner.write_framed_parts(
                writer, parts, kind, timeout=timeout
            )
        await self._with_delay(
            d.delay,
            lambda: self._inner.write_framed_parts(
                writer, parts, kind, timeout=timeout
            ),
            self._inner._write_timeout if timeout is None else timeout,
        )

    async def start_server(self, host, port, handler):
        return await self._inner.start_server(host, port, handler)

    def peer_cert_names(self, writer):
        return self._inner.peer_cert_names(writer)

    def __getattr__(self, name: str):
        # Anything else (private fields, future methods) passes through —
        # the wrapper only intercepts the fault-bearing operations.
        return getattr(self._inner, name)
