"""Runtime fault injection: a FaultPlan compiled against the asyncio
backend.

:class:`FaultController` turns a plan into a deterministic per-link
decision stream; :class:`FaultyTransport` wraps the real
``GossipTransport`` and consults the controller around every initiator
operation — connect attempts (injected refusals/delays), framed writes
(drops as connection resets, slow-peer delays, duplication) and framed
reads (drops, delays, **mid-handshake EOF**). The connection pool is
covered transitively: it dials through the wrapped ``connect``, so
pooled borrows, the reconnect-retry path and stale eviction all see the
same hostile network.

Injection is initiator-side: every link gets both endpoints' outbound
operations degraded, which fully cuts a partitioned link (neither side's
handshakes go out) without the responder needing to attribute inbound
connections. Crashed-node windows additionally refuse all of the down
node's own traffic in both roles.

Determinism: each probability draw is
``blake2b(seed | src | dst | op | op_index | check)`` — a pure function
of the plan and the per-link operation sequence, independent of
wall-clock, PRNG state, or scheduling (tests/test_faults.py asserts two
controllers replay identical schedules). Fault *windows* (start/end)
are evaluated against an injectable clock so tests can step time
explicitly.

With ``Config.fault_plan=None`` none of this is constructed: the
transport is the plain ``GossipTransport`` and every wrapped path is
byte-identical to the fault-free build.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
import weakref
from collections.abc import Callable
from dataclasses import dataclass

from ..obs.registry import MetricsRegistry
from .plan import FaultPlan

# Operation labels the transport wrapper reports; part of the hash
# domain, so renaming one would re-key its schedule.
OPS = ("connect", "read", "write")


@dataclass(frozen=True, slots=True)
class Decision:
    """One injected-fault verdict for one operation."""

    action: str  # "ok" | "drop" | "eof" | "down" | "partition"
    delay: float = 0.0
    duplicate: bool = False


class FaultController:
    """Deterministic fault schedule for one node (see module docstring).

    ``clock`` defaults to ``time.monotonic``; tests inject a fake. The
    epoch is latched by :meth:`start` (the ChaosHarness synchronises one
    epoch across a fleet so partitions heal simultaneously) or lazily on
    the first decision.
    """

    def __init__(
        self,
        plan: FaultPlan,
        self_name: str,
        *,
        metrics: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._plan = plan
        self._self = self_name
        self._clock = clock
        self._t0: float | None = None
        self._op_index: dict[tuple[str, str], int] = {}
        self._injected = self._partition_gauge = None
        if metrics is not None:
            self._injected = metrics.counter(
                "aiocluster_faults_injected_total",
                "Faults injected into the runtime transport, by kind",
                labels=("kind",),
            )
            self._partition_gauge = metrics.gauge(
                "aiocluster_fault_partition_active",
                "Fault-plan partitions currently active (0 = fully healed)",
            )

    @property
    def plan(self) -> FaultPlan:
        return self._plan

    # -- time -----------------------------------------------------------------

    def start(self, epoch: float | None = None) -> None:
        """Latch the plan's t=0. An EXPLICIT epoch always wins: the
        cluster's own boot traffic lazily latches a local t0 via
        elapsed() before a harness can reach the controller, and a
        restarted node must rejoin the fleet's ORIGINAL epoch — not
        restart the plan clock at its own reboot."""
        if epoch is not None:
            self._t0 = epoch
        elif self._t0 is None:
            self._t0 = self._clock()

    def elapsed(self) -> float:
        self.start()
        return self._clock() - self._t0

    # -- deterministic draws --------------------------------------------------

    def _u(self, dst: str, op: str, k: int, check: str) -> float:
        """Uniform [0, 1) draw for check ``check`` of the k-th ``op`` on
        link self->dst. blake2b, not ``hash()``: stable across processes
        and runs, so (seed, plan) fully determines the schedule."""
        key = f"{self._plan.seed}|{self._self}|{dst}|{op}|{k}|{check}"
        digest = hashlib.blake2b(key.encode(), digest_size=8).digest()
        return int.from_bytes(digest, "big") / 2**64

    # -- decision -------------------------------------------------------------

    def partitions_active(self, t: float | None = None) -> int:
        t = self.elapsed() if t is None else t
        n = sum(1 for p in self._plan.partitions if p.active(t))
        if self._partition_gauge is not None:
            self._partition_gauge.set(n)
        return n

    def _node_down(self, name: str, t: float) -> bool:
        return any(
            cr.down(t) and cr.nodes.matches_name(name)
            for cr in self._plan.crashes
        )

    def _partition_blocked(self, dst: str, t: float) -> bool:
        self.partitions_active(t)  # keep the gauge current
        for p in self._plan.partitions:
            if not p.active(t):
                continue
            g_self = p.group_of_name(self._self)
            g_dst = p.group_of_name(dst)
            # None = unlisted under explicit groups: fail-closed — an
            # unattributable peer is cut from every island rather than
            # hash-bucketed into (possibly) our own.
            if g_self is None or g_dst is None or g_self != g_dst:
                return True
        return False

    def decide(self, dst: str, op: str, t: float | None = None) -> Decision:
        """The verdict for the next ``op`` on link self->dst. Advances
        the link's operation counter; every probability check consumes
        its own named draw, so the schedule does not depend on which
        check short-circuits first."""
        t = self.elapsed() if t is None else t
        k = self._op_index[(dst, op)] = self._op_index.get((dst, op), 0) + 1
        if self._node_down(self._self, t) or self._node_down(dst, t):
            return Decision("down")
        if self._partition_blocked(dst, t):
            return Decision("partition")
        delay = 0.0
        duplicate = False
        for idx, lf in enumerate(self._plan.links):
            if not lf.active(t):
                continue
            if not (
                lf.src.matches_name(self._self) and lf.dst.matches_name(dst)
            ):
                continue
            if (
                op == "read"
                and lf.eof > 0
                and self._u(dst, op, k, f"{idx}:eof") < lf.eof
            ):
                return Decision("eof")
            if lf.drop > 0 and self._u(dst, op, k, f"{idx}:drop") < lf.drop:
                return Decision("drop")
            if (
                lf.delay_prob > 0
                and self._u(dst, op, k, f"{idx}:delay") < lf.delay_prob
            ):
                delay = max(delay, lf.delay)
            if (
                op == "write"
                and lf.duplicate > 0
                and self._u(dst, op, k, f"{idx}:dup") < lf.duplicate
            ):
                duplicate = True
        return Decision("ok", delay=delay, duplicate=duplicate)

    # -- application ----------------------------------------------------------

    def _count(self, kind: str) -> None:
        if self._injected is not None:
            self._injected.labels(kind).inc()

    def apply(self, dst: str, op: str) -> Decision:
        """Decide, count, and raise injected failures (as the exception
        the real network would produce). Returns the Decision; the
        transport wrapper owns delay composition, because an injected
        delay must consume the OPERATION'S own timeout budget — a
        slow-peer plan whose delay exceeds ``read_timeout`` has to
        surface as the TimeoutError the fault-free code paths handle,
        not silently stretch the round."""
        d = self.decide(dst, op)
        if d.action == "ok":
            if d.delay > 0:
                self._count("delay")
            if d.duplicate:
                self._count("duplicate")
            return d
        self._count(d.action)
        if d.action == "eof":
            raise asyncio.IncompleteReadError(partial=b"", expected=None)
        if op == "connect":
            raise ConnectionRefusedError(f"fault injected: {d.action}")
        raise ConnectionResetError(f"fault injected: {d.action}")


class FaultyTransport:
    """``GossipTransport`` wrapper consulting a FaultController around
    every initiator-side operation. Constructed only when
    ``Config.fault_plan`` is set; reads/writes on connections the
    wrapper did not dial (the responder role) pass through untouched.
    """

    def __init__(
        self,
        inner,
        controller: FaultController,
        resolve_label: Callable[[str, int], str],
    ) -> None:
        self._inner = inner
        self._ctl = controller
        self._resolve = resolve_label
        # Dialed streams -> peer label, so read/write ops can be
        # attributed without threading labels through the call sites.
        self._peer_of: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()

    @property
    def controller(self) -> FaultController:
        return self._ctl

    async def _with_delay(self, delay: float, make_coro, budget: float):
        """Run ``make_coro()`` after an injected delay, with delay +
        operation together bounded by the operation's OWN configured
        timeout — so a delay past the budget surfaces as the
        TimeoutError a real slow peer would produce (the code path the
        plan exists to exercise), never as a silently stretched round.
        ``make_coro`` is a factory (not a coroutine) so a timeout that
        lands inside the sleep leaves no never-awaited coroutine."""
        if delay <= 0:
            return await make_coro()
        async def delayed():
            await asyncio.sleep(delay)
            return await make_coro()
        return await asyncio.wait_for(delayed(), timeout=budget)

    async def connect(self, host: str, port: int, tls_name: str | None = None):
        label = self._resolve(host, port)
        d = self._ctl.apply(label, "connect")
        reader, writer = await self._with_delay(
            d.delay,
            lambda: self._inner.connect(host, port, tls_name),
            self._inner._connect_timeout,
        )
        self._peer_of[reader] = label
        self._peer_of[writer] = label
        return reader, writer

    async def read_packet(self, reader, timeout: float | None = None):
        label = self._peer_of.get(reader)
        if label is None:
            return await self._inner.read_packet(reader, timeout)
        d = self._ctl.apply(label, "read")
        budget = self._inner._read_timeout if timeout is None else timeout
        return await self._with_delay(
            d.delay, lambda: self._inner.read_packet(reader, timeout), budget
        )

    async def write_packet(self, writer, packet) -> None:
        label = self._peer_of.get(writer)
        if label is None:
            return await self._inner.write_packet(writer, packet)
        d = self._ctl.apply(label, "write")
        if d.duplicate:
            await self._inner.write_packet(writer, packet)
        await self._with_delay(
            d.delay,
            lambda: self._inner.write_packet(writer, packet),
            self._inner._write_timeout,
        )

    async def write_framed(self, writer, payload: bytes, kind: str) -> None:
        label = self._peer_of.get(writer)
        if label is None:
            return await self._inner.write_framed(writer, payload, kind)
        d = self._ctl.apply(label, "write")
        if d.duplicate:
            await self._inner.write_framed(writer, payload, kind)
        await self._with_delay(
            d.delay,
            lambda: self._inner.write_framed(writer, payload, kind),
            self._inner._write_timeout,
        )

    async def start_server(self, host, port, handler):
        return await self._inner.start_server(host, port, handler)

    def peer_cert_names(self, writer):
        return self._inner.peer_cert_names(writer)

    def __getattr__(self, name: str):
        # Anything else (private fields, future methods) passes through —
        # the wrapper only intercepts the fault-bearing operations.
        return getattr(self._inner, name)
