"""Deterministic fault plans: one scenario model for both backends.

The paper's algorithms (phi-accrual failure detection, ScuttleButt
anti-entropy) only earn their keep under hostile networks, yet neither
backend could previously *produce* one. A :class:`FaultPlan` names the
hostile conditions — per-link drop/delay/duplication, timed partitions
with heal, asymmetric links, node crash/restart, slow-peer throttling —
as seeded, serializable data that compiles into

- a runtime :class:`~aiocluster_tpu.faults.runtime.FaultController`
  wrapping the asyncio transport/pool (``Config.fault_plan``), and
- per-round link/crash masks for the JAX engines
  (:mod:`aiocluster_tpu.faults.sim`, ``SimConfig.fault_plan``), so the
  same scenario runs at 10k-100k nodes.

Determinism contract: every injected fault is a pure function of
``(plan.seed, link, operation index)`` in the runtime and of
``(plan.seed, tick, src, dst)`` in the sim — the same (seed, plan)
yields the identical schedule on every run (tests/test_faults.py).

Time units: plan times are **seconds in the runtime and gossip rounds
(ticks) in the sim**. The reference's round interval is 1 s, so the two
scales coincide for reference-shaped clusters; scale windows by your
``gossip_interval`` otherwise.

Everything here is stdlib-only and hashable (frozen dataclasses over
tuples), so a plan can ride inside the sim's jit-static ``SimConfig``.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import asdict, dataclass, fields, replace


def _frac_of(name: str) -> float:
    """Stable position of a node *name* in [0, 1): the runtime's analogue
    of the sim's index/n coordinate, so fraction-addressed NodeSets mean
    the same thing in both backends (crc32 is stable across processes,
    unlike ``hash``)."""
    return (zlib.crc32(name.encode()) & 0xFFFFFFFF) / 2**32


@dataclass(frozen=True, slots=True, eq=True)
class NodeSet:
    """Which nodes a fault applies to.

    - ``names``: explicit node names (runtime) — exact matches.
    - ``frac``: a half-open [lo, hi) window of the node-coordinate space.
      The sim places node ``i`` at ``i / n``; the runtime places a node
      at ``crc32(name) / 2**32``. Fraction-addressed sets are therefore
      the portable way to say "a third of the cluster" in one plan that
      runs on both backends.
    - both empty/None: matches every node.
    """

    names: tuple[str, ...] = ()
    frac: tuple[float, float] | None = None

    def matches_all(self) -> bool:
        return not self.names and self.frac is None

    def matches_name(self, name: str) -> bool:
        if self.matches_all():
            return True
        if name in self.names:
            return True
        if self.frac is not None:
            lo, hi = self.frac
            return lo <= _frac_of(name) < hi
        return False


ALL_NODES = NodeSet()


@dataclass(frozen=True, slots=True, eq=True)
class LinkFault:
    """Directional link degradation from ``src`` to ``dst`` (asymmetric
    by construction: a plan with one direction only degrades that
    direction).

    Probabilities are per *operation* (a connect attempt, one framed
    read/write) in the runtime and per *sub-exchange direction* in the
    sim:

    - ``drop``: the operation fails — a connect is refused, a framed
      write/read sees a connection reset. In the sim the exchange simply
      does not happen this round.
    - ``delay`` / ``delay_prob``: with probability ``delay_prob`` the
      operation is stalled ``delay`` seconds (slow-peer throttling). In
      the sim a delay of >= 1 tick means the exchange misses its round
      deadline — observationally a drop for that tick; sub-tick delays
      are invisible at tick resolution.
    - ``duplicate``: a framed write is sent twice. Runtime only, and a
      STREAM-CORRUPTION fault, not benign datagram re-delivery: the
      duplicated frame lands where the Syn/SynAck/Ack state machine
      expects the next message, so the responder rejects it and closes
      the connection — the handshake's responder-side merge is lost and
      recovered by a later round/reconnect (the recovery is the point;
      tests/test_faults.py::test_duplicate_frames_desync_but_converge).
      The sim ignores duplication entirely: its connectionless
      max-merge has no stream to corrupt.
    - ``eof``: a framed read sees EOF mid-handshake — the peer appears
      to hang up between our write and its reply.

    ``start``/``end`` bound the active window (``end=None`` = forever).
    """

    src: NodeSet = ALL_NODES
    dst: NodeSet = ALL_NODES
    drop: float = 0.0
    delay: float = 0.0
    delay_prob: float = 0.0
    duplicate: float = 0.0
    eof: float = 0.0
    start: float = 0.0
    end: float | None = None

    def active(self, t: float) -> bool:
        return t >= self.start and (self.end is None or t < self.end)


@dataclass(frozen=True, slots=True, eq=True)
class Partition:
    """A timed partition into ``n_groups`` islands, healing at ``end``.

    Group assignment: explicit ``groups`` (tuples of node labels,
    runtime only) when given; otherwise derived — the sim cuts the
    index space into ``n_groups`` contiguous blocks
    (``i * n_groups // n``), the runtime buckets by the stable name
    hash (``frac * n_groups``). Traffic crossing group boundaries is
    blocked while the window is active; at ``end`` the partition heals
    and anti-entropy reconverges the islands.

    Explicit groups are FAIL-CLOSED: a label not listed in any group is
    isolated from everyone while the partition is active. Runtime plans
    must therefore list each member under BOTH its node name and its
    ``host:port`` — before a peer's first handshake the dialer can only
    label it by address, and bucketing that unresolved label by hash
    could silently land it in the dialer's own group, leaking traffic
    across the cut (``ChaosHarness.name_groups`` builds the aliased
    groups for you).
    """

    n_groups: int = 2
    start: float = 0.0
    end: float | None = None
    groups: tuple[tuple[str, ...], ...] = ()

    def active(self, t: float) -> bool:
        return t >= self.start and (self.end is None or t < self.end)

    def group_of_name(self, name: str) -> int | None:
        """The label's group, or None when explicit groups are given
        and the label is unlisted (fail-closed: an unknown peer is cut
        from every island while the partition is active — see class
        docstring)."""
        if self.groups:
            for g, members in enumerate(self.groups):
                if name in members:
                    return g
            return None
        # Derived assignment: stable hash bucket (total by construction).
        g = int(_frac_of(name) * self.n_groups)
        return min(g, self.n_groups - 1)


RECOVERY_KINDS = ("amnesia", "warm")


@dataclass(frozen=True, slots=True, eq=True)
class NodeCrash:
    """Nodes in ``nodes`` crash at ``at`` and restart ``down_for``
    later. While down, peers' connects to it are refused (runtime) /
    its exchanges no-op and its heartbeat and writes freeze (sim).
    ``recovery`` names what the restart comes back WITH
    (docs/robustness.md "Durability & lifecycle"):

    - ``"amnesia"`` (the default — the reference's restart semantics):
      the node reboots with an empty keyspace. The ChaosHarness boots a
      fresh Cluster with a **bumped generation** (newer-generation-wins
      exercised for real); the sim resets the node's knowledge row at
      the restart tick, so it re-replicates the whole cluster from
      zero — the full-state anti-entropy cost a rolling restart pays.
      (The sim's watermark model has no generations: owner ground truth
      persists and only the replica knowledge resets; the runtime's
      generation bump additionally re-replicates the node's OWN state,
      which the sim does not model.)
    - ``"warm"``: the node reboots with its durable store
      (``Config.persistence`` — the ChaosHarness requires a
      ``persist_root``). The crash itself is an ``abort()`` (no clean
      marker), so the generation still bumps, but the restored
      version/GC watermarks turn rejoin into delta catch-up. In the sim
      the crash window freezes and nothing resets — the watermarks ARE
      the persisted knowledge.
    """

    nodes: NodeSet = ALL_NODES
    at: float = 0.0
    down_for: float = 1.0
    recovery: str = "amnesia"

    def down(self, t: float) -> bool:
        return self.at <= t < self.at + self.down_for


BYZANTINE_KINDS = ("stale_replay", "digest_inflation", "owner_violation")


@dataclass(frozen=True, slots=True, eq=True)
class ByzantineFault:
    """Wrong-data faults: nodes in ``nodes`` actively lie on the wire
    while the window is open (versus everything above, which only
    degrades delivery). The three kinds violate the two assumptions the
    paper's correctness rests on — each node is the sole writer of its
    own keyspace (van Renesse et al.), and advertised state is honest:

    - ``stale_replay``: the attacker re-advertises OLD versions for the
      ``victims``' keys — its digests claim ancient knowledge of them
      (heartbeat included: stale heartbeat adverts are the phi-accrual
      attack surface) and its outbound deltas replay below-floor
      versions while keeping the ``max_version`` stamp, the poison that
      would fast-forward an unguarded receiver past data it never got.
    - ``digest_inflation``: the attacker's digests claim ``max_version``
      for ``victims`` AHEAD of reality by ``amount``, and its outbound
      delta stamps are inflated the same way — honest responders
      withhold the victims' data from it (it "already has" everything),
      and an unguarded receiver of an inflated stamp would skip every
      future legitimate update below it.
    - ``owner_violation``: the attacker ships deltas mutating keyspaces
      it does not own — the ACT03x invariant as a runtime attack:
      fabricated key-values (version ``amount`` past the stamp) replace
      its genuine relays for each victim, including deltas that target
      the receiver's OWN keyspace when it gossips with a victim.

    Defenses land with the kinds (docs/faults.md "byzantine"): the
    apply-delta path rejects self-keyspace writes, below-floor replays,
    over-stamp key-values and unsupported ``max_version`` fast-forwards
    (core/guards.py), counting each in
    ``aiocluster_byzantine_rejected_total{kind}``; the sim lowers the
    guarded outcome as per-round masks (faults/sim.py). A combined
    attack that fabricates a self-consistent future history is
    detectable only by the true owner — that residual surface is what
    the tolerance atlas (benchmarks/byzantine_bench.py) maps.

    ``rate`` is the per-message injection probability (runtime) and the
    per-(src, dst, tick) mask probability (sim). ``amount`` is the
    version-space offset inflation/fabrication uses.
    """

    kind: str
    nodes: NodeSet = ALL_NODES
    victims: NodeSet = ALL_NODES
    rate: float = 1.0
    amount: int = 1 << 20
    start: float = 0.0
    end: float | None = None

    def active(self, t: float) -> bool:
        return t >= self.start and (self.end is None or t < self.end)


@dataclass(frozen=True, slots=True, eq=True)
class FaultPlan:
    """A complete, seeded fault scenario (see module docstring)."""

    seed: int = 0
    links: tuple[LinkFault, ...] = ()
    partitions: tuple[Partition, ...] = ()
    crashes: tuple[NodeCrash, ...] = ()
    byzantine: tuple[ByzantineFault, ...] = ()

    # -- validation -----------------------------------------------------------

    def __post_init__(self) -> None:
        for lf in self.links:
            for name in ("drop", "delay_prob", "duplicate", "eof"):
                p = getattr(lf, name)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(f"LinkFault.{name} must be in [0, 1], got {p}")
            if lf.delay < 0:
                raise ValueError("LinkFault.delay must be >= 0")
        for part in self.partitions:
            if part.n_groups < 2:
                raise ValueError("Partition.n_groups must be >= 2")
            if part.groups and len(part.groups) != part.n_groups:
                raise ValueError("Partition.groups length must equal n_groups")
        for cr in self.crashes:
            if cr.down_for <= 0:
                raise ValueError("NodeCrash.down_for must be > 0")
            if cr.recovery not in RECOVERY_KINDS:
                raise ValueError(
                    f"unknown NodeCrash.recovery {cr.recovery!r} "
                    f"(one of {RECOVERY_KINDS})"
                )
        for bf in self.byzantine:
            if bf.kind not in BYZANTINE_KINDS:
                raise ValueError(
                    f"unknown ByzantineFault.kind {bf.kind!r} "
                    f"(one of {BYZANTINE_KINDS})"
                )
            if not 0.0 <= bf.rate <= 1.0:
                raise ValueError(
                    f"ByzantineFault.rate must be in [0, 1], got {bf.rate}"
                )
            if bf.amount < 1:
                raise ValueError("ByzantineFault.amount must be >= 1")

    def check_sim_compatible(self) -> None:
        """The sim addresses nodes by index fraction only: a plan whose
        NodeSets use explicit ``names`` or whose partitions use explicit
        ``groups`` cannot be compiled to masks. Raise a descriptive
        error instead of silently matching nothing."""
        sets = [(lf.src, "LinkFault.src") for lf in self.links]
        sets += [(lf.dst, "LinkFault.dst") for lf in self.links]
        sets += [(cr.nodes, "NodeCrash.nodes") for cr in self.crashes]
        sets += [(bf.nodes, "ByzantineFault.nodes") for bf in self.byzantine]
        sets += [
            (bf.victims, "ByzantineFault.victims") for bf in self.byzantine
        ]
        for ns, where in sets:
            if ns.names:
                raise ValueError(
                    f"{where} uses explicit names — the sim backend only "
                    "supports fraction-addressed NodeSets (frac=(lo, hi))"
                )
        for part in self.partitions:
            if part.groups:
                raise ValueError(
                    "Partition.groups uses explicit names — the sim "
                    "backend derives groups from contiguous index blocks"
                )

    # -- serialization --------------------------------------------------------

    def to_dict(self) -> dict:
        return asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, data: dict) -> "FaultPlan":
        def _nodeset(d: dict) -> NodeSet:
            return NodeSet(
                names=tuple(d.get("names", ())),
                frac=tuple(d["frac"]) if d.get("frac") is not None else None,
            )

        def _load(dc_cls, d: dict, nodeset_keys: tuple[str, ...]):
            kwargs = dict(d)
            for key in nodeset_keys:
                if key in kwargs:
                    kwargs[key] = _nodeset(kwargs[key])
            allowed = {f.name for f in fields(dc_cls)}
            unknown = set(kwargs) - allowed
            if unknown:
                raise ValueError(
                    f"unknown {dc_cls.__name__} fields: {sorted(unknown)}"
                )
            return dc_cls(**kwargs)

        return cls(
            seed=int(data.get("seed", 0)),
            links=tuple(
                _load(LinkFault, d, ("src", "dst"))
                for d in data.get("links", ())
            ),
            partitions=tuple(
                _load(
                    Partition,
                    {**d, "groups": tuple(tuple(g) for g in d.get("groups", ()))},
                    (),
                )
                for d in data.get("partitions", ())
            ),
            crashes=tuple(
                _load(NodeCrash, d, ("nodes",)) for d in data.get("crashes", ())
            ),
            byzantine=tuple(
                _load(ByzantineFault, d, ("nodes", "victims"))
                for d in data.get("byzantine", ())
            ),
        )

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        return cls.from_dict(json.loads(raw))


def with_extra_links(
    plan: "FaultPlan | None", links: tuple[LinkFault, ...]
) -> "FaultPlan | None":
    """``plan`` with ``links`` appended (a fresh plan when None) — how
    heterogeneity's WAN classes (models/topology.py) fold into the one
    fault-injection machinery on both backends. Appending keeps every
    existing entry's index, so the plan's probabilistic draw streams
    (keyed per link-fault index) are unchanged for the original links."""
    if not links:
        return plan
    if plan is None:
        return FaultPlan(links=tuple(links))
    # dataclasses.replace keeps the copy complete by construction: a
    # future FaultPlan field cannot be silently dropped here.
    return replace(plan, links=plan.links + tuple(links))
