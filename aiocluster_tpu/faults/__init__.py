"""Deterministic fault injection across both backends (docs/faults.md).

- :mod:`.plan` — the scenario model: seeded, serializable
  :class:`FaultPlan` dataclasses shared by the asyncio runtime and the
  TPU sim.
- :mod:`.scenarios` — the named library (``split_brain``,
  ``flaky_links``, ``rolling_restart``, ``slow_third``).
- :mod:`.runtime` — FaultController + transport wrapping (compiled in
  by ``Config.fault_plan``).
- :mod:`.sim` — jit-compatible link/crash masks (compiled in by
  ``SimConfig.fault_plan``).
- :mod:`.runner` — ChaosHarness: a real loopback fleet under one plan,
  crash/restart with generation bump included.
"""

from .plan import (
    ALL_NODES,
    FaultPlan,
    LinkFault,
    NodeCrash,
    NodeSet,
    Partition,
)
from .scenarios import (
    SCENARIOS,
    flaky_links,
    rolling_restart,
    round_robin_groups,
    slow_third,
    split_brain,
)

__all__ = (
    "ALL_NODES",
    "FaultPlan",
    "LinkFault",
    "NodeCrash",
    "NodeSet",
    "Partition",
    "SCENARIOS",
    "flaky_links",
    "rolling_restart",
    "round_robin_groups",
    "slow_third",
    "split_brain",
)
