"""Deterministic fault injection across both backends (docs/faults.md).

- :mod:`.plan` — the scenario model: seeded, serializable
  :class:`FaultPlan` dataclasses shared by the asyncio runtime and the
  TPU sim.
- :mod:`.scenarios` — the named library (``split_brain``,
  ``flaky_links``, ``rolling_restart``, ``slow_third``).
- :mod:`.runtime` — FaultController + transport wrapping (compiled in
  by ``Config.fault_plan``).
- :mod:`.sim` — jit-compatible link/crash masks (compiled in by
  ``SimConfig.fault_plan``).
- :mod:`.runner` — ChaosHarness: a real loopback fleet under one plan,
  crash/restart with generation bump included.
"""

from .plan import (
    ALL_NODES,
    BYZANTINE_KINDS,
    ByzantineFault,
    FaultPlan,
    LinkFault,
    NodeCrash,
    NodeSet,
    Partition,
    with_extra_links,
)
from .scenarios import (
    SCENARIOS,
    byzantine_fraction,
    byzantine_storm,
    flaky_links,
    rolling_restart,
    round_robin_groups,
    slow_third,
    split_brain,
)

__all__ = (
    "ALL_NODES",
    "BYZANTINE_KINDS",
    "ByzantineFault",
    "FaultPlan",
    "LinkFault",
    "NodeCrash",
    "NodeSet",
    "Partition",
    "SCENARIOS",
    "byzantine_fraction",
    "byzantine_storm",
    "flaky_links",
    "rolling_restart",
    "round_robin_groups",
    "slow_third",
    "split_brain",
    "with_extra_links",
)
