"""Named fault scenarios: the library every robustness test, soak and
benchmark draws from (docs/faults.md).

Each scenario function returns a plain :class:`FaultPlan` — seeded,
serializable, and runnable on **both** backends (all NodeSets here are
fraction-addressed, so ``SimConfig.fault_plan`` accepts them at 10k-100k
nodes unchanged). Times are seconds in the runtime and gossip rounds in
the sim (the reference's 1 s interval makes them coincide).

``SCENARIOS`` maps names to builders for CLI/tooling lookup.
"""

from __future__ import annotations

from .plan import (
    ALL_NODES,
    ByzantineFault,
    FaultPlan,
    LinkFault,
    NodeCrash,
    NodeSet,
    Partition,
)


def split_brain(
    n_groups: int = 3,
    start: float = 0.0,
    heal: float | None = 30.0,
    *,
    seed: int = 0,
    groups: tuple[tuple[str, ...], ...] = (),
) -> FaultPlan:
    """A clean ``n_groups``-way partition from ``start`` until ``heal``
    (None = never heals). The canonical convergence-under-fault probe:
    cross-island state must stall while partitioned and fully reconverge
    after heal (benchmarks/fault_bench.py measures the reconvergence).
    ``groups`` pins explicit name groups for runtime fleets."""
    return FaultPlan(
        seed=seed,
        partitions=(
            Partition(n_groups=n_groups, start=start, end=heal, groups=groups),
        ),
    )


def flaky_links(
    drop: float = 0.2,
    *,
    delay: float = 0.0,
    delay_prob: float = 0.0,
    duplicate: float = 0.0,
    start: float = 0.0,
    end: float | None = None,
    seed: int = 0,
) -> FaultPlan:
    """Every link drops each operation with probability ``drop`` (plus
    optional delay/duplication). Anti-entropy must still converge —
    just slower; the chaos soak pins this."""
    return FaultPlan(
        seed=seed,
        links=(
            LinkFault(
                drop=drop,
                delay=delay,
                delay_prob=delay_prob,
                duplicate=duplicate,
                start=start,
                end=end,
            ),
        ),
    )


def rolling_restart(
    n_waves: int = 4,
    *,
    start: float = 2.0,
    wave_every: float = 2.0,
    down_for: float = 1.0,
    seed: int = 0,
    recovery: str = "amnesia",
) -> FaultPlan:
    """Restart the cluster one index-fraction wave at a time: wave ``k``
    (nodes in [k/n_waves, (k+1)/n_waves)) goes down at
    ``start + k * wave_every`` for ``down_for``. ``recovery`` picks the
    rejoin semantics (NodeCrash docstring): ``"amnesia"`` reboots empty
    with a bumped generation (the reference's restart — the sim resets
    the wave's knowledge rows at restart), ``"warm"`` reboots from the
    durable store (``Config.persistence``) and catches up by delta —
    ``benchmarks/restart_bench.py`` runs this plan both ways and gates
    the ratio."""
    crashes = tuple(
        NodeCrash(
            nodes=NodeSet(frac=(k / n_waves, (k + 1) / n_waves)),
            at=start + k * wave_every,
            down_for=down_for,
            recovery=recovery,
        )
        for k in range(n_waves)
    )
    return FaultPlan(seed=seed, crashes=crashes)


def slow_third(
    delay: float = 0.5,
    *,
    delay_prob: float = 1.0,
    frac: tuple[float, float] = (0.0, 1.0 / 3.0),
    start: float = 0.0,
    end: float | None = None,
    seed: int = 0,
) -> FaultPlan:
    """A third of the cluster serves and receives slowly: every
    operation touching a slow node in either direction stalls ``delay``
    seconds with probability ``delay_prob`` (asymmetric variants: build
    the one-direction LinkFault yourself). In the sim, a delay >= 1 tick
    turns the slow nodes' exchanges into per-round misses."""
    slow = NodeSet(frac=frac)
    return FaultPlan(
        seed=seed,
        links=(
            LinkFault(
                src=slow, dst=ALL_NODES,
                delay=delay, delay_prob=delay_prob, start=start, end=end,
            ),
            LinkFault(
                src=ALL_NODES, dst=slow,
                delay=delay, delay_prob=delay_prob, start=start, end=end,
            ),
        ),
    )


def byzantine_fraction(
    kind: str = "stale_replay",
    frac: float = 0.25,
    *,
    victims: NodeSet = ALL_NODES,
    rate: float = 1.0,
    amount: int = 1 << 20,
    start: float = 0.0,
    end: float | None = None,
    seed: int = 0,
) -> FaultPlan:
    """The first index-fraction ``frac`` of the cluster turns byzantine
    with one wrong-data ``kind`` (docs/faults.md "byzantine") — the
    attacker window [0, frac) is exactly what a ``byz_frac`` sweep lane
    overrides, so this is the tolerance atlas's base plan
    (benchmarks/byzantine_bench.py)."""
    return FaultPlan(
        seed=seed,
        byzantine=(
            ByzantineFault(
                kind=kind,
                nodes=NodeSet(frac=(0.0, frac)),
                victims=victims,
                rate=rate,
                amount=amount,
                start=start,
                end=end,
            ),
        ),
    )


def byzantine_storm(
    frac: float = 0.25,
    *,
    victims: NodeSet = ALL_NODES,
    start: float = 0.0,
    end: float | None = None,
    seed: int = 0,
) -> FaultPlan:
    """All three byzantine kinds at once from the same attacker
    fraction — the composite worst case the defense guards and the
    atlas are exercised against."""
    attackers = NodeSet(frac=(0.0, frac))
    return FaultPlan(
        seed=seed,
        byzantine=tuple(
            ByzantineFault(
                kind=kind,
                nodes=attackers,
                victims=victims,
                start=start,
                end=end,
            )
            for kind in (
                "stale_replay", "digest_inflation", "owner_violation"
            )
        ),
    )


SCENARIOS = {
    "split_brain": split_brain,
    "flaky_links": flaky_links,
    "rolling_restart": rolling_restart,
    "slow_third": slow_third,
    "byzantine_fraction": byzantine_fraction,
    "byzantine_storm": byzantine_storm,
}


def round_robin_groups(
    names: list[str] | tuple[str, ...], n_groups: int
) -> tuple[tuple[str, ...], ...]:
    """Explicit balanced groups for a runtime fleet (``names[i]`` joins
    group ``i % n_groups``) — the hash-derived buckets are balanced only
    in expectation, which a 6-node test fleet cannot rely on."""
    groups: list[list[str]] = [[] for _ in range(n_groups)]
    for i, name in enumerate(names):
        groups[i % n_groups].append(name)
    return tuple(tuple(g) for g in groups)
