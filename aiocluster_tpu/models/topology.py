"""Gossip topologies as padded adjacency tensors.

The reference's peer pool is implicit (whoever state has been learned
about); the benchmark configs (BASELINE.md) name explicit topologies —
ring-seeded, random-fanout, scale-free — so the sim takes an optional
``(N, max_degree)`` adjacency with a ``(N,)`` degree vector and samples
uniform neighbors by gather (ops/gossip.py::select_peers). ``None`` means
fully-connected random fanout, the reference's steady-state behavior.

Static shapes: adjacency rows are padded to max_degree with self-loops
(sampling a pad slot can't happen because degrees bounds the draw).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True, slots=True, eq=True)
class Heterogeneity:
    """Per-node gossip-cadence classes, WAN latency/loss classes and
    zone-aware peer bias — one hashable model lowered to BOTH backends
    (docs/faults.md "heterogeneity").

    The node-coordinate space is the fault plan's: the sim places node
    ``i`` at ``i / n``, the runtime places a node at
    ``crc32(name) / 2**32`` (faults/plan._frac_of), so classes and
    zones mean the same thing in one config that runs on both.

    - **Cadence classes**: ``class_frac`` cuts the coordinate space
      into consecutive windows (must sum to 1); a class-``k`` node
      initiates gossip every ``gossip_every[k]`` rounds. Runtime: the
      node's ticker interval is scaled by its class
      (``Cluster.effective_gossip_interval``). Sim: a symmetric
      ("matching") pair exchanges when EITHER side is on-cadence this
      tick — a quiet node still responds, as in the reference; the
      directional pairings ("permutation", "choice") gate each
      handshake by its initiator's cadence (responders always serve).
    - **WAN classes**: ``zones`` contiguous coordinate blocks; every
      cross-zone link drops each operation with probability
      ``wan_loss`` and stalls ``wan_delay`` seconds (ticks in the sim —
      a delay >= 1 tick misses the round) with probability 1. Lowered
      as derived :class:`~aiocluster_tpu.faults.plan.LinkFault` entries
      appended to the effective fault plan (``wan_link_faults``), so
      one injection machinery serves both backends.
    - **Zone bias**: with probability ``zone_bias`` a peer pick is
      drawn from the node's own zone. Runtime: biases the live-target
      sample (runtime/peers.py). Sim: requires ``pairing="choice"``
      (a global matching cannot honour per-node preference).
    """

    gossip_every: tuple[int, ...] = (1,)
    class_frac: tuple[float, ...] = (1.0,)
    zones: int = 1
    wan_delay: float = 0.0
    wan_loss: float = 0.0
    zone_bias: float = 0.0

    def __post_init__(self) -> None:
        if len(self.gossip_every) != len(self.class_frac):
            raise ValueError(
                "gossip_every and class_frac must have the same length"
            )
        if not self.gossip_every:
            raise ValueError("need at least one cadence class")
        if any(int(g) != g or g < 1 for g in self.gossip_every):
            raise ValueError("gossip_every periods must be integers >= 1")
        if any(f < 0 for f in self.class_frac):
            raise ValueError("class_frac entries must be >= 0")
        if abs(sum(self.class_frac) - 1.0) > 1e-6:
            raise ValueError("class_frac must sum to 1")
        if self.zones < 1:
            raise ValueError("zones must be >= 1")
        if self.wan_delay < 0:
            raise ValueError("wan_delay must be >= 0")
        if not 0.0 <= self.wan_loss <= 1.0:
            raise ValueError("wan_loss must be in [0, 1]")
        if not 0.0 <= self.zone_bias <= 1.0:
            raise ValueError("zone_bias must be in [0, 1]")
        if (self.wan_loss > 0 or self.wan_delay > 0) and self.zones < 2:
            raise ValueError("WAN loss/delay needs zones >= 2")

    # -- coordinate classification (shared by both backends) ------------------

    def class_of_frac(self, frac: float) -> int:
        """Cadence class of a node at coordinate ``frac`` in [0, 1)."""
        cum = 0.0
        for k, f in enumerate(self.class_frac):
            cum += f
            if frac < cum:
                return k
        return len(self.class_frac) - 1

    def zone_of_frac(self, frac: float) -> int:
        """Zone of a node at coordinate ``frac`` — floor(frac * zones),
        the same bucketing Partition uses for derived groups."""
        return min(int(frac * self.zones), self.zones - 1)

    def class_of_name(self, name: str) -> int:
        from ..faults.plan import _frac_of

        return self.class_of_frac(_frac_of(name))

    def zone_of_name(self, name: str) -> int:
        from ..faults.plan import _frac_of

        return self.zone_of_frac(_frac_of(name))

    def gossip_every_of_name(self, name: str) -> int:
        return self.gossip_every[self.class_of_name(name)]

    # -- behaviour predicates -------------------------------------------------

    def cadence_effective(self) -> bool:
        return any(g != 1 for g in self.gossip_every)

    def wan_effective(self) -> bool:
        return self.zones >= 2 and (self.wan_loss > 0 or self.wan_delay > 0)

    def effective(self) -> bool:
        """Whether this model changes ANY behaviour (the all-defaults
        instance is free: nothing is constructed or masked)."""
        return (
            self.cadence_effective()
            or self.wan_effective()
            or self.zone_bias > 0
        )

    # -- WAN lowering ---------------------------------------------------------

    def wan_link_faults(self):
        """The cross-zone degradation as directional LinkFaults over the
        zones' coordinate windows — appended to the effective fault plan
        by both backends (faults.plan.with_extra_links)."""
        from ..faults.plan import LinkFault, NodeSet

        if not self.wan_effective():
            return ()
        z = self.zones

        def window(a: int) -> NodeSet:
            return NodeSet(frac=(a / z, (a + 1) / z))

        return tuple(
            LinkFault(
                src=window(a),
                dst=window(b),
                drop=self.wan_loss,
                delay=self.wan_delay,
                delay_prob=1.0 if self.wan_delay > 0 else 0.0,
            )
            for a in range(z)
            for b in range(z)
            if a != b
        )


@dataclass(frozen=True)
class Topology:
    """Padded adjacency: node i may gossip with adjacency[i, :degrees[i]]."""

    adjacency: np.ndarray  # (N, max_degree) int32
    degrees: np.ndarray  # (N,) int32

    @property
    def n_nodes(self) -> int:
        return self.adjacency.shape[0]


def ring(n: int, neighbors_each_side: int = 1) -> Topology:
    """Ring lattice: each node sees the k nearest nodes on each side —
    BASELINE config 2's 'ring-seeded' shape."""
    offsets = np.concatenate(
        [np.arange(1, neighbors_each_side + 1), -np.arange(1, neighbors_each_side + 1)]
    )
    idx = (np.arange(n)[:, None] + offsets[None, :]) % n
    degrees = np.full(n, len(offsets), np.int32)
    return Topology(idx.astype(np.int32), degrees)


def _from_neighbor_sets(n: int, neighbors: list[set[int]]) -> Topology:
    """Pad per-node neighbor sets into the dense Topology layout
    (self-loop padding; isolated nodes get a degree-1 self edge)."""
    degrees = np.array([max(1, len(s)) for s in neighbors], np.int32)
    width = int(degrees.max())
    adjacency = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, width))
    for i, s in enumerate(neighbors):
        row = sorted(s) if s else [i]
        adjacency[i, : len(row)] = row
    return Topology(adjacency.astype(np.int32), degrees)


def scale_free(
    n: int, attach: int = 3, max_degree: int | None = None, seed: int = 0
) -> Topology:
    """Barabási–Albert preferential attachment — BASELINE config 4's
    'scale-free' shape. Degrees are capped at ``max_degree`` (default
    16*attach) to keep the padded adjacency tensor dense-friendly; the cap
    sheds only the heaviest hub edges."""
    rng = np.random.default_rng(seed)
    cap = max_degree or 16 * attach
    if cap <= attach:
        raise ValueError(f"max_degree ({cap}) must exceed attach ({attach})")
    neighbors: list[set[int]] = [set() for _ in range(n)]
    # Seed clique over the first attach+1 nodes.
    for i in range(attach + 1):
        for j in range(i + 1, attach + 1):
            neighbors[i].add(j)
            neighbors[j].add(i)
    repeated: list[int] = [i for i in range(attach + 1) for _ in neighbors[i]]
    for v in range(attach + 1, n):
        targets: set[int] = set()
        # Preferential picks, bounded; if the degree cap starves the pool
        # (every candidate saturated), fall back to uniform under-cap nodes
        # and accept fewer than ``attach`` edges rather than spinning.
        for _ in range(20 * attach):
            if len(targets) >= attach:
                break
            pick = repeated[rng.integers(len(repeated))] if repeated else int(
                rng.integers(v)
            )
            if pick != v and pick not in targets and len(neighbors[pick]) < cap:
                targets.add(pick)
        if len(targets) < attach:
            under_cap = [
                u for u in range(v)
                if u not in targets and len(neighbors[u]) < cap
            ]
            rng.shuffle(under_cap)
            targets.update(under_cap[: attach - len(targets)])
        for t in targets:
            neighbors[v].add(t)
            neighbors[t].add(v)
            repeated.extend((v, t))
    return _from_neighbor_sets(n, neighbors)


def small_world(
    n: int, neighbors_each_side: int = 2, rewire_p: float = 0.1, seed: int = 0
) -> Topology:
    """Watts–Strogatz small-world graph: a ring lattice with each edge
    rewired to a uniform random endpoint with probability ``rewire_p``.
    Interpolates between config 2's ring (p=0) and random-fanout (p=1) —
    the shape where gossip latency drops from O(N) hops to O(log N) with
    only a few long links, a useful fidelity point between the two
    BASELINE extremes."""
    if not 0.0 <= rewire_p <= 1.0:
        raise ValueError("rewire_p must be in [0, 1]")
    if neighbors_each_side < 1 or 2 * neighbors_each_side >= n:
        raise ValueError(
            "need 1 <= neighbors_each_side and 2*neighbors_each_side < n"
        )
    rng = np.random.default_rng(seed)
    neighbors: list[set[int]] = [set() for _ in range(n)]
    for k in range(1, neighbors_each_side + 1):
        for i in range(n):
            j = (i + k) % n
            if rng.random() < rewire_p:
                # Rewire i--(i+k) to i--random, avoiding self/duplicates.
                for _ in range(8):
                    cand = int(rng.integers(n))
                    if cand != i and cand not in neighbors[i]:
                        j = cand
                        break
            neighbors[i].add(j)
            neighbors[j].add(i)
    return _from_neighbor_sets(n, neighbors)


def hierarchical(
    n: int, rack_size: int = 16, uplinks_per_node: int = 1, seed: int = 0
) -> Topology:
    """Two-level datacenter shape: full connectivity inside each rack of
    ``rack_size`` nodes plus ``uplinks_per_node`` random cross-rack
    links per node. Models gossip whose fast path is rack-local (ToR
    switch) with sparse inter-rack spillover — the regime where the
    reference's seed-node re-gossip (server.py:670-682) matters most,
    because cross-partition links are scarce."""
    if rack_size < 2:
        raise ValueError("rack_size must be >= 2")
    if uplinks_per_node > 0 and n <= rack_size:
        raise ValueError("cross-rack uplinks need more than one rack")
    rng = np.random.default_rng(seed)
    neighbors: list[set[int]] = [set() for _ in range(n)]
    for i in range(n):
        rack = i // rack_size
        lo, hi = rack * rack_size, min((rack + 1) * rack_size, n)
        for j in range(lo, hi):
            if j != i:
                neighbors[i].add(j)
        for _ in range(uplinks_per_node):
            for _ in range(16):
                cand = int(rng.integers(n))
                if cand // rack_size != rack and cand not in neighbors[i]:
                    neighbors[i].add(cand)
                    neighbors[cand].add(i)
                    break
    return _from_neighbor_sets(n, neighbors)
