"""Cluster topology models for the simulator (ring, small-world,
scale-free, hierarchical racks, full)."""

from .topology import (Topology, hierarchical, ring, scale_free,
                       small_world)

__all__ = ("Topology", "hierarchical", "ring", "scale_free", "small_world")
