"""Cluster topology models for the simulator (ring, scale-free, full)."""

from .topology import Topology, ring, scale_free

__all__ = ("Topology", "ring", "scale_free")
