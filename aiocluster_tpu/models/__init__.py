"""Cluster topology models for the simulator (ring, small-world,
scale-free, hierarchical racks, full) plus the heterogeneity classes
(per-node gossip cadence, WAN latency/loss zones, zone-aware bias)
shared by both backends."""

from .topology import (Heterogeneity, Topology, hierarchical, ring,
                       scale_free, small_world)

__all__ = ("Heterogeneity", "Topology", "hierarchical", "ring",
           "scale_free", "small_world")
