"""Host-side driver for the batched gossip simulator.

Keeps SimState resident on device (optionally sharded over a mesh), steps
it in jit-compiled chunks to amortise dispatch, and polls convergence with
cheap device-scalar reads. This is the sim-backend analogue of the
runtime's Ticker + Cluster loop — except one "tick" advances all N nodes.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax import lax, random
from jax.sharding import Mesh

from ..models.topology import Topology
from ..obs.registry import MetricsRegistry
from ..obs.sim import SimMetrics
from ..obs.trace import TraceWriter
from ..ops.gossip import (
    convergence_metrics,
    sim_step,
    staleness_percentiles,
    version_spread,
)
from ..parallel.mesh import (
    shard_state,
    sharded_chunk_fn,
    sharded_metrics_fn,
    sharded_tracked_chunk_fn,
)
from .config import SimConfig
from .state import SimState, init_state


@jax.jit
def _metrics_sample(state: SimState) -> dict[str, jax.Array]:
    """convergence_metrics + version spread + the staleness-tensor
    percentiles in one fused device pass — the quantity bundle the obs
    stride sampler buffers per window."""
    out = convergence_metrics(state)
    out["version_spread"] = version_spread(state)
    out.update(staleness_percentiles(state))
    return out


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _chunk(state: SimState, key: jax.Array, cfg: SimConfig, m,
           adjacency=None, degrees=None) -> SimState:
    """``m`` is a TRACED round count: one compile serves every chunk
    length, so the partial tail chunk of a run whose round count is not
    a chunk multiple (``min(chunk, remaining)``) never retraces — the
    fori_loop lowers to the same while loop a static bound does."""
    return lax.fori_loop(
        0,
        m,
        lambda _, s: sim_step(s, key, cfg, adjacency=adjacency, degrees=degrees),
        state,
    )


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _chunk_tracked(state: SimState, key: jax.Array, cfg: SimConfig, m,
                   adjacency=None, degrees=None):
    """m rounds (traced, like _chunk) + the EXACT tick at which full
    convergence first held inside the chunk (0 = didn't). One extra
    fused read of w per round — only run_until_converged pays it;
    rate measurement (run) doesn't."""
    import jax.numpy as jnp

    def one(_, carry):
        s, first = carry
        # On the pair-fused kernel path the flag rides the round's last
        # sub-exchange (zero extra HBM traffic); elsewhere this is the
        # same separate check as before.
        s, conv = sim_step(
            s, key, cfg, adjacency=adjacency, degrees=degrees,
            return_converged=True,
        )
        first = jnp.where((first == 0) & conv, s.tick, first)
        return s, first

    return lax.fori_loop(0, m, one, (state, jnp.zeros((), jnp.int32)))


class BoundedFnCache:
    """Small LRU for compiled chunk callables.

    The traced-``m`` refactor removed the per-chunk-length cache
    dimension (one compile serves every length), but the sharded chunk
    builders are still cached per kind/topology — this bound guarantees
    that any future key growth (or a regression back to per-``m`` keys)
    cannot accumulate compiled programs without limit. Size is exported
    as the ``aiocluster_sim_chunk_cache_size`` gauge when obs is on."""

    def __init__(self, maxsize: int = 8) -> None:
        if maxsize < 1:
            raise ValueError("maxsize must be >= 1")
        from collections import OrderedDict

        self.maxsize = maxsize
        self._entries: "OrderedDict[object, object]" = OrderedDict()

    def get_or_build(self, key, build):
        fn = self._entries.get(key)
        if fn is None:
            fn = build()
            self._entries[key] = fn
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)  # evict oldest
        else:
            self._entries.move_to_end(key)
        return fn

    def __len__(self) -> int:
        return len(self._entries)


class Simulator:
    """Runs one simulated cluster to convergence (or for a fixed budget).

    ``mesh=None`` runs on the default device; passing a Mesh shards the
    owner axis across it. Both paths produce bit-identical trajectories
    for the same seed (tests/test_sim_sharded.py).
    """

    def __init__(
        self,
        cfg: SimConfig,
        *,
        seed: int = 0,
        mesh: Mesh | None = None,
        topology: Topology | None = None,
        chunk: int = 8,
        initial_versions=None,
        trace: bool = False,
        state: SimState | None = None,
        metrics: MetricsRegistry | None = None,
        metrics_stride: int = 64,
        trace_writer: TraceWriter | None = None,
    ) -> None:
        if topology is not None and topology.n_nodes != cfg.n_nodes:
            raise ValueError("topology size != cfg.n_nodes")
        if topology is not None and cfg.version_dtype == "u4r":
            raise ValueError(
                "version_dtype='u4r' does not support topology runs "
                "(the adjacency path's scatter-max is unpacked-only)"
            )
        if (
            topology is not None
            and cfg.heterogeneity is not None
            and cfg.heterogeneity.zone_bias > 0
        ):
            raise ValueError(
                "zone_bias does not support topology runs (the "
                "adjacency draw carries no zone bias; refusing beats "
                "silently sampling unbiased)"
            )
        from ..ops.gossip import resolve_variant_env

        # Fold the AIOCLUSTER_TPU_PALLAS_VARIANT override into the config
        # HERE so the resolved variant is part of the jit static argument
        # (= the compile cache key): flipping the env var mid-process can
        # then never reuse a stale compiled variant while provenance
        # reports the new one (ADVICE r3). Consumers reading provenance
        # must read ``sim.cfg``, not the cfg they passed in.
        self.cfg = cfg = resolve_variant_env(cfg)
        self.chunk = chunk
        self.seed = seed
        self._key = random.key(seed)
        self._adj = (
            None if topology is None else jax.numpy.asarray(topology.adjacency)
        )
        self._deg = (
            None if topology is None else jax.numpy.asarray(topology.degrees)
        )
        # Opt-in per-chunk observability (the sim analogue of the
        # runtime's HookStats/snapshot counters, reference
        # server.py:50-56,168-175): each entry is one sampled round.
        self._trace_enabled = trace
        self.trace: list[dict[str, float]] = []
        # A provided state (checkpoint resume) skips init_state so peak
        # memory stays at one state's worth, not two.
        self.state: SimState = (
            state if state is not None else init_state(cfg, initial_versions)
        )
        # Compact-dtype horizon guard (host arithmetic only — run() must
        # never add a device sync to the hot loop): record the largest
        # version and the tick once, at construction, where a sync is
        # free. _host_tick advances with each run(); _version_base_tick
        # stays frozen so the growth bound charges writes_per_round only
        # for ticks run SINCE the recorded max (a resumed checkpoint's
        # max already contains its past writes). Host-side writers
        # (SimCluster) report direct version bumps via
        # note_max_version_increase.
        self._known_max_version = int(np.asarray(self.state.max_version).max())
        self._host_tick = int(np.asarray(self.state.tick))
        self._version_base_tick = self._host_tick
        # Unified telemetry (obs/): a stride sampler that buffers DEVICE
        # scalars at chunk boundaries and converts only on
        # flush_metrics() — the jit'd hot loop never syncs for metrics.
        # Enabled by passing a registry and/or a JSONL trace writer.
        # start_tick anchors the rounds counter for resumed checkpoints.
        self._obs: SimMetrics | None = None
        if metrics is not None or trace_writer is not None:
            self._obs = SimMetrics(
                metrics, trace_writer, stride=metrics_stride, engine="xla",
                start_tick=self._host_tick,
                writes_per_round=cfg.writes_per_round,
            )
            # Memory-ladder provenance gauge: the rung's planned
            # resident bytes (host arithmetic; docs/observability.md).
            from .memory import plan as _mem_plan

            self._obs.set_state_bytes(
                _mem_plan(cfg, 1 if mesh is None else mesh.size).state_bytes
            )
        # select_peers' churn-free 'choice' fast path samples uniformly
        # over ALL nodes (the alive mask is statically all-true for
        # states this config family produces). A provided state carrying
        # dead nodes — e.g. a checkpoint from a churn run — would be
        # silently mis-sampled; refuse it here, where alive is concrete
        # and the check is free. peer_mode='view' samples from live_view
        # instead of the alive mask, so view-mode resumes with dead nodes
        # are legitimate and pass (the guard matches EXACTLY the
        # select_peers fast path it protects).
        if (
            state is not None
            and cfg.pairing == "choice"
            and cfg.peer_mode == "alive"
            and cfg.death_rate == 0.0
            and cfg.revival_rate == 0.0
            and not bool(np.asarray(self.state.alive).all())
        ):
            raise ValueError(
                "churn-free 'choice' config resumed with dead nodes in "
                "state.alive — peer sampling would ignore them; run this "
                "state under a config with churn enabled"
            )
        self._mesh = mesh
        if mesh is not None:
            self.state = shard_state(self.state, mesh)
            # Bounded (was: unbounded dicts keyed per chunk length —
            # every distinct tail length compiled and retained a fresh
            # program; chunk lengths are traced operands now, so the
            # cache holds one entry per kind).
            self._chunk_fns = BoundedFnCache(maxsize=8)
            self._sharded_metrics = sharded_metrics_fn(mesh)

    def _note_chunk_cache(self) -> None:
        if self._obs is not None:
            self._obs.set_chunk_cache_size(len(self._chunk_fns))

    def _sharded_chunk(self):
        """shard_map'd traced-m chunk (one compile per cfg)."""
        fn = self._chunk_fns.get_or_build(
            ("chunk", self._adj is not None),
            lambda: sharded_chunk_fn(
                self.cfg, self._mesh, topology=self._adj is not None
            ),
        )
        self._note_chunk_cache()
        return fn

    def _sharded_tracked_chunk(self):
        """Convergence-tracking variant (also traced-m)."""
        fn = self._chunk_fns.get_or_build(
            ("tracked", self._adj is not None),
            lambda: sharded_tracked_chunk_fn(
                self.cfg, self._mesh, topology=self._adj is not None
            ),
        )
        self._note_chunk_cache()
        return fn

    # -- stepping -------------------------------------------------------------

    def note_max_version_increase(self, delta: int) -> None:
        """Host-side writers that raise ``max_version`` directly on the
        state (SimCluster's write flush) report the largest per-node
        bump here so the int16 horizon guard stays sound. Direct state
        surgery that bypasses this is outside the guard's coverage."""
        self._known_max_version += int(delta)

    def _check_horizon(self, rounds: int) -> None:
        """Raise before a narrow rung silently wraps: heartbeats store
        the tick (horizon < the rung's limit — 2^15 int16, 2^7 int8),
        and narrow watermarks store versions (known max +
        writes_per_round per tick run < the rung's limit; the packed
        u4 residual rung bounds max_version itself at 15, since a
        never-contacted observer's residual equals it). Host-side
        arithmetic from construction-time facts (the dtype knobs are
        validated literal strings) — zero device traffic, so timing
        loops see no sync. Limits live in sim/state.py next to
        init_state's initial-version checks, so a new rung extends one
        table."""
        from .state import HEARTBEAT_LIMITS, VERSION_LIMITS

        end_tick = self._host_tick + rounds
        hb_limit = HEARTBEAT_LIMITS[self.cfg.heartbeat_dtype]
        if (
            self.cfg.track_heartbeats
            and hb_limit < 2**31
            and end_tick >= hb_limit
        ):
            raise ValueError(
                f"running to tick {end_tick} overflows "
                f"{self.cfg.heartbeat_dtype} heartbeats (heartbeat_dtype "
                f"stores the tick; horizons >= {hb_limit} rounds need a "
                "wider rung)"
            )
        v_limit = VERSION_LIMITS[self.cfg.version_dtype]
        if v_limit < 2**31:
            bound = self._known_max_version + self.cfg.writes_per_round * (
                end_tick - self._version_base_tick
            )
            if bound >= v_limit:
                raise ValueError(
                    f"versions may reach {bound} by tick {end_tick}, "
                    f"overflowing version_dtype='{self.cfg.version_dtype}' "
                    f"(limit {v_limit}; lower writes_per_round/horizon or "
                    "use a wider rung)"
                )

    def run(self, rounds: int) -> None:
        """Advance a fixed number of gossip rounds."""
        self._check_horizon(rounds)
        done = 0
        while done < rounds:
            m = min(self.chunk, rounds - done)
            if self._mesh is not None:
                if self._adj is not None:
                    self.state = self._sharded_chunk()(
                        self.state, self._key, m, self._adj, self._deg
                    )
                else:
                    self.state = self._sharded_chunk()(self.state, self._key, m)
            else:
                self.state = _chunk(
                    self.state, self._key, self.cfg, m, self._adj, self._deg
                )
            done += m
            self._host_tick += m
            self._maybe_sample()
            if self._trace_enabled:
                self._record_trace()

    def run_until_converged(self, max_rounds: int = 100_000) -> int | None:
        """Step until every alive node holds every alive owner's full
        keyspace; returns the EXACT first round at which that held (the
        check runs inside the chunk every round, so the count is
        invariant to ``chunk``), or None if max_rounds elapsed."""
        if bool(self.metrics()["all_converged"]):
            return int(self.state.tick)  # converged before any stepping
        # The two int() polls below sync once per CHUNK, not per round —
        # that amortisation is the point of chunked stepping (PR 1's
        # device-scalar buffering handles the per-round metrics instead).
        while int(self.state.tick) < max_rounds:  # noqa: ACT021 -- chunk-boundary poll, amortised over `chunk` rounds
            m = min(self.chunk, max_rounds - int(self.state.tick))  # noqa: ACT021 -- same chunk-boundary sync as the loop test
            self._check_horizon(m)
            if self._mesh is not None:
                args = (
                    (self.state, self._key, m, self._adj, self._deg)
                    if self._adj is not None
                    else (self.state, self._key, m)
                )
                self.state, first = self._sharded_tracked_chunk()(*args)
            else:
                self.state, first = _chunk_tracked(
                    self.state, self._key, self.cfg, m, self._adj, self._deg
                )
            self._host_tick += m
            self._maybe_sample()
            if self._trace_enabled:
                self._record_trace()
            first = int(first)  # noqa: ACT021 -- the convergence answer itself; one sync per chunk
            if first:
                return first
        return None

    # -- observation ----------------------------------------------------------

    def _sample_now(self) -> None:
        """Device-side metric sample (no host sync): the dispatch queues
        a small fused reduction; conversion waits for flush_metrics()."""
        if self._mesh is not None:
            sample = self._sharded_metrics(self.state)
        else:
            sample = _metrics_sample(self.state)
        self._obs.record(self._host_tick, sample)

    def _maybe_sample(self) -> None:
        if self._obs is not None and self._obs.due(self._host_tick):
            self._sample_now()

    def flush_metrics(self) -> list[dict]:
        """Convert buffered metric samples (one device sync), update the
        registry gauges, emit trace events; returns the sampled series.
        No-op (empty list) when obs was not enabled."""
        if self._obs is None:
            return []
        # Close the series at the run's final state: a run whose last
        # rounds fell inside one stride window would otherwise end its
        # series (and leave the gauges) strides short of convergence.
        if self._obs.last_tick != self._host_tick:
            self._sample_now()
        return self._obs.flush()

    def _record_trace(self) -> None:
        m = self.metrics()
        self.trace.append(
            {
                "tick": float(self.tick),
                "converged_owners": float(m["converged_owners"]),
                "min_fraction": float(m["min_fraction"]),
                "mean_fraction": float(m["mean_fraction"]),
                "alive_count": float(m["alive_count"]),
            }
        )

    def metrics(self) -> dict[str, np.ndarray]:
        if self._mesh is not None:
            m = self._sharded_metrics(self.state)
        else:
            m = _metrics_sample(self.state)
        return {k: np.asarray(v) for k, v in m.items()}

    @property
    def tick(self) -> int:
        return int(self.state.tick)

    # -- checkpoint / resume ---------------------------------------------------

    def save(self, path) -> None:
        """Checkpoint the full device state (gathers to host first), plus
        the seed and topology flag needed to continue the trajectory."""
        from .checkpoint import save_state

        save_state(
            path,
            jax.device_get(self.state),
            self.cfg,
            seed=self.seed,
            has_topology=self._adj is not None,
        )

    @classmethod
    def resume(
        cls,
        path,
        *,
        seed: int | None = None,
        mesh: Mesh | None = None,
        topology: Topology | None = None,
        chunk: int = 8,
        trace: bool = False,
    ) -> "Simulator":
        """Continue a checkpointed run — on any device layout, since the
        kernel's randomness depends only on (seed, tick). The original
        seed is stored in the checkpoint and used unless overridden."""
        from .checkpoint import load_state

        state, cfg, meta = load_state(path)
        if meta["has_topology"] and topology is None:
            raise ValueError(
                "checkpoint was taken with a topology; pass the same "
                "topology to resume (adjacency is not persisted)"
            )
        return cls(
            cfg,
            seed=meta["seed"] if seed is None else seed,
            mesh=mesh,
            topology=topology,
            chunk=chunk,
            trace=trace,
            state=state,  # __init__ shards it when mesh is not None
        )
