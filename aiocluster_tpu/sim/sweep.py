"""Multi-scenario sweeps: vmap-batched simulation over a lane axis.

One process used to simulate exactly one (seed, config, fault-plan)
tuple and pay the full XLA compile for it. The FaultPlan library and
the tuning workloads (phi-threshold sweeps, seed ensembles, fault-plan
sensitivity) want dozens of variants at once — so ``SweepSimulator``
adds a LANE axis: ``sim_step`` is vmapped over a leading scenario
dimension of S lanes, where each lane gets

- its own PRNG seed (``random.key(seed)`` per lane — exactly the key a
  sequential ``Simulator(cfg, seed=...)`` would use),
- its own fault-plan salt (the link-fault draws depend on the plan only
  through ``plan.seed``, so a traced per-lane seed reproduces
  ``replace(plan, seed=...)`` bit-for-bit — faults/sim.py), and
- its own values for the declared sweepable scalars — ``fanout``,
  ``phi_threshold``, ``writes_per_round`` — lifted from static config
  fields to per-lane traced operands (``SweepParams``, ops/gossip.py).

One jit compile therefore serves all S scenarios. Per-lane convergence
flags accumulate ON DEVICE (the ``first`` tick array rides the chunk
carry), so lanes retire without per-chunk host syncs; the host polls a
single all-lanes-done scalar per chunk, exactly like the sequential
driver's chunk-boundary poll. Results come back as a ``SweepResult``
table: per-lane rounds-to-convergence, version spread, and final
convergence metrics.

Sweeps compose with the ``owners`` shard axis (parallel/mesh.py): lane
x owner-sharded matrices are (S, N, n_local) with lanes and rows
unsharded, and every collective becomes one batched (S,)-wide dispatch.

Bit-identity contract (tests/test_sweep.py, tests/test_fused_kernel.py):
an S-lane sweep is bit-identical to S sequential single-sim runs with
the same seeds and the lane's values applied as static config fields —
unsharded and under a mesh. Sweep steps engage the fused Pallas path
whenever the pairs variant serves the shape: the pairs kernels carry a
lane grid axis (ops/pallas_pull.py custom_vmap dispatch lifts the
vmapped call onto it, per-lane scalars riding scalar prefetch), so the
multi-scenario path — the one an operator actually runs — is no longer
pinned to the slowest backend. Off the pairs domain sweeps run plain
XLA; either way every lane matches the equivalent sequential run
bit-for-bit because the kernels are bit-identical to XLA by
construction.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax import lax, random
from jax.sharding import Mesh

from ..obs.registry import MetricsRegistry
from ..obs.sim import SweepMetrics
from ..ops.gossip import resolve_variant_env, sim_step
from ..parallel.mesh import (
    shard_sweep_state,
    sharded_sweep_chunk_fn,
    sharded_sweep_metrics_fn,
)
from .config import SimConfig
from .simulator import BoundedFnCache, _metrics_sample
from .state import SimState, SweepParams, init_state


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _sweep_chunk(states, keys, sweep, cfg: SimConfig, m):
    """m rounds for every lane (m traced — one compile per cfg)."""

    def one_lane(state, key, sw):
        return lax.fori_loop(
            0, m, lambda _, s: sim_step(s, key, cfg, sweep=sw), state
        )

    return jax.vmap(one_lane)(states, keys, sweep)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _sweep_chunk_tracked(states, keys, sweep, first, cfg: SimConfig, m):
    """m rounds per lane + the per-lane first-converged tick accumulator
    (0 = not yet), carried across chunks on device."""
    import jax.numpy as jnp

    def one_lane(state, key, sw, f0):
        def one(_, carry):
            s, f = carry
            s, conv = sim_step(s, key, cfg, sweep=sw, return_converged=True)
            f = jnp.where((f == 0) & conv, s.tick, f)
            return s, f

        return lax.fori_loop(0, m, one, (state, f0))

    return jax.vmap(one_lane)(states, keys, sweep, first)


@jax.jit
def _sweep_metrics(states):
    return jax.vmap(_metrics_sample)(states)


class SweepResult:
    """Per-lane results table of one sweep (plain host data)."""

    def __init__(
        self,
        *,
        seeds: list[int],
        params: dict[str, list],
        rounds_to_convergence: list[int | None],
        metrics: dict[str, np.ndarray],
    ) -> None:
        self.lanes = len(seeds)
        self.seeds = list(seeds)
        self.params = {k: list(v) for k, v in params.items()}
        self.rounds_to_convergence = list(rounds_to_convergence)
        self.version_spread = np.asarray(metrics["version_spread"]).tolist()
        self.converged_owners = np.asarray(metrics["converged_owners"]).tolist()
        self.mean_fraction = np.asarray(metrics["mean_fraction"]).tolist()
        self.min_fraction = np.asarray(metrics["min_fraction"]).tolist()
        self.alive_count = np.asarray(metrics["alive_count"]).tolist()
        # FD liveness quality (present only when the config tracks the
        # failure detector) — the byzantine atlas's second axis.
        fp = metrics.get("fd_false_positive_fraction")
        self.fd_false_positive_fraction = (
            None if fp is None else np.asarray(fp).tolist()
        )

    def rows(self) -> list[dict]:
        """One dict per lane — the table the bench/CLI prints."""
        out = []
        for lane in range(self.lanes):
            row = {
                "lane": lane,
                "seed": self.seeds[lane],
                "rounds_to_convergence": self.rounds_to_convergence[lane],
                "version_spread": self.version_spread[lane],
                "converged_owners": self.converged_owners[lane],
                "mean_fraction": self.mean_fraction[lane],
                "min_fraction": self.min_fraction[lane],
                "alive_count": self.alive_count[lane],
            }
            if self.fd_false_positive_fraction is not None:
                row["fd_false_positive_fraction"] = (
                    self.fd_false_positive_fraction[lane]
                )
            for name, values in self.params.items():
                row[name] = values[lane]
            out.append(row)
        return out

    def summary(self) -> dict:
        conv = [r for r in self.rounds_to_convergence if r]
        return {
            "lanes": self.lanes,
            "lanes_converged": len(conv),
            "rounds_to_convergence_min": min(conv) if conv else None,
            "rounds_to_convergence_max": max(conv) if conv else None,
            "swept": sorted(self.params),
        }

    # -- objective evaluation --------------------------------------------------

    def evaluate(self, objective) -> list:
        """Score every lane: ``objective(row) -> float | None`` over the
        per-lane rows (None = lane infeasible under the objective). The
        generic entry point SLO-style consumers — above all the twin's
        autotuner (twin/autotune.py, docs/twin.md) — run over ONE
        sweep's evidence table instead of re-simulating per candidate."""
        return [objective(row) for row in self.rows()]

    def best_lane(self, objective) -> tuple[int, float] | None:
        """The feasible lane minimizing ``objective`` as
        ``(lane, score)``, or None when every lane is infeasible. Ties
        break toward the LOWER lane index, so callers order their
        candidate grids cheapest-first and get the cheapest winner."""
        best: tuple[int, float] | None = None
        for lane, score in enumerate(self.evaluate(objective)):
            if score is None:
                continue
            if best is None or score < best[1]:
                best = (lane, float(score))
        return best


class SweepSimulator:
    """Runs S simulated scenarios under ONE compiled step.

    ``seeds`` declares the lanes (one per seed). The keyword lists —
    ``fanout``, ``phi_threshold``, ``writes_per_round``, ``fault_seeds``
    — are optional per-lane values for the sweepable scalars; each must
    be length S when given. ``mesh`` composes lanes with the owner shard
    axis. The per-lane trajectory is bit-identical to
    ``Simulator(replace(cfg, <lane values>), seed=seeds[lane])``.
    """

    def __init__(
        self,
        cfg: SimConfig,
        seeds,
        *,
        fanout=None,
        phi_threshold=None,
        writes_per_round=None,
        fault_seeds=None,
        byz_frac=None,
        mesh: Mesh | None = None,
        chunk: int = 8,
        initial_versions=None,
        states: SimState | None = None,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        import jax.numpy as jnp

        self.cfg = cfg = resolve_variant_env(cfg)
        self.chunk = chunk
        self.seeds = [int(s) for s in seeds]
        lanes = len(self.seeds)
        if lanes < 1:
            raise ValueError("need at least one sweep lane (seed)")
        if any(not (0 <= s < 2**32) for s in self.seeds):
            # Lane keys are built from a uint32 array so they equal
            # random.key(seed) exactly; 64-bit seeds would seed the
            # upper key word differently.
            raise ValueError("sweep seeds must be in [0, 2**32)")

        def lane_list(name, values, lo=None, hi=None):
            if values is None:
                return None
            values = list(values)
            if len(values) != lanes:
                raise ValueError(
                    f"{name} must have one value per lane "
                    f"({len(values)} != {lanes})"
                )
            if lo is not None and any(v < lo for v in values):
                raise ValueError(f"{name} values must be >= {lo}")
            if hi is not None and any(v > hi for v in values):
                raise ValueError(f"{name} values must be <= {hi}")
            return values

        # cfg.fanout is the STATIC sub-exchange bound; lanes at a lower
        # value mask their excess sub-exchanges to no-ops (gossip.py).
        fanout = lane_list("fanout", fanout, lo=0, hi=cfg.fanout)
        if fanout is not None and cfg.pairing == "choice":
            raise ValueError(
                "fanout sweeps require pairing='matching' or "
                "'permutation' (sim_step's contract)"
            )
        phi_threshold = lane_list("phi_threshold", phi_threshold)
        if phi_threshold is not None and not cfg.track_failure_detector:
            raise ValueError("phi_threshold sweep requires the failure detector")
        writes_per_round = lane_list("writes_per_round", writes_per_round, lo=0)
        fault_seeds = lane_list("fault_seeds", fault_seeds)
        if fault_seeds is not None and cfg.fault_plan is None:
            raise ValueError("fault_seeds sweep requires cfg.fault_plan")
        byz_frac = lane_list("byz_frac", byz_frac, lo=0.0, hi=1.0)
        if byz_frac is not None and not (
            cfg.fault_plan is not None and cfg.fault_plan.byzantine
        ):
            raise ValueError(
                "byz_frac sweep requires a cfg.fault_plan with byzantine "
                "entries (the lane value overrides their attacker windows)"
            )

        self.params: dict[str, list] = {}
        for name, values in (
            ("fanout", fanout),
            ("phi_threshold", phi_threshold),
            ("writes_per_round", writes_per_round),
            ("fault_seeds", fault_seeds),
            ("byz_frac", byz_frac),
        ):
            if values is not None:
                self.params[name] = values
        self._sweep = SweepParams(
            fanout=None if fanout is None else jnp.asarray(fanout, jnp.int32),
            phi_threshold=(
                None
                if phi_threshold is None
                else jnp.asarray(phi_threshold, jnp.float32)
            ),
            writes_per_round=(
                None
                if writes_per_round is None
                else jnp.asarray(writes_per_round, jnp.int32)
            ),
            fault_seed=(
                None
                if fault_seeds is None
                else jnp.asarray(
                    [int(s) & 0xFFFFFFFF for s in fault_seeds], jnp.uint32
                )
            ),
            byz_frac=(
                None
                if byz_frac is None
                else jnp.asarray(byz_frac, jnp.float32)
            ),
        )
        # Horizon guard facts (host arithmetic only, like Simulator's):
        # the version bound must charge the FASTEST-writing lane.
        self._max_wpr = (
            max(writes_per_round)
            if writes_per_round is not None
            else cfg.writes_per_round
        )
        # Lane keys: exactly random.key(seed) per lane (vmapped over a
        # uint32 seed vector — bitwise equal to the scalar construction,
        # so lane randomness matches the sequential Simulator's).
        self._keys = jax.vmap(random.key)(
            jnp.asarray(self.seeds, jnp.uint32)
        )
        self._host_tick = 0
        self._version_base_tick = 0
        if states is not None:
            # A provided lane-batched state (checkpoint resume) skips
            # the fresh broadcast entirely — peak memory stays at one
            # sweep's worth, not two.
            if np.shape(states.w)[0] != lanes:
                raise ValueError(
                    f"provided states carry {np.shape(states.w)[0]} "
                    f"lanes, expected {lanes}"
                )
            self.states = states
        else:
            base = init_state(cfg, initial_versions)
            # All lanes start from the same fresh state: materialize
            # the broadcast so the buffers are real (donation rewrites
            # them).
            self.states = jax.tree.map(
                lambda x: jnp.array(
                    jnp.broadcast_to(x[None, ...], (lanes,) + x.shape)
                ),
                base,
            )
        self._known_max_version = int(np.asarray(self.states.max_version).max())
        self._first = jnp.zeros((lanes,), jnp.int32)
        self._mesh = mesh
        self._obs = SweepMetrics(metrics) if metrics is not None else None
        if mesh is not None:
            self.states = shard_sweep_state(self.states, mesh)
            self._chunk_fns = BoundedFnCache(maxsize=4)
            self._sharded_metrics = sharded_sweep_metrics_fn(mesh)

    @property
    def lanes(self) -> int:
        return len(self.seeds)

    # -- stepping -------------------------------------------------------------

    def _check_horizon(self, rounds: int) -> None:
        """Simulator._check_horizon with the sweep's worst-lane write
        rate (host-side arithmetic; no device traffic). Same per-rung
        limit tables (sim/state.py), so new rungs extend one place."""
        from .state import HEARTBEAT_LIMITS, VERSION_LIMITS

        end_tick = self._host_tick + rounds
        cfg = self.cfg
        hb_limit = HEARTBEAT_LIMITS[cfg.heartbeat_dtype]
        if cfg.track_heartbeats and hb_limit < 2**31 and end_tick >= hb_limit:
            raise ValueError(
                f"running to tick {end_tick} overflows "
                f"{cfg.heartbeat_dtype} heartbeats"
            )
        v_limit = VERSION_LIMITS[cfg.version_dtype]
        if v_limit < 2**31:
            bound = self._known_max_version + self._max_wpr * (
                end_tick - self._version_base_tick
            )
            if bound >= v_limit:
                raise ValueError(
                    f"versions may reach {bound} by tick {end_tick}, "
                    f"overflowing version_dtype='{cfg.version_dtype}' "
                    f"(limit {v_limit})"
                )

    def _sharded_chunk(self, tracked: bool):
        return self._chunk_fns.get_or_build(
            ("sweep-tracked" if tracked else "sweep",),
            lambda: sharded_sweep_chunk_fn(
                self.cfg, self._mesh, tracked=tracked
            ),
        )

    def run(self, rounds: int) -> None:
        """Advance every lane by a fixed number of gossip rounds."""
        self._check_horizon(rounds)
        done = 0
        while done < rounds:
            m = min(self.chunk, rounds - done)
            if self._mesh is not None:
                self.states = self._sharded_chunk(False)(
                    self.states, self._keys, self._sweep, m
                )
            else:
                self.states = _sweep_chunk(
                    self.states, self._keys, self._sweep, self.cfg, m
                )
            done += m
            self._host_tick += m

    def run_until_converged(self, max_rounds: int = 100_000) -> list[int | None]:
        """Step all lanes until each has held full convergence once (or
        ``max_rounds`` elapsed); returns the per-lane EXACT first
        converged round (None = lane never converged). The flags
        accumulate on device — the host reads ONE scalar per chunk (the
        all-lanes-retired test), the same amortized chunk-boundary poll
        the sequential driver makes."""
        import jax.numpy as jnp

        # Entry check mirrors Simulator's converged-before-stepping
        # answer: a lane already converged records the CURRENT tick (a
        # tick-0 pre-convergence needs keys_per_node == 0, where the 0
        # sentinel is ambiguous — no real config hits that).
        conv0 = np.asarray(self.metrics()["all_converged"])
        if conv0.any():
            first = np.asarray(self._first).copy()
            mask = (first == 0) & conv0
            if mask.any():
                first[mask] = self._host_tick
                self._first = jnp.asarray(first, jnp.int32)
        while self._host_tick < max_rounds:
            if bool(np.asarray((self._first != 0).all())):  # noqa: ACT021 -- one scalar per chunk, the amortized retirement poll
                break
            m = min(self.chunk, max_rounds - self._host_tick)
            self._check_horizon(m)
            if self._mesh is not None:
                self.states, self._first = self._sharded_chunk(True)(
                    self.states, self._keys, self._sweep, self._first, m
                )
            else:
                self.states, self._first = _sweep_chunk_tracked(
                    self.states,
                    self._keys,
                    self._sweep,
                    self._first,
                    self.cfg,
                    m,
                )
            self._host_tick += m
        first = np.asarray(self._first)
        out = [int(f) if f else None for f in first.tolist()]
        if self._obs is not None:
            self._obs.update(out)
        return out

    # -- observation ----------------------------------------------------------

    def metrics(self) -> dict[str, np.ndarray]:
        """Per-lane convergence metrics: dict of (S,) host arrays (one
        sync for the whole bundle)."""
        if self._mesh is not None:
            m = self._sharded_metrics(self.states)
        else:
            m = _sweep_metrics(self.states)
        return {k: np.asarray(v) for k, v in m.items()}

    def result(self) -> SweepResult:
        """The per-lane results table at the current state (one metrics
        sync; rounds-to-convergence reflects what run_until_converged
        has observed so far)."""
        first = np.asarray(self._first)
        rounds = [int(f) if f else None for f in first.tolist()]
        metrics = self.metrics()
        if self._obs is not None:
            self._obs.update(rounds, metrics["version_spread"])
        return SweepResult(
            seeds=self.seeds,
            params=self.params,
            rounds_to_convergence=rounds,
            metrics=metrics,
        )

    @property
    def tick(self) -> int:
        return self._host_tick

    # -- checkpoint / resume ---------------------------------------------------

    def save(self, path) -> None:
        """Checkpoint all lanes (gathers to host), plus seeds, sweep
        values and the convergence accumulator."""
        from .checkpoint import save_sweep

        save_sweep(
            path,
            jax.device_get(self.states),
            self.cfg,
            seeds=self.seeds,
            params=self.params,
            first=np.asarray(self._first),
            host_tick=self._host_tick,
        )

    @classmethod
    def resume(
        cls,
        path,
        *,
        mesh: Mesh | None = None,
        chunk: int = 8,
        metrics: MetricsRegistry | None = None,
    ) -> "SweepSimulator":
        """Continue a checkpointed sweep on any device layout (lane
        randomness is keyed by (seed, tick), exactly like the
        single-scenario resume)."""
        import jax.numpy as jnp

        from .checkpoint import load_sweep

        states, cfg, meta = load_sweep(path)
        params = meta["params"]
        sim = cls(
            cfg,
            meta["seeds"],
            fanout=params.get("fanout"),
            phi_threshold=params.get("phi_threshold"),
            writes_per_round=params.get("writes_per_round"),
            fault_seeds=params.get("fault_seeds"),
            byz_frac=params.get("byz_frac"),
            mesh=mesh,
            chunk=chunk,
            states=states,  # __init__ skips the fresh broadcast
            metrics=metrics,  # (and shards the provided states on a mesh)
        )
        sim._first = jnp.asarray(meta["first"], jnp.int32)
        sim._host_tick = int(meta["host_tick"])
        # The resumed guard charges writes only for ticks run SINCE the
        # checkpoint: the checkpointed max_version already contains its
        # past writes (same contract as Simulator's version_base_tick).
        sim._version_base_tick = sim._host_tick
        return sim
