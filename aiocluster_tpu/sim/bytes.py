"""Bytes-budget mode: tie the sim's key-version budget to the real MTU.

The tensor sim bounds each exchange by ``SimConfig.budget`` key-versions —
an abstraction of the object model's byte-exact MTU packer (reference
state.py:392-398, our core/cluster_state.py). This module closes the loop:
``budget_from_mtu`` converts a wire MTU (e.g. the reference's 65,507-byte
``max_payload_size``, entities.py:105) into the equivalent key-version
budget using the SAME exact proto3 size accounting the asyncio backend
packs with (wire/sizes.DeltaSizeModel), so sim rounds-to-convergence is
directly comparable to a socket-backend run at a given MTU
(tests/test_sim.py::test_sim_matches_object_model_at_matched_mtu).

The conversion needs a representative workload shape — key/value byte
lengths and how many stale owners a delta typically spans — because the
real packer's overhead is per-node-delta while the kv cost is per
key-version. Defaults mirror the bench workload.
"""

from __future__ import annotations

from ..core.identity import NodeId
from ..core.messages import KeyValueUpdate, VersionStatusEnum
from ..wire.sizes import DeltaSizeModel

__all__ = ("budget_from_mtu",)


def budget_from_mtu(
    mtu_bytes: int,
    *,
    key_bytes: int = 8,
    value_bytes: int = 8,
    stale_owners: int = 1,
    node_name_bytes: int = 8,
    version_scale: int = 1000,
) -> int:
    """Key-versions that fit one ``mtu_bytes`` delta for this workload.

    ``stale_owners`` is how many distinct owners' updates share the delta
    (each adds one NodeDelta envelope); ``version_scale`` sets the varint
    width of representative version numbers. Raises if not even one
    key-version fits (the packer would make no progress at that MTU — the
    object model's pathological-MTU case, reference state.py:412-413).
    """
    if mtu_bytes <= 0:
        raise ValueError("mtu_bytes must be positive")
    node = NodeId("n" * node_name_bytes, version_scale, ("h" * 9, 65_000))
    kv = KeyValueUpdate(
        key="k" * key_bytes,
        value="v" * value_bytes,
        version=version_scale,
        status=VersionStatusEnum.SET,
    )
    model = DeltaSizeModel()
    base = model.node_delta_base(
        node,
        from_version_excluded=version_scale,
        last_gc_version=0,
        max_version=version_scale,
    )
    kv_inc = model.kv_increment(kv)
    # Total delta = committed node-deltas; reserve each owner's envelope
    # (base + length framing) via the same accounting the packer uses.
    envelope = model.delta_total_with(base) - model.total()
    overhead = stale_owners * envelope
    budget = (mtu_bytes - overhead) // kv_inc
    if budget < 1:
        raise ValueError(
            f"mtu_bytes={mtu_bytes} cannot carry one key-version "
            f"(overhead {overhead}B + {kv_inc}B per key-version)"
        )
    return int(budget)
