"""Bytes models: the MTU <-> key-version budget bridge and the
per-round HBM-traffic model behind the bench roofline.

The tensor sim bounds each exchange by ``SimConfig.budget`` key-versions —
an abstraction of the object model's byte-exact MTU packer (reference
state.py:392-398, our core/cluster_state.py). This module closes the loop:
``budget_from_mtu`` converts a wire MTU (e.g. the reference's 65,507-byte
``max_payload_size``, entities.py:105) into the equivalent key-version
budget using the SAME exact proto3 size accounting the asyncio backend
packs with (wire/sizes.DeltaSizeModel), so sim rounds-to-convergence is
directly comparable to a socket-backend run at a given MTU
(tests/test_sim.py::test_sim_matches_object_model_at_matched_mtu).

The conversion needs a representative workload shape — key/value byte
lengths and how many stale owners a delta typically spans — because the
real packer's overhead is per-node-delta while the kv cost is per
key-version. Defaults mirror the bench workload.
"""

from __future__ import annotations

from ..core.identity import NodeId
from ..core.messages import KeyValueUpdate, VersionStatusEnum
from ..wire.sizes import DeltaSizeModel

__all__ = (
    "budget_from_mtu",
    "ladder",
    "per_round_bytes",
    "roofline_models",
    "state_bytes_per_pair",
)


# -- the memory ladder: resident bytes per (observer, owner) pair -------------
#
# Storage width per rung of each SimState matrix. Fractional entries are
# the packed forms (sim/packed.py): "u4r" stores two saturating
# watermark residuals per byte; live_bits stores eight liveness bits per
# byte. THE single per-pair accounting — sim/memory.py's plan() and the
# docs/sim.md ladder table both read it, so a new rung changes one dict.

W_BYTES = {"int32": 4.0, "int16": 2.0, "int8": 1.0, "u4r": 0.5}
HB_BYTES = {"int32": 4.0, "int16": 2.0, "int8": 1.0}
FD_BYTES = {"float32": 4.0, "bfloat16": 2.0}
ICOUNT_BYTES = {"int16": 2.0, "int8": 1.0}


def state_bytes_per_pair(cfg) -> float:
    """Resident SimState bytes per (observer, owner) pair for this
    config's rung — the ladder's figure of merit (may be fractional for
    the packed forms; multiply by N^2 and round for totals)."""
    b = W_BYTES[cfg.version_dtype]
    if cfg.track_heartbeats:
        b += HB_BYTES[cfg.heartbeat_dtype]  # hb_known
    if cfg.track_failure_detector:
        b += HB_BYTES[cfg.heartbeat_dtype]  # last_change
        b += FD_BYTES[cfg.fd_dtype]  # imean
        b += ICOUNT_BYTES[cfg.icount_dtype]  # icount
        b += 0.125 if cfg.live_bits else 1.0  # live_view
        if cfg.dead_grace_ticks is not None:
            b += HB_BYTES[cfg.heartbeat_dtype]  # dead_since
    return b


def ladder(n_nodes: int = 1024) -> list[dict]:
    """The per-rung B/pair table (docs/sim.md "memory ladder"): one row
    per named rung of each profile family, with the exact SimConfig
    overrides that select it. ``n_nodes`` only shapes the illustrative
    config (the per-pair figure is N-independent)."""
    from .memory import full_config, lean_config

    rows = []
    for family, builder, rungs in (
        ("full-fd", full_config, ("int32", "int16", "shrunk", "deep")),
        ("lean", lean_config, ("int32", "int16", "int8", "u4r")),
    ):
        for rung in rungs:
            cfg = builder(n_nodes, rung=rung)
            rows.append(
                {
                    "family": family,
                    "rung": rung,
                    "bytes_per_pair": state_bytes_per_pair(cfg),
                    "version_dtype": cfg.version_dtype,
                    "heartbeat_dtype": (
                        cfg.heartbeat_dtype if cfg.track_heartbeats else None
                    ),
                    "fd_dtype": (
                        cfg.fd_dtype if cfg.track_failure_detector else None
                    ),
                    "icount_dtype": (
                        cfg.icount_dtype
                        if cfg.track_failure_detector
                        else None
                    ),
                    "live_bits": cfg.live_bits,
                }
            )
    return rows


# -- per-round HBM traffic model ----------------------------------------------
#
# Analytic bytes one gossip round moves through device memory, per
# execution path — the denominator of the bench roofline
# (bench.py::sim_rounds_per_sec). Passes per (N, N) matrix per
# sub-exchange:
#
# - "pairs": the pair-fused kernel reads and writes every row exactly
#   once (2 passes) — visiting pair (g, gm[g]) covers both directions.
# - "m8": the single-pass kernel streams each row as self, gathers it
#   again as its partner's peer, and writes it (3 passes).
# - "xla": the plain XLA matching path materializes the peer-row gather
#   (read w + write gather + read gather + write result = 4 passes).
#
# FD phase (full profiles):
#
# - "kernel"/"xla": a separate pass over the heartbeat matrices — hb +
#   round-start hb reads, last_change/imean/icount read+write, live
#   read+write (the accounting every BENCH record through r05 used).
# - "fused": the FD rides the round's last pairs sub-exchange, which
#   already holds the post-exchange hb tiles in VMEM — only the
#   bookkeeping moves (last_change/imean/icount in-place read+write,
#   one live write), plus one round-start hb read when fanout > 1
#   (at fanout == 1 the sub-exchange's own input IS round-start).
#
# The fully-fused model ("pairs" + "fused") is the minimal-traffic
# denominator — one read and one write of w/hb per sub-exchange, FD for
# the price of its bookkeeping — which is what the ≥0.6-of-HBM-peak
# target in ROADMAP item 3 is measured against.

_PULL_PASSES = {"pairs": 2, "m8": 3, "xla": 4}


def per_round_bytes(
    cfg, *, variant: str = "pairs", fd_phase: str | None = None
) -> int:
    """Analytic HBM bytes of one gossip round for ``cfg`` executed on
    the given pull ``variant`` ("pairs"/"m8"/"xla") and FD phase
    ("fused"/"kernel"/"xla"/"off"; None derives off/xla from the
    config). Shared by bench.py's roofline block so the recorded
    fractions always divide by a model named next to the variant
    provenance. Rung-aware: packed forms move their PACKED bytes (the
    byte-space XLA path never materializes a wide matrix —
    sim/packed.py), so the traffic model reads the same W_BYTES/HB_BYTES
    tables the resident ladder does."""
    if variant not in _PULL_PASSES:
        raise ValueError(f"unknown variant {variant!r}")
    if fd_phase is None:
        fd_phase = "xla" if cfg.track_failure_detector else "off"
    if fd_phase == "off" and cfg.track_failure_detector:
        raise ValueError("fd_phase='off' on an FD-tracking config")
    n2 = cfg.n_nodes * cfg.n_nodes
    m_w = n2 * W_BYTES[cfg.version_dtype]
    m_hb = n2 * HB_BYTES[cfg.heartbeat_dtype] if cfg.track_heartbeats else 0
    total = cfg.fanout * _PULL_PASSES[variant] * (m_w + m_hb)
    if cfg.version_dtype == "u4r" and variant != "pairs":
        # The packed-KERNEL arm folds the round-start refresh (writes
        # shift + diagonal zero) into the first sub-exchange's tiles;
        # the byte-space XLA arm materializes the refreshed packed
        # matrix before the first gather — one extra read + write of
        # the packed width per round.
        total += 2 * m_w
    if cfg.track_failure_detector:
        m_fd = n2 * FD_BYTES[cfg.fd_dtype]
        m_lc = m_hb  # last_change is heartbeat-dtype
        m_ic = n2 * ICOUNT_BYTES[cfg.icount_dtype]
        m_live = n2 * (0.125 if cfg.live_bits else 1.0)
        if fd_phase == "fused":
            if cfg.fanout > 1:
                total += m_hb  # round-start hb0 stream
            total += 2 * m_lc  # last_change r/w (in place)
            total += 2 * m_fd  # imean r/w
            total += 2 * m_ic  # icount r/w
            total += m_live  # live_view write
        else:
            total += 2 * m_hb  # hb + round-start hb reads
            total += 2 * m_lc  # last_change r/w
            total += 2 * m_fd  # imean r/w
            total += 2 * m_ic  # icount r/w
            total += 2 * m_live  # live_view r/w
    return int(total)


def roofline_models(cfg, *, variant: str, fd_phase: str) -> dict:
    """The three denominators a BENCH roofline block reports: the
    ENGAGED path's bytes (what actually ran — the headline fraction),
    the fully-fused minimal-traffic model, and the plain-XLA model.
    ``variant``/``fd_phase`` come from the same gossip.py resolutions
    sim_step dispatches on (pallas_variant_engaged / fd_phase_engaged),
    so the stamp can never drift from the compiled step."""
    fd_on = cfg.track_failure_detector
    return {
        "engaged": per_round_bytes(cfg, variant=variant, fd_phase=fd_phase),
        "fused": per_round_bytes(
            cfg, variant="pairs", fd_phase="fused" if fd_on else "off"
        ),
        "xla": per_round_bytes(
            cfg, variant="xla", fd_phase="xla" if fd_on else "off"
        ),
    }


def budget_from_mtu(
    mtu_bytes: int,
    *,
    key_bytes: int = 8,
    value_bytes: int = 8,
    stale_owners: int = 1,
    node_name_bytes: int = 8,
    version_scale: int = 1000,
) -> int:
    """Key-versions that fit one ``mtu_bytes`` delta for this workload.

    ``stale_owners`` is how many distinct owners' updates share the delta
    (each adds one NodeDelta envelope); ``version_scale`` sets the varint
    width of representative version numbers. Raises if not even one
    key-version fits (the packer would make no progress at that MTU — the
    object model's pathological-MTU case, reference state.py:412-413).
    """
    if mtu_bytes <= 0:
        raise ValueError("mtu_bytes must be positive")
    node = NodeId("n" * node_name_bytes, version_scale, ("h" * 9, 65_000))
    kv = KeyValueUpdate(
        key="k" * key_bytes,
        value="v" * value_bytes,
        version=version_scale,
        status=VersionStatusEnum.SET,
    )
    model = DeltaSizeModel()
    base = model.node_delta_base(
        node,
        from_version_excluded=version_scale,
        last_gc_version=0,
        max_version=version_scale,
    )
    kv_inc = model.kv_increment(kv)
    # Total delta = committed node-deltas; reserve each owner's envelope
    # (base + length framing) via the same accounting the packer uses.
    envelope = model.delta_total_with(base) - model.total()
    overhead = stale_owners * envelope
    budget = (mtu_bytes - overhead) // kv_inc
    if budget < 1:
        raise ValueError(
            f"mtu_bytes={mtu_bytes} cannot carry one key-version "
            f"(overhead {overhead}B + {kv_inc}B per key-version)"
        )
    return int(budget)
