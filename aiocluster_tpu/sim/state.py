"""Tensor state for the batched gossip simulator.

The object model's per-replica ``NodeState`` maps (dict of versioned keys)
collapse into a single **watermark matrix**: deltas are sent in increasing
version order (core/cluster_state.py packer), so what replica ``i`` knows
about owner ``j`` is always a version-prefix of ``j``'s history —
completely described by one integer ``w[i, j]``. Values never need to live
on device: convergence is a property of versions alone, and SimCluster
rematerialises replica views host-side from the watermark.

Sharding: all (N, N) matrices are sharded along the **owner axis (columns,
axis 1)** over the device mesh. Every per-exchange update touches full
columns of a shard only (gathering peer *rows* is shard-local because rows
are unsharded), so gossip itself needs zero cross-device traffic; only the
budget's owner-order cumsum offsets and convergence checks are collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from .config import SimConfig


@struct.dataclass
class SimState:
    """One cluster's complete simulated state (a pytree of arrays)."""

    tick: jax.Array  # () int32 — gossip round counter
    max_version: jax.Array  # (N,) int32 — owner version counters
    heartbeat: jax.Array  # (N,) int32 — owner heartbeat counters
    alive: jax.Array  # (N,) bool — ground-truth liveness (churn target)
    w: jax.Array  # (N, N) version_dtype — w[i, j]: i's watermark on owner j
    hb_known: jax.Array  # (N, N) heartbeat_dtype — highest hb of j known to i

    # Failure-detector state (zero-sized when disabled). The sampling
    # window is held as a running (mean, count) pair — algebraically
    # identical to the object model's (window sum, count) with
    # mean-eviction at the cap, but 6 bytes/pair lighter on HBM.
    last_change: jax.Array  # (N, N) heartbeat_dtype — tick of last hb increase
    imean: jax.Array  # (N, N) fd_dtype — mean of sampled intervals (ticks)
    icount: jax.Array  # (N, N) int16 — number of samples (window-capped)
    live_view: jax.Array  # (N, N) bool — i's belief that j is alive
    # Tick at which observer i stamped owner j dead (0 = believed alive /
    # never stamped / forgotten). Drives the two-stage lifecycle when
    # SimConfig.dead_grace_ticks is set; zero-sized when the FD is off.
    dead_since: jax.Array  # (N, N) heartbeat_dtype


@struct.dataclass
class SweepParams:
    """Per-lane traced overrides for the sweepable SimConfig scalars.

    Each field is either ``None`` (the lane uses the static config
    value — the field stays out of the pytree, so the compiled step is
    byte-identical to a sweep-free trace of the same math) or a scalar
    array that ``sim_step`` folds into the round exactly where the
    static field would have been read. ``SweepSimulator`` vmaps over a
    leading lane axis, so one jit compile serves every lane's values.

    - ``fanout`` (int32, <= cfg.fanout): sub-exchanges ``c >= fanout``
      are masked to no-ops and the budget-dither salt uses the lane's
      value, so a lane is bit-identical to a sequential run with
      ``replace(cfg, fanout=...)`` (matching/permutation pairing only —
      "choice" draws peers with shape-dependent PRNG streams).
    - ``phi_threshold`` (float32): the FD liveness comparison's bound.
    - ``writes_per_round`` (int32): the owner-side write rate.
    - ``fault_seed`` (uint32, pre-masked to 32 bits): overrides
      ``fault_plan.seed`` in the probabilistic link draws — one lane
      per plan-ensemble member (faults/sim.py).
    - ``byz_frac`` (float32 in [0, 1]): overrides the attacker windows
      of every byzantine entry in the plan with [0, byz_frac) — the
      tolerance atlas's swept axis (benchmarks/byzantine_bench.py). A
      lane equals a sequential run whose plan addresses its attackers
      as ``NodeSet(frac=(0, value))``; requires a plan with byzantine
      entries (their kinds/victims/windows stay static).
    """

    fanout: jax.Array | None = None
    phi_threshold: jax.Array | None = None
    writes_per_round: jax.Array | None = None
    fault_seed: jax.Array | None = None
    byz_frac: jax.Array | None = None


# Largest representable watermark per version-dtype rung (docs/sim.md
# "memory ladder"): init_state and the horizon guards
# (Simulator._check_horizon) enforce these BOUNDS loudly instead of
# letting a narrow rung wrap. The u4r rung stores residuals below the
# owner's max_version, so the bound applies to max_version itself (a
# never-contacted observer's residual equals it).
VERSION_LIMITS = {"int32": 2**31, "int16": 2**15, "int8": 2**7, "u4r": 16}
HEARTBEAT_LIMITS = {"int32": 2**31, "int16": 2**15, "int8": 2**7}


def state_n_local(state: SimState) -> int:
    """This block's LOCAL owner-column count, decoding the packed u4
    rung (whose stored width is halved). The single derivation every
    shape-driven consumer (sim_step, the convergence metrics) uses."""
    w = state.w
    if jnp.dtype(w.dtype) == jnp.uint8:  # packed u4 residual rung
        return int(w.shape[-1]) * 2
    return int(w.shape[-1])


def expected_dtypes(cfg: SimConfig) -> dict[str, str]:
    """Storage dtype per SimState field for this config's rung — the
    layout contract checkpoints are validated against (a packed-rung
    file loaded under an unpacked config would silently reinterpret
    residual bytes as watermarks; sim/checkpoint.py rejects it loudly)."""
    vdt = "uint8" if cfg.version_dtype == "u4r" else cfg.version_dtype
    hdt = cfg.heartbeat_dtype
    return {
        "tick": "int32",
        "max_version": "int32",
        "heartbeat": "int32",
        "alive": "bool",
        "w": vdt,
        "hb_known": hdt,
        "last_change": hdt,
        "imean": cfg.fd_dtype,
        "icount": cfg.icount_dtype,
        "live_view": "uint8" if cfg.live_bits else "bool",
        "dead_since": hdt,
    }


def init_state(cfg: SimConfig, initial_versions: jax.Array | None = None) -> SimState:
    """Fresh cluster: every node owns ``keys_per_node`` versions (versions
    1..K) — or per-node counts via ``initial_versions`` — knows only
    itself, and has heartbeat 1 (parity with the runtime seeding one
    heartbeat at boot, runtime/cluster.py)."""
    from .packed import pack_bits, pack_u4

    n = cfg.n_nodes
    fd_shape = (n, n) if cfg.track_failure_detector else (0, 0)
    # dead_since only drives the two-stage lifecycle; without it the FD
    # branch passes the array through untouched, so a zero-sized matrix
    # saves a full (N, N) heartbeat-dtype allocation (20 GB at 100k).
    ds_shape = (
        (n, n)
        if cfg.track_failure_detector and cfg.dead_grace_ticks is not None
        else (0, 0)
    )
    eye = jnp.eye(n, dtype=bool)
    hdt = jnp.dtype(cfg.heartbeat_dtype)
    if initial_versions is None:
        initial_versions = jnp.full((n,), cfg.keys_per_node, jnp.int32)
    initial_versions = jnp.asarray(initial_versions, jnp.int32)
    limit = VERSION_LIMITS[cfg.version_dtype]
    if int(jnp.max(initial_versions)) >= limit:
        raise ValueError(
            f"initial versions overflow version_dtype={cfg.version_dtype} "
            f"(must stay < {limit})"
        )
    if cfg.version_dtype == "u4r":
        # Packed residual rung: a fresh observer's residual on owner j
        # IS j's initial version count (w = 0 off-diagonal), 0 on the
        # diagonal — stored two per byte.
        w = pack_u4(jnp.where(eye, 0, initial_versions[None, :]))
    else:
        w = jnp.where(eye, initial_versions[None, :], 0).astype(
            jnp.dtype(cfg.version_dtype)
        )
    if cfg.track_failure_detector:
        live0 = jnp.eye(*fd_shape, dtype=bool)
        live_view = pack_bits(live0) if cfg.live_bits else live0
    else:
        live_view = jnp.zeros(fd_shape, bool)
    return SimState(
        tick=jnp.asarray(0, jnp.int32),
        max_version=initial_versions,
        heartbeat=jnp.ones((n,), jnp.int32),
        alive=jnp.ones((n,), bool),
        w=w,
        hb_known=eye.astype(hdt) if cfg.track_heartbeats
        else jnp.zeros((0, 0), hdt),
        last_change=jnp.zeros(fd_shape, hdt),
        imean=jnp.zeros(fd_shape, jnp.dtype(cfg.fd_dtype)),
        icount=jnp.zeros(fd_shape, jnp.dtype(cfg.icount_dtype)),
        live_view=live_view,
        dead_since=jnp.zeros(ds_shape, hdt),
    )
