"""Checkpoint / resume for simulated clusters.

The reference has no persistence at all (SURVEY.md §5: a restarted node
rejoins empty and re-replicates over gossip). Long tensor-sim runs are a
new capability, so they get one: the full SimState pytree plus the exact
SimConfig and the run's PRNG seed round-trip through one ``.npz`` file,
and a resumed run continues the trajectory (same state, same tick, same
seed) on any device layout — single chip or a sharded mesh — because the
kernel's randomness is keyed by (seed, tick), not by historical host
state.

Non-numpy dtypes (bfloat16 lives in ml_dtypes) are stored as raw bit
patterns plus a dtype string; np.savez would otherwise round-trip them as
void dtypes that refuse to load.
"""

from __future__ import annotations

import dataclasses
import json
import warnings
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .state import SimState

_FIELDS = [f.name for f in dataclasses.fields(SimState)]


def save_state(
    path: str | Path,
    state: SimState,
    cfg: SimConfig,
    *,
    seed: int = 0,
    has_topology: bool = False,
) -> None:
    """Write state + config + run metadata to ``path`` (.npz, atomic via
    temp rename)."""
    path = Path(path)
    arrays = {}
    dtypes: dict[str, str] = {}
    for name in _FIELDS:
        arr = np.asarray(getattr(state, name))  # noqa: ACT021 -- checkpointing IS the device->host gather
        dtypes[name] = str(arr.dtype)
        if arr.dtype.kind not in "biufc":  # e.g. bfloat16 -> void in npz
            arr = arr.view(np.uint8).reshape(arr.shape + (arr.dtype.itemsize,))
        arrays[name] = arr
    meta = {
        "config": dataclasses.asdict(cfg),
        "dtypes": dtypes,
        "seed": seed,
        "has_topology": has_topology,
    }
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **arrays)
    tmp.replace(path)


def load_state(
    path: str | Path,
) -> tuple[SimState, SimConfig, dict]:
    """Read a checkpoint; returns (state, config, meta) where meta carries
    ``seed`` and ``has_topology``. The caller re-shards with
    parallel.shard_state when resuming on a mesh."""
    with np.load(Path(path)) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        # Tolerate config keys this code version doesn't know (a NEWER
        # writer's fields): unknown knobs can't influence a build that
        # lacks them, and refusing the load would strand otherwise
        # readable state. Missing keys take their defaults (the OLDER
        # writer case, pinned by the forward-compat test).
        known = {f.name for f in dataclasses.fields(SimConfig)}
        raw = dict(meta["config"])
        unknown = sorted(set(raw) - known)
        if unknown:
            warnings.warn(
                f"checkpoint config has unknown keys {unknown} "
                "(written by a newer version?); ignoring them",
                stacklevel=2,
            )
        cfg = SimConfig(**{k: v for k, v in raw.items() if k in known})
        fields = {}
        for name in _FIELDS:
            arr = data[name]
            want = jnp.dtype(meta["dtypes"][name])
            if arr.dtype == np.uint8 and want.kind not in "biufc":
                arr = arr.reshape(arr.shape[:-1] + (-1,)).view(want)
                arr = arr.reshape(arr.shape[:-1])
            fields[name] = jnp.asarray(arr, dtype=want)
        state = SimState(**fields)
    return state, cfg, meta
