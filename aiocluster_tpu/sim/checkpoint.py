"""Checkpoint / resume for simulated clusters.

The reference has no persistence at all (SURVEY.md §5: a restarted node
rejoins empty and re-replicates over gossip). Long tensor-sim runs are a
new capability, so they get one: the full SimState pytree plus the exact
SimConfig and the run's PRNG seed round-trip through one ``.npz`` file,
and a resumed run continues the trajectory (same state, same tick, same
seed) on any device layout — single chip or a sharded mesh — because the
kernel's randomness is keyed by (seed, tick), not by historical host
state.

Sweep checkpoints (sim/sweep.py) use the same container with a leading
lane axis on every array and ``meta["sweep"]`` marking the layout; one
shared field codec serves both, so the two formats cannot drift.

Non-numpy dtypes (bfloat16 lives in ml_dtypes) are stored as raw bit
patterns plus a dtype string; np.savez would otherwise round-trip them as
void dtypes that refuse to load.
"""

from __future__ import annotations

import dataclasses
import json
import warnings
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .state import SimState

_FIELDS = [f.name for f in dataclasses.fields(SimState)]


def _config_from_meta(raw: dict) -> SimConfig:
    """SimConfig from a checkpoint's ``dataclasses.asdict`` snapshot.
    ``asdict`` recurses into the (frozen) FaultPlan, so a fault-plan
    config round-trips as a plain dict — rebuild it through the plan's
    own deserializer or SimConfig's validation rejects it."""
    known = {f.name for f in dataclasses.fields(SimConfig)}
    kwargs = {k: v for k, v in raw.items() if k in known}
    if isinstance(kwargs.get("fault_plan"), dict):
        from ..faults.plan import FaultPlan

        kwargs["fault_plan"] = FaultPlan.from_dict(kwargs["fault_plan"])
    return SimConfig(**kwargs)


def _encode_fields(state: SimState) -> tuple[dict, dict[str, str]]:
    """(arrays, dtypes) for one state pytree — the single npz field
    codec (non-numpy dtypes stored as uint8 bit patterns)."""
    arrays: dict = {}
    dtypes: dict[str, str] = {}
    for name in _FIELDS:
        arr = np.asarray(getattr(state, name))  # noqa: ACT021 -- checkpointing IS the device->host gather
        dtypes[name] = str(arr.dtype)
        if arr.dtype.kind not in "biufc":  # e.g. bfloat16 -> void in npz
            arr = arr.view(np.uint8).reshape(arr.shape + (arr.dtype.itemsize,))
        arrays[name] = arr
    return arrays, dtypes


def _check_layout(cfg: SimConfig, dtypes: dict[str, str], path) -> None:
    """Loud cross-rung rejection: the stored field dtypes must be the
    layout the (decoded) config implies. The memory ladder makes the
    same VALUES representable several ways — packed u4 residual bytes
    reinterpreted as int16 watermarks would be silent garbage — so a
    checkpoint whose arrays and config disagree (tampered meta, a
    writer/loader drift) is refused by name instead of loaded."""
    from .state import expected_dtypes

    exp = expected_dtypes(cfg)
    bad = {
        name: (stored, exp[name])
        for name, stored in dtypes.items()
        if name in exp and jnp.dtype(stored) != jnp.dtype(exp[name])
    }
    if bad:
        detail = ", ".join(
            f"{k}: stored {s!r} != rung-expected {e!r}"
            for k, (s, e) in sorted(bad.items())
        )
        raise ValueError(
            f"checkpoint {path} layout does not match its config's "
            f"memory-ladder rung ({detail}); refuse to reinterpret "
            "packed/narrow state across rungs"
        )


def _decode_fields(data, dtypes: dict[str, str]) -> SimState:
    """Inverse of _encode_fields, onto device arrays."""
    fields = {}
    for name in _FIELDS:
        arr = data[name]
        want = jnp.dtype(dtypes[name])
        if arr.dtype == np.uint8 and want.kind not in "biufc":
            arr = arr.reshape(arr.shape[:-1] + (-1,)).view(want)
            arr = arr.reshape(arr.shape[:-1])
        fields[name] = jnp.asarray(arr, dtype=want)
    return SimState(**fields)


def _atomic_savez(path: Path, arrays: dict, meta: dict) -> None:
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **arrays)
    tmp.replace(path)


def save_state(
    path: str | Path,
    state: SimState,
    cfg: SimConfig,
    *,
    seed: int = 0,
    has_topology: bool = False,
) -> None:
    """Write state + config + run metadata to ``path`` (.npz, atomic via
    temp rename)."""
    arrays, dtypes = _encode_fields(state)
    meta = {
        "config": dataclasses.asdict(cfg),
        "dtypes": dtypes,
        "seed": seed,
        "has_topology": has_topology,
    }
    _atomic_savez(Path(path), arrays, meta)


def save_sweep(
    path: str | Path,
    states: SimState,
    cfg: SimConfig,
    *,
    seeds: list[int],
    params: dict[str, list],
    first,
    host_tick: int,
) -> None:
    """Checkpoint a lane-batched sweep (sim/sweep.py): the (S, ...)
    state pytree plus the per-lane seeds, the declared sweep values and
    the on-device convergence accumulator. Same npz container and field
    codec as single-sim checkpoints; ``meta["sweep"]`` marks the
    lane-batched layout so load_state can refuse it loudly."""
    arrays, dtypes = _encode_fields(states)
    arrays["__first__"] = np.asarray(first, np.int32)
    meta = {
        "config": dataclasses.asdict(cfg),
        "dtypes": dtypes,
        "sweep": {
            "seeds": [int(s) for s in seeds],
            "params": {k: list(v) for k, v in params.items()},
            "host_tick": int(host_tick),
        },
    }
    _atomic_savez(Path(path), arrays, meta)


def load_sweep(path: str | Path) -> tuple[SimState, SimConfig, dict]:
    """Read a sweep checkpoint; returns (lane-batched states, config,
    meta) with meta carrying ``seeds``, ``params``, ``first`` and
    ``host_tick``."""
    with np.load(Path(path)) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        if "sweep" not in meta:
            raise ValueError(
                "not a sweep checkpoint (single-sim file? use load_state)"
            )
        cfg = _config_from_meta(dict(meta["config"]))
        _check_layout(cfg, meta["dtypes"], path)
        states = _decode_fields(data, meta["dtypes"])
        out_meta = dict(meta["sweep"])
        out_meta["first"] = np.asarray(data["__first__"])
    return states, cfg, out_meta


def load_state(
    path: str | Path,
) -> tuple[SimState, SimConfig, dict]:
    """Read a checkpoint; returns (state, config, meta) where meta carries
    ``seed`` and ``has_topology``. The caller re-shards with
    parallel.shard_state when resuming on a mesh."""
    with np.load(Path(path)) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        if "sweep" in meta:
            raise ValueError(
                "lane-batched sweep checkpoint; use load_sweep / "
                "SweepSimulator.resume"
            )
        # Tolerate config keys this code version doesn't know (a NEWER
        # writer's fields): unknown knobs can't influence a build that
        # lacks them, and refusing the load would strand otherwise
        # readable state. Missing keys take their defaults (the OLDER
        # writer case, pinned by the forward-compat test).
        known = {f.name for f in dataclasses.fields(SimConfig)}
        raw = dict(meta["config"])
        unknown = sorted(set(raw) - known)
        if unknown:
            warnings.warn(
                f"checkpoint config has unknown keys {unknown} "
                "(written by a newer version?); ignoring them",
                stacklevel=2,
            )
        cfg = _config_from_meta(raw)
        _check_layout(cfg, meta["dtypes"], path)
        state = _decode_fields(data, meta["dtypes"])
    return state, cfg, meta
