// Native host fast-path for the lean matching gossip round.
//
// Reproduces ops/gossip.py::sim_step's matching sub-exchange BIT-EXACTLY
// for the lean profile (int16 watermarks — held here as lossless int8,
// see acg_hostsim_subexchange; no heartbeats/FD, no churn, proportional
// budget): pair (a, b) of the involution advances both rows
// toward each other under the budgeted watermark advance
// (gossip.py::_budgeted_advance), including the f32 proportional scaling
// and the multiplicative-hash dithered rounding (gossip.py::_hash_uniform,
// bits=24). Every float operation below mirrors one XLA elementwise op:
//   d     = max(w_send - w_recv, 0)                    (int16 math)
//   total = sum(d)              exact: integer < 2^24, so the f32 sum
//                               XLA performs is order-independent and
//                               equals this int32 accumulation
//   scale = min(1f, (float)budget / max((float)total, 1f))
//   x     = (float)d * scale                           (one f32 rounding)
//   fl    = floorf(x); frac = x - fl                   (exact)
//   u     = clip((float)(int32)(h >> 8) * 2^-24, 1e-12f, 1 - 2^-24)
//   adv   = min((int32)fl + (u < frac), (int32)d)
//
// Why this exists: the XLA CPU path at the 100k-node config-5 scale runs
// ~10^3 s/round on a 1-core host (virtual-mesh collectives or not), which
// makes exact rounds-to-convergence unmeasurable there; this kernel walks
// the identical trajectory at ~10^1-10^2 s/round, and the XLA path then
// certifies the final round from a checkpoint (see sim/hostsim.py).
// Single-threaded by design (the builder host has one core); the j-loops
// are written branch-light so the compiler can vectorize.
//
// Reference anchors (jettify/aiocluster): the round being simulated is
// server.py:378-495 (gossip round) with state.py:340-415's MTU-bounded
// delta collapsed into the budgeted watermark advance.

#include <cstdint>
#include <cmath>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

// gossip.py::_hash_uniform constants (bits=24 path).
constexpr uint32_t K1 = 0x9E3779B1u;
constexpr uint32_t K2 = 0x85EBCA77u;
constexpr uint32_t K3 = 0xC2B2AE3Du;
constexpr uint32_t KM = 0x27D4EB2Fu;
constexpr float INV24 = 5.9604644775390625e-08f;  // 2^-24 (exact)

inline float hash_u24(uint32_t i, uint32_t j, uint32_t s) {
    uint32_t h = i * K1 ^ j * K2 ^ s * K3;
    h = (h ^ (h >> 15)) * KM;
    h = h ^ (h >> 13);
    // (h >> 8) fits 24 bits: the int32 cast and f32 convert are exact.
    float u = (float)(int32_t)(h >> 8) * INV24;
    // jnp.clip(u, 1e-12, 1 - 2^-24): upper clip is a no-op by
    // construction (max is exactly 1 - 2^-24); lower clip guards u == 0.
    if (u < 1e-12f) u = 1e-12f;
    return u;
}

// One budgeted direction for a single element (the scalar reference the
// vector path reproduces lane-for-lane; also the tail loop).
inline int8_t adv_scalar(int8_t orecv, int8_t osend, float scale,
                          uint32_t row, uint32_t j, uint32_t s) {
    int32_t d = (int32_t)osend - (int32_t)orecv;
    d = d > 0 ? d : 0;
    float x = (float)d * scale;
    float fl = std::floor(x);
    float u = hash_u24(row, j, s);
    int32_t adv = (int32_t)fl + (u < (x - fl) ? 1 : 0);
    adv = adv < d ? adv : d;
    return (int8_t)((int32_t)orecv + adv);
}

#ifdef __AVX2__
// 8-lane form of _budgeted_advance's elementwise tail. Every intrinsic
// is the IEEE-exact vector twin of the scalar op (cvtepi32_ps exact for
// |v| < 2^24, mul_ps round-to-nearest like the scalar multiply,
// floor_ps == floorf, cvttps_epi32 == the truncating C cast), so the
// lanes are bit-identical to the scalar path — asserted by the
// full-trajectory tests, which run whichever build the host produced.
struct Hash8 {
    __m256i iK1_s;  // row * K1 ^ s*K3, broadcast
    __m256i jK2;    // current j * K2 per lane
    __m256i stepK2; // 16 * K2 — each 16-wide iteration consumes one
                    // next() from the lo stream (j..j+7) and one from
                    // the hi stream (j+8..j+15)
    inline void init(uint32_t row, uint32_t s, uint32_t j0) {
        iK1_s = _mm256_set1_epi32((int32_t)(row * K1 ^ s * K3));
        __m256i j = _mm256_add_epi32(
            _mm256_set1_epi32((int32_t)j0),
            _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7));
        jK2 = _mm256_mullo_epi32(j, _mm256_set1_epi32((int32_t)K2));
        stepK2 = _mm256_set1_epi32((int32_t)(16u * K2));
    }
    inline __m256 next() {  // u for the current 8 columns, then advance
        __m256i h = _mm256_xor_si256(iK1_s, jK2);
        jK2 = _mm256_add_epi32(jK2, stepK2);  // (j+16)*K2 == j*K2 + 16*K2
        h = _mm256_mullo_epi32(
            _mm256_xor_si256(h, _mm256_srli_epi32(h, 15)),
            _mm256_set1_epi32((int32_t)KM));
        h = _mm256_xor_si256(h, _mm256_srli_epi32(h, 13));
        __m256 u = _mm256_mul_ps(
            _mm256_cvtepi32_ps(_mm256_srli_epi32(h, 8)),
            _mm256_set1_ps(INV24));
        return _mm256_max_ps(u, _mm256_set1_ps(1e-12f));
    }
};

// Budgeted advance for 8 int32 lanes: recv + min(floor(d*scale)+bump, d).
inline __m256i adv8(__m256i orecv, __m256i osend, __m256 scale,
                    Hash8& hash) {
    __m256i d = _mm256_max_epi32(_mm256_sub_epi32(osend, orecv),
                                 _mm256_setzero_si256());
    __m256 x = _mm256_mul_ps(_mm256_cvtepi32_ps(d), scale);
    __m256 fl = _mm256_floor_ps(x);
    __m256 frac = _mm256_sub_ps(x, fl);
    __m256 u = hash.next();
    // bump: lanes where u < frac have mask -1; subtracting the mask
    // adds 1 exactly there.
    __m256i bump = _mm256_castps_si256(_mm256_cmp_ps(u, frac, _CMP_LT_OQ));
    __m256i adv = _mm256_sub_epi32(_mm256_cvttps_epi32(fl), bump);
    adv = _mm256_min_epi32(adv, d);
    return _mm256_add_epi32(orecv, adv);
}

inline void widen16(const int8_t* p, __m256i& lo, __m256i& hi) {
    // 16 int8 -> two 8-lane int32 vectors.
    __m128i v = _mm_loadu_si128((const __m128i*)p);
    lo = _mm256_cvtepi8_epi32(v);
    hi = _mm256_cvtepi8_epi32(_mm_srli_si128(v, 8));
}

inline void store16(int8_t* p, __m256i lo, __m256i hi) {
    // Watermarks are 0..127 (hostsim.supported gates keys_per_node), so
    // the signed saturations never engage; packs_epi32 interleaves
    // 128-bit lanes, which the permute undoes before the int16->int8
    // pack.
    __m256i p16 = _mm256_permute4x64_epi64(
        _mm256_packs_epi32(lo, hi), 0xD8);
    __m128i p8 = _mm_packs_epi16(
        _mm256_castsi256_si128(p16), _mm256_extracti128_si256(p16, 1));
    _mm_storeu_si128((__m128i*)p, p8);
}
#endif  // __AVX2__

// Advance both directions of one pair in place. a_scale/b_scale == 1.0f
// means that direction saturates (recv = max(recv, send) — exactly what
// the budgeted formula degenerates to at scale 1, see the module
// comment); the flags let us skip the hash work for saturating sides.
inline void advance_pair(int8_t* __restrict ra, int8_t* __restrict rb,
                         int64_t n, uint32_t a, uint32_t b, uint32_t s,
                         float sa, float sb, bool a_sat, bool b_sat) {
    int64_t j = 0;
#ifdef __AVX2__
    Hash8 hash_a_lo, hash_a_hi, hash_b_lo, hash_b_hi;
    if (!a_sat) { hash_a_lo.init(a, s, 0); hash_a_hi.init(a, s, 8); }
    if (!b_sat) { hash_b_lo.init(b, s, 0); hash_b_hi.init(b, s, 8); }
    __m256 vsa = _mm256_set1_ps(sa), vsb = _mm256_set1_ps(sb);
    for (; j + 16 <= n; j += 16) {
        __m256i alo, ahi, blo, bhi;
        widen16(ra + j, alo, ahi);
        widen16(rb + j, blo, bhi);
        __m256i nalo, nahi, nblo, nbhi;
        if (a_sat) {
            nalo = _mm256_max_epi32(alo, blo);
            nahi = _mm256_max_epi32(ahi, bhi);
        } else {
            nalo = adv8(alo, blo, vsa, hash_a_lo);
            nahi = adv8(ahi, bhi, vsa, hash_a_hi);
        }
        if (b_sat) {
            nblo = _mm256_max_epi32(alo, blo);
            nbhi = _mm256_max_epi32(ahi, bhi);
        } else {
            nblo = adv8(blo, alo, vsb, hash_b_lo);
            nbhi = adv8(bhi, ahi, vsb, hash_b_hi);
        }
        store16(ra + j, nalo, nahi);
        store16(rb + j, nblo, nbhi);
    }
#endif
    for (; j < n; ++j) {
        const int8_t oa = ra[j], ob = rb[j];
        ra[j] = a_sat ? (oa > ob ? oa : ob)
                      : adv_scalar(oa, ob, sa, a, (uint32_t)j, s);
        rb[j] = b_sat ? (oa > ob ? oa : ob)
                      : adv_scalar(ob, oa, sb, b, (uint32_t)j, s);
    }
}

}  // namespace

extern "C" {

// Advance one matching sub-exchange over all pairs, in place.
//   w        : (n, n) int8, row-major — the watermark matrix. The sim
//              stores int16, but on the supported domain every
//              watermark is <= keys_per_node <= 127, so the int8
//              REPRESENTATION is lossless and the arithmetic (which
//              widens to int32/f32 exactly like the int16 path) is
//              unchanged — it just halves the DRAM traffic this
//              memory-bound loop is made of.
//   hb       : (n, n) int16 heartbeat-knowledge matrix, or nullptr on
//              the lean profile. A matched pair absorbs each other's
//              heartbeat rows with an elementwise max — gossip.py's
//              hb_absorb computes both rows' maxima from PRE-exchange
//              values in one vectorized op, and max is symmetric, so
//              writing max(ha, hb) to both sides is exact.
//   A, B     : pair index arrays (A[k] < B[k] = p[A[k]], each row of the
//              involution appears in exactly one pair; self-pairs are
//              excluded by the caller — they are no-ops)
//   salt     : gossip.py sub_salt(c, 0) for this sub-exchange
//   run_salt : random.bits(base_key) — the per-run hash salt
//   budget   : key-versions per exchange (the MTU analogue)
//   compute_min / row_min : when nonzero, write min(row) after the
//              update for every touched row (len-n int32 buffer) — the
//              convergence check rides the round's last sub-exchange.
// Returns the number of pairs that took the saturating fast path
// (total <= budget on both sides), for diagnostics.
long acg_hostsim_subexchange(int8_t* w, int16_t* hb, int64_t n,
                             const int32_t* A, const int32_t* B,
                             int64_t n_pairs,
                             int32_t salt, uint32_t run_salt,
                             int32_t budget,
                             int32_t compute_min,
                             int32_t* row_min) {
    const uint32_t s = (uint32_t)salt ^ run_salt;
    long fast = 0;
    for (int64_t k = 0; k < n_pairs; ++k) {
        const int64_t a = A[k], b = B[k];
        int8_t* __restrict ra = w + a * n;
        int8_t* __restrict rb = w + b * n;
        if (hb) {
            int16_t* __restrict ha = hb + a * n;
            int16_t* __restrict hbp = hb + b * n;
            for (int64_t j = 0; j < n; ++j) {
                int16_t m = ha[j] > hbp[j] ? ha[j] : hbp[j];
                ha[j] = m;
                hbp[j] = m;
            }
        }
        // Pass 1: both directions' total deficits (rows land in cache
        // for pass 2).
        int32_t tota = 0, totb = 0;
        for (int64_t j = 0; j < n; ++j) {
            int32_t da = (int32_t)rb[j] - (int32_t)ra[j];
            tota += da > 0 ? da : 0;
            totb += da < 0 ? -da : 0;
        }
        const bool fa = tota <= budget;  // scale == 1 exactly
        const bool fb = totb <= budget;
        if (fa && fb) {
            ++fast;
            if (tota | totb) {  // identical rows need no writes at all
                for (int64_t j = 0; j < n; ++j) {
                    int8_t m = ra[j] > rb[j] ? ra[j] : rb[j];
                    ra[j] = m;
                    rb[j] = m;
                }
            }
        } else {
            // total > budget on at least one side (scale < 1 there: the
            // f32 division can only equal 1.0f when total == budget,
            // which the fast path already took). BOTH directions read
            // the PRE-exchange rows — element j of one row only depends
            // on element j of the other, so the per-element
            // load-both-then-write-both in advance_pair keeps the
            // in-place update exact.
            const float sa = fa ? 1.0f : std::fmin(
                1.0f, (float)budget / std::fmax((float)tota, 1.0f));
            const float sb = fb ? 1.0f : std::fmin(
                1.0f, (float)budget / std::fmax((float)totb, 1.0f));
            advance_pair(ra, rb, n, (uint32_t)a, (uint32_t)b, s,
                         sa, sb, fa, fb);
        }
        if (compute_min) {
            int32_t ma = 32767, mb = 32767;
            for (int64_t j = 0; j < n; ++j) {
                if (ra[j] < ma) ma = ra[j];
                if (rb[j] < mb) mb = rb[j];
            }
            row_min[a] = ma;
            row_min[b] = mb;
        }
    }
    return fast;
}

namespace {

// Single-direction budgeted advance of one row toward a sender row,
// writing (or max-accumulating into) ``dst`` — the 'choice' twin of
// advance_pair. AVX2 16-lane main loop with the same IEEE-exact vector
// building blocks as the matching kernel (Hash8/adv8), scalar tail;
// the hash row index is the INITIATOR ``row`` for both directions.
inline void advance_row(int8_t* __restrict dst,
                        const int8_t* __restrict recv,
                        const int8_t* __restrict send,
                        int64_t n, uint32_t row, uint32_t s,
                        float scale, bool sat, bool accum_max) {
    int64_t j = 0;
#ifdef __AVX2__
    Hash8 hash_lo, hash_hi;
    if (!sat) { hash_lo.init(row, s, 0); hash_hi.init(row, s, 8); }
    __m256 vs = _mm256_set1_ps(scale);
    for (; j + 16 <= n; j += 16) {
        __m256i rlo, rhi, slo, shi;
        widen16(recv + j, rlo, rhi);
        widen16(send + j, slo, shi);
        __m256i vlo, vhi;
        if (sat) {
            vlo = _mm256_max_epi32(rlo, slo);
            vhi = _mm256_max_epi32(rhi, shi);
        } else {
            vlo = adv8(rlo, slo, vs, hash_lo);
            vhi = adv8(rhi, shi, vs, hash_hi);
        }
        if (accum_max) {
            __m256i dlo, dhi;
            widen16(dst + j, dlo, dhi);
            vlo = _mm256_max_epi32(vlo, dlo);
            vhi = _mm256_max_epi32(vhi, dhi);
        }
        store16(dst + j, vlo, vhi);
    }
#endif
    for (; j < n; ++j) {
        int8_t v = sat ? (recv[j] > send[j] ? recv[j] : send[j])
                       : adv_scalar(recv[j], send[j], scale, row,
                                    (uint32_t)j, s);
        dst[j] = accum_max && dst[j] > v ? dst[j] : v;
    }
}

}  // namespace

// One 'choice'-pairing sub-exchange (gossip.py sim_step's else-branch:
// every node independently samples a peer — the reference's
// server.py:699 semantics, inbound load varies). All reads come from
// ``w_pre``, the caller's pre-sub-exchange snapshot, exactly like the
// XLA form where both _budgeted_advance calls and the scatter operand
// derive from the loop-carry value:
//   pass A (initiator applies responder's delta):
//     w[i] = w_pre[i] + adv(recv=w_pre[i], send=w_pre[p[i]], row=i, salt0)
//   pass B (responder applies initiator's delta, scatter-max over
//     duplicate responders — max is associative+commutative, so the
//     sequential loop equals XLA's .at[p].max):
//     w[p[i]] = max(w[p[i]],
//                   w_pre[p[i]] + adv(recv=w_pre[p[i]], send=w_pre[i],
//                                     row=i, salt1))
// The dither hash row index is the INITIATOR i for BOTH directions
// (each _budgeted_advance's d matrix is indexed by initiator row).
void acg_hostsim_choice_subexchange(int8_t* w, const int8_t* w_pre,
                                    int64_t n, const int32_t* p,
                                    int32_t salt0, int32_t salt1,
                                    uint32_t run_salt, int32_t budget) {
    const uint32_t s0 = (uint32_t)salt0 ^ run_salt;
    const uint32_t s1 = (uint32_t)salt1 ^ run_salt;
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* __restrict recv = w_pre + i * n;
        const int8_t* __restrict send = w_pre + p[i] * n;
        int32_t tot = 0;
        for (int64_t j = 0; j < n; ++j) {
            int32_t d = (int32_t)send[j] - (int32_t)recv[j];
            tot += d > 0 ? d : 0;
        }
        const float sc = tot <= budget ? 1.0f : std::fmin(
            1.0f, (float)budget / std::fmax((float)tot, 1.0f));
        advance_row(w + i * n, recv, send, n, (uint32_t)i, s0,
                    sc, tot <= budget, false);
    }
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* __restrict recv = w_pre + p[i] * n;  // responder's pre
        const int8_t* __restrict send = w_pre + i * n;     // initiator's pre
        int32_t tot = 0;
        for (int64_t j = 0; j < n; ++j) {
            int32_t d = (int32_t)send[j] - (int32_t)recv[j];
            tot += d > 0 ? d : 0;
        }
        const float sc = tot <= budget ? 1.0f : std::fmin(
            1.0f, (float)budget / std::fmax((float)tot, 1.0f));
        advance_row(w + p[i] * n, recv, send, n, (uint32_t)i, s1,
                    sc, tot <= budget, true);
    }
}

// Row minima of w into row_min (the convergence check for paths whose
// last sub-exchange cannot carry it, e.g. 'choice' scatters).
void acg_hostsim_rowmin(const int8_t* w, int64_t n, int32_t* row_min) {
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* __restrict row = w + i * n;
        int32_t m = 127;
        for (int64_t j = 0; j < n; ++j)
            if (row[j] < m) m = row[j];
        row_min[i] = m;
    }
}

// Refresh owner diagonals: w[i, i] = mv[i] (gossip.py's diagonal refresh
// — a no-op for write-free runs after init, kept for fidelity).
void acg_hostsim_diag(int8_t* w, int64_t n, const int32_t* mv) {
    for (int64_t i = 0; i < n; ++i) {
        int32_t v = mv[i];
        w[i * n + i] = (int8_t)v;
    }
}

// Heartbeat diagonal refresh: hb[i, i] = heartbeat[i] (the hbv_vec
// select in sim_step — runs BEFORE the round-start copy the FD reads).
void acg_hostsim_diag_hb(int16_t* hb, int64_t n, const int32_t* hbv) {
    for (int64_t i = 0; i < n; ++i) {
        hb[i * n + i] = (int16_t)hbv[i];
    }
}

namespace {

// XLA's f32 -> bf16 convert (round-to-nearest-even). Values here are
// finite interval means, so no NaN handling is needed.
inline uint16_t f32_to_bf16(float f) {
    uint32_t x;
    __builtin_memcpy(&x, &f, 4);
    uint32_t lsb = (x >> 16) & 1u;
    x += 0x7FFFu + lsb;
    return (uint16_t)(x >> 16);
}

inline float bf16_to_f32(uint16_t b) {
    uint32_t x = ((uint32_t)b) << 16;
    float f;
    __builtin_memcpy(&f, &x, 4);
    return f;
}

}  // namespace

// One full vectorized phi-accrual FD round — the elementwise twin of
// gossip.py sim_step's XLA failure-detector block (the branch with no
// churn and no lifecycle: the host fast-path domain). Per element
// (observer row i, owner j), every op mirrors one XLA f32/int op in the
// same order, so the result is bit-identical:
//   increased  = hb > hb0                       (post vs round-start)
//   never_seen = lc == 0
//   interval   = (f32)(tick - lc)
//   sampled    = increased & !never_seen & interval <= max_interval
//   icount'    = min(icount + sampled, window)          (int16)
//   imean'     = sampled ? imean + (interval - imean)/max((f32)icount', 1)
//                        : imean                        (f32 math)
//   lc'        = increased ? tick : lc
//   elapsed    = (f32)(tick - lc')
//   live       = icount' >= 1 &&
//                elapsed * ((f32)icount' + pw)
//                  <= phi * (imean' * (f32)icount' + pw_pm)
//   live      |= (i == j)                       (self-belief diagonal)
//   imean_out  = live ? imean' : 0    (stored at fd dtype: f32 or bf16,
//                                      rounded AFTER the live test, as
//                                      XLA's .astype does)
//   icount_out = live ? icount' : 0
// pw/phi are the f32 casts of the config floats; pw_pm is
// f32(double(prior_weight) * double(prior_mean_ticks)) — the exact
// value XLA folds for its `pw * pm` scalar.
void acg_hostsim_fd(const int16_t* hb, const int16_t* hb0,
                    int16_t* lc, void* imean, int32_t imean_is_bf16,
                    int16_t* icount, uint8_t* live_view,
                    int64_t n, int32_t tick,
                    int32_t max_interval, int32_t window,
                    float pw, float pw_pm, float phi) {
    const int16_t tick16 = (int16_t)tick;
    for (int64_t i = 0; i < n; ++i) {
        const int16_t* __restrict hrow = hb + i * n;
        const int16_t* __restrict h0row = hb0 + i * n;
        int16_t* __restrict lrow = lc + i * n;
        int16_t* __restrict crow = icount + i * n;
        uint8_t* __restrict vrow = live_view + i * n;
        float* __restrict mrow_f32 =
            imean_is_bf16 ? nullptr : (float*)imean + i * n;
        uint16_t* __restrict mrow_bf16 =
            imean_is_bf16 ? (uint16_t*)imean + i * n : nullptr;
        for (int64_t j = 0; j < n; ++j) {
            const bool increased = hrow[j] > h0row[j];
            const int32_t lc_old = lrow[j];
            const int32_t interval_i = tick - lc_old;
            const bool sampled = increased && lc_old != 0 &&
                                 interval_i <= max_interval;
            int32_t cnt = (int32_t)crow[j] + (sampled ? 1 : 0);
            cnt = cnt < window ? cnt : window;
            float mean = mrow_bf16 ? bf16_to_f32(mrow_bf16[j])
                                   : mrow_f32[j];
            if (sampled) {
                const float interval = (float)interval_i;
                float denom = (float)cnt;
                denom = denom > 1.0f ? denom : 1.0f;
                mean = mean + (interval - mean) / denom;
            }
            const int16_t lc_new = increased ? tick16 : (int16_t)lc_old;
            const float elapsed = (float)(tick - (int32_t)lc_new);
            const float cnt_f = (float)cnt;
            bool live = cnt >= 1 &&
                        elapsed * (cnt_f + pw) <=
                            phi * (mean * cnt_f + pw_pm);
            live = live || i == j;
            lrow[j] = lc_new;
            crow[j] = live ? (int16_t)cnt : (int16_t)0;
            vrow[j] = live ? 1 : 0;
            const float mean_out = live ? mean : 0.0f;
            if (mrow_bf16) {
                mrow_bf16[j] = f32_to_bf16(mean_out);
            } else {
                mrow_f32[j] = mean_out;
            }
        }
    }
}

}  // extern "C"
