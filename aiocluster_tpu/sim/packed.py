"""Packed state dtypes: the u4 residual watermark rung and bit-packed
liveness, plus THE sanctioned widen helpers.

The memory ladder (docs/sim.md) ends in storage forms narrower than any
machine dtype:

- ``version_dtype="u4r"`` stores each watermark as a **saturating
  residual below the owner's max_version** — ``r[i, j] =
  clip(max_version[j] - w[i, j], 0, 15)`` — two residuals per byte
  (0.5 B/pair). Residual space is closed under the gossip math: the
  deficit of one handshake direction is ``max(r_recv - r_send, 0)``
  (the per-owner ``max_version`` cancels out of ``w_send - w_recv``),
  an advance of ``a`` key-versions is ``r -= a``, the owner-diagonal
  refresh is ``r = 0``, and full convergence is ``r == 0``. The hot
  path (ops/gossip.py) therefore never unpacks the matrix into HBM: it
  computes on the nibbles inside the XLA fusion (byte-space), and only
  planners/metrics/checkpoint inspection widen — through the helpers
  here.
- ``live_bits=True`` stores the failure detector's live_view as a
  column-packed bitmap (1 bit/pair instead of bool's byte).

Every *deliberate* widening of a packed (or narrow) state field routes
through this module: the static analyzer's ACT025 rule flags
``astype``/int32-promotion on ``w``/``hb_known``/``imean``-named targets
anywhere else in sim//ops/ — a silent widen materializes the wide matrix
in HBM and quietly un-earns the rung's memory claim.
"""

from __future__ import annotations

import jax.numpy as jnp

U4_MAX = 15  # saturating residual ceiling (one nibble)

__all__ = (
    "U4_MAX",
    "imean_f32",
    "is_packed_live",
    "is_packed_w",
    "live_view_bool",
    "pack_bits",
    "pack_u4",
    "residuals_u4",
    "unpack_bits",
    "unpack_u4",
    "watermarks_i32",
)


# -- u4 residual codec (two values per byte, column-packed) -------------------


def pack_u4(values) -> jnp.ndarray:
    """(…, n) integer residuals in [0, 15] -> (…, n // 2) uint8, column
    2k in the low nibble and 2k + 1 in the high nibble. Saturates (does
    not wrap) values above 15 — the rung's overflow discipline; the
    horizon guards keep valid runs below the ceiling."""
    v = jnp.clip(values, 0, U4_MAX).astype(jnp.uint8)
    lo = v[..., 0::2]
    hi = v[..., 1::2]
    return lo | (hi << 4)


def unpack_u4(packed) -> jnp.ndarray:
    """Inverse of :func:`pack_u4`: (…, n // 2) uint8 -> (…, n) int32
    residuals. A SANCTIONED widen — callers materialize the wide form
    only off the hot path (metrics, checkpoint inspection, parity
    tests); ops/gossip.py computes on the nibbles in place instead."""
    lo = (packed & 0xF).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], -1)


def is_packed_w(w) -> bool:
    """Whether a state's watermark matrix is the packed u4 residual
    form. Dtype IS the discriminator: every unpacked rung is signed
    (int32/int16/int8); only the packed rung stores uint8 bytes."""
    return jnp.dtype(w.dtype) == jnp.uint8


# -- liveness bitmap (eight pairs per byte, column-packed) --------------------


def pack_bits(mask) -> jnp.ndarray:
    """(…, n) bool -> (…, n // 8) uint8 bitmap, column j in bit
    j % 8 of byte j // 8."""
    b = mask.astype(jnp.uint8).reshape(*mask.shape[:-1], -1, 8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return (b * weights).sum(axis=-1).astype(jnp.uint8)


def unpack_bits(packed) -> jnp.ndarray:
    """Inverse of :func:`pack_bits`: (…, n // 8) uint8 -> (…, n) bool."""
    bits = (packed[..., :, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return (bits > 0).reshape(*packed.shape[:-1], -1)


def is_packed_live(live_view) -> bool:
    """Whether a state's live_view is the packed bitmap form (unpacked
    states store bool)."""
    return jnp.dtype(live_view.dtype) == jnp.uint8


# -- sanctioned widen helpers -------------------------------------------------
#
# These are the ONLY places a packed/narrow state field may be widened
# by name (analyzer rule ACT025). They exist so consumers that need the
# canonical wide view — planners, metrics, tests, host tooling — share
# one correct decode instead of re-deriving residual semantics.


def watermarks_i32(state, owners=None) -> jnp.ndarray:
    """The watermark matrix as int32 VALUES for any rung.

    Packed states store residuals relative to the owner's max_version,
    so the decode needs the owner ids of this block's columns
    (``owners``: global owner index per local column; defaults to
    ``arange`` — the unsharded layout)."""
    w = state.w
    if not is_packed_w(w):
        return w.astype(jnp.int32)
    r = unpack_u4(w)
    if owners is None:
        owners = jnp.arange(r.shape[-1], dtype=jnp.int32)
    return state.max_version[owners].astype(jnp.int32)[None, :] - r


def residuals_u4(state) -> jnp.ndarray:
    """The stored residuals of a packed state as int32 (raises on
    unpacked rungs — callers wanting values use watermarks_i32)."""
    if not is_packed_w(state.w):
        raise ValueError("state.w is not the packed u4 residual rung")
    return unpack_u4(state.w)


def live_view_bool(state) -> jnp.ndarray:
    """live_view as bool for any rung (unpacks the bitmap form)."""
    lv = state.live_view
    if is_packed_live(lv):
        return unpack_bits(lv)
    return lv


def imean_f32(imean) -> jnp.ndarray:
    """The failure detector's stored interval mean widened to the f32
    the update math runs in (bfloat16 storage rounds only the stored
    value — SimConfig.fd_dtype)."""
    return imean.astype(jnp.float32)
