"""SimCluster: the Cluster-shaped API over the tensor simulator.

Gives the sim backend the same observable surface as the asyncio runtime
(runtime/cluster.py): named nodes, owner-side set/delete, replica views,
liveness, and snapshots — while rounds execute as one jit'd step for the
whole cluster.

Values stay host-side. Each node keeps an append-only **write log**; entry
``v-1`` is the write that created version ``v``. Because deltas ship in
increasing version order (core/cluster_state.py packer), replica ``i``'s
view of owner ``j`` is exactly the first ``w[i, j]`` log entries with
last-writer-wins per key — so materialising a replica is a host-side
prefix fold, no per-key device state needed.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh

from ..core.values import KeyStatus
from ..models.topology import Topology
from .config import SimConfig
from .simulator import Simulator


@dataclass(frozen=True, slots=True)
class _LogEntry:
    key: str
    value: str
    status: KeyStatus


class SimCluster:
    """A whole simulated cluster with per-node KV API parity.

    Long-running clusters compact their write logs with :meth:`compact` —
    the sim analogue of the object model's tombstone GC + watermark
    (core/kvstate.py gc_marked_for_deletion): entries every live replica
    has already absorbed fold into a per-node base view, so host memory
    tracks the live keyspace instead of the full write history.
    """

    def __init__(
        self,
        cfg: SimConfig,
        *,
        names: list[str] | None = None,
        initial_key_values: dict[str, dict[str, str]] | None = None,
        seed: int = 0,
        mesh: Mesh | None = None,
        topology: Topology | None = None,
        trace: bool = False,
    ) -> None:
        n = cfg.n_nodes
        if cfg.version_dtype == "u4r":
            # SimCluster's write flush bumps max_version by direct state
            # surgery, which on the packed residual rung would require a
            # matching residual shift outside sim_step — an invariant too
            # easy to silently break. The KV-faithful host layer targets
            # small-N fidelity anyway; packed rungs are for scale runs.
            raise ValueError(
                "SimCluster does not support version_dtype='u4r' "
                "(host-side write flush bypasses the residual encoding); "
                "use an unpacked rung"
            )
        self.cfg = cfg
        self.names = names or [f"node-{i}" for i in range(n)]
        if len(self.names) != n:
            raise ValueError("names length != n_nodes")
        self._index = {name: i for i, name in enumerate(self.names)}
        self._logs: list[list[_LogEntry]] = [[] for _ in range(n)]
        # Compaction state: log_base[j] versions of owner j live in
        # base_views[j] (a folded prefix); self._logs[j][k] is version
        # log_base[j] + k + 1.
        self._log_base = np.zeros(n, np.int64)
        # base view entry: key -> (value, status, version at fold time).
        # The version is kept so replica_view stays correct for observers
        # whose watermark sits BELOW the compaction base — possible after
        # the dead-node lifecycle forgets an owner (w reset to 0) and a
        # revival re-replicates it from scratch.
        self._base_views: list[dict[str, tuple[str, KeyStatus, int]]] = [
            {} for _ in range(n)
        ]
        self._pending_writes = np.zeros(n, np.int32)

        initial_key_values = initial_key_values or {}
        for name, kvs in initial_key_values.items():
            i = self._index[name]
            for key, value in kvs.items():
                self._logs[i].append(_LogEntry(key, value, KeyStatus.SET))
        # Synthetic keyspace for nodes without explicit initial values, so
        # benchmark configs ("16 KV per node") need no per-key setup.
        if not initial_key_values and cfg.keys_per_node > 0:
            for i in range(n):
                self._logs[i] = [
                    _LogEntry(f"key-{k:04d}", f"{self.names[i]}:{k}", KeyStatus.SET)
                    for k in range(cfg.keys_per_node)
                ]
        versions = np.array([len(log) for log in self._logs], np.int32)
        # Current owner-side view per node, maintained incrementally so
        # writes stay O(1) (replica_view still folds the log prefix).
        self._views: list[dict[str, tuple[str, KeyStatus]]] = [
            self._materialize(log, None) for log in self._logs
        ]

        self.sim = Simulator(
            cfg, seed=seed, mesh=mesh, topology=topology,
            initial_versions=versions, trace=trace,
        )

    # -- owner-side writes (host log + deferred device bump) ------------------

    def _log_write(self, node: str, entry: _LogEntry) -> None:
        i = self._index[node]
        self._logs[i].append(entry)
        self._views[i][entry.key] = (entry.value, entry.status)
        self._pending_writes[i] += 1

    def set(self, node: str, key: str, value: str) -> None:
        current = self._views[self._index[node]].get(key)
        if current is not None and current[1] is KeyStatus.SET and current[0] == value:
            return  # idempotent set, parity with NodeState.set
        self._log_write(node, _LogEntry(key, value, KeyStatus.SET))

    def delete(self, node: str, key: str) -> None:
        if key not in self._views[self._index[node]]:
            return
        self._log_write(node, _LogEntry(key, "", KeyStatus.DELETED))

    def set_with_ttl(self, node: str, key: str, value: str) -> None:
        current = self._views[self._index[node]].get(key)
        if (
            current is not None
            and current[1] is KeyStatus.DELETE_AFTER_TTL
            and current[0] == value
        ):
            return  # idempotent TTL set, parity with NodeState.set_with_ttl
        self._log_write(node, _LogEntry(key, value, KeyStatus.DELETE_AFTER_TTL))

    def get(self, node: str, key: str) -> str | None:
        entry = self._views[self._index[node]].get(key)
        if entry is None or entry[1] in (KeyStatus.DELETED, KeyStatus.DELETE_AFTER_TTL):
            return None
        return entry[0]

    # -- stepping -------------------------------------------------------------

    def _flush_writes(self) -> None:
        if self._pending_writes.any():
            state = self.sim.state
            self.sim.state = state.replace(
                max_version=state.max_version + self._pending_writes
            )
            # Keep the int16 horizon guard sound: the largest per-node
            # bump bounds how much the global max can have grown
            # (conservative — the most-written node may not be the
            # max-version node).
            self.sim.note_max_version_increase(int(self._pending_writes.max()))
            self._pending_writes[:] = 0

    def step(self, rounds: int = 1) -> None:
        """Advance gossip; owner writes issued since the last step become
        visible to the cluster this round (the owner's digest advertises
        the new max_version and peers pull the delta)."""
        self._flush_writes()
        self.sim.run(rounds)

    def run_until_converged(self, max_rounds: int = 100_000) -> int | None:
        self._flush_writes()
        return self.sim.run_until_converged(max_rounds)

    # -- replica observation --------------------------------------------------

    @staticmethod
    def _materialize(
        log: list[_LogEntry], prefix: int | None
    ) -> dict[str, tuple[str, KeyStatus]]:
        entries = log if prefix is None else log[:prefix]
        view: dict[str, tuple[str, KeyStatus]] = {}
        for e in entries:
            view[e.key] = (e.value, e.status)
        return view

    def replica_view(self, observer: str, owner: str) -> dict[str, str]:
        """What ``observer`` currently knows of ``owner``'s live keys.

        A watermark below the compaction base happens when the dead-node
        lifecycle has forgotten the owner (w = 0 -> empty view) or while
        a revived owner is being re-replicated from scratch; folded
        entries then apply only once the watermark reaches their fold
        version — the same prefix-of-current-state a reference re-learner
        receives from a from_version_excluded=0 delta."""
        i, j = self._index[observer], self._index[owner]
        watermark = int(np.asarray(self.sim.state.w[i, j]))
        view: dict[str, tuple[str, KeyStatus]] = {
            k: (v, status)
            for k, (v, status, ver) in self._base_views[j].items()
            if ver <= watermark
        }
        prefix = max(0, watermark - int(self._log_base[j]))
        for e in self._logs[j][:prefix]:
            view[e.key] = (e.value, e.status)
        return {
            k: v for k, (v, status) in view.items() if status is KeyStatus.SET
        }

    # -- log compaction (the GC analogue) -------------------------------------

    def compact(self) -> int:
        """Fold every write-log prefix that ALL replicas (alive or dead,
        any of whom may revive and resume pulling) have already absorbed
        into the per-node base view, dropping tombstoned/TTL keys outright
        — absence and tombstone are indistinguishable below the floor.
        Returns the number of log entries folded away.

        This is the sim's version of the object model's two-part GC
        (owner purge + replicated watermark, core/kvstate.py): the
        cluster-wide min watermark IS the safe GC horizon, available here
        as one device reduction instead of a grace-period protocol.
        """
        self._flush_writes()
        w = np.asarray(self.sim.state.w)
        floors = w.min(axis=0).astype(np.int64)  # includes the owner diag
        folded = 0
        for j in range(len(self._logs)):
            k = int(floors[j] - self._log_base[j])  # noqa: ACT021, ACT023 -- host numpy scalar; w was gathered once above the loop
            if k <= 0:
                continue
            k = min(k, len(self._logs[j]))
            base = self._base_views[j]
            for idx, e in enumerate(self._logs[j][:k]):
                if e.status is KeyStatus.SET:
                    version = int(self._log_base[j]) + idx + 1  # noqa: ACT021, ACT023 -- host-side log counter, no device involved
                    base[e.key] = (e.value, e.status, version)
                else:
                    base.pop(e.key, None)
            self._logs[j] = self._logs[j][k:]
            self._log_base[j] += k
            folded += k
        return folded

    def live_view(self, observer: str) -> list[str]:
        """Node names ``observer`` currently believes are alive (requires
        track_failure_detector)."""
        if not self.cfg.track_failure_detector:
            raise ValueError("failure detector disabled for this sim")
        from .packed import live_view_bool

        i = self._index[observer]
        row = np.asarray(live_view_bool(self.sim.state)[i])
        return [self.names[j] for j in np.flatnonzero(row)]

    def alive_nodes(self) -> list[str]:
        mask = np.asarray(self.sim.state.alive)
        return [self.names[i] for i in np.flatnonzero(mask)]

    def kill(self, node: str) -> None:
        """Crash ``node``: it stops heartbeating and exchanging. Peers'
        failure detectors notice over the following rounds; with
        SimConfig.dead_grace_ticks set, its state is eventually excluded
        from digests and then forgotten (the reference's two-stage GC).
        The sim analogue of stopping a reference process."""
        i = self._index[node]
        st = self.sim.state
        self.sim.state = st.replace(alive=st.alive.at[i].set(False))

    def revive(self, node: str) -> None:
        """Restart a killed ``node`` with its state intact. It resumes
        heartbeating and must re-earn liveness at each observer with
        fresh heartbeat samples (the FD window was reset on death)."""
        i = self._index[node]
        st = self.sim.state
        self.sim.state = st.replace(alive=st.alive.at[i].set(True))

    @property
    def tick(self) -> int:
        return self.sim.tick

    def metrics(self) -> dict[str, np.ndarray]:
        return self.sim.metrics()
