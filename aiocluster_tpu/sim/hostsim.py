"""Native host fast-path simulator for the lean matching profile.

``HostSimulator`` walks the EXACT same trajectory as ``Simulator`` (the
XLA path, and therefore the Pallas kernels and the sharded mesh path,
which are all bit-identity-tested against each other) for configs on its
domain, at 10-100x the XLA-CPU speed on a 1-core host. It exists for one
job: measuring exact rounds-to-convergence at populations where XLA CPU
needs ~10^3 s/round (the 100k-node BASELINE config 5), so the full-scale
convergence count can be MEASURED rather than extrapolated — with the
real XLA path certifying the final round from a checkpoint
(``benchmarks/records/_r4_northstar_run.py``).

Bit-exactness contract, by construction:

- The per-round randomness (grouped matchings, salts) is drawn by
  calling the SAME jax functions ``sim_step`` calls
  (``ops.gossip._grouped_matching``, ``random.fold_in``/``split``/
  ``bits``) with the same keys — tiny (N/8,) arrays, computed on CPU.
- The (N, N) arithmetic runs in ``_hostsim.cpp``, which mirrors each
  XLA elementwise op of ``_budgeted_advance`` + ``_hash_uniform`` at
  f32/int16 precision (the f32 row totals are integers < 2^24, so XLA's
  f32 summation order is immaterial — the int32 accumulation is equal).
- Verified: full-trajectory equality vs ``Simulator`` in
  tests/test_hostsim.py, every round compared to convergence.

Domain: lean profile only — ``pairing="matching"``, proportional budget,
``n % 128 == 0`` (the grouped-matching family), int16 watermarks, no
heartbeats, no failure detector, no churn, no writes, no topology.
``supported()`` is the gate.

Reference anchor: the loop simulated is jettify/aiocluster
server.py:378-495; convergence semantics state.py:310-322.
"""

from __future__ import annotations

import ctypes
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable

import numpy as np

from ..faults import sim as _faults_sim
from ..obs.registry import MetricsRegistry
from ..obs.sim import SimMetrics
from ..obs.trace import TraceWriter
from ..utils.cbuild import build_and_load
from .config import SimConfig

_SRC = Path(__file__).with_name("_hostsim.cpp")
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _build() -> ctypes.CDLL | None:
    """Shared compile-and-cache loader (utils/cbuild.py — the host-ISA
    cache key matters here because of -march=native). The aggressive
    flags change instruction selection, not IEEE f32 results, so the
    build stays bit-exact with the scalar path. ``-ffp-contract=off``
    is load-bearing: the FD pass has a mul+add (mean*count + pw*pm)
    that GCC's default contraction would fuse into an FMA, while XLA
    emits separate f32 multiply and add ops."""
    lib = build_and_load(
        _SRC,
        flags=("-O3", "-march=native", "-funroll-loops", "-ffp-contract=off"),
    )
    if lib is None:
        return None
    lib.acg_hostsim_subexchange.restype = ctypes.c_long
    lib.acg_hostsim_subexchange.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_uint32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_void_p,
    ]
    lib.acg_hostsim_diag.restype = None
    lib.acg_hostsim_diag.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.acg_hostsim_choice_subexchange.restype = None
    lib.acg_hostsim_choice_subexchange.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32, ctypes.c_int32,
    ]
    lib.acg_hostsim_rowmin.restype = None
    lib.acg_hostsim_rowmin.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.acg_hostsim_diag_hb.restype = None
    lib.acg_hostsim_diag_hb.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.acg_hostsim_fd.restype = None
    lib.acg_hostsim_fd.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float,
    ]
    return lib


def _lib() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if not _TRIED:
        _LIB = _build()
        _TRIED = True
    return _LIB


def available() -> bool:
    return _lib() is not None


# -- the support domain, AS DATA ----------------------------------------------
#
# The exact domain on which HostSimulator's trajectory equals
# Simulator's, one row per FEATURE: each row classifies the config into
# a value and names the admissible values. ``supported()`` is the
# conjunction; ``unsupported_features()`` names the offending rows. A
# new memory-ladder rung (or any future feature) extends ONE row here
# — and tests/test_hostsim.py enumerates the whole matrix off this
# table, so the gate and its test cannot drift apart.
#
# Domain rationale (everything mirrors a branch sim_step would take
# differently):
# - profiles: lean (no hb/FD matrices) and — since round 5 — FULL
#   (heartbeats + phi-accrual FD, the reference's operating shape,
#   server.py:471-474 + failure_detector.py:56-128) at int16 hb ticks
#   and int16 sample counters with bool liveness: the FD block is then
#   purely elementwise (_hostsim.cpp::acg_hostsim_fd, op-for-op).
# - "choice" pairing (reference server.py:699 independent sampling) is
#   native for the lean profile only: the responder-side heartbeat
#   absorb would need a scatter the hb kernel doesn't model, and
#   "view" sampling reads live_view.
# - version rungs int16 AND int8 qualify (the kernel stores int8
#   internally either way — lossless while watermarks fit int8, which
#   the keys_per_node row guarantees on this no-writes domain); the
#   packed u4r rung does not (no byte-space form in the C kernel).
# - deficit-total exactness: XLA sums deficits in f32, the kernel in
#   int32; they agree only below 2^24 (_hostsim.cpp header). Max
#   possible row total = K * (n - 1).
# - fault plans lower to per-round link/crash masks the native kernel
#   does not model (docs/faults.md); a plan with no effective behavior
#   injects nothing and stays native.


@dataclass(frozen=True)
class DomainRow:
    """One feature of the native fast path's support domain."""

    feature: str
    allowed: tuple
    value: "Callable[[SimConfig], object]"
    note: str = ""


# (No "profile" row: SimConfig validation already makes lean / full the
# only constructible profiles — an FD without heartbeats is rejected at
# construction — so the hb/FD features below cover the whole space.)
SUPPORT_DOMAIN: tuple[DomainRow, ...] = (
    DomainRow(
        "heartbeat_dtype",
        ("int16", None),
        lambda c: c.heartbeat_dtype if c.track_heartbeats else None,
        "the C FD/hb kernels stamp int16 ticks",
    ),
    DomainRow(
        "icount_dtype",
        ("int16", None),
        lambda c: c.icount_dtype if c.track_failure_detector else None,
        "the C FD kernel's sample counters are int16",
    ),
    DomainRow(
        "live_bits",
        (False,),
        lambda c: c.live_bits,
        "the C FD kernel writes bool liveness, not the bitmap rung",
    ),
    DomainRow(
        "dead_grace",
        (None,),
        lambda c: c.dead_grace_ticks,
        "no dead-node lifecycle (column masks / forgets)",
    ),
    DomainRow(
        "pairing",
        ("matching", "choice-lean"),
        lambda c: (
            "matching"
            if c.pairing == "matching"
            else (
                "choice-lean"
                if (
                    c.pairing == "choice"
                    and c.peer_mode == "alive"
                    and not c.track_heartbeats
                )
                else c.pairing
            )
        ),
        "matching, or lean-profile alive-mode choice",
    ),
    DomainRow(
        "budget_policy",
        ("proportional",),
        lambda c: c.budget_policy,
        "greedy's owner-order cumsum is not mirrored",
    ),
    DomainRow(
        "shape_mod_128",
        (True,),
        lambda c: c.n_nodes % 128 == 0,
        "the grouped-matching family's domain",
    ),
    DomainRow(
        "version_dtype",
        ("int16", "int8"),
        lambda c: c.version_dtype,
        "unpacked narrow rungs; kernel storage is int8 either way",
    ),
    DomainRow(
        "keys_fit_int8",
        (True,),
        lambda c: c.keys_per_node <= 127,
        "watermarks never exceed keys_per_node here (no writes)",
    ),
    DomainRow(
        "deficit_total_f32_exact",
        (True,),
        lambda c: c.keys_per_node * (c.n_nodes - 1) < 2**24,
        "f32 vs int32 deficit-sum agreement bound",
    ),
    DomainRow(
        "churn_free",
        (True,),
        lambda c: c.death_rate == 0.0 and c.revival_rate == 0.0,
        "peer validity masks must be all-true",
    ),
    DomainRow(
        "writes_free",
        (True,),
        lambda c: c.writes_per_round == 0,
        "owner-side writes are not mirrored",
    ),
    DomainRow(
        "fault_plan_inert",
        (True,),
        lambda c: not (
            _faults_sim.plan_affects_links(
                _faults_sim.effective_fault_plan(c.fault_plan, c.heterogeneity)
            )
            or _faults_sim.plan_affects_nodes(c.fault_plan)
            or _faults_sim.plan_affects_byzantine(c.fault_plan)
        ),
        "link/crash/byzantine masks (incl. derived WAN faults) run on "
        "the XLA engine",
    ),
    DomainRow(
        "heterogeneity_inert",
        (True,),
        lambda c: c.heterogeneity is None or not (
            c.heterogeneity.cadence_effective()
            or c.heterogeneity.zone_bias > 0
        ),
        "cadence masks / zone-biased draws are not mirrored in the C "
        "kernels (WAN classes already fail the fault row)",
    ),
    DomainRow(
        "quarantine",
        (False,),
        lambda c: c.quarantine,
        "breaker-quarantine peer masks run on the XLA engine (the C "
        "matching draw carries no per-peer mask)",
    ),
)


def supported(cfg: SimConfig) -> bool:
    """Whether ``cfg`` is inside the native fast path's domain — the
    conjunction of SUPPORT_DOMAIN's rows (see the table above)."""
    return all(row.value(cfg) in row.allowed for row in SUPPORT_DOMAIN)


def unsupported_features(cfg: SimConfig) -> list[str]:
    """The SUPPORT_DOMAIN feature names ``cfg`` violates (empty when
    supported) — for error messages and the domain-matrix test."""
    return [
        row.feature
        for row in SUPPORT_DOMAIN
        if row.value(cfg) not in row.allowed
    ]


class HostSimulator:
    """Drop-in convergence runner for lean matching configs (native C
    inner loop, jax PRNG draws). API mirrors the Simulator subset the
    north-star tooling needs: run / run_until_converged / save."""

    def __init__(
        self,
        cfg: SimConfig,
        *,
        seed: int = 0,
        state_w: np.ndarray | None = None,
        tick: int = 0,
        state_extra: dict[str, np.ndarray] | None = None,
        metrics: MetricsRegistry | None = None,
        metrics_stride: int = 64,
        trace_writer: TraceWriter | None = None,
    ) -> None:
        if not supported(cfg):
            raise ValueError(
                "config outside the host fast-path domain — offending "
                f"features: {unsupported_features(cfg)} "
                "(see hostsim.SUPPORT_DOMAIN)"
            )
        lib = _lib()
        if lib is None:
            raise RuntimeError("native hostsim library failed to build")
        self._lib = lib
        self.cfg = cfg
        self.seed = seed
        n = cfg.n_nodes
        self.max_version = np.full(
            (n,), cfg.keys_per_node, dtype=np.int32
        )
        # The watermark matrix lives as int8 (lossless on this domain:
        # values <= keys_per_node <= 127; supported() gates it) — half
        # the footprint and DRAM traffic of the sim's int16. Comparisons
        # against Simulator state are by VALUE, not dtype.
        if state_w is None:
            # init_state: each node knows only its own keyspace.
            self.w = np.zeros((n, n), dtype=np.int8)
            np.fill_diagonal(self.w, cfg.keys_per_node)
        else:
            assert state_w.shape == (n, n)
            assert state_w.dtype in (np.int8, np.int16), state_w.dtype
            if state_w.dtype == np.int16:
                assert int(state_w.max(initial=0)) <= 127
                state_w = state_w.astype(np.int8)
            self.w = np.ascontiguousarray(state_w)
        self.tick = int(tick)
        self._row_min = np.zeros((n,), dtype=np.int32)
        # Unified telemetry (obs/): the same stride sampler the XLA
        # Simulator uses, engine-labelled "host-native". Each sample costs
        # one pass over w, so the stride bounds the overhead exactly.
        self._obs: SimMetrics | None = None
        if metrics is not None or trace_writer is not None:
            self._obs = SimMetrics(
                metrics, trace_writer, stride=metrics_stride,
                engine="host-native", start_tick=self.tick,
            )
        # Full-profile state (mirrors init_state's hb/FD matrices at the
        # Simulator's exact dtypes — the bit-identity tests compare these
        # arrays directly). ``state_extra`` restores them on resume.
        self._track_hb = cfg.track_heartbeats
        self._track_fd = cfg.track_failure_detector
        # Shared by BOTH profile blocks below — hoisted so the FD block
        # never depends on the heartbeat block having run (SimConfig
        # currently rejects FD-without-heartbeats, but that invariant
        # must not be what keeps this code a going concern).
        extra = state_extra or {}

        def take(name, default):
            arr = extra.get(name)
            if arr is None:
                return default
            # Hard errors, not asserts: under python -O a
            # wrong-shape array would flow straight into the
            # raw-pointer C kernels.
            if arr.shape != default.shape or arr.dtype != default.dtype:
                raise ValueError(
                    f"checkpoint {name}: {arr.dtype}{arr.shape} != "
                    f"expected {default.dtype}{default.shape}"
                )
            return np.ascontiguousarray(arr)

        if self._track_hb:
            hb0 = np.zeros((n, n), np.int16)
            np.fill_diagonal(hb0, 1)
            self.hb = take("hb", hb0)
            self.heartbeat = take(
                "heartbeat", np.ones((n,), np.int32)
            )
        if self._track_fd:
            self._fd_bf16 = cfg.fd_dtype == "bfloat16"
            if self._fd_bf16:
                import ml_dtypes

                imean_dtype = np.dtype(ml_dtypes.bfloat16)
            else:
                imean_dtype = np.dtype(np.float32)
            self.last_change = take(
                "last_change", np.zeros((n, n), np.int16)
            )
            self.imean = take("imean", np.zeros((n, n), imean_dtype))
            self.icount = take("icount", np.zeros((n, n), np.int16))
            self.live_view = take("live_view", np.eye(n, dtype=bool))
        # Same key derivation as Simulator: base key from the seed; the
        # per-round salt is random.bits(base_key) exactly as sim_step
        # computes it (gossip.py run_salt).
        import jax

        # The tiny PRNG draws below only need CPU-placed arrays, but on
        # this image backend init may hang forever on a down accelerator
        # tunnel, so standalone callers (CLI --host-native, the northstar
        # scripts) want the process pinned to CPU. Pinning is a
        # process-GLOBAL side effect, so do it only while no backend is
        # initialized yet: a library user who already brought up an
        # accelerator keeps it (ADVICE r4, medium).
        try:
            from jax._src import xla_bridge as _xb

            uninitialized = not _xb.backends_are_initialized()
        except Exception:
            # Private API (no stability guarantee): if it moves, fall
            # back to the old unconditional pin rather than breaking
            # construction.
            uninitialized = True
        if uninitialized:
            jax.config.update("jax_platforms", "cpu")
        from jax import random

        self._key = random.key(seed)
        self._run_salt = int(
            np.asarray(random.bits(self._key, dtype=np.uint32))
        )

    # -- round advancement ----------------------------------------------------

    def _round_pairs(self, tick: int) -> list[tuple[np.ndarray, np.ndarray]]:
        """The fanout matchings for one round, drawn with sim_step's own
        key schedule and matching family (ops.gossip._grouped_matching)."""
        from jax import random

        from ..ops.gossip import _grouped_matching

        round_key = random.fold_in(self._key, tick)
        _churn_key, peer_key = random.split(round_key)
        out = []
        n = self.cfg.n_nodes
        idx = np.arange(n, dtype=np.int32)
        for c in range(self.cfg.fanout):
            ck = random.fold_in(peer_key, c)
            _gm, _c8, p = _grouped_matching(ck, n)
            p = np.asarray(p, dtype=np.int32)  # noqa: ACT021 -- deliberate: the host-native path pulls each draw to host memory
            a = idx[idx < p]  # self-pairs (p[i] == i) are no-op exchanges
            out.append((a, p[a]))
        return out

    def _round_peers(self, tick: int) -> np.ndarray:
        """(n, fanout) independent peer draws for 'choice' pairing, via
        sim_step's own select_peers with the identical key schedule."""
        from jax import numpy as jnp
        from jax import random

        from ..ops.gossip import select_peers

        round_key = random.fold_in(self._key, tick)
        _churn_key, peer_key = random.split(round_key)
        view_salt = jnp.int32(-(tick + 1) * self.cfg.fanout)
        peers = select_peers(
            peer_key, jnp.ones((self.cfg.n_nodes,), bool), None, self.cfg,
            None, None, axis_name=None, view_salt=view_salt,
            run_salt=jnp.uint32(self._run_salt),
        )
        return np.asarray(peers, dtype=np.int32)

    def _step(self, track: bool) -> bool:
        """One full gossip round in place; returns the post-round
        all-converged flag when ``track`` (else False)."""
        tick = self.tick + 1
        n = self.cfg.n_nodes
        if self.cfg.pairing == "choice":
            return self._step_choice(tick, track)
        hb_ptr = None
        hb0 = None
        if self._track_hb:
            # heartbeat = tick + 1 (starts at 1), so the last safe tick
            # is 32766 — at 32767 the owner's self-heartbeat would wrap
            # to int16 minimum on the diagonal refresh.
            if tick + 1 >= 2**15:
                raise RuntimeError(
                    "tick horizon exceeds the int16 heartbeat matrices"
                )
            # Owner-side activity: every node is alive on this domain.
            self.heartbeat += 1
            self._lib.acg_hostsim_diag_hb(
                self.hb.ctypes.data, n, self.heartbeat.ctypes.data
            )
            hb_ptr = self.hb.ctypes.data
        self._lib.acg_hostsim_diag(
            self.w.ctypes.data, n, self.max_version.ctypes.data
        )
        if self._track_fd:
            # The FD compares against the round-start matrix (post
            # diagonal refresh, pre exchanges) — sim_step's
            # hb_round_start. Reuse one preallocated buffer: a fresh
            # (n, n) copy per round would be a multi-GB mmap+fault
            # cycle at scale.
            if not hasattr(self, "_hb0"):
                self._hb0 = np.empty_like(self.hb)
            hb0 = self._hb0
            np.copyto(hb0, self.hb)
        pairs = self._round_pairs(tick)
        fan = self.cfg.fanout
        for c, (a, b) in enumerate(pairs):
            last = c == fan - 1
            salt = tick * (2 * fan) + 2 * c  # gossip.py sub_salt(c, 0)
            self._lib.acg_hostsim_subexchange(
                self.w.ctypes.data, hb_ptr, n,
                a.ctypes.data, b.ctypes.data, len(a),
                np.int32(salt), np.uint32(self._run_salt),
                self.cfg.budget,
                1 if (track and last) else 0,
                self._row_min.ctypes.data,
            )
        if self._track_fd:
            cfg = self.cfg
            self._lib.acg_hostsim_fd(
                self.hb.ctypes.data, hb0.ctypes.data,
                self.last_change.ctypes.data,
                self.imean.ctypes.data, 1 if self._fd_bf16 else 0,
                self.icount.ctypes.data, self.live_view.ctypes.data,
                n, np.int32(tick),
                np.int32(cfg.max_interval_ticks),
                np.int32(cfg.window_ticks),
                # The f32 scalars exactly as XLA folds them: pw and phi
                # are f32 casts of the config doubles; pw*pm multiplies
                # in doubles FIRST (Python) and casts the product.
                float(np.float32(cfg.prior_weight)),
                float(np.float32(cfg.prior_weight * cfg.prior_mean_ticks)),
                float(np.float32(cfg.phi_threshold)),
            )
        self.tick = tick
        if not track:
            return False
        # all_converged_flag semantics for the lean profile: every row's
        # watermark has reached every owner's max_version (all alive).
        # Rows untouched this round (self-pairs) keep a stale _row_min;
        # with n % 128 == 0 the group count is even, so grouped
        # matchings have no self-pairs — but guard anyway.
        touched = np.zeros((n,), dtype=bool)
        a, b = pairs[-1]
        touched[a] = True
        touched[b] = True
        if not touched.all():
            untouched = ~touched
            self._row_min[untouched] = self.w[untouched].min(axis=1)
        return bool((self._row_min >= self.max_version).all())

    def _step_choice(self, tick: int, track: bool) -> bool:
        """One 'choice'-pairing round: fanout independent sub-exchanges,
        each reading a pre-sub-exchange snapshot (the XLA loop carry)."""
        n = self.cfg.n_nodes
        fan = self.cfg.fanout
        self._lib.acg_hostsim_diag(
            self.w.ctypes.data, n, self.max_version.ctypes.data
        )
        peers = self._round_peers(tick)
        if not hasattr(self, "_w_pre"):
            self._w_pre = np.empty_like(self.w)
        for c in range(fan):
            np.copyto(self._w_pre, self.w)
            p = np.ascontiguousarray(peers[:, c])
            base = tick * (2 * fan) + 2 * c  # sub_salt(0, d) + 2c
            self._lib.acg_hostsim_choice_subexchange(
                self.w.ctypes.data, self._w_pre.ctypes.data, n,
                p.ctypes.data, np.int32(base), np.int32(base + 1),
                np.uint32(self._run_salt), self.cfg.budget,
            )
        self.tick = tick
        if not track:
            return False
        # The scatter pass can touch any row after its min was last
        # known; one dedicated min pass gives the exact flag.
        self._lib.acg_hostsim_rowmin(
            self.w.ctypes.data, n, self._row_min.ctypes.data
        )
        return bool((self._row_min >= self.max_version).all())

    def run(self, rounds: int) -> None:
        for _ in range(rounds):
            self._step(track=False)
            self._maybe_sample()

    def run_until_converged(
        self,
        max_rounds: int = 100_000,
        on_round=None,
    ) -> int | None:
        """Exact first round at which full convergence holds (checked
        every round, like Simulator's in-chunk tracker). ``on_round`` is
        an optional callback(tick) between rounds — checkpoint/pause
        hooks for multi-hour runs."""
        if self.tick == 0:
            pass  # fresh cluster: trivially unconverged (w off-diag 0)
        elif bool((self.w.min(axis=1) >= self.max_version).all()):
            return self.tick
        while self.tick < max_rounds:
            converged = self._step(track=True)
            self._maybe_sample()
            if converged:
                return self.tick
            if on_round is not None:
                on_round(self.tick)
        return None

    # -- telemetry ------------------------------------------------------------

    def _maybe_sample(self) -> None:
        if self._obs is None or not self._obs.due(self.tick):
            return
        self._sample_now()

    def _sample_now(self) -> None:
        k = self.cfg.keys_per_node
        col_min = self.w.min(axis=0)
        w_min = int(self.w.min())
        self._obs.record(
            self.tick,
            {
                "converged_owners": int((col_min >= k).sum()),
                "min_fraction": w_min / k,
                "mean_fraction": float(self.w.mean(dtype=np.float64)) / k,
                "alive_count": self.cfg.n_nodes,
                # max_version is uniform on this domain (no writes), so
                # the worst pair lag collapses to max - global min, and
                # w <= k everywhere makes the plain sum the capped one.
                "version_spread": int(self.max_version.max()) - w_min,
                "kv_known": float(self.w.sum(dtype=np.int64)),
            },
        )

    def flush_metrics(self) -> list[dict]:
        """Push buffered samples into the registry/trace; returns the
        series (empty when obs was not enabled). Host arrays mean no
        device sync — this exists for API symmetry with Simulator."""
        if self._obs is None:
            return []
        if self._obs.last_tick != self.tick:
            self._sample_now()  # close the series at the final state
        return self._obs.flush()

    # -- checkpointing --------------------------------------------------------

    _EXTRA_FIELDS = ("hb", "heartbeat", "last_change", "imean", "icount",
                     "live_view")

    def save(self, path: str) -> None:
        """Raw checkpoint (np.save of the int8 matrix — 10 GB at the
        100k scale — plus a JSON sidecar), cheap enough to take every
        few dozen rounds. Full-profile runs save each hb/FD matrix as
        its own sidecar .npy (one np.save per array keeps peak memory
        flat — an npz would buffer a second copy)."""
        tmp = f"{path}.w.tmp.npy"
        np.save(tmp, self.w)
        os.replace(tmp, f"{path}.w.npy")
        extras = [f for f in self._EXTRA_FIELDS if hasattr(self, f)]
        for name in extras:
            arr = getattr(self, name)
            if arr.dtype == bool:
                arr = arr.view(np.uint8)
            elif arr.dtype.name == "bfloat16":
                arr = arr.view(np.uint16)
            np.save(f"{path}.{name}.tmp.npy", arr)
            os.replace(f"{path}.{name}.tmp.npy", f"{path}.{name}.npy")
        meta = {
            "tick": self.tick,
            "seed": self.seed,
            "n_nodes": self.cfg.n_nodes,
            "keys_per_node": self.cfg.keys_per_node,
            "fanout": self.cfg.fanout,
            "budget": self.cfg.budget,
            "extras": extras,
            "fd_dtype": self.cfg.fd_dtype if self._track_fd else None,
            # Rung provenance: the ladder makes the same VALUES
            # representable several ways; a resume must not silently
            # reinterpret a checkpoint across rungs.
            "version_dtype": self.cfg.version_dtype,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        with open(f"{path}.json.tmp", "w") as f:
            json.dump(meta, f)
        os.replace(f"{path}.json.tmp", f"{path}.json")

    @classmethod
    def resume(cls, path: str, cfg: SimConfig) -> "HostSimulator":
        with open(f"{path}.json") as f:
            meta = json.load(f)
        for field in ("n_nodes", "keys_per_node", "fanout", "budget"):
            if meta[field] != getattr(cfg, field):
                raise ValueError(
                    f"checkpoint {field}={meta[field]} != cfg "
                    f"{getattr(cfg, field)}"
                )
        # Loud cross-rung rejection (checkpoints written before the
        # ladder carry no rung field and were int16-only).
        saved_rung = meta.get("version_dtype", "int16")
        if saved_rung != cfg.version_dtype:
            raise ValueError(
                f"checkpoint version_dtype={saved_rung!r} != cfg "
                f"{cfg.version_dtype!r} (cross-rung resume refused; load "
                "under the rung that wrote it)"
            )
        saved = set(meta.get("extras", []))
        wanted = {
            f
            for f in cls._EXTRA_FIELDS
            if (cfg.track_heartbeats and f in ("hb", "heartbeat"))
            or (
                cfg.track_failure_detector
                and f in ("last_change", "imean", "icount", "live_view")
            )
        }
        if saved != wanted:
            raise ValueError(
                f"checkpoint profile {sorted(saved)} != cfg profile "
                f"{sorted(wanted)}"
            )
        if wanted and meta.get("fd_dtype") not in (None, cfg.fd_dtype):
            raise ValueError(
                f"checkpoint fd_dtype={meta['fd_dtype']} != cfg {cfg.fd_dtype}"
            )
        extra = {}
        for name in saved:
            arr = np.load(f"{path}.{name}.npy")
            if name == "live_view":
                arr = arr.view(bool)
            elif name == "imean" and cfg.fd_dtype == "bfloat16":
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            extra[name] = arr
        w = np.load(f"{path}.w.npy")
        return cls(
            cfg, seed=meta["seed"], state_w=w, tick=meta["tick"],
            state_extra=extra or None,
        )
