"""Static configuration for the TPU gossip simulator.

Maps the object-model knobs (core/config.py, reference entities.py:85-115)
into tick-time tensor equivalents. All fields are static (hashable) so the
config can be a jit static argument; everything data-dependent lives in
SimState.

Key modeling decisions (SURVEY.md §7 "hard parts"):

- **Time is measured in gossip ticks**, not wall-clock: one step = one
  round for the entire cluster. The failure detector's intervals/means are
  re-derived in tick units (``prior_mean_ticks`` defaults to the
  reference's 5 s prior over its 1 s round interval).
- **The MTU becomes a key-version budget**: the byte-accurate greedy
  packer (core/cluster_state.py) sends versions in increasing order until
  the MTU; the sim advances watermarks by at most ``budget`` versions per
  exchange, allocated greedily in owner order — same observable shape,
  documented divergence from byte-exact packing.
- **Peer sampling is with replacement** (a gather of categorical draws);
  the reference samples without replacement (server.py:699). For
  fanout ≪ N the collision probability is negligible, and a self/dead
  pick degenerates to a no-op exchange, which also models connection
  failure.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..faults.plan import FaultPlan
from ..models.topology import Heterogeneity


@dataclass(frozen=True, slots=True, eq=True)
class SimConfig:
    """Static shape/tuning parameters for one simulated cluster."""

    n_nodes: int
    keys_per_node: int = 16
    fanout: int = 3  # gossip_count
    budget: int = 64  # key-versions per exchange (the "MTU")
    writes_per_round: int = 0  # ongoing owner writes per node per tick

    # Failure detection (tick-time phi-accrual). When False, the sim tracks
    # only KV convergence — the memory-lean mode for 100k-node runs.
    track_failure_detector: bool = True
    phi_threshold: float = 8.0
    prior_mean_ticks: float = 5.0  # initial_interval in rounds
    prior_weight: float = 5.0
    max_interval_ticks: int = 10
    window_ticks: int = 1000  # caps the sample count like the ring buffer

    # Churn: per-tick probability that an alive node dies / a dead node
    # rejoins (BASELINE.json config 3: "5% node churn/round").
    death_rate: float = 0.0
    revival_rate: float = 0.0

    # Two-stage dead-node lifecycle in ticks (reference
    # failure_detector.py:108-128 + server.py:328-329, our
    # core/failure.py). When set (requires the failure detector), each
    # OBSERVER row runs the reference's per-node FD lifecycle against its
    # own belief: once it has believed a node dead for
    # ``dead_grace_ticks // 2`` it stops propagating that node's state
    # (the digest-exclusion analogue — its exchanges mask that owner's
    # column), and at ``dead_grace_ticks`` it forgets the node entirely
    # (watermark, heartbeat knowledge and FD window reset — the
    # ClusterState.remove_node analogue). A node revived in time re-earns
    # liveness with fresh heartbeat samples and is un-scheduled, exactly
    # like the reference's dead-set discard. None disables the lifecycle
    # (dead state is kept and re-propagated forever). The tick values
    # stored in dead_since must fit heartbeat_dtype — same horizon
    # contract as heartbeats.
    dead_grace_ticks: int | None = None

    # Peer selection — only consulted when pairing="choice" (the
    # matching/permutation pairings match over ALL nodes; dead matches
    # no-op, standing in for the reference's failed connections):
    # "alive" samples uniformly over truly-alive nodes (scalable, matches
    # epidemic-sim practice); "view" samples from each node's own
    # live_view row (FD-faithful, needs track_failure_detector).
    peer_mode: str = "alive"

    # Pairing of one sub-exchange:
    # - "matching" (default): a random perfect matching (p is an
    #   involution), so one bidirectional handshake per node per
    #   sub-exchange — HALF the full-matrix traffic of "permutation" per
    #   sub-exchange. The most faithful model of the reference's paired
    #   Syn/SynAck/Ack exchange, and the fastest per-round path; measured
    #   on a v5e chip at 10k nodes it converges in the same number of
    #   rounds as "permutation" at 1.3x the round rate.
    # - "permutation": each node initiates one handshake (with p[i]) and
    #   responds to exactly one (from inv[i]). Gather-only on TPU; both
    #   exchanges are computed from the pre-round state and joined with an
    #   elementwise max — the same semantics as the reference's 3-way
    #   handshake, where both sides' deltas derive from the pre-handshake
    #   digests.
    # - "choice": every node independently samples a peer (reference
    #   server.py:699 semantics: inbound load varies); needs a scatter-max
    #   for the responder side. Topology (adjacency) runs force this mode.
    pairing: str = "matching"

    # Dtypes for the big (N, N) knowledge matrices — the memory-ladder
    # rungs (docs/sim.md "memory ladder"). "int32" is always safe;
    # each narrower rung is bit-identical in trajectory whenever the
    # stored quantity fits, and init_state / the horizon guards enforce
    # the fit loudly instead of wrapping:
    #
    # - "int16": watermarks need max total versions per owner
    #   (initial + writes_per_round * horizon) < 32768; heartbeat
    #   knowledge needs the run horizon in ticks < 32768.
    # - "int8": the same bounds at < 128 — the lean-ladder rung the
    #   fused pairs kernel serves natively (values widen transiently in
    #   VMEM, never in HBM).
    # - "u4r" (version_dtype only): watermarks stored as a SATURATING
    #   RESIDUAL below the owner's max_version, two per byte
    #   (0.5 B/pair; sim/packed.py). Residual space is closed under the
    #   gossip math, so the XLA path computes on the nibbles inside the
    #   fusion and never materializes a wide matrix in HBM. Bound: max
    #   total versions per owner <= 15. Packed-rung restrictions
    #   (validated below): matching/permutation pairing only (the
    #   choice path's scatter-max has no byte-space form), proportional
    #   budget, no dead-node lifecycle, even n_nodes. On its lean
    #   (heartbeat-free) matching domain the rung rides the pairs
    #   kernel's VMEM nibble codec (ops/pallas_pull.py — DMA the packed
    #   bytes, widen/advance/saturate/repack in VMEM, in place); off
    #   that domain (heartbeats tracked, a pinned m8 variant, widths
    #   off the 256-alignment) it runs byte-space XLA, loudly
    #   (ops/gossip.pallas_fallbacks reason "packed_dtype").
    version_dtype: str = "int32"
    heartbeat_dtype: str = "int32"

    # Storage dtype of the failure detector's interval means. "bfloat16"
    # halves that matrix; the update math always runs in float32, so only
    # the stored mean is rounded (≤0.4% relative) — far inside the
    # phi-threshold's slack.
    fd_dtype: str = "float32"

    # Failure-detector bookkeeping rungs (the shrunk-FD ladder toward
    # 9.125 B/pair): "int8" icount needs window_ticks + 1 < 128 (the
    # kernel-order increment-then-clamp contract below); live_bits
    # packs live_view as a column bitmap (1 bit/pair; n_nodes % 8 == 0,
    # not peer_mode="view" — the view draw reads bool rows). The FUSED
    # pairs epilogue models both shrunk forms natively (int8 counters
    # widen per tile in VMEM, the live bitmap is written straight from
    # the kernel); only the STANDALONE FD kernel (non-pairs pull paths)
    # remains unpacked-only — those configs run the FD phase on XLA,
    # loudly (pallas_fallbacks reason "fd_packed_bookkeeping") while
    # the pull kernels stay engaged.
    icount_dtype: str = "int16"
    live_bits: bool = False

    # How an exchange's key-version budget is split across stale owners:
    # - "proportional" (default): every stale owner's deficit is scaled by
    #   budget/total and rounded with a dithered Bernoulli — the total per
    #   exchange equals the budget in expectation (overshoot is a
    #   binomial O(sqrt(stale owners)) tail, not a hard cap). Two cheap
    #   passes, no scan.
    # - "greedy": exact prefix allocation in global owner order — the
    #   reference packer's observable behavior (state.py:370-413), costs a
    #   full cumsum per exchange.
    budget_policy: str = "proportional"

    # Heartbeat knowledge matrix; required by the failure detector. Turn
    # off (with the FD) for memory-lean pure-convergence runs at 100k.
    track_heartbeats: bool = True

    # Deterministic fault injection (docs/faults.md): the same FaultPlan
    # the runtime compiles into its transport wrapper lowers here to
    # per-round link masks (partitions/drops/delays mask sub-exchanges
    # exactly like the churn mask masks dead pairs) and crash windows
    # (heartbeats/writes freeze, exchanges no-op, then the node returns).
    # Plan times are in TICKS; node sets must be fraction-addressed
    # (validated below). The plan is part of this (hashable) config, so
    # it is a jit static argument like everything else. Fault-INJECTING
    # runs take the XLA path — the fused Pallas kernels carry no link
    # mask (pallas_path_engaged and hostsim.supported gate on the plan
    # carrying effective behavior; a no-op plan keeps the fast paths).
    fault_plan: FaultPlan | None = None

    # Breaker quarantine (docs/robustness.md): the runtime's per-peer
    # circuit breaker lowered to a per-round peer-selection mask
    # (faults/sim.quarantine_mask) — peers a link fault makes
    # effectively unreachable are removed from the target draw
    # ``quarantine_open_after`` ticks into the fault window (the
    # failures-to-open threshold at one contact per round), so the
    # fleet stops burning sub-exchanges on them, exactly like the
    # runtime under the same plan. Requires pairing="choice" with
    # peer_mode="alive" (the draw the mask biases; matchings pair over
    # all nodes and the view draw has its own belief mask) and no
    # topology. False (the default) keeps the peer draw — and every
    # existing trace — byte-identical.
    quarantine: bool = False
    quarantine_open_after: int = 3

    # Heterogeneity (models/topology.Heterogeneity, docs/faults.md):
    # per-node gossip-cadence classes (a class-k node initiates every
    # k-th tick; a "matching" pair exchanges when either side is
    # on-cadence, the directional "permutation"/"choice" pairings gate
    # each handshake by its initiator), WAN latency/loss
    # classes (lowered as derived LinkFaults appended to the effective
    # fault plan, so they ride the exact link-mask machinery), and
    # zone-aware peer bias (choice pairing only: with probability
    # zone_bias a draw stays in the node's own zone). Hashable, so it
    # is jit-static like the plan. None (or the all-defaults instance)
    # changes nothing. Effective WAN classes take the XLA path like any
    # fault plan; cadence masks fold into pair validity, which the
    # fused kernels carry natively.
    heterogeneity: Heterogeneity | None = None

    # Run each sub-exchange through the fused Pallas TPU kernel
    # (ops/pallas_pull.py): one pass over HBM instead of several, exact
    # same results (the XLA matching path shares the kernel's
    # grouped-matching family whenever n % 128 == 0), measured 1.3x the
    # round rate at 10k nodes on a v5e chip. "auto" (default) enables it
    # on real TPU backends and stays on XLA elsewhere (interpret mode is
    # only for tests); True forces it (interpreted off-TPU), False
    # disables. Matching pairing, n % 128 == 0, proportional budget, no
    # dead-node lifecycle qualify — other configs use the XLA path
    # regardless. Column-sharded runs qualify too when every shard's
    # column block is lane-aligned (n_local % 128 == 0): a two-pass
    # kernel + one psum reproduces the global budget exactly, and a
    # one-shard mesh short-circuits to the single-pass form. Both
    # storage profiles do: with heartbeats the kernel fuses w and hb;
    # the lean convergence-only profile runs a w-only variant.
    use_pallas: bool | str = "auto"

    # Which fused-pull kernel implementation serves eligible matching
    # sub-exchanges (only consulted when the Pallas path is engaged):
    # - "auto" (default): the pair-fused kernel (fused_pull_pairs — each
    #   row read once and written once per sub-exchange, 2/3 the HBM
    #   traffic of the single-pass form) whenever the shape allows,
    #   falling back to the single-pass kernel ("m8") otherwise — e.g.
    #   multi-shard meshes, or shapes whose pair tiles exceed VMEM.
    # - "m8" / "pairs": pin one implementation (benchmark A/B). "pairs"
    #   still falls back to m8 off its domain. All variants are
    #   bit-identical (tests/test_pallas_pairs.py), so this knob never
    #   changes a trajectory.
    pallas_variant: str = "auto"

    # The streaming failure-detector kernel (ops/pallas_fd.py),
    # independently of the pull kernel: "auto" (default) follows
    # ``use_pallas``'s resolution; False pins the FD phase to the XLA
    # block while the pull kernel stays engaged — the A/B seam for
    # measuring what the FD kernel pays on chip (and a kill switch,
    # mirroring pallas_variant). True forces it (interpreted off-TPU).
    # Bit-identical either way (tests/test_pallas_fd.py), so this knob
    # never changes a trajectory.
    use_pallas_fd: bool | str = "auto"

    def __post_init__(self) -> None:
        if self.n_nodes < 2:
            raise ValueError("need at least 2 nodes")
        if self.peer_mode not in ("alive", "view"):
            raise ValueError(f"unknown peer_mode: {self.peer_mode}")
        if self.peer_mode == "view" and not self.track_failure_detector:
            raise ValueError("peer_mode='view' requires track_failure_detector")
        if self.pairing not in ("permutation", "matching", "choice"):
            raise ValueError(f"unknown pairing: {self.pairing}")
        if self.version_dtype not in ("int32", "int16", "int8", "u4r"):
            raise ValueError(f"unknown version_dtype: {self.version_dtype}")
        if self.heartbeat_dtype not in ("int32", "int16", "int8"):
            raise ValueError(f"unknown heartbeat_dtype: {self.heartbeat_dtype}")
        if self.fd_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown fd_dtype: {self.fd_dtype}")
        if self.icount_dtype not in ("int16", "int8"):
            raise ValueError(f"unknown icount_dtype: {self.icount_dtype}")
        # The kernel increments the sample counter BEFORE clamping to
        # the cap, so window_ticks + 1 must also fit the counter dtype.
        if self.window_ticks >= 2**15 - 1:
            raise ValueError("window_ticks must fit the int16 sample counter")
        if self.icount_dtype == "int8" and self.window_ticks >= 2**7 - 1:
            raise ValueError(
                "window_ticks must fit the int8 sample counter "
                "(icount_dtype='int8' needs window_ticks <= 126)"
            )
        if self.version_dtype == "u4r":
            # The packed residual rung's domain (sim/packed.py): the
            # choice path's responder scatter-max has no byte-space
            # form, the greedy policy's global cumsum would interleave
            # nibbles, the lifecycle's forget rewrites w to 0 = a
            # residual of max_version (unrepresentable), and packing
            # pairs columns.
            if self.pairing == "choice":
                raise ValueError(
                    "version_dtype='u4r' requires pairing='matching' or "
                    "'permutation' (the choice scatter path is unpacked-only)"
                )
            if self.budget_policy != "proportional":
                raise ValueError(
                    "version_dtype='u4r' requires budget_policy="
                    "'proportional' (greedy's owner-order cumsum has no "
                    "byte-space form)"
                )
            if self.dead_grace_ticks is not None:
                raise ValueError(
                    "version_dtype='u4r' does not support the dead-node "
                    "lifecycle (forgetting rewrites w outside the "
                    "residual range)"
                )
            if self.n_nodes % 2 != 0:
                raise ValueError(
                    "version_dtype='u4r' packs two owners per byte; "
                    "n_nodes must be even"
                )
        if self.live_bits:
            if not self.track_failure_detector:
                raise ValueError("live_bits requires track_failure_detector")
            if self.peer_mode == "view":
                raise ValueError(
                    "live_bits with peer_mode='view' is unsupported (the "
                    "view draw samples from bool live rows)"
                )
            if self.n_nodes % 8 != 0:
                raise ValueError(
                    "live_bits packs eight owners per byte; n_nodes must "
                    "be a multiple of 8"
                )
        if self.peer_mode == "view" and self.pairing != "choice":
            raise ValueError(
                "peer_mode='view' requires pairing='choice' (a matching "
                "cannot honour per-node live views)"
            )
        if self.budget_policy not in ("proportional", "greedy"):
            raise ValueError(f"unknown budget_policy: {self.budget_policy}")
        if self.fault_plan is not None:
            if not isinstance(self.fault_plan, FaultPlan):
                raise ValueError("fault_plan must be a faults.FaultPlan")
            self.fault_plan.check_sim_compatible()
            if self.fault_plan.byzantine and self.version_dtype == "u4r":
                raise ValueError(
                    "byzantine fault kinds are unpacked-only (the guard "
                    "masks are owner-column selects with no byte-space "
                    "form); version_dtype='u4r' cannot run them"
                )
            amnesia = any(
                cr.recovery == "amnesia" for cr in self.fault_plan.crashes
            )
            if amnesia and self.version_dtype == "u4r":
                raise ValueError(
                    "recovery='amnesia' crash windows are unpacked-only "
                    "(the knowledge-row reset writes w=0, which in "
                    "residual space is a per-owner value, not a "
                    "constant); version_dtype='u4r' cannot run them — "
                    "use recovery='warm' or a wider rung"
                )
            if amnesia and self.live_bits:
                raise ValueError(
                    "recovery='amnesia' crash windows do not support "
                    "live_bits (the live-view row reset has no packed "
                    "form); use recovery='warm' or live_bits=False"
                )
        if self.quarantine:
            if self.pairing != "choice":
                raise ValueError(
                    "quarantine requires pairing='choice' (the matching/"
                    "permutation pairings draw over all nodes; only the "
                    "choice draw can honour a per-peer quarantine mask)"
                )
            if self.peer_mode != "alive":
                raise ValueError(
                    "quarantine requires peer_mode='alive' (the view-mode "
                    "Gumbel-max draw carries its own belief mask)"
                )
            if self.quarantine_open_after < 0:
                raise ValueError("quarantine_open_after must be >= 0")
            if self.heterogeneity is not None and any(
                k != 1 for k in self.heterogeneity.gossip_every
            ):
                raise ValueError(
                    "quarantine cannot combine with heterogeneity cadence "
                    "classes: a class-k initiator accumulates its "
                    "failures-to-open k times slower, but the mask opens "
                    "at a fixed start+open_after for every initiator — "
                    "the sim would quarantine more than the runtime does"
                )
        if self.heterogeneity is not None:
            if not isinstance(self.heterogeneity, Heterogeneity):
                raise ValueError(
                    "heterogeneity must be a models.topology.Heterogeneity"
                )
            if self.heterogeneity.zone_bias > 0 and self.pairing != "choice":
                raise ValueError(
                    "zone_bias requires pairing='choice' (a global "
                    "matching cannot honour per-node zone preference)"
                )
            if self.heterogeneity.zone_bias > 0 and self.peer_mode != "alive":
                raise ValueError(
                    "zone_bias requires peer_mode='alive' (the view-mode "
                    "Gumbel-max draw carries no zone bias; refusing "
                    "beats silently sampling unbiased)"
                )
        if self.track_failure_detector and not self.track_heartbeats:
            raise ValueError("failure detector requires track_heartbeats")
        if self.dead_grace_ticks is not None:
            if not self.track_failure_detector:
                raise ValueError(
                    "dead_grace_ticks requires track_failure_detector"
                )
            if self.dead_grace_ticks < 2:
                raise ValueError("dead_grace_ticks must be >= 2")
        # Identity checks, not `in (True, False, "auto")`: equality would
        # admit 1/0/np.bool_, which the sim_step gate's `is True` test
        # would then silently treat as False.
        if not (
            self.use_pallas is True
            or self.use_pallas is False
            or self.use_pallas == "auto"
        ):
            raise ValueError(f"unknown use_pallas: {self.use_pallas!r}")
        if self.pallas_variant not in ("auto", "m8", "pairs"):
            raise ValueError(f"unknown pallas_variant: {self.pallas_variant!r}")
        if not (
            self.use_pallas_fd is True
            or self.use_pallas_fd is False
            or self.use_pallas_fd == "auto"
        ):
            raise ValueError(f"unknown use_pallas_fd: {self.use_pallas_fd!r}")
