"""JAX/XLA simulation backend (under construction this round).

Recasts one gossip round for the whole cluster as a single jit'd tensor
step over an (N, N) version-watermark matrix — see SURVEY.md §7 steps 6-8.
"""
