"""JAX/XLA simulation backend.

Recasts one gossip round for the whole cluster as a single jit'd tensor
step over an (N, N) version-watermark matrix (SURVEY.md §7 steps 6-8):
``SimConfig``/``SimState`` hold the tensors, ``Simulator`` drives chunked
device-resident rounds (optionally sharded over a mesh), and
``SimCluster`` offers the Cluster-shaped API with host-side values.
"""

from .bytes import budget_from_mtu
from .config import SimConfig
from .state import SimState, SweepParams, init_state

__all__ = ("HostSimulator", "SimCluster", "SimConfig", "SimState",
           "SweepParams", "SweepResult", "SweepSimulator",
           "Simulator", "budget_from_mtu", "init_state")


def __getattr__(name: str):
    # Simulator/SimCluster import ops.gossip, which imports sim.state —
    # loading them lazily keeps `import aiocluster_tpu.ops` acyclic.
    # HostSimulator is lazy for a different reason: importing it may
    # g++-compile the native kernel on first use.
    if name == "Simulator":
        from .simulator import Simulator

        return Simulator
    if name == "SimCluster":
        from .simcluster import SimCluster

        return SimCluster
    if name == "HostSimulator":
        from .hostsim import HostSimulator

        return HostSimulator
    if name == "SweepSimulator":
        from .sweep import SweepSimulator

        return SweepSimulator
    if name == "SweepResult":
        from .sweep import SweepResult

        return SweepResult
    raise AttributeError(name)
