"""Device-memory planning for simulated cluster sizes.

The sim's footprint is dominated by the (N, N) knowledge matrices
(sim/state.py). Which matrices exist — and how wide their elements are —
depends on SimConfig, so feasibility at a target scale is a pure function
of the config. This module answers "will it fit?" before any device
allocation, and is what ``bench.py --probe`` and the 100k-node planning
in BASELINE.md are computed from.

Reference parity note: the object model (reference state.py) needs O(keys)
host memory per node pair view; the tensor sim collapses each pair to a
few bytes. A 100k-node convergence sim in the lean profile is
2 B/pair * 100k^2 = 20 GB — sharded over a v5e-8's owner axis, 2.5 GB per
chip plus the gathered operands (two per step under 'permutation'
pairing — both handshake directions are computed from pre-round state —
one under the default 'matching').
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from .config import SimConfig


@dataclass(frozen=True)
class MemoryPlan:
    """Estimated device bytes for one simulated cluster (or a sweep of
    ``lanes`` of them — the sweep memory model is ``lanes x per-lane
    bytes``: every lane holds its own full state and its own step
    transients). ``shards`` counts GLOBAL shards; ``hosts`` records how
    they are spread across processes (parallel/multihost.py) — memory-
    neutral (each shard sees the same per-chip HBM either way), but part
    of the planning identity so the largest-N tables and the measured-
    boundary evidence are keyed per (rung, shards, hosts)."""

    n_nodes: int
    state_bytes: int  # resident SimState matrices (all lanes)
    transient_bytes: int  # largest gathered operand alive during a step
    shards: int
    lanes: int = 1
    hosts: int = 1

    @property
    def per_shard_bytes(self) -> int:
        return (self.state_bytes + self.transient_bytes) // self.shards

    def fits(self, hbm_bytes_per_chip: int = 16 * 1024**3) -> bool:
        # Leave 20% headroom for XLA scratch and fusion temporaries.
        return self.per_shard_bytes <= int(hbm_bytes_per_chip * 0.8)


def engaged_variant(cfg: SimConfig, shards: int = 1, lanes: int = 1) -> str:
    """Which pull path would actually dispatch for ``cfg`` on the chip:
    "pairs", "m8", or "xla". THE single resolution shared by the
    analytic plan and the measured-boundary key — the two must never
    key memory behavior off different answers. Resolves the env
    override and "auto" as if on the accelerator (planning hosts must
    agree with the chip). ``lanes > 1`` asks for the SWEEP dispatch
    (sim_step's sweep-aware gate: the lane-lifted pairs kernels or
    nothing — m8 has no lane axis)."""
    from ..ops.gossip import (
        pallas_path_engaged,
        pallas_variant_engaged,
        resolve_variant_env,
    )

    cfg = resolve_variant_env(cfg)
    axis = None if shards == 1 else "owners"
    n_local = cfg.n_nodes // shards
    if not pallas_path_engaged(
        cfg, axis, n_local=n_local, assume_accelerator=True,
        sweep=lanes > 1,
    ):
        return "xla"
    return pallas_variant_engaged(cfg, axis, n_local)


def plan(
    cfg: SimConfig, shards: int = 1, lanes: int = 1, hosts: int = 1
) -> MemoryPlan:
    """Bytes needed for ``cfg`` sharded ``shards`` ways (globally, over
    ``hosts`` processes) on the owner axis. ``lanes`` > 1 models a
    SweepSimulator run: state and step transients scale linearly with
    the lane count. Sweeps served by the lane-lifted pairs kernels
    (engaged_variant(cfg, shards, lanes) == "pairs") earn the same
    in-place discount as single runs — per lane; sweeps off the pairs
    domain run XLA and pay the gathered-operand transients per lane.

    Per-pair resident bytes come from ONE table
    (sim.bytes.state_bytes_per_pair — the memory ladder), so every rung
    including the packed forms is planned from the same accounting the
    docs publish. Transients are rung-aware too: the packed u4 path's
    XLA arm gathers PACKED peer rows and computes on the nibbles inside
    the fusion (ops/gossip.py), so its gather transient is the packed
    width; kernel-served packed rungs (the pairs kernel's VMEM nibble
    codec) earn the same ZERO-gather in-place discount as the unpacked
    rungs — that discount is what lifts the lean u4r single-chip
    ceiling past the old 117k XLA-transient model; and FD configs off
    the fused path additionally retain the round-start heartbeat
    matrix (hb0) for the phi phase."""
    from .bytes import HB_BYTES, W_BYTES, state_bytes_per_pair

    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    if hosts < 1 or shards % hosts != 0:
        raise ValueError("hosts must divide the global shard count")
    n = cfg.n_nodes
    state = int(state_bytes_per_pair(cfg) * n * n)
    # Permuted gathers of w (and hb when tracked) are live alongside the
    # donated state during a pull. The 'permutation' pairing
    # computes BOTH handshake directions from pre-round state, so two
    # gathered peer matrices (plus their advance temporaries, bounded by
    # the same size) can be live at peak; 'matching' needs only one.
    hb_bytes = (
        int(HB_BYTES[cfg.heartbeat_dtype] * n * n)
        if cfg.track_heartbeats
        else 0
    )
    gathered = int(W_BYTES[cfg.version_dtype] * n * n) + hb_bytes
    directions = 2 if cfg.pairing == "permutation" else 1
    transient = directions * gathered
    if cfg.track_failure_detector:
        # The XLA FD phase compares post-exchange heartbeats against the
        # retained round-start matrix (hb_round_start) — a full second
        # hb matrix live at peak that earlier plans never charged.
        transient += hb_bytes
    # The pair-fused kernel path updates w/hb IN PLACE
    # (input_output_aliases) and never materializes a gather: its
    # steady-state peak is the resident state alone. Decided by the
    # same resolution sim_step dispatches on (engaged_variant: env
    # override folded in, "auto" resolved as if on the accelerator,
    # lane-batched sweeps resolved through the sweep gate) — the
    # planner answers "will it fit the chip?" and must give the same
    # answer from a CPU planning host (tests/test_benchmarks.py pins
    # it to bench's constant). Since the lane-lifted kernels landed,
    # the discount applies per LANE too: a pairs-served sweep holds
    # one resident copy per lane, no gathers.
    if engaged_variant(cfg, shards, lanes) == "pairs":
        # FD configs retain the round-start heartbeat matrix for the
        # phi phase, so the first sub-exchange does NOT alias hb
        # (gossip.py alias_hb) — a second full (N, N) heartbeat matrix
        # is live at peak alongside the resident state (ADVICE r3).
        # Only heartbeat-free profiles earn the zero-transient claim.
        if cfg.track_failure_detector and cfg.track_heartbeats:
            transient = hb_bytes
        else:
            transient = 0
    return MemoryPlan(n, state * lanes, transient * lanes, shards, lanes,
                      hosts)


# -- measured fit/no-fit boundaries -------------------------------------------
#
# Round-3 lesson (window 1): the model said a 52,096-node lean sim fits
# one 16 GiB chip with 20% headroom; the chip said RESOURCE_EXHAUSTED.
# Every on-chip run therefore persists its fit/no-fit outcome here, and
# the planner consults the measured table BEFORE trusting the model.
# Entries are keyed by the execution path that produced them — kernel
# variant + profile dtypes/flags + shard count — because memory behavior
# is a property of the compiled program, not of n alone (the 52k OOM ran
# the non-aliased single-pass path; it says nothing about the in-place
# pairs path). Within one key group, fit is monotone in n_nodes.
#
# The table ships WITH the package (calibration data versioned next to
# the model it corrects); builder tooling appends to it in-repo.

_BOUNDARIES_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "measured_boundaries.json"
)


def _boundaries_path() -> str:
    """On a read-only / system-site install the in-package path is not
    writable and a measured hardware fact would be silently dropped
    (callers log-and-continue); AIOCLUSTER_TPU_BOUNDARIES_PATH redirects
    both reads and writes (ADVICE r4, low). Resolved at every call, not
    at import, so setting it after the package is imported works."""
    return os.environ.get("AIOCLUSTER_TPU_BOUNDARIES_PATH", _BOUNDARIES_DEFAULT)


def _boundary_key(
    cfg: SimConfig,
    shards: int,
    hbm_bytes_per_chip: int,
    lanes: int = 1,
    hosts: int = 1,
) -> dict:
    """The signature a measured verdict is valid for: the execution
    path (kernel variant + profile + shards + sweep lanes + host
    spread) AND the chip capacity it was observed on — a 16 GiB no-fit
    says nothing about a 32 GiB part, and an 8-lane sweep OOM says
    nothing about a single-run fit at the same (variant, profile,
    shards): lanes multiply resident state, so they are part of the key
    (entries recorded before the sweep engine carry no ``lanes`` field
    and read as 1 — see fits_verdict; ``hosts`` likewise — pre-
    multihost entries were single-process). The bookkeeping rungs
    (icount_dtype, live_bits) are part of the profile too: shrinking
    them changes resident bytes, so evidence must not cross rungs."""
    return {
        "variant": engaged_variant(cfg, shards, lanes),
        "version_dtype": cfg.version_dtype,
        "heartbeat_dtype": cfg.heartbeat_dtype if cfg.track_heartbeats else None,
        "fd_dtype": cfg.fd_dtype if cfg.track_failure_detector else None,
        "icount_dtype": (
            cfg.icount_dtype if cfg.track_failure_detector else None
        ),
        "live_bits": cfg.live_bits,
        "track_heartbeats": cfg.track_heartbeats,
        "track_failure_detector": cfg.track_failure_detector,
        "pairing": cfg.pairing,
        "shards": shards,
        "lanes": lanes,
        "hosts": hosts,
        "hbm_bytes_per_chip": hbm_bytes_per_chip,
    }


def load_boundaries(path: str | None = None) -> list[dict]:
    try:
        with open(path or _boundaries_path()) as f:
            return json.load(f)["entries"]
    except Exception:
        return []


def record_boundary(
    cfg: SimConfig,
    shards: int,
    fits: bool,
    *,
    rounds_per_sec: float | None = None,
    source: str = "",
    path: str | None = None,
    hbm_bytes_per_chip: int = 16 * 1024**3,
    lanes: int = 1,
    hosts: int = 1,
) -> dict:
    """Append one measured fit/no-fit outcome (atomic rewrite under an
    inter-process lock — the bench ladder and the battery can both run
    inside one tunnel window and a lost entry would be a lost hardware
    fact). Returns the entry. Callers: bench.py's max-scale ladder and
    the measurement battery, after every on-chip attempt."""
    import fcntl
    import time

    path = path or _boundaries_path()
    entry = {
        **_boundary_key(cfg, shards, hbm_bytes_per_chip, lanes, hosts),
        "n_nodes": cfg.n_nodes,
        "fits": bool(fits),
        "rounds_per_sec": rounds_per_sec,
        "source": source,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(path + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        entries = load_boundaries(path)  # re-read under the lock
        entries.append(entry)
        payload = {
            "note": "Measured single-run fit/no-fit outcomes, keyed by "
            "the execution path (kernel variant, profile, shards) and "
            "chip capacity. Consulted by sim.memory.fits_verdict before "
            "the analytic model is trusted.",
            "entries": entries,
        }
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    return entry


def fits_verdict(
    cfg: SimConfig,
    shards: int = 1,
    hbm_bytes_per_chip: int = 16 * 1024**3,
    path: str | None = None,
    lanes: int = 1,
    hosts: int = 1,
) -> dict:
    """Will this config fit one chip's HBM — measured evidence first,
    model second.

    Returns ``{"fits", "measured", "evidence", "model_fits",
    "per_shard_bytes"}``: ``measured=True`` when an on-chip outcome for
    the same execution path AND chip capacity decides it (a recorded
    fit at n >= ours ⇒ fits; a recorded OOM at n <= ours ⇒ doesn't —
    memory use is monotone in n within a path). When fit and OOM
    evidence contradict each other (physically impossible under
    monotonicity — one of them was flaky or predates a fix), the more
    RECENT observation wins, so a transient OOM cannot poison the
    table forever: the next successful run at that size self-corrects
    it. Otherwise the analytic MemoryPlan answers, flagged
    ``measured=False`` so consumers (bench, README claims) can label
    planner-derived numbers honestly."""
    p = plan(cfg, shards, lanes, hosts)
    key = _boundary_key(cfg, shards, hbm_bytes_per_chip, lanes, hosts)
    # Fields added to the key AFTER evidence was first recorded read at
    # their historical value when absent, so old entries keep deciding
    # the queries they were measured for: pre-sweep entries were single
    # runs (lanes=1), pre-multihost entries single-process (hosts=1),
    # pre-ladder entries the int16 bookkeeping profile.
    legacy_defaults = {
        "lanes": 1,
        "hosts": 1,
        "icount_dtype": "int16" if cfg.track_failure_detector else None,
        "live_bits": False,
    }
    # Latest-per-n first: re-measuring a rung supersedes its old verdict.
    latest: dict[int, dict] = {}
    for e in load_boundaries(path):
        if any(
            e.get(k, legacy_defaults.get(k)) != v for k, v in key.items()
        ):
            continue
        n = e["n_nodes"]
        if n not in latest or e.get("ts", "") >= latest[n].get("ts", ""):
            latest[n] = e
    fit_ev = oom_ev = None
    for e in latest.values():
        if e["fits"] and e["n_nodes"] >= cfg.n_nodes:
            if fit_ev is None or e["n_nodes"] < fit_ev["n_nodes"]:
                fit_ev = e
        if not e["fits"] and e["n_nodes"] <= cfg.n_nodes:
            if oom_ev is None or e["n_nodes"] > oom_ev["n_nodes"]:
                oom_ev = e
    model_fits = p.fits(hbm_bytes_per_chip)
    if oom_ev is not None and fit_ev is not None:
        # Contradiction (OOM below a fit): recency decides; an exact
        # timestamp tie stays conservative (OOM).
        if fit_ev.get("ts", "") > oom_ev.get("ts", ""):
            verdict, measured, evidence = True, True, fit_ev
        else:
            verdict, measured, evidence = False, True, oom_ev
    elif oom_ev is not None:
        verdict, measured, evidence = False, True, oom_ev
    elif fit_ev is not None:
        verdict, measured, evidence = True, True, fit_ev
    else:
        verdict, measured, evidence = model_fits, False, None
    return {
        "fits": verdict,
        "measured": measured,
        "evidence": evidence,
        "model_fits": model_fits,
        "per_shard_bytes": p.per_shard_bytes,
    }


# -- the memory ladder's named rungs ------------------------------------------
#
# One override table per profile family (docs/sim.md "memory ladder"):
# a rung name selects the dtype/packing set, and a NEW rung is one new
# dict entry here — the planners, the bytes table (sim/bytes.ladder)
# and the docs all read these builders.
#
# Horizon contracts per rung (enforced by init_state + _check_horizon):
#   int16  — versions/ticks < 32768
#   int8   — versions/ticks < 128
#   u4r    — max versions per owner <= 15 (watermarks live as packed
#            saturating residuals; keys_per_node drops to 15)
#   shrunk/deep (full-FD) — icount_dtype int8 caps window_ticks at 126.

_LEAN_RUNGS: dict[str, dict] = {
    "int32": dict(version_dtype="int32"),
    "int16": dict(version_dtype="int16"),
    "int8": dict(version_dtype="int8"),
    "u4r": dict(version_dtype="u4r", keys_per_node=15),
}

_FULL_RUNGS: dict[str, dict] = {
    "int32": dict(
        version_dtype="int32", heartbeat_dtype="int32", fd_dtype="float32"
    ),
    "int16": dict(),  # the r5 profile — full_config's defaults
    # Shrunk FD bookkeeping: int8 sample counters + bit-packed liveness
    # (9.125 B/pair — the VERDICT target figure at int16 matrices).
    "shrunk": dict(icount_dtype="int8", live_bits=True, window_ticks=100),
    # The deepest rung: int8 watermarks/ticks on top of the shrunk
    # bookkeeping (6.125 B/pair; horizon < 128 rounds — the 100k-class
    # convergence runs finish in ~20).
    "deep": dict(
        version_dtype="int8",
        heartbeat_dtype="int8",
        icount_dtype="int8",
        live_bits=True,
        window_ticks=100,
    ),
}


def lean_config(n_nodes: int, rung: str = "int16", **overrides) -> SimConfig:
    """The memory-lean convergence profile used for max-scale runs: no
    heartbeat matrix, no failure detector, watermarks at the named
    ladder rung (default int16 — the profile every committed boundary
    measurement ran). Explicit ``overrides`` win over the rung's."""
    defaults = dict(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=2048,
        track_failure_detector=False,
        track_heartbeats=False,
    )
    defaults.update(_LEAN_RUNGS[rung])
    defaults.update(overrides)
    return SimConfig(**defaults)


def full_config(n_nodes: int, rung: str = "int16", **overrides) -> SimConfig:
    """The scale-tuned FULL profile: heartbeats + phi-accrual failure
    detector (the reference's actual operating shape — it never gossips
    without heartbeats, reference server.py:471-474) at the named
    ladder rung. The default "int16" is the r5 profile: int16
    watermarks and heartbeat ticks (horizon < 32768 rounds), bfloat16
    stored interval means (update math stays f32) — the profile the
    full-FD scale ladder and the full-profile exact-R datum ran.
    "shrunk" and "deep" descend the bookkeeping ladder toward (and
    past) the 9.125 B/pair target. Explicit ``overrides`` win."""
    defaults = dict(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=2048,
        version_dtype="int16",
        heartbeat_dtype="int16",
        fd_dtype="bfloat16",
        track_failure_detector=True,
        track_heartbeats=True,
    )
    defaults.update(_FULL_RUNGS[rung])
    defaults.update(overrides)
    return SimConfig(**defaults)


def max_scale_model(
    profile: str = "lean",
    rung: str = "int16",
    shards: int = 1,
    hosts: int = 1,
    hbm_bytes_per_chip: int = 16 * 1024**3,
) -> dict:
    """Largest aligned population the ANALYTIC plan fits for one
    (profile, rung, shards, hosts) cell — the planner's answer to "how
    far does this rung scale?", labelled a MODEL (``certified: false``)
    until a chip calibrates the boundary table for that execution path
    (the round-3 honesty discipline: the model has been wrong before;
    fits_verdict consults measured evidence first).

    Alignment: 128 x shards (256 x shards for the packed u4r rung,
    whose BYTE width must stay 128-lane aligned per shard), so every
    shard's column block stays on the fused kernels' domain and the
    measured-fastest XLA shape."""
    from .bytes import state_bytes_per_pair

    builder = {"lean": lean_config, "full": full_config}[profile]
    # The packed rung's kernel domain needs every shard's BYTE width
    # lane-aligned (n_local % 256 — two owners per byte), so its ladder
    # walks 256-aligned shapes; off-alignment steps would flap between
    # the in-place kernel plan and the XLA gather plan and break the
    # search's monotonicity.
    align = 256 if rung == "u4r" else 128
    step = align * shards
    lo, hi = step, step * 20_000  # 2.56M at 1 shard — beyond any model
    while lo + step <= hi:
        mid = ((lo + hi) // 2) // step * step
        if mid <= lo:
            break
        if plan(builder(mid, rung=rung), shards, hosts=hosts).fits(
            hbm_bytes_per_chip
        ):
            lo = mid
        else:
            hi = mid
    p = plan(builder(lo, rung=rung), shards, hosts=hosts)
    return {
        "profile": profile,
        "rung": rung,
        "shards": shards,
        "hosts": hosts,
        "max_nodes_model": lo,
        "bytes_per_pair": state_bytes_per_pair(builder(lo, rung=rung)),
        "per_shard_bytes": p.per_shard_bytes,
        "variant": engaged_variant(builder(lo, rung=rung), shards),
        "certified": False,  # analytic model, not a chip measurement
    }


def packed_kernel_engagement(n_nodes: int = 12_800) -> dict:
    """Whether each PACKED ladder rung rides the in-place Pallas path
    at a representative planning shape (12,800 — 256-aligned, inside
    every rung's kernel domain, and exactly the per-shard width of the
    102,400-node deep-rung target on a v5e-8): the u4r lean rung
    through the pairs kernel's VMEM nibble codec, the shrunk/deep
    full-FD rungs through the fused FD epilogue's packed bookkeeping. Resolved through the
    SAME dispatch sim_step uses (assume-accelerator, env override
    folded in), stamped into every BENCH record as
    ``packed_kernel_engaged`` — so a dispatch regression that silently
    returns a packed rung to the XLA gather path shows up in the
    record diff, not in a tunnel-window surprise."""
    from ..ops.gossip import fd_phase_engaged, resolve_variant_env

    def fd_fused(cfg) -> bool:
        cfg = resolve_variant_env(cfg)
        return (
            fd_phase_engaged(cfg, assume_accelerator=True) == "fused"
        )

    return {
        "u4r": engaged_variant(lean_config(n_nodes, rung="u4r")) == "pairs",
        "shrunk": fd_fused(full_config(n_nodes, rung="shrunk")),
        "deep": fd_fused(full_config(n_nodes, rung="deep")),
    }


def ladder_models(hbm_bytes_per_chip: int = 16 * 1024**3) -> dict:
    """The memory ladder's headline planning claims, machine-readable
    (bench.py stamps this into records as ``memory_ladder``, each entry
    carrying ``certified: false`` until a tunnel window measures it):

    - the deepest full-FD rung's B/pair (the <= 9.125 target) and
      whether 100k-class full-FD fits a modeled 16 GB x 8 mesh;
    - the lean ladder's largest modeled single-chip population per rung
      (the >= 100k / >= 3x-over-32k claim rides the u4r rung).
    """
    from .bytes import state_bytes_per_pair

    # 102,400 = 128 * 800: the smallest 1024-aligned 100k-class shape,
    # so an 8-shard mesh keeps lane-aligned column blocks.
    n100k = 102_400
    deep = full_config(n100k, rung="deep")
    deep_plan = plan(deep, shards=8, hosts=1)
    out = {
        "full_fd_deepest": {
            "rung": "deep",
            "bytes_per_pair": state_bytes_per_pair(deep),
            "target_bytes_per_pair": 9.125,
            "meets_target": state_bytes_per_pair(deep) <= 9.125,
            "n_nodes": n100k,
            "fits_16gb_x8_model": deep_plan.fits(hbm_bytes_per_chip),
            "per_shard_bytes": deep_plan.per_shard_bytes,
            "certified": False,
        },
        "lean_single_chip": {
            rung: max_scale_model(
                "lean", rung, hbm_bytes_per_chip=hbm_bytes_per_chip
            )
            for rung in _LEAN_RUNGS
        },
    }
    deepest_lean = out["lean_single_chip"]["u4r"]
    out["lean_max_scale_claim"] = {
        "rung": "u4r",
        "max_nodes_model": deepest_lean["max_nodes_model"],
        "baseline_measured_nodes": 32_768,  # bench.py SCALE_PROBE_N
        "lift": round(deepest_lean["max_nodes_model"] / 32_768, 2),
        "certified": False,
    }
    return out
