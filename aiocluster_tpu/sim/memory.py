"""Device-memory planning for simulated cluster sizes.

The sim's footprint is dominated by the (N, N) knowledge matrices
(sim/state.py). Which matrices exist — and how wide their elements are —
depends on SimConfig, so feasibility at a target scale is a pure function
of the config. This module answers "will it fit?" before any device
allocation, and is what ``bench.py --probe`` and the 100k-node planning
in BASELINE.md are computed from.

Reference parity note: the object model (reference state.py) needs O(keys)
host memory per node pair view; the tensor sim collapses each pair to a
few bytes. A 100k-node convergence sim in the lean profile is
2 B/pair * 100k^2 = 20 GB — sharded over a v5e-8's owner axis, 2.5 GB per
chip plus the gathered operands (two per step under 'permutation'
pairing — both handshake directions are computed from pre-round state —
one under the default 'matching').
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .config import SimConfig


@dataclass(frozen=True)
class MemoryPlan:
    """Estimated device bytes for one simulated cluster."""

    n_nodes: int
    state_bytes: int  # resident SimState matrices
    transient_bytes: int  # largest gathered operand alive during a step
    shards: int

    @property
    def per_shard_bytes(self) -> int:
        return (self.state_bytes + self.transient_bytes) // self.shards

    def fits(self, hbm_bytes_per_chip: int = 16 * 1024**3) -> bool:
        # Leave 20% headroom for XLA scratch and fusion temporaries.
        return self.per_shard_bytes <= int(hbm_bytes_per_chip * 0.8)


def plan(cfg: SimConfig, shards: int = 1) -> MemoryPlan:
    """Bytes needed for ``cfg`` sharded ``shards`` ways on the owner axis."""
    n = cfg.n_nodes
    pair = jnp.dtype(cfg.version_dtype).itemsize  # w
    if cfg.track_heartbeats:
        pair += jnp.dtype(cfg.heartbeat_dtype).itemsize  # hb_known
    if cfg.track_failure_detector:
        pair += jnp.dtype(cfg.heartbeat_dtype).itemsize  # last_change
        pair += jnp.dtype(cfg.fd_dtype).itemsize  # imean
        pair += 2  # icount int16
        pair += 1  # live_view bool
    state = pair * n * n
    # Permuted gathers of w (and hb when tracked) are live alongside the
    # donated state during a pull. The 'permutation' pairing
    # computes BOTH handshake directions from pre-round state, so two
    # gathered peer matrices (plus their advance temporaries, bounded by
    # the same size) can be live at peak; 'matching' needs only one.
    gathered = jnp.dtype(cfg.version_dtype).itemsize * n * n
    if cfg.track_heartbeats:
        gathered += jnp.dtype(cfg.heartbeat_dtype).itemsize * n * n
    directions = 2 if cfg.pairing == "permutation" else 1
    transient = directions * gathered
    # The pair-fused kernel path updates w/hb IN PLACE
    # (input_output_aliases) and never materializes a gather: its
    # steady-state peak is the resident state alone. Decided by the
    # same gates sim_step dispatches on (env override folded in first,
    # so the plan matches what would actually dispatch), resolving
    # "auto" AS IF on the accelerator — the planner answers "will it
    # fit the chip?" and must give the same answer from a CPU planning
    # host (tests/test_benchmarks.py pins it to bench's constant).
    from ..ops.gossip import (
        pallas_path_engaged,
        pallas_variant_engaged,
        resolve_variant_env,
    )

    cfg = resolve_variant_env(cfg)
    axis = None if shards == 1 else "owners"
    n_local = n // shards
    if pallas_path_engaged(
        cfg, axis, n_local=n_local, assume_accelerator=True
    ) and pallas_variant_engaged(cfg, axis, n_local) == "pairs":
        # FD configs retain the round-start heartbeat matrix for the
        # phi phase, so the first sub-exchange does NOT alias hb
        # (gossip.py alias_hb) — a second full (N, N) heartbeat matrix
        # is live at peak alongside the resident state (ADVICE r3).
        # Only heartbeat-free profiles earn the zero-transient claim.
        if cfg.track_failure_detector and cfg.track_heartbeats:
            transient = jnp.dtype(cfg.heartbeat_dtype).itemsize * n * n
        else:
            transient = 0
    return MemoryPlan(n, state, transient, shards)


def lean_config(n_nodes: int, **overrides) -> SimConfig:
    """The memory-lean convergence profile used for max-scale runs:
    int16 watermarks, no heartbeat matrix, no failure detector."""
    defaults = dict(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=2048,
        version_dtype="int16",
        track_failure_detector=False,
        track_heartbeats=False,
    )
    defaults.update(overrides)
    return SimConfig(**defaults)
