"""Device-memory planning for simulated cluster sizes.

The sim's footprint is dominated by the (N, N) knowledge matrices
(sim/state.py). Which matrices exist — and how wide their elements are —
depends on SimConfig, so feasibility at a target scale is a pure function
of the config. This module answers "will it fit?" before any device
allocation, and is what ``bench.py --probe`` and the 100k-node planning
in BASELINE.md are computed from.

Reference parity note: the object model (reference state.py) needs O(keys)
host memory per node pair view; the tensor sim collapses each pair to a
few bytes. A 100k-node convergence sim in the lean profile is
2 B/pair * 100k^2 = 20 GB — sharded over a v5e-8's owner axis, 2.5 GB per
chip plus the gathered operands (two per step under 'permutation'
pairing — both handshake directions are computed from pre-round state —
one under the default 'matching').
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import jax.numpy as jnp

from .config import SimConfig


@dataclass(frozen=True)
class MemoryPlan:
    """Estimated device bytes for one simulated cluster (or a sweep of
    ``lanes`` of them — the sweep memory model is ``lanes x per-lane
    bytes``: every lane holds its own full state and its own step
    transients)."""

    n_nodes: int
    state_bytes: int  # resident SimState matrices (all lanes)
    transient_bytes: int  # largest gathered operand alive during a step
    shards: int
    lanes: int = 1

    @property
    def per_shard_bytes(self) -> int:
        return (self.state_bytes + self.transient_bytes) // self.shards

    def fits(self, hbm_bytes_per_chip: int = 16 * 1024**3) -> bool:
        # Leave 20% headroom for XLA scratch and fusion temporaries.
        return self.per_shard_bytes <= int(hbm_bytes_per_chip * 0.8)


def engaged_variant(cfg: SimConfig, shards: int = 1, lanes: int = 1) -> str:
    """Which pull path would actually dispatch for ``cfg`` on the chip:
    "pairs", "m8", or "xla". THE single resolution shared by the
    analytic plan and the measured-boundary key — the two must never
    key memory behavior off different answers. Resolves the env
    override and "auto" as if on the accelerator (planning hosts must
    agree with the chip). ``lanes > 1`` asks for the SWEEP dispatch
    (sim_step's sweep-aware gate: the lane-lifted pairs kernels or
    nothing — m8 has no lane axis)."""
    from ..ops.gossip import (
        pallas_path_engaged,
        pallas_variant_engaged,
        resolve_variant_env,
    )

    cfg = resolve_variant_env(cfg)
    axis = None if shards == 1 else "owners"
    n_local = cfg.n_nodes // shards
    if not pallas_path_engaged(
        cfg, axis, n_local=n_local, assume_accelerator=True,
        sweep=lanes > 1,
    ):
        return "xla"
    return pallas_variant_engaged(cfg, axis, n_local)


def plan(cfg: SimConfig, shards: int = 1, lanes: int = 1) -> MemoryPlan:
    """Bytes needed for ``cfg`` sharded ``shards`` ways on the owner
    axis. ``lanes`` > 1 models a SweepSimulator run: state and step
    transients scale linearly with the lane count. Sweeps served by the
    lane-lifted pairs kernels (engaged_variant(cfg, shards, lanes) ==
    "pairs") earn the same in-place discount as single runs — per lane;
    sweeps off the pairs domain run XLA and pay the gathered-operand
    transients per lane."""
    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    n = cfg.n_nodes
    pair = jnp.dtype(cfg.version_dtype).itemsize  # w
    if cfg.track_heartbeats:
        pair += jnp.dtype(cfg.heartbeat_dtype).itemsize  # hb_known
    if cfg.track_failure_detector:
        pair += jnp.dtype(cfg.heartbeat_dtype).itemsize  # last_change
        pair += jnp.dtype(cfg.fd_dtype).itemsize  # imean
        pair += 2  # icount int16
        pair += 1  # live_view bool
        # dead_since is (N, N) only when the two-stage lifecycle is on
        # (init_state's ds_shape; zero-sized otherwise) — round 4's plan
        # neither charged it when it was allocated nor does the state
        # allocate it unused any more.
        if cfg.dead_grace_ticks is not None:
            pair += jnp.dtype(cfg.heartbeat_dtype).itemsize
    state = pair * n * n
    # Permuted gathers of w (and hb when tracked) are live alongside the
    # donated state during a pull. The 'permutation' pairing
    # computes BOTH handshake directions from pre-round state, so two
    # gathered peer matrices (plus their advance temporaries, bounded by
    # the same size) can be live at peak; 'matching' needs only one.
    gathered = jnp.dtype(cfg.version_dtype).itemsize * n * n
    if cfg.track_heartbeats:
        gathered += jnp.dtype(cfg.heartbeat_dtype).itemsize * n * n
    directions = 2 if cfg.pairing == "permutation" else 1
    transient = directions * gathered
    # The pair-fused kernel path updates w/hb IN PLACE
    # (input_output_aliases) and never materializes a gather: its
    # steady-state peak is the resident state alone. Decided by the
    # same resolution sim_step dispatches on (engaged_variant: env
    # override folded in, "auto" resolved as if on the accelerator,
    # lane-batched sweeps resolved through the sweep gate) — the
    # planner answers "will it fit the chip?" and must give the same
    # answer from a CPU planning host (tests/test_benchmarks.py pins
    # it to bench's constant). Since the lane-lifted kernels landed,
    # the discount applies per LANE too: a pairs-served sweep holds
    # one resident copy per lane, no gathers.
    if engaged_variant(cfg, shards, lanes) == "pairs":
        # FD configs retain the round-start heartbeat matrix for the
        # phi phase, so the first sub-exchange does NOT alias hb
        # (gossip.py alias_hb) — a second full (N, N) heartbeat matrix
        # is live at peak alongside the resident state (ADVICE r3).
        # Only heartbeat-free profiles earn the zero-transient claim.
        if cfg.track_failure_detector and cfg.track_heartbeats:
            transient = jnp.dtype(cfg.heartbeat_dtype).itemsize * n * n
        else:
            transient = 0
    return MemoryPlan(n, state * lanes, transient * lanes, shards, lanes)


# -- measured fit/no-fit boundaries -------------------------------------------
#
# Round-3 lesson (window 1): the model said a 52,096-node lean sim fits
# one 16 GiB chip with 20% headroom; the chip said RESOURCE_EXHAUSTED.
# Every on-chip run therefore persists its fit/no-fit outcome here, and
# the planner consults the measured table BEFORE trusting the model.
# Entries are keyed by the execution path that produced them — kernel
# variant + profile dtypes/flags + shard count — because memory behavior
# is a property of the compiled program, not of n alone (the 52k OOM ran
# the non-aliased single-pass path; it says nothing about the in-place
# pairs path). Within one key group, fit is monotone in n_nodes.
#
# The table ships WITH the package (calibration data versioned next to
# the model it corrects); builder tooling appends to it in-repo.

_BOUNDARIES_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "measured_boundaries.json"
)


def _boundaries_path() -> str:
    """On a read-only / system-site install the in-package path is not
    writable and a measured hardware fact would be silently dropped
    (callers log-and-continue); AIOCLUSTER_TPU_BOUNDARIES_PATH redirects
    both reads and writes (ADVICE r4, low). Resolved at every call, not
    at import, so setting it after the package is imported works."""
    return os.environ.get("AIOCLUSTER_TPU_BOUNDARIES_PATH", _BOUNDARIES_DEFAULT)


def _boundary_key(
    cfg: SimConfig, shards: int, hbm_bytes_per_chip: int, lanes: int = 1
) -> dict:
    """The signature a measured verdict is valid for: the execution
    path (kernel variant + profile + shards + sweep lanes) AND the chip
    capacity it was observed on — a 16 GiB no-fit says nothing about a
    32 GiB part, and an 8-lane sweep OOM says nothing about a
    single-run fit at the same (variant, profile, shards): lanes
    multiply resident state, so they are part of the key (entries
    recorded before the sweep engine carry no ``lanes`` field and read
    as 1 — see fits_verdict)."""
    return {
        "variant": engaged_variant(cfg, shards, lanes),
        "version_dtype": cfg.version_dtype,
        "heartbeat_dtype": cfg.heartbeat_dtype if cfg.track_heartbeats else None,
        "fd_dtype": cfg.fd_dtype if cfg.track_failure_detector else None,
        "track_heartbeats": cfg.track_heartbeats,
        "track_failure_detector": cfg.track_failure_detector,
        "pairing": cfg.pairing,
        "shards": shards,
        "lanes": lanes,
        "hbm_bytes_per_chip": hbm_bytes_per_chip,
    }


def load_boundaries(path: str | None = None) -> list[dict]:
    try:
        with open(path or _boundaries_path()) as f:
            return json.load(f)["entries"]
    except Exception:
        return []


def record_boundary(
    cfg: SimConfig,
    shards: int,
    fits: bool,
    *,
    rounds_per_sec: float | None = None,
    source: str = "",
    path: str | None = None,
    hbm_bytes_per_chip: int = 16 * 1024**3,
    lanes: int = 1,
) -> dict:
    """Append one measured fit/no-fit outcome (atomic rewrite under an
    inter-process lock — the bench ladder and the battery can both run
    inside one tunnel window and a lost entry would be a lost hardware
    fact). Returns the entry. Callers: bench.py's max-scale ladder and
    the measurement battery, after every on-chip attempt."""
    import fcntl
    import time

    path = path or _boundaries_path()
    entry = {
        **_boundary_key(cfg, shards, hbm_bytes_per_chip, lanes),
        "n_nodes": cfg.n_nodes,
        "fits": bool(fits),
        "rounds_per_sec": rounds_per_sec,
        "source": source,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(path + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        entries = load_boundaries(path)  # re-read under the lock
        entries.append(entry)
        payload = {
            "note": "Measured single-run fit/no-fit outcomes, keyed by "
            "the execution path (kernel variant, profile, shards) and "
            "chip capacity. Consulted by sim.memory.fits_verdict before "
            "the analytic model is trusted.",
            "entries": entries,
        }
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    return entry


def fits_verdict(
    cfg: SimConfig,
    shards: int = 1,
    hbm_bytes_per_chip: int = 16 * 1024**3,
    path: str | None = None,
    lanes: int = 1,
) -> dict:
    """Will this config fit one chip's HBM — measured evidence first,
    model second.

    Returns ``{"fits", "measured", "evidence", "model_fits",
    "per_shard_bytes"}``: ``measured=True`` when an on-chip outcome for
    the same execution path AND chip capacity decides it (a recorded
    fit at n >= ours ⇒ fits; a recorded OOM at n <= ours ⇒ doesn't —
    memory use is monotone in n within a path). When fit and OOM
    evidence contradict each other (physically impossible under
    monotonicity — one of them was flaky or predates a fix), the more
    RECENT observation wins, so a transient OOM cannot poison the
    table forever: the next successful run at that size self-corrects
    it. Otherwise the analytic MemoryPlan answers, flagged
    ``measured=False`` so consumers (bench, README claims) can label
    planner-derived numbers honestly."""
    p = plan(cfg, shards, lanes)
    key = _boundary_key(cfg, shards, hbm_bytes_per_chip, lanes)
    # Latest-per-n first: re-measuring a rung supersedes its old verdict.
    latest: dict[int, dict] = {}
    for e in load_boundaries(path):
        # Entries recorded before the sweep engine carry no "lanes"
        # field: they were single runs, so they read as lanes=1 — a
        # sweep OOM can therefore never poison single-run verdicts for
        # the same (variant, profile, shards) key, and vice versa.
        if any(
            (e.get(k, 1) if k == "lanes" else e.get(k)) != v
            for k, v in key.items()
        ):
            continue
        n = e["n_nodes"]
        if n not in latest or e.get("ts", "") >= latest[n].get("ts", ""):
            latest[n] = e
    fit_ev = oom_ev = None
    for e in latest.values():
        if e["fits"] and e["n_nodes"] >= cfg.n_nodes:
            if fit_ev is None or e["n_nodes"] < fit_ev["n_nodes"]:
                fit_ev = e
        if not e["fits"] and e["n_nodes"] <= cfg.n_nodes:
            if oom_ev is None or e["n_nodes"] > oom_ev["n_nodes"]:
                oom_ev = e
    model_fits = p.fits(hbm_bytes_per_chip)
    if oom_ev is not None and fit_ev is not None:
        # Contradiction (OOM below a fit): recency decides; an exact
        # timestamp tie stays conservative (OOM).
        if fit_ev.get("ts", "") > oom_ev.get("ts", ""):
            verdict, measured, evidence = True, True, fit_ev
        else:
            verdict, measured, evidence = False, True, oom_ev
    elif oom_ev is not None:
        verdict, measured, evidence = False, True, oom_ev
    elif fit_ev is not None:
        verdict, measured, evidence = True, True, fit_ev
    else:
        verdict, measured, evidence = model_fits, False, None
    return {
        "fits": verdict,
        "measured": measured,
        "evidence": evidence,
        "model_fits": model_fits,
        "per_shard_bytes": p.per_shard_bytes,
    }


def lean_config(n_nodes: int, **overrides) -> SimConfig:
    """The memory-lean convergence profile used for max-scale runs:
    int16 watermarks, no heartbeat matrix, no failure detector."""
    defaults = dict(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=2048,
        version_dtype="int16",
        track_failure_detector=False,
        track_heartbeats=False,
    )
    defaults.update(overrides)
    return SimConfig(**defaults)


def full_config(n_nodes: int, **overrides) -> SimConfig:
    """The scale-tuned FULL profile: heartbeats + phi-accrual failure
    detector (the reference's actual operating shape — it never gossips
    without heartbeats, reference server.py:471-474) at the narrowest
    exact dtypes: int16 watermarks and heartbeat ticks (horizon < 32768
    rounds), bfloat16 stored interval means (update math stays f32).
    This is the profile the full-FD scale ladder and the full-profile
    exact-R datum run."""
    defaults = dict(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=2048,
        version_dtype="int16",
        heartbeat_dtype="int16",
        fd_dtype="bfloat16",
        track_failure_detector=True,
        track_heartbeats=True,
    )
    defaults.update(overrides)
    return SimConfig(**defaults)
