"""aiocluster_tpu: TPU-native ScuttleButt gossip cluster membership.

Two backends behind one data model (SURVEY.md §7):

- ``aiocluster_tpu.runtime`` — asyncio TCP/TLS backend for real clusters,
  wire-compatible with the reference jettify/aiocluster.
- ``aiocluster_tpu.sim`` — JAX/XLA batched simulation backend running
  whole-cluster gossip rounds as tensor kernels on TPU.

The top-level exports mirror the reference package ``__init__`` (reference
__init__.py:1-20) with its two export bugs fixed: ``NodeState`` is exported
under its real name and ``HookStats`` is actually importable.
"""

from .core.config import Config, FailureDetectorConfig
from .core.identity import Address, NodeId
from .core.kvstate import NodeState
from .core.values import VersionedValue, VersionStatusEnum
from .runtime.cluster import (
    Cluster,
    ClusterSnapshot,
    KeyChangeCallback,
    NodeEventCallback,
)
from .runtime.hooks import HookStats

__all__ = (
    "Address",
    "Cluster",
    "ClusterSnapshot",
    "Config",
    "FailureDetectorConfig",
    "HookStats",
    "KeyChangeCallback",
    "NodeEventCallback",
    "NodeId",
    "NodeState",
    "VersionStatusEnum",
    "VersionedValue",
)

__version__ = "0.1.0"
