"""Long-horizon scenario pack (docs/virtual-time.md): the soaks that
only exist because virtual time makes them affordable.

Each scenario is an async function meant to run under
:func:`aiocluster_tpu.vtime.run` — it boots a real loopback fleet
(``ChaosHarness(virtual_time=True)``), drives hours-to-days of virtual
time through it in seconds of wall time, and returns a result dict whose
``ok`` key is the scenario's own acceptance verdict. They are driven by
``benchmarks/vtime_bench.py`` (scaled up), ``make vtime-smoke`` (scaled
down) and tests/test_vtime.py.

The pack covers the bug classes a wall-clock CI can never reach:

- :func:`dead_node_gc_cycles` — a node stays down past the phi
  detector's dead-node grace period, is garbage-collected from every
  peer's state, then reboots and must re-join from nothing (the
  full lifecycle: live -> dead -> FORGOTTEN -> live again).
- :func:`week_long_drift` — a quiet fleet gossips for days: heartbeat
  versions, phi windows and virtual wall time all run far past their
  usual test horizons, and nobody may ever falsely suspect a live peer.
- :func:`slow_leak_churn` — rolling crash/restart churn for hours; the
  per-peer state a restart leaves behind (old incarnations, breaker
  entries, phi samples) must be garbage-collected, not accumulated.

Every fleet here scales the phi configuration with the gossip interval
— heartbeats arrive once per round, so a detector tuned for 1 s rounds
would declare the whole fleet dead at a 60 s round cadence.
"""

from __future__ import annotations

from datetime import timedelta

from ..core.config import FailureDetectorConfig
from ..faults.plan import FaultPlan, NodeCrash
from ..faults.runner import ChaosHarness
from ..utils.clock import current_clock
from ..utils.clock import sleep as clock_sleep


def _scaled_fd(interval: float, grace: float) -> FailureDetectorConfig:
    """Phi tuning proportional to the round cadence: samples arrive
    once per round, so the window bounds scale with ``interval`` and
    the dead-node grace period is the scenario's to choose."""
    return FailureDetectorConfig(
        initial_interval=timedelta(seconds=2 * interval),
        max_interval=timedelta(seconds=4 * interval),
        dead_node_grace_period=timedelta(seconds=grace),
    )


def _fleet(
    n_nodes: int,
    plan,
    *,
    interval: float,
    grace: float,
    seed: int,
    marked_gc: float | None = None,
) -> ChaosHarness:
    overrides: dict = {"failure_detector": _scaled_fd(interval, grace)}
    if marked_gc is not None:
        overrides["marked_for_deletion_grace_period"] = int(marked_gc)
    return ChaosHarness(
        n_nodes,
        plan,
        cluster_id="vtime",
        gossip_interval=interval,
        config_overrides=overrides,
        virtual_time=True,
        seed=seed,
    )


def _forgotten(harness: ChaosHarness, victim: str) -> bool:
    """No running peer retains ANY incarnation of ``victim`` — the
    post-GC state (stronger than "marked dead")."""
    return all(
        not any(nid.name == victim for nid in
                harness.clusters[peer].node_states_view())
        for peer in harness.running()
        if peer != victim
    )


def _false_dead_events(harness: ChaosHarness) -> int:
    """fd transitions to dead/GC recorded by running clusters — zero on
    a fleet where nothing actually died (the false-suspicion probe)."""
    count = 0
    for name in harness.running():
        for entry in harness.clusters[name].flight_record():
            if entry.get("kind") == "fd" and entry.get("to") in (
                "dead",
                "gc",
            ):
                count += 1
    return count


async def dead_node_gc_cycles(
    *,
    nodes: int = 8,
    cycles: int = 2,
    seed: int = 0,
    interval: float = 30.0,
    grace: float = 900.0,
) -> dict:
    """``cycles`` full lifecycle loops: the victim crashes, stays down
    past the dead-node grace period (so every peer garbage-collects it
    entirely), reboots with a bumped generation, and the fleet must
    reconverge around the returned stranger. ~``cycles * 2.3 * grace``
    virtual seconds, a few wall seconds."""
    victim = "n01"
    cycle_len = 2.3 * grace
    down_for = 1.6 * grace  # well past grace: GC fires mid-window

    def plan(h: ChaosHarness) -> FaultPlan:
        return FaultPlan(
            seed=seed,
            crashes=tuple(
                NodeCrash(
                    nodes=h.node_set(victim),
                    at=grace + i * cycle_len,
                    down_for=down_for,
                )
                for i in range(cycles)
            ),
        )

    gc_observed: list[bool] = []
    reconverged: list[bool] = []
    async with _fleet(
        nodes, plan, interval=interval, grace=grace, seed=seed
    ) as h:
        await h.wait_converged(timeout=grace)
        for i in range(cycles):
            # Sample late in the down window, after the grace expired.
            down_at = grace + i * cycle_len
            while h.elapsed() < down_at + 1.5 * grace:
                await clock_sleep(interval)
            gc_observed.append(_forgotten(h, victim))
            # Past the restart edge: the fleet reabsorbs the stranger.
            while h.elapsed() < down_at + down_for + 0.1 * grace:
                await clock_sleep(interval)
            try:
                await h.wait_converged(timeout=2 * grace)
                reconverged.append(True)
            except TimeoutError:
                reconverged.append(False)
        virtual_elapsed = h.elapsed()
        incarnations = len(h.generations.get(victim, []))
    return {
        "scenario": "dead_node_gc_cycles",
        "nodes": nodes,
        "cycles": cycles,
        "virtual_seconds": round(virtual_elapsed, 3),
        "gc_observed": gc_observed,
        "reconverged": reconverged,
        "victim_incarnations": incarnations,
        "ok": all(gc_observed) and all(reconverged)
        and incarnations == cycles + 1,
    }


async def week_long_drift(
    *,
    nodes: int = 6,
    days: float = 7.0,
    seed: int = 0,
    interval: float = 3600.0,
) -> dict:
    """A quiet fleet gossips for ``days`` of virtual time at a one-round
    -per-``interval`` cadence, with one owner write per virtual day as
    the only churn. Verdict: no false dead/GC verdicts ever, the fleet
    is converged at the horizon, and the virtual wall really moved
    ``days`` forward (the clock seam carried every subsystem along)."""
    horizon = days * 86400.0
    last_day = max(1, int(days))
    async with _fleet(
        nodes, None, interval=interval, grace=horizon * 10, seed=seed
    ) as h:
        wall0 = current_clock().wall()
        await h.wait_converged(timeout=40 * interval)
        written = 0
        while h.elapsed() < horizon:
            await clock_sleep(interval)
            # One owner write per virtual day, stamped at midday so the
            # final key still has half a day to replicate fleet-wide.
            midday = (written + 0.5) * 86400.0
            if written < last_day and h.elapsed() >= midday:
                written += 1
                h.clusters["n00"].set(f"day-{written}", str(written))
        try:
            await h.wait_converged(timeout=40 * interval)
            converged = True
        except TimeoutError:
            converged = False
        false_dead = _false_dead_events(h)
        wall_moved = current_clock().wall() - wall0
        last_replicated = any(
            nid.name == "n00" and ns.get(f"day-{last_day}") is not None
            for nid, ns in h.clusters["n01"].node_states_view().items()
        )
    return {
        "scenario": "week_long_drift",
        "nodes": nodes,
        "virtual_days": round(wall_moved / 86400.0, 3),
        "false_dead_events": false_dead,
        "converged": converged,
        "last_day_replicated": last_replicated,
        "ok": converged
        and false_dead == 0
        and wall_moved >= horizon
        and last_replicated,
    }


async def slow_leak_churn(
    *,
    nodes: int = 8,
    hours: float = 2.0,
    restart_every: float = 600.0,
    seed: int = 0,
    interval: float = 30.0,
) -> dict:
    """Rolling crash/restart churn for ``hours`` of virtual time: node
    ``i % nodes`` crashes at ``i * restart_every`` and reboots two
    rounds later with a bumped generation. The leak probe runs after a
    post-churn quiet window long enough for phi accrual plus the
    dead-node grace period on the LAST restart: every dead incarnation
    must then be garbage-collected from every peer's view — the final
    state is exactly the live fleet, churn state recycled rather than
    accumulated. (Detector latency varies per peer, which is why the
    probe waits for quiescence instead of modeling a tail.)

    The grace period is deliberately LONG relative to the detector:
    observers declare one death hundreds of intervals apart (phi
    accrual depends on each one's sample history), and a grace shorter
    than twice that spread lets a collected incarnation be re-learned
    from a peer still advertising it — the zombie-resurrection cycle
    the reference's 24 h grace makes impossible. ``grace/2`` must stay
    above the spread, so both scale in interval units here."""
    horizon = hours * 3600.0
    grace = 300 * interval
    n_restarts = int(horizon / restart_every) - 1
    # Post-churn drain: worst-case dead declaration (phi with samples
    # capped at max_interval = 4*interval accrues slowly) + full grace.
    drain = grace + 200 * interval

    def plan(h: ChaosHarness) -> FaultPlan:
        return FaultPlan(
            seed=seed,
            crashes=tuple(
                NodeCrash(
                    nodes=h.node_set(h.names[(i + 1) % nodes]),
                    at=(i + 1) * restart_every,
                    down_for=2 * interval,
                )
                for i in range(n_restarts)
            ),
        )

    async with _fleet(
        nodes,
        plan,
        interval=interval,
        grace=grace,
        seed=seed,
        marked_gc=int(grace),
    ) as h:
        await h.wait_converged(timeout=grace)
        while h.elapsed() < horizon:
            await clock_sleep(interval)
        peak_view = max(
            len(h.clusters[name].node_states_view())
            for name in h.running()
        )
        # Quiet drain, then the exact-state probe.
        while h.elapsed() < horizon + drain:
            await clock_sleep(interval)
        try:
            await h.wait_converged(timeout=grace)
            converged = True
        except TimeoutError:
            converged = False
        total_incarnations = sum(len(g) for g in h.generations.values())
        view_sizes = {
            name: len(h.clusters[name].node_states_view())
            for name in h.running()
        }
        recycled = all(v == nodes for v in view_sizes.values())
        virtual_elapsed = h.elapsed()
    return {
        "scenario": "slow_leak_churn",
        "nodes": nodes,
        "virtual_hours": round(virtual_elapsed / 3600.0, 3),
        "restarts": n_restarts,
        "total_incarnations": total_incarnations,
        "peak_view_size": peak_view,
        "final_view_sizes": sorted(view_sizes.values()),
        "converged": converged,
        "ok": converged
        and recycled
        and total_incarnations >= nodes + n_restarts,
    }
