"""Virtual-time runtime: a deterministic compressed-clock event loop.

``vtime.run(coro, seed=...)`` executes real asyncio cluster code — real
sockets, real protocol bytes — under a virtual clock that jumps across
every idle gap, turning an hour of cluster time into seconds of CPU,
with seeded same-deadline scheduling so a seeded chaos soak replays
bit-identically (docs/virtual-time.md; migration.md difference #18).
"""

from .loop import (
    DEFAULT_WALL_BASE,
    VirtualClock,
    VirtualClockLoop,
    run,
)

__all__ = [
    "DEFAULT_WALL_BASE",
    "VirtualClock",
    "VirtualClockLoop",
    "run",
]
