"""A deterministic virtual-time asyncio event loop.

``VirtualClockLoop`` is a ``SelectorEventLoop`` whose ``time()`` is a
virtual clock: the idle step *advances the clock to the next scheduled
callback* instead of sleeping through the gap, while real loopback I/O
still drains — the selector is polled with a zero timeout (twice, with a
scheduler yield between, giving the kernel one beat to surface in-flight
loopback events), and only when no fd is ready and no callback is due
does virtual time jump. TCP handshakes between co-hosted ``Cluster``
instances therefore complete at virtual-instant speed, and an hour of
gossip-interval waiting costs microseconds of wall clock — the
FoundationDB-style deterministic-simulation posture, applied to the
asyncio backend (docs/virtual-time.md has the full contract).

Determinism: asyncio's ready queue is FIFO and the timer heap orders by
deadline — but under a virtual clock, *same-deadline* timers are the
common case (every ticker armed in one ``gather`` shares an exact float
deadline), and heap order among equals is an implementation accident.
``VirtualClockLoop`` therefore schedules every timer through a seeded
tie-break: each ``call_at`` draws a 64-bit key from a seeded stream, and
same-deadline timers execute in the seeded permutation. Same seed ⇒ same
interleaving, bit-identical replay; different seed ⇒ a genuinely
different legal schedule (the cheapest chaos amplifier there is).
The fd side gets the same treatment: every non-empty selector batch is
settled (one scheduler beat for in-flight loopback bytes to surface)
and returned in canonical fd order, so wake order within a batch is a
function of the ready SET, never of epoll's internal list order.

What stays real: the fd world. Socket readiness, kernel buffers, and
worker threads (``asyncio.to_thread``, executor jobs) run in real time —
virtual time can advance while a thread works, which is exactly the
documented determinism boundary (keep blocking-thread work out of
determinism-sensitive soaks; ``ChaosHarness(virtual_time=True)`` does).
"""

from __future__ import annotations

import asyncio
import heapq
import os
import random
import selectors
from datetime import datetime

from ..utils.clock import UTC

__all__ = [
    "DEFAULT_WALL_BASE",
    "VirtualClock",
    "VirtualClockLoop",
    "run",
]

# The virtual epoch: a fixed, obviously-synthetic wall base so virtual
# runs are reproducible run-to-run (a real ``time.time()`` base would
# leak wall-clock nondeterminism into every trace timestamp).
# 2020-01-01T00:00:00Z.
DEFAULT_WALL_BASE = 1_577_836_800.0


class VirtualClock:
    """The loop's clock, satisfying ``utils.clock.Clock``: ``monotonic``
    is the virtual axis the loop advances, ``wall``/``now`` are the same
    axis offset by a fixed synthetic epoch. Only the loop's idle step
    moves it (monotonically — time never runs backwards)."""

    __slots__ = ("_t", "wall_base")

    def __init__(
        self, start: float = 0.0, *, wall_base: float = DEFAULT_WALL_BASE
    ) -> None:
        self._t = float(start)
        self.wall_base = float(wall_base)

    def monotonic(self) -> float:
        return self._t

    def wall(self) -> float:
        return self.wall_base + self._t

    def now(self) -> datetime:
        return datetime.fromtimestamp(self.wall(), UTC)

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"clocks do not run backwards: advance({dt})")
        self._t += dt
        return self._t


class _VirtualSelector:
    """Selector wrapper implementing the idle-step contract.

    ``select(timeout)`` never sleeps through a positive timeout: it
    polls at zero timeout, yields the OS scheduler one beat and polls
    once more (in-flight loopback events — an accepted connection, a
    written buffer — become epoll-visible within that beat), and only
    then, with the fd world provably quiet, advances virtual time by
    the full timeout and reports idleness. A ``None`` timeout (no
    timers scheduled at all) blocks for REAL — the loop is waiting on
    I/O or a cross-thread wakeup, and spinning would burn a core.
    """

    __slots__ = ("_real", "_clock")

    def __init__(
        self, real: selectors.BaseSelector, clock: VirtualClock
    ) -> None:
        self._real = real
        self._clock = clock

    # -- the virtual-time idle step --------------------------------------
    def select(self, timeout: float | None = None):
        events = self._real.select(0)
        if events:
            return self._settled(events)
        if timeout is not None and timeout <= 0:
            return events
        if timeout is None:
            # No timers scheduled: there is nothing to advance TO. Wait
            # for real I/O (or a call_soon_threadsafe self-pipe wakeup).
            return self._settled(self._real.select(None))
        os.sched_yield()
        events = self._real.select(0)
        if events:
            return self._settled(events)
        self._clock.advance(timeout)
        return []

    def _settled(self, events):
        """Canonicalize an event batch: one scheduler beat for in-flight
        stragglers to become epoll-visible, merge, and return in fd
        order. epoll's ready-list order (and which side of a poll
        boundary a just-written fd lands on) is kernel timing, not
        protocol state — without this, two tasks woken "simultaneously"
        can swap between same-seed runs and break byte-replay."""
        if not events:
            return events
        os.sched_yield()
        merged = {key.fd: (key, mask) for key, mask in events}
        for key, mask in self._real.select(0):
            prev = merged.get(key.fd)
            merged[key.fd] = (key, mask | (prev[1] if prev else 0))
        return [merged[fd] for fd in sorted(merged)]

    # -- plain delegation -------------------------------------------------
    def register(self, fileobj, events, data=None):
        return self._real.register(fileobj, events, data)

    def unregister(self, fileobj):
        return self._real.unregister(fileobj)

    def modify(self, fileobj, events, data=None):
        return self._real.modify(fileobj, events, data)

    def get_key(self, fileobj):
        return self._real.get_key(fileobj)

    def get_map(self):
        return self._real.get_map()

    def close(self):
        return self._real.close()


class _SeededTimerHandle(asyncio.TimerHandle):
    """A TimerHandle whose ordering among same-deadline peers is a
    seeded 64-bit key instead of heap accident."""

    __slots__ = ("_vtb",)

    def __init__(self, vtb, when, callback, args, loop, context=None):
        super().__init__(when, callback, args, loop, context)
        self._vtb = vtb

    def _key(self):
        return (self._when, self._vtb)

    def __lt__(self, other):
        when = getattr(other, "_when", None)
        if when is None:
            return NotImplemented
        if self._when != when:
            return self._when < when
        return self._vtb < getattr(other, "_vtb", self._vtb)

    def __le__(self, other):
        lt = self.__lt__(other)
        if lt is NotImplemented:
            return NotImplemented
        return lt or self == other

    def __gt__(self, other):
        le = self.__le__(other)
        if le is NotImplemented:
            return NotImplemented
        return not le

    def __ge__(self, other):
        lt = self.__lt__(other)
        if lt is NotImplemented:
            return NotImplemented
        return not lt


class VirtualClockLoop(asyncio.SelectorEventLoop):
    """The deterministic compressed-clock event loop (module docstring
    has the contract). ``aiocluster_clock`` is the attribute the
    ``utils.clock`` seam resolves, so every clock consumer in
    runtime/serve/faults/obs follows this clock automatically."""

    def __init__(
        self,
        *,
        seed: int = 0,
        start: float = 0.0,
        wall_base: float = DEFAULT_WALL_BASE,
    ) -> None:
        self.vclock = VirtualClock(start, wall_base=wall_base)
        super().__init__(_VirtualSelector(selectors.DefaultSelector(), self.vclock))
        self.seed = seed
        # The seam contract (utils/clock.py): a loop that carries
        # ``aiocluster_clock`` owns ambient time for code running on it.
        self.aiocluster_clock = self.vclock
        self.aiocluster_virtual = True
        # The tie-break stream: one seeded Mersenne Twister, one 64-bit
        # draw per scheduled timer. Deterministic across platforms and
        # runs for a given seed; a different seed permutes every
        # same-deadline group differently.
        self._vtb_rng = random.Random(seed)

    def time(self) -> float:
        return self.vclock.monotonic()

    def call_at(self, when, callback, *args, context=None):
        """``BaseEventLoop.call_at`` with the seeded tie-break handle —
        the only scheduling entry point for timers (``call_later`` and
        every ``asyncio.sleep``/``wait_for`` funnel through here)."""
        self._check_closed()
        if self._debug:
            self._check_thread()
            self._check_callback(callback, "call_at")
        timer = _SeededTimerHandle(
            self._vtb_rng.getrandbits(64), when, callback, args, self, context
        )
        if timer._source_traceback:
            del timer._source_traceback[-1]
        heapq.heappush(self._scheduled, timer)
        timer._scheduled = True
        return timer


def _cancel_all_tasks(loop: asyncio.AbstractEventLoop) -> None:
    # asyncio.runners shape: cancel stragglers, drain them, surface
    # their exceptions through the loop handler.
    to_cancel = asyncio.all_tasks(loop)
    if not to_cancel:
        return
    for task in to_cancel:
        task.cancel()
    loop.run_until_complete(
        asyncio.gather(*to_cancel, return_exceptions=True)
    )
    for task in to_cancel:
        if task.cancelled():
            continue
        if task.exception() is not None:
            loop.call_exception_handler(
                {
                    "message": "unhandled exception during vtime.run() shutdown",
                    "exception": task.exception(),
                    "task": task,
                }
            )


def run(
    main,
    *,
    seed: int = 0,
    start: float = 0.0,
    wall_base: float = DEFAULT_WALL_BASE,
    debug: bool | None = None,
):
    """``asyncio.run``, on a ``VirtualClockLoop``.

    The virtual-time entry point: creates the loop with the given seed
    and virtual epoch, installs it as the thread's event loop (so
    libraries that call ``get_event_loop`` inside follow the virtual
    clock), runs ``main`` to completion, then tears down exactly as
    ``asyncio.run`` would (cancel stragglers, drain async generators
    and the default executor, close). Returns ``main``'s result.
    """
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        pass
    else:
        raise RuntimeError(
            "vtime.run() cannot be called from a running event loop"
        )
    loop = VirtualClockLoop(seed=seed, start=start, wall_base=wall_base)
    try:
        asyncio.set_event_loop(loop)
        if debug is not None:
            loop.set_debug(debug)
        return loop.run_until_complete(main)
    finally:
        try:
            _cancel_all_tasks(loop)
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            asyncio.set_event_loop(None)
            loop.close()
