"""Byzantine defense guards for the apply-delta path (docs/faults.md).

ScuttleButt's reconciliation correctness rests on two assumptions a
hostile fleet violates: each node is the sole writer of its own keyspace
(van Renesse et al.), and advertised version state is honest. These
guards re-establish what is *verifiable without signatures* at the
receiver, as pure self-consistency checks on the inbound
:class:`~aiocluster_tpu.core.messages.Delta` — no receiver state is
consulted, so a verdict depends only on the message:

1. **Owner-write guard** — a NodeDelta targeting the RECEIVER'S own
   node id is rejected whole (kind ``owner_violation``): the receiver
   is the sole writer of its keyspace, and no honest peer ever sends a
   node its own state (a peer's digest view of you can never be ahead
   of you). The ACT03x static invariant, enforced at runtime against
   remote writers.
2. **Floor guard** — a key-value at or below the delta's own
   ``from_version_excluded`` is dropped (kind ``stale_replay``): the
   delta claims to carry "everything strictly above the floor", so a
   below-floor entry is self-inconsistent — the stale-version replay
   shape, whose real payload is the ``max_version`` stamp that would
   fast-forward the receiver past data it never got.
3. **Over-stamp guard** — a key-value whose version exceeds the delta's
   own ``max_version`` stamp is dropped (kind ``owner_violation``): an
   honest sender's stamp is the highest version it has seen, so carried
   data past it is fabricated.
4. **Support guard** — a ``max_version`` fast-forward must be supported
   by the delta itself: honest senders always satisfy
   ``max_version <= max(carried key-value versions, last_gc_version)``
   (every version the owner ever issued is live, tombstoned, or GC'd —
   the invariant is preserved inductively through apply_delta, including
   under concurrent handshakes). An unsupported stamp is refused — set
   to None, the truncated-delta semantics — and counted (kind
   ``digest_inflation``). A delta that lost ANY key-value to guards 2/3
   also has its stamp refused (uncounted): fast-forwarding past dropped
   data would be exactly the poison the attack intends.

Honest traffic is untouched — ``sanitize_delta`` returns the original
``Delta`` object (and an empty rejection dict) on the clean path, so the
fault-free hot path allocates nothing. The GossipEngine counts
rejections in ``aiocluster_byzantine_rejected_total{kind}``; rejection
units match the injector's (faults/runtime.py): per key-value for floor
and over-stamp violations and for owner-guard hits (fabricated
NodeDeltas carry one key-value each), per stamp for support refusals —
so a test can assert EXACT injected == rejected equality.

A residual surface remains by construction: a fabricator that invents a
self-consistent future history (stamp raised to match its fabrication)
is detectable only by the true owner (guard 1). That surface is what
the tolerance atlas maps (benchmarks/byzantine_bench.py).
"""

from __future__ import annotations

from .identity import NodeId
from .messages import Delta, NodeDelta

# Rejection-metric label values (aiocluster_byzantine_rejected_total).
REJECT_KINDS = ("owner_violation", "stale_replay", "digest_inflation")


def _bump(rejections: dict[str, int], kind: str, n: int = 1) -> None:
    rejections[kind] = rejections.get(kind, 0) + n


def sanitize_node_delta(
    nd: NodeDelta, self_id: NodeId, rejections: dict[str, int]
) -> NodeDelta | None:
    """One NodeDelta through the guards: the (possibly rebuilt) delta to
    apply, or None when nothing survives. ``rejections`` is bumped in
    place. Returns the ORIGINAL object when clean."""
    if nd.node_id == self_id:
        # Guard 1: nobody writes our keyspace but us.
        _bump(rejections, "owner_violation", max(1, len(nd.key_values)))
        return None
    floor = nd.from_version_excluded
    stamp = nd.max_version
    kept = []
    dropped = False
    for kv in nd.key_values:
        if kv.version <= floor:
            _bump(rejections, "stale_replay")
            dropped = True
            continue
        if stamp is not None and kv.version > stamp:
            _bump(rejections, "owner_violation")
            dropped = True
            continue
        kept.append(kv)
    new_stamp = stamp
    if stamp is not None:
        if dropped:
            # Data was rejected: fast-forwarding past it would be the
            # poison itself. Truncated-delta semantics, not counted
            # (the per-kv rejections above already were).
            new_stamp = None
        else:
            support = max(
                (kv.version for kv in kept), default=0
            )
            support = max(support, nd.last_gc_version)
            if stamp > support:
                # Guard 4: the stamp claims versions the delta itself
                # cannot account for.
                _bump(rejections, "digest_inflation")
                new_stamp = None
    if not dropped and new_stamp == stamp:
        return nd
    if not kept and new_stamp is None and nd.last_gc_version == 0:
        return None  # nothing left to apply
    return NodeDelta(
        node_id=nd.node_id,
        from_version_excluded=nd.from_version_excluded,
        last_gc_version=nd.last_gc_version,
        key_values=kept,
        max_version=new_stamp,
    )


def sanitize_delta(
    delta: Delta, self_id: NodeId
) -> tuple[Delta, dict[str, int]]:
    """The whole inbound delta through the guards: (clean delta,
    rejection counts by kind). The clean path returns ``delta`` itself
    and ``{}`` — zero allocation for honest traffic."""
    rejections: dict[str, int] = {}
    out: list[NodeDelta] = []
    dirty = False
    for nd in delta.node_deltas:
        clean = sanitize_node_delta(nd, self_id, rejections)
        if clean is None:
            dirty = True
            continue
        if clean is not nd:
            dirty = True
        out.append(clean)
    if not dirty:
        return delta, rejections
    return Delta(node_deltas=out), rejections
