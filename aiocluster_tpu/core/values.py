"""Versioned values and their lifecycle status.

Parity: reference entities.py:25-49. A key's value carries a per-owner
monotonic version and a status:

- ``SET``: live value.
- ``DELETED``: tombstone (value cleared); removed for good once the
  grace period elapses and the GC watermark advances past it.
- ``DELETE_AFTER_TTL``: like SET but scheduled to become eligible for GC
  after the grace period (a soft TTL).
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime
from enum import IntEnum


class VersionStatusEnum(IntEnum):
    """Lifecycle status of a versioned key (wire values match the reference
    proto enum messages.proto:33-37 for interop)."""

    SET = 0
    DELETED = 1
    DELETE_AFTER_TTL = 2


# Shorter idiomatic alias used internally.
KeyStatus = VersionStatusEnum


@dataclass(slots=True)
class VersionedValue:
    """A value with its owner-assigned version, status, and the time the
    status last changed (drives tombstone/TTL GC)."""

    value: str
    version: int
    status: VersionStatusEnum
    status_change_ts: datetime

    def is_deleted(self) -> bool:
        return self.status in (KeyStatus.DELETED, KeyStatus.DELETE_AFTER_TTL)
