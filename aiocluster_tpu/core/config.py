"""Cluster configuration.

Parity: reference entities.py:85-115. Field names and defaults are kept
identical so code written against the reference's ``Config`` ports over
unchanged. New fields beyond the reference are documented inline.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass, field
from datetime import timedelta

from typing import TYPE_CHECKING

from ..faults.plan import FaultPlan
from .identity import Address, NodeId

if TYPE_CHECKING:  # keep core/ numpy-free: models.topology imports numpy
    from ..models.topology import Heterogeneity

# The reference's default delta MTU (entities.py:105): the cap on one
# encoded DeltaPb. The number happens to be the classic UDP-payload
# maximum, but the transport is TCP (asyncio.start_server /
# open_connection) — 65,507 only bounds delta payloads. Shared by
# Config, the benchmarks, and the sim's bytes-budget conversion so there
# is exactly one copy of the number.
DEFAULT_MAX_PAYLOAD_SIZE = 65_507


@dataclass(frozen=True, slots=True, eq=True)
class FailureDetectorConfig:
    """Phi-accrual tuning (reference entities.py:85-91; the ``phi_threshhold``
    spelling is preserved for API compatibility)."""

    phi_threshhold: float = 8.0
    sampling_window_size: int = 1_000
    max_interval: timedelta = timedelta(seconds=10)
    initial_interval: timedelta = timedelta(seconds=5)
    dead_node_grace_period: timedelta = timedelta(hours=24)


@dataclass(frozen=True, slots=True, eq=True)
class PersistenceConfig:
    """Durable node state (runtime/persist.py, docs/robustness.md
    "Durability & lifecycle"). ``path`` is this node's private store
    directory (one node per directory). Every snapshot/marker file is
    written tmp+fsync+``os.replace``; the intent log is CRC-framed and
    torn tails truncate at the last valid frame. A corrupt snapshot is
    refused loudly (counted fallback to the reference's amnesiac boot —
    never a wrong recovery)."""

    path: str
    # Snapshot the keyspace every N initiated gossip rounds (the intent
    # log covers writes between snapshots), or earlier once the log
    # outgrows ``log_max_bytes``.
    snapshot_interval_rounds: int = 64
    log_max_bytes: int = 1 << 20
    # Also persist the replicated peer view (peer NodeStates) so a warm
    # rejoin advertises real digest floors and peers send deltas, not
    # full keyspaces. Recovered peer entries are HINTS: they re-verify
    # through normal digests and never bypass newer-generation-wins.
    restore_peers: bool = True
    # fsync the intent log on every appended write. Off by default: the
    # log is flushed per write and fsync'd at every snapshot/close, and
    # the CRC framing guarantees recovery is the pre- or post-write
    # state either way; per-write fsync only narrows the window in
    # which a power loss drops the tail writes. NOTE: the journal write
    # runs inline on the event loop (the KV API is synchronous), so
    # turning this on blocks the loop for one fsync per owner write —
    # milliseconds to tens of milliseconds on loaded disks, enough to
    # skew adaptive-timeout RTT samples and trip serve-tier loop-lag
    # shedding under write bursts.
    fsync_writes: bool = False


@dataclass(frozen=True, slots=True, eq=True)
class Config:
    """Runtime configuration for one cluster node."""

    node_id: NodeId
    cluster_id: str = "default-cluster"
    gossip_interval: float = 1.0  # seconds between gossip rounds
    gossip_count: int = 3  # live peers contacted per round
    seed_nodes: list[Address] = field(default_factory=list)
    marked_for_deletion_grace_period: int = 3600 * 2  # seconds
    failure_detector: FailureDetectorConfig = field(
        default_factory=FailureDetectorConfig,
    )
    max_payload_size: int = DEFAULT_MAX_PAYLOAD_SIZE  # delta MTU, encoded bytes
    connect_timeout: float = 3.0
    read_timeout: float = 3.0
    write_timeout: float = 3.0
    max_concurrent_gossip: int = 32
    hook_queue_maxsize: int = 10_000
    drain_hooks_on_shutdown: bool = True
    hook_shutdown_timeout: float = 5.0
    tls_server_context: ssl.SSLContext | None = None
    tls_client_context: ssl.SSLContext | None = None
    tls_server_hostname: str | None = None
    # New in aiocluster_tpu: fraction of gossip_interval used as random
    # startup jitter so co-booted nodes desynchronise their rounds
    # (the reference left this as a TODO, ticker.py:27-28).
    gossip_jitter: float = 0.0
    # New in aiocluster_tpu: persistent peer channels. When True (the
    # default) the initiator keeps gossip connections in a per-peer pool
    # and the responder serves successive handshakes on one connection;
    # wire format AND lifecycle interop with close-per-handshake peers
    # (the reference) is preserved — EOF after an Ack is a normal close,
    # and a pooled connection found dead is retried once on a fresh one.
    # False restores the reference's connect/teardown-per-round lifecycle.
    persistent_connections: bool = True
    # Idle pooled connections beyond this per-peer count are closed on
    # release (borrowed connections are not bounded: concurrent
    # handshakes to one peer are rare and short).
    pool_max_idle_per_peer: int = 2
    # Seconds an idle pooled connection survives between uses; the
    # responder waits the same window for the next Syn on a persistent
    # connection before closing it.
    pool_idle_timeout: float = 60.0
    # New in aiocluster_tpu: overload & degradation control
    # (docs/robustness.md). When True (the default) every handshake's
    # measured RTT feeds a per-peer EWMA mean + variance estimator
    # (runtime/health.py) and the gossip path's connect/read/write
    # waits use the ADAPTIVE per-peer timeout
    # ``mean + adaptive_timeout_k * stddev`` clamped to
    # [adaptive_timeout_min, read_timeout] — a slow peer surfaces as a
    # fast, cheap failure instead of burning the full fixed constant.
    # False restores the reference's fixed-constant liveness posture
    # exactly (like persistent_connections): no estimator is built and
    # every operation uses the configured constants.
    adaptive_timeouts: bool = True
    adaptive_timeout_k: float = 4.0
    adaptive_timeout_min: float = 0.25
    # Per-peer circuit breaker (docs/robustness.md): after
    # ``breaker_failure_threshold`` CONSECUTIVE handshake failures a
    # peer is quarantined from the gossip target draw (closed -> open)
    # and redialed on a decorrelated-jitter exponential backoff; when
    # the backoff expires, exactly one probe handshake is admitted
    # (half-open) — success closes the breaker, failure re-opens it
    # with a grown backoff. Backoff is measured in EFFECTIVE gossip
    # intervals so the quarantine cadence follows the round clock.
    # False constructs no breaker: failing peers are redialed at full
    # cadence forever, the reference behavior.
    circuit_breaker: bool = True
    breaker_failure_threshold: int = 3
    breaker_base_backoff_intervals: float = 2.0
    breaker_max_backoff_intervals: float = 64.0
    # New in aiocluster_tpu: deterministic fault injection
    # (docs/faults.md). When set, the cluster's transport (and, through
    # its dial path, the connection pool) is wrapped by a
    # FaultController compiled from the plan — injected connect
    # refusals, framed-read/write drops and delays, mid-handshake EOF,
    # partitions, crash windows. None (the default) constructs none of
    # it: every path is byte-identical to the fault-free build.
    fault_plan: FaultPlan | None = None
    # New in aiocluster_tpu: heterogeneity classes
    # (models/topology.Heterogeneity, docs/faults.md). Cadence classes
    # scale this node's gossip interval by its class
    # (``Cluster.effective_gossip_interval``); WAN latency/loss classes
    # compile to derived LinkFaults appended to the effective fault
    # plan (one injection machinery for configured and derived faults);
    # zone_bias biases live-target selection toward the node's own
    # zone. None (or the all-defaults instance) changes nothing.
    heterogeneity: "Heterogeneity | None" = None
    # New in aiocluster_tpu: the zero-copy wire data plane
    # (wire/segments.py, docs/migration.md difference #16). When True
    # (the default) outbound SynAck/Ack deltas are assembled from
    # segment-cached per-key-value encodings (each (node, key, version)
    # encodes ONCE, MTU packing runs on cached segment LENGTHS instead
    # of a size-then-encode double walk), the encoded digest section is
    # maintained incrementally per digest epoch, frames go out as
    # scatter-gather buffer lists (``writelines`` — no full-payload
    # ``b"".join``), and inbound frames decode from memoryview spans.
    # Frames are byte-identical either way (differential-fuzzed);
    # False restores the encode-per-peer-per-round reference-shaped
    # paths exactly.
    wire_fastpath: bool = True
    # New in aiocluster_tpu: durable node state (runtime/persist.py,
    # docs/robustness.md). When set, the cluster journals its own
    # keyspace to a crash-safe local store, restores it at boot (keeping
    # its previous generation when the store proves a clean shutdown,
    # else bumping it while still seeding version/GC watermarks for
    # delta catch-up), and ``Cluster.leave()`` drains gracefully. None
    # (the default) constructs none of it: every path is byte-identical
    # to the reference's amnesiac restart semantics.
    persistence: PersistenceConfig | None = None
    # New in aiocluster_tpu: wire-level span context
    # (docs/observability.md "Fleet telemetry"). When True, every
    # Syn/SynAck/Ack this node sends carries envelope field 7 — the
    # sender's name plus an initiator-chosen handshake id echoed by the
    # responder — so responder-side provenance applies name their
    # ``from_peer`` EXACTLY (no 30s send-join heuristic) and flight
    # recorders on both sides correlate one handshake's three packets.
    # Reference peers skip the unknown field. False (the default)
    # appends nothing: frames are byte-identical to the reference.
    trace_context: bool = False
    # New in aiocluster_tpu: gossip-borne self-telemetry
    # (obs/fleet.py, docs/observability.md "Fleet telemetry"). When
    # set, the node folds a compact health digest (heartbeat, phi
    # posture, live/dead counts, breaker-open peers, persist/rejoin
    # state, round-latency p50/p99, serve epoch, applied-kv watermark)
    # into its OWN keyspace under TELEMETRY_PREFIX every this-many
    # seconds — one owner write per interval, so the content epoch
    # bumps at most once per interval and SnapshotCache dedup / shared
    # payloads stay effective. Replicates like any key (guards,
    # segments fastpath, MTU budget); ``Cluster.fleet_view()`` and
    # ``GET /fleet`` assemble the fleet table from it. None (the
    # default) publishes nothing: the keyspace is byte-identical to the
    # reference's.
    telemetry_interval: float | None = None
