"""Phi-accrual failure detection.

Parity: reference failure_detector.py:12-162. The detector keeps, per peer,
a bounded window of inter-heartbeat intervals and scores suspicion as

    phi = elapsed_since_last_heartbeat / prior_weighted_mean_interval

(the reference's simplification of the Hayashibara et al. log-CDF phi; the
threshold default of 8.0 is calibrated for this ratio form). The mean is
regularised toward a configured prior with weight ``PRIOR_WEIGHT`` so a
freshly-seen node with few samples is not declared dead by noise.

Lifecycle: phi > threshold flips a node to dead and resets its window (so a
returning node must accumulate fresh evidence); dead for half the grace
period ⇒ excluded from digests (stops re-propagation); dead for the full
grace period ⇒ garbage-collected entirely. All methods take ``ts`` for
deterministic time-travel tests.
"""

from __future__ import annotations

from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .config import FailureDetectorConfig
from .identity import NodeId

__all__ = ("BoundedWindow", "FailureDetector", "HeartbeatWindow")

PRIOR_WEIGHT = 5.0  # pseudo-samples of the prior interval (reference :23)


class BoundedWindow:
    """Fixed-capacity ring of float samples with an O(1) running sum.

    Parity: reference BoundedArrayStats failure_detector.py:131-162.
    """

    __slots__ = ("_capacity", "_samples", "_next", "_sum", "_count")

    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("window capacity must be positive")
        self._capacity = capacity
        self._samples: list[float] = []
        self._next = 0  # slot to overwrite once full
        self._sum = 0.0
        self._count = 0

    def append(self, sample: float) -> None:
        if self._count < self._capacity:
            self._samples.append(sample)
            self._count += 1
        else:
            self._sum -= self._samples[self._next]
            self._samples[self._next] = sample
            self._next = (self._next + 1) % self._capacity
        self._sum += sample

    def sum(self) -> float:
        return self._sum

    def clear(self) -> None:
        self._samples.clear()
        self._next = 0
        self._sum = 0.0
        self._count = 0

    def __len__(self) -> int:
        return self._count


class HeartbeatWindow:
    """Inter-heartbeat sampling for one peer (reference SamplingWindow
    failure_detector.py:12-53)."""

    __slots__ = ("_intervals", "_last_heartbeat", "_max_interval", "_prior_mean")

    def __init__(
        self,
        window_size: int,
        max_interval: timedelta,
        prior_interval: timedelta,
    ) -> None:
        self._intervals = BoundedWindow(window_size)
        self._last_heartbeat: datetime | None = None
        self._max_interval = max_interval
        self._prior_mean = prior_interval.total_seconds()

    def report_heartbeat(self, ts: datetime | None = None) -> None:
        now = ts if ts is not None else utc_now()
        if self._last_heartbeat is not None:
            interval = now - self._last_heartbeat
            # Gaps beyond max_interval are outages, not samples — admitting
            # them would inflate the mean and mask real failures.
            if interval <= self._max_interval:
                self._intervals.append(interval.total_seconds())
        self._last_heartbeat = now

    def mean(self) -> float | None:
        n = len(self._intervals)
        if n == 0:
            return None
        return (self._intervals.sum() + PRIOR_WEIGHT * self._prior_mean) / (
            n + PRIOR_WEIGHT
        )

    def phi(self, ts: datetime | None = None) -> float | None:
        if self._last_heartbeat is None:
            return None
        mean = self.mean()
        if mean is None:
            return None
        now = ts if ts is not None else utc_now()
        elapsed = (now - self._last_heartbeat).total_seconds()
        return elapsed / mean

    def reset(self) -> None:
        self._intervals.clear()


class FailureDetector:
    """Tracks live/dead sets for all peers plus two-stage dead-node GC."""

    def __init__(self, config: FailureDetectorConfig) -> None:
        self._config = config
        self._windows: dict[NodeId, HeartbeatWindow] = {}
        self._live: set[NodeId] = set()
        self._dead: dict[NodeId, datetime] = {}  # node -> time of death

    # -- observations ---------------------------------------------------------

    def report_heartbeat(self, node_id: NodeId, ts: datetime | None = None) -> None:
        self._window_for(node_id).report_heartbeat(ts=ts)

    def phi(self, node_id: NodeId, ts: datetime | None = None) -> float | None:
        window = self._windows.get(node_id)
        return None if window is None else window.phi(ts=ts)

    def _window_for(self, node_id: NodeId) -> HeartbeatWindow:
        window = self._windows.get(node_id)
        if window is None:
            window = HeartbeatWindow(
                self._config.sampling_window_size,
                self._config.max_interval,
                self._config.initial_interval,
            )
            self._windows[node_id] = window
        return window

    # -- liveness -------------------------------------------------------------

    def live_nodes(self) -> list[NodeId]:
        return list(self._live)

    def dead_nodes(self) -> list[NodeId]:
        return list(self._dead)

    def update_node_liveness(
        self, node_id: NodeId, ts: datetime | None = None
    ) -> float | None:
        """Re-evaluate one peer's live/dead state; returns the phi the
        decision used (None = no heartbeat evidence yet), so telemetry
        can sample exactly the decision value without recomputing it."""
        now = ts if ts is not None else utc_now()
        phi = self.phi(node_id, ts=now)
        alive = phi is not None and phi <= self._config.phi_threshhold
        if alive:
            self._live.add(node_id)
            self._dead.pop(node_id, None)
        else:
            self._live.discard(node_id)
            self._dead.setdefault(node_id, now)
            window = self._windows.get(node_id)
            if window is not None:
                # A dead node must re-earn its liveness with fresh samples.
                window.reset()
        return phi

    def mark_dead(self, node_id: NodeId, ts: datetime | None = None) -> bool:
        """Administratively move a peer to the dead set NOW — the
        graceful-departure path (a ``Leave`` announcement is proof of
        death no phi accrual needs to infer). Returns True when this
        call actually transitioned the node (already-dead peers keep
        their original time of death, so the two-stage GC clock is not
        reset by duplicate announcements). The window resets like a
        phi-detected death: a returning incarnation re-earns liveness
        with fresh samples."""
        now = ts if ts is not None else utc_now()
        self._live.discard(node_id)
        if node_id in self._dead:
            return False
        self._dead[node_id] = now
        window = self._windows.get(node_id)
        if window is not None:
            window.reset()
        return True

    # -- dead-node lifecycle --------------------------------------------------

    def scheduled_for_deletion_nodes(self, ts: datetime | None = None) -> list[NodeId]:
        """Dead for half the grace period: excluded from digests so their
        state stops re-propagating while still being individually GC-able."""
        now = ts if ts is not None else utc_now()
        half_grace = self._config.dead_node_grace_period / 2
        return [
            node_id
            for node_id, died_at in self._dead.items()
            if now >= died_at + half_grace
        ]

    def garbage_collect(self, ts: datetime | None = None) -> list[NodeId]:
        """Dead for the full grace period: forget them entirely. Returns the
        collected nodes so the caller can drop their cluster state too."""
        now = ts if ts is not None else utc_now()
        grace = self._config.dead_node_grace_period
        collected = [
            node_id for node_id, died_at in self._dead.items() if now >= died_at + grace
        ]
        for node_id in collected:
            del self._dead[node_id]
            self._windows.pop(node_id, None)
        return collected
