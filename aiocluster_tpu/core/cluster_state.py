"""Cluster-wide replicated state and ScuttleButt reconciliation.

Parity: reference state.py:290-433 (``ClusterState``, ``staleness_score``).

The interesting method is ``compute_partial_delta_respecting_mtu``: given a
peer's digest, build the delta of everything the peer is missing, greedily
packed under a byte MTU. Two deliberate improvements over the reference:

1. **O(total kvs) packing.** The reference re-serialises the entire delta
   protobuf after every appended key-value to test the MTU
   (state.py:392-398) — quadratic in delta size. We account encoded sizes
   incrementally with exact proto3 arithmetic (wire/sizes.py), so packing
   is linear while selecting the *same* key-values byte-for-byte (the
   ``max_version`` field is reserved in the accounting regardless of
   whether it is finally emitted).
2. **No lost updates on truncation.** The reference always stamps the
   delta with the owner's full ``max_version`` (state.py:389); a receiver
   of an MTU-truncated delta then advertises versions it never received
   and the gap is never retransmitted. We only stamp ``max_version`` when
   every stale key-value fit — the chitchat-correct rule — so truncated
   ranges are re-requested on the next round.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from dataclasses import dataclass
from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .identity import Address, NodeId
from .kvstate import KeyChangeFn, NodeState
from .messages import Delta, Digest, KeyValueUpdate, NodeDelta


@dataclass(frozen=True, slots=True)
class Staleness:
    """How far behind a peer is on one node's keyspace."""

    is_unknown: bool
    max_version: int
    num_stale_key_values: int


def staleness_score(node_state: NodeState, floor_version: int) -> Staleness | None:
    """None when the peer is up to date; otherwise a score (parity:
    reference state.py:425-433)."""
    if node_state.max_version <= floor_version:
        return None
    is_unknown = floor_version == 0
    if is_unknown:
        num_stale = len(node_state.key_values)
    else:
        num_stale = sum(1 for _ in node_state.stale_key_values(floor_version))
    return Staleness(is_unknown, node_state.max_version, num_stale)


class ClusterState:
    """All node keyspaces known to this process, keyed by NodeId."""

    def __init__(self, seed_addrs: set[Address] | None = None) -> None:
        self._node_states: dict[NodeId, NodeState] = {}
        self._seed_addrs: set[Address] = seed_addrs or set()

    # -- membership -----------------------------------------------------------

    def node_state(self, node_id: NodeId) -> NodeState | None:
        return self._node_states.get(node_id)

    def node_state_or_default(self, node_id: NodeId) -> NodeState:
        return self._node_states.setdefault(node_id, NodeState(node_id))

    def nodes(self) -> Sequence[NodeId]:
        return tuple(self._node_states)

    def node_states(self) -> dict[NodeId, NodeState]:
        """Shallow copy of the per-node state map — the snapshot surface
        (Cluster.snapshot), so readers never hold the live dict while
        gossip mutates it."""
        return dict(self._node_states)

    def seed_addrs(self) -> Sequence[Address]:
        return tuple(self._seed_addrs)

    def remove_node(self, node_id: NodeId) -> None:
        self._node_states.pop(node_id, None)

    # -- reconciliation -------------------------------------------------------

    def apply_delta(
        self,
        delta: Delta,
        ts: datetime | None = None,
        on_key_change: KeyChangeFn | None = None,
    ) -> None:
        now = ts if ts is not None else utc_now()
        for nd in delta.node_deltas:
            ns = self.node_state_or_default(nd.node_id)
            ns.apply_delta(nd, ts=now, on_key_change=on_key_change)

    def compute_digest(self, scheduled_for_deletion: set[NodeId]) -> Digest:
        """Digest of every known node except those scheduled for deletion
        (excluding them stops their state re-propagating before GC)."""
        return Digest(
            {
                node_id: ns.digest()
                for node_id, ns in self._node_states.items()
                if node_id not in scheduled_for_deletion
            }
        )

    def gc_marked_for_deletion(self, grace_period: timedelta) -> None:
        for ns in self._node_states.values():
            ns.gc_marked_for_deletion(grace_period)

    def compute_partial_delta_respecting_mtu(
        self,
        digest: Digest,
        mtu: int,
        scheduled_for_deletion: set[NodeId],
        size_model: Callable[..., object] | None = None,
    ) -> Delta:
        """Build the delta a peer (described by ``digest``) is missing,
        packed under ``mtu`` encoded bytes.

        For each node the peer is stale on, key-values above the peer's
        floor version are sent in increasing version order, so a replica's
        knowledge of any owner is always a *version-prefix* of the owner's
        history — the invariant the TPU sim backend exploits by collapsing
        per-replica knowledge to a single watermark integer.
        """
        if size_model is None:
            from ..wire.sizes import DeltaSizeModel

            size_model = DeltaSizeModel
        sizes = size_model()

        candidates: list[tuple[NodeState, int]] = []
        for node_id, ns in self._node_states.items():
            if node_id in scheduled_for_deletion:
                continue
            peer = digest.node_digests.get(node_id)
            peer_gc = peer.last_gc_version if peer is not None else 0
            peer_max = peer.max_version if peer is not None else 0
            if ns.max_version <= peer_max:
                continue
            # If the peer is so far behind that our GC watermark has passed
            # everything it knows, restart it from scratch (version floor 0).
            reset = peer_gc < ns.last_gc_version and peer_max < ns.last_gc_version
            floor = 0 if reset else peer_max
            # ns.max_version > peer_max >= floor always holds here, so the
            # node is stale by construction (no need to score it).
            candidates.append((ns, floor))

        node_deltas: list[NodeDelta] = []
        for ns, floor in candidates:
            stale = sorted(
                (
                    KeyValueUpdate(k, vv.value, vv.version, vv.status)
                    for k, vv in ns.stale_key_values(floor)
                ),
                key=lambda kv: kv.version,
            )
            if not stale:
                continue

            # Reserve max_version bytes up front so packing decisions match
            # the reference's accounting; emit it only if nothing truncates.
            body = sizes.node_delta_base(ns.node, floor, ns.last_gc_version,
                                         ns.max_version)
            selected: list[KeyValueUpdate] = []
            truncated = False
            for kv in stale:
                grown = body + sizes.kv_increment(kv)
                if sizes.delta_total_with(grown) > mtu:
                    truncated = True
                    break
                body = grown
                selected.append(kv)

            if selected:
                node_deltas.append(
                    NodeDelta(
                        node_id=ns.node,
                        from_version_excluded=floor,
                        last_gc_version=ns.last_gc_version,
                        key_values=selected,
                        max_version=None if truncated else ns.max_version,
                    )
                )
                sizes.commit(body)

            if sizes.total() >= mtu:
                break

        return Delta(node_deltas=node_deltas)
