"""Cluster-wide replicated state and ScuttleButt reconciliation.

Parity: reference state.py:290-433 (``ClusterState``, ``staleness_score``).

The interesting method is ``compute_partial_delta_respecting_mtu``: given a
peer's digest, build the delta of everything the peer is missing, greedily
packed under a byte MTU. Two deliberate improvements over the reference:

1. **O(total kvs) packing.** The reference re-serialises the entire delta
   protobuf after every appended key-value to test the MTU
   (state.py:392-398) — quadratic in delta size. We account encoded sizes
   incrementally with exact proto3 arithmetic (wire/sizes.py), so packing
   is linear while selecting the *same* key-values byte-for-byte (the
   ``max_version`` field is reserved in the accounting regardless of
   whether it is finally emitted).
2. **No lost updates on truncation.** The reference always stamps the
   delta with the owner's full ``max_version`` (state.py:389); a receiver
   of an MTU-truncated delta then advertises versions it never received
   and the gap is never retransmitted. We only stamp ``max_version`` when
   every stale key-value fit — the chitchat-correct rule — so truncated
   ranges are re-requested on the next round.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from dataclasses import dataclass
from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .identity import Address, NodeId
from .kvstate import KeyChangeFn, NodeState
from .messages import Delta, Digest, KeyValueUpdate, NodeDelta, NodeDigest

# The wire layer's encode surface, used by the zero-copy packers below.
# Safe at module level: wire/ imports only core SUBMODULES (identity,
# messages, values), never this one — and importing it here keeps the
# helpers out of the per-handshake call path.
from ..wire.proto import _encode_digest_entry  # noqa: E402
from ..wire.segments import (  # noqa: E402
    EMPTY_ENCODED_DELTA,
    EncodedDelta,
    SharedNodePayload,
    node_delta_parts,
)
from ..wire.sizes import DeltaSizeModel as _DeltaSizeModel  # noqa: E402


@dataclass(frozen=True, slots=True)
class Staleness:
    """How far behind a peer is on one node's keyspace."""

    is_unknown: bool
    max_version: int
    num_stale_key_values: int


def staleness_score(node_state: NodeState, floor_version: int) -> Staleness | None:
    """None when the peer is up to date; otherwise a score (parity:
    reference state.py:425-433)."""
    if node_state.max_version <= floor_version:
        return None
    is_unknown = floor_version == 0
    if is_unknown:
        num_stale = len(node_state.key_values)
    else:
        num_stale = sum(1 for _ in node_state.stale_key_values(floor_version))
    return Staleness(is_unknown, node_state.max_version, num_stale)


class ClusterState:
    """All node keyspaces known to this process, keyed by NodeId.

    Digest computation is incrementally cached: every NodeState created
    here carries a change hook that adds its node to a dirty-set when a
    digest field (heartbeat / max_version / last_gc_version) moves, so
    ``compute_digest`` rebuilds O(dirty) per-node entries — and when
    nothing moved at all, returns the previously assembled ``Digest``
    object outright. ``digest_cache_stats`` exposes plain counters
    (rebuilds / hits / reuses) that the runtime exports as metrics and
    tests assert on; ``digest_epoch`` is a monotonic generation the
    engine keys its encoded-Syn cache on.
    """

    def __init__(self, seed_addrs: set[Address] | None = None) -> None:
        self._node_states: dict[NodeId, NodeState] = {}
        self._seed_addrs: set[Address] = seed_addrs or set()
        self._digest_cache: dict[NodeId, NodeDigest] = {}
        self._dirty: set[NodeId] = set()
        self._epoch = 0
        self._assembled: Digest | None = None
        self._assembled_key: tuple[int, frozenset[NodeId]] | None = None
        self.digest_cache_stats: dict[str, int] = {
            "rebuilds": 0,  # per-node NodeDigest reconstructions
            "hits": 0,      # per-node entries served from cache
            "reuses": 0,    # whole assembled Digests served unchanged
            # Wire fast path (digest_wire_parts): per-node encoded
            # digest entries rebuilt, and whole assembled parts lists
            # served unchanged.
            "parts_rebuilds": 0,
            "parts_reuses": 0,
        }
        # Encoded digest section, maintained incrementally alongside
        # the NodeDigest cache above: one encoded entry (the complete
        # field-1 submessage bytes) per node, its own dirty set (the
        # two consumers must not clear each other's), and the live
        # assembled parts list — patched IN PLACE per dirty entry
        # (O(dirty) per epoch), fully rebuilt only when membership
        # order changes or an excluded set is in force.
        self._dp_entries: dict[NodeId, bytes] = {}
        self._dp_dirty: set[NodeId] = set()
        self._dp_parts: list[bytes] | None = None
        self._dp_index: dict[NodeId, int] = {}
        self._dp_total = 0
        self._dp_order_dirty = True
        self._dp_assembled: tuple | None = None

    # -- membership -----------------------------------------------------------

    def node_state(self, node_id: NodeId) -> NodeState | None:
        return self._node_states.get(node_id)

    def node_state_or_default(self, node_id: NodeId) -> NodeState:
        ns = self._node_states.get(node_id)
        if ns is None:
            ns = NodeState(node_id)
            ns._on_change = lambda: self.mark_dirty(node_id)
            self._node_states[node_id] = ns
            self.mark_dirty(node_id)
        return ns

    def install_node_state(self, ns: NodeState) -> None:
        """Install a fully-built NodeState — the persistence layer's
        recovery path (runtime/persist.py): a restored keyspace (own or
        peer hint) enters through the same hook-wiring and dirty-marking
        as ``node_state_or_default``, so digest caching stays sound.
        Replaces any existing state for the node."""
        ns._on_change = lambda: self.mark_dirty(ns.node)
        self._node_states[ns.node] = ns
        self.mark_dirty(ns.node)

    def mark_dirty(self, node_id: NodeId) -> None:
        """Invalidate the cached digest entry for ``node_id``. Fired
        automatically by every NodeState mutator; call it manually after
        white-box direct field writes."""
        self._dirty.add(node_id)
        self._dp_dirty.add(node_id)
        self._epoch += 1

    @property
    def digest_epoch(self) -> int:
        """Monotonic generation: bumps whenever any digest field changes
        (or membership does). Equal epochs ⇒ identical digests."""
        return self._epoch

    def nodes(self) -> Sequence[NodeId]:
        return tuple(self._node_states)

    def node_states(self) -> dict[NodeId, NodeState]:
        """Shallow copy of the per-node state map (live NodeState refs) —
        for synchronous O(changes) readers, so they never hold the live
        dict while gossip mutates it."""
        return dict(self._node_states)

    def node_states_copy(self) -> dict[NodeId, NodeState]:
        """Detached deep copy of every node's state — the snapshot
        surface (Cluster.snapshot): mutating the fleet afterwards can
        never retroactively mutate a taken snapshot (delete/TTL rewrite
        VersionedValues in place, so sharing refs would leak future
        mutations into it)."""
        return {nid: ns.copy() for nid, ns in self._node_states.items()}

    def seed_addrs(self) -> Sequence[Address]:
        return tuple(self._seed_addrs)

    def remove_node(self, node_id: NodeId) -> None:
        self._node_states.pop(node_id, None)
        self._digest_cache.pop(node_id, None)
        self._dirty.discard(node_id)
        self._dp_entries.pop(node_id, None)
        self._dp_dirty.discard(node_id)
        self._dp_order_dirty = True
        self._epoch += 1

    # -- reconciliation -------------------------------------------------------

    def apply_delta(
        self,
        delta: Delta,
        ts: datetime | None = None,
        on_key_change: KeyChangeFn | None = None,
    ) -> None:
        now = ts if ts is not None else utc_now()
        for nd in delta.node_deltas:
            ns = self.node_state_or_default(nd.node_id)
            ns.apply_delta(nd, ts=now, on_key_change=on_key_change)

    def compute_digest(self, scheduled_for_deletion: set[NodeId]) -> Digest:
        """Digest of every known node except those scheduled for deletion
        (excluding them stops their state re-propagating before GC).

        Incremental: only dirty nodes rebuild their NodeDigest; a fully
        quiescent call returns the previously assembled Digest object
        (callers treat digests as read-only — the wire layer only
        encodes them)."""
        stats = self.digest_cache_stats
        if self._dirty:
            rebuilt = 0
            for node_id in self._dirty:
                ns = self._node_states.get(node_id)
                if ns is not None:
                    self._digest_cache[node_id] = ns.digest()
                    rebuilt += 1
            self._dirty.clear()
            stats["rebuilds"] += rebuilt
        key = (self._epoch, frozenset(scheduled_for_deletion))
        if self._assembled is not None and self._assembled_key == key:
            stats["reuses"] += 1
            return self._assembled
        cache = self._digest_cache
        # Iterate _node_states (not the cache) so entry order — and
        # therefore encoded bytes — matches the uncached implementation.
        # A state injected behind the API (white-box tests) has no cache
        # entry yet; build it here rather than KeyError.
        entries: dict[NodeId, NodeDigest] = {}
        for node_id, ns in self._node_states.items():
            if node_id in scheduled_for_deletion:
                continue
            nd = cache.get(node_id)
            if nd is None:
                nd = ns.digest()
                cache[node_id] = nd
                stats["rebuilds"] += 1
            else:
                stats["hits"] += 1
            entries[node_id] = nd
        digest = Digest(entries)
        self._assembled = digest
        self._assembled_key = key
        return digest

    def digest_wire_parts(
        self, scheduled_for_deletion: set[NodeId]
    ) -> tuple[list[bytes], int]:
        """The encoded digest section as (buffer list, total length) —
        the wire fast path's counterpart of
        ``encode_digest(compute_digest(...))``, byte-identical by
        construction (each buffer IS a memoized ``_encode_digest_entry``
        output, in ``_node_states`` iteration order).

        Incremental at both levels: only dirty nodes re-encode their
        entry, and the assembled list is PATCHED in place per dirty
        entry — O(dirty) per epoch, which on a live fleet is usually
        the one node whose heartbeat moved; a full O(n) rebuild happens
        only on membership-order changes or under a non-empty excluded
        set (rare: nodes scheduled for deletion). Callers must not
        mutate the returned list, and must not hold it across state
        mutations (the engine's packet assemblers copy it into their
        frame synchronously — the cached Syn parts are flattened
        copies, so in-place patching can never reach into an
        already-assembled frame)."""
        stats = self.digest_cache_stats
        entries = self._dp_entries
        if scheduled_for_deletion:
            # Exclusion in force: serve from the keyed-assembly slow
            # path (the incremental list below always carries every
            # member).
            if self._dp_dirty:
                rebuilt = 0
                for node_id in self._dp_dirty:
                    ns = self._node_states.get(node_id)
                    if ns is not None:
                        entries[node_id] = _encode_digest_entry(ns.digest())
                        rebuilt += 1
                self._dp_dirty.clear()
                self._dp_order_dirty = True  # entries moved under the list
                stats["parts_rebuilds"] += rebuilt
            key = (self._epoch, frozenset(scheduled_for_deletion))
            cached = self._dp_assembled
            if cached is not None and cached[0] == key:
                stats["parts_reuses"] += 1
                return cached[1], cached[2]
            parts: list[bytes] = []
            total = 0
            for node_id, ns in self._node_states.items():
                if node_id in scheduled_for_deletion:
                    continue
                e = entries.get(node_id)
                if e is None:
                    e = _encode_digest_entry(ns.digest())
                    entries[node_id] = e
                    stats["parts_rebuilds"] += 1
                parts.append(e)
                total += len(e)
            self._dp_assembled = (key, parts, total)
            return parts, total
        if self._dp_order_dirty or self._dp_parts is None:
            # Full rebuild: membership changed (add order is handled
            # incrementally below; removals and excluded-set calls
            # invalidate order wholesale). Also covers white-box states
            # injected behind the API — every node re-enters here.
            rebuilt = 0
            index: dict[NodeId, int] = {}
            parts = []
            total = 0
            for node_id, ns in self._node_states.items():
                e = entries.get(node_id)
                if e is None or node_id in self._dp_dirty:
                    e = _encode_digest_entry(ns.digest())
                    entries[node_id] = e
                    rebuilt += 1
                index[node_id] = len(parts)
                parts.append(e)
                total += len(e)
            self._dp_dirty.clear()
            self._dp_order_dirty = False
            self._dp_parts = parts
            self._dp_index = index
            self._dp_total = total
            stats["parts_rebuilds"] += rebuilt
        elif self._dp_dirty:
            parts = self._dp_parts
            index = self._dp_index
            total = self._dp_total
            new_ids: set[NodeId] | None = None
            rebuilt = 0
            for node_id in self._dp_dirty:
                ns = self._node_states.get(node_id)
                if ns is None:
                    continue  # raced a removal; order flag handles it
                e = _encode_digest_entry(ns.digest())
                entries[node_id] = e
                rebuilt += 1
                i = index.get(node_id)
                if i is None:
                    if new_ids is None:
                        new_ids = set()
                    new_ids.add(node_id)
                else:
                    total += len(e) - len(parts[i])
                    parts[i] = e
            if new_ids:
                # Fresh members append in _node_states order (insertion
                # order — new keys land at the end, matching how a full
                # rebuild would lay them out).
                for node_id in self._node_states:
                    if node_id in new_ids and node_id not in index:
                        e = entries[node_id]
                        index[node_id] = len(parts)
                        parts.append(e)
                        total += len(e)
            self._dp_dirty.clear()
            self._dp_total = total
            stats["parts_rebuilds"] += rebuilt
        else:
            stats["parts_reuses"] += 1
        return self._dp_parts, self._dp_total

    def gc_marked_for_deletion(self, grace_period: timedelta) -> None:
        for ns in self._node_states.values():
            ns.gc_marked_for_deletion(grace_period)

    def compute_partial_delta_respecting_mtu(
        self,
        digest: Digest,
        mtu: int,
        scheduled_for_deletion: set[NodeId],
        size_model: Callable[..., object] | None = None,
    ) -> Delta:
        """Build the delta a peer (described by ``digest``) is missing,
        packed under ``mtu`` encoded bytes.

        For each node the peer is stale on, key-values above the peer's
        floor version are sent in increasing version order, so a replica's
        knowledge of any owner is always a *version-prefix* of the owner's
        history — the invariant the TPU sim backend exploits by collapsing
        per-replica knowledge to a single watermark integer.
        """
        if size_model is None:
            size_model = _DeltaSizeModel
        sizes = size_model()

        candidates = self._stale_candidates(digest, scheduled_for_deletion)

        node_deltas: list[NodeDelta] = []
        for ns, floor in candidates:
            # Reserve max_version bytes up front so packing decisions match
            # the reference's accounting; emit it only if nothing truncates.
            body = sizes.node_delta_base(ns.node, floor, ns.last_gc_version,
                                         ns.max_version)
            selected: list[KeyValueUpdate] = []
            truncated = False
            # stale_key_values yields in increasing version order straight
            # off the node's version index, so packing consumes it lazily:
            # an MTU-truncated node stops scanning at the cutoff instead
            # of materialising (and sorting) its whole stale range.
            for key, vv in ns.stale_key_values(floor):
                kv = KeyValueUpdate(key, vv.value, vv.version, vv.status)
                grown = body + sizes.kv_increment(kv)
                if sizes.delta_total_with(grown) > mtu:
                    truncated = True
                    break
                body = grown
                selected.append(kv)

            if selected:
                node_deltas.append(
                    NodeDelta(
                        node_id=ns.node,
                        from_version_excluded=floor,
                        last_gc_version=ns.last_gc_version,
                        key_values=selected,
                        max_version=None if truncated else ns.max_version,
                    )
                )
                sizes.commit(body)

            if sizes.total() >= mtu:
                break

        return Delta(node_deltas=node_deltas)

    def _stale_candidates(
        self, digest: Digest, scheduled_for_deletion: set[NodeId]
    ) -> list[tuple[NodeState, int]]:
        """(node state, floor) pairs the peer described by ``digest`` is
        stale on — THE candidate walk, shared verbatim by the object
        packer above and the encoded packer below so the two can never
        select differently."""
        candidates: list[tuple[NodeState, int]] = []
        for node_id, ns in self._node_states.items():
            if node_id in scheduled_for_deletion:
                continue
            peer = digest.node_digests.get(node_id)
            peer_gc = peer.last_gc_version if peer is not None else 0
            peer_max = peer.max_version if peer is not None else 0
            if ns.max_version <= peer_max:
                continue
            # If the peer is so far behind that our GC watermark has passed
            # everything it knows, restart it from scratch (version floor 0).
            reset = peer_gc < ns.last_gc_version and peer_max < ns.last_gc_version
            floor = 0 if reset else peer_max
            # ns.max_version > peer_max >= floor always holds here, so the
            # node is stale by construction (no need to score it).
            candidates.append((ns, floor))
        return candidates

    def compute_partial_delta_encoded(
        self,
        digest: Digest,
        mtu: int,
        scheduled_for_deletion: set[NodeId],
        segments,
        shared=None,
        collect_kvs: bool = False,
    ):
        """The wire fast path's packer: same candidate walk, same MTU
        accounting (one shared ``DeltaSizeModel``), same selection —
        but each key-value is priced by its cached segment LENGTH and
        the result is an :class:`~..wire.segments.EncodedDelta` of
        buffer refs, never a re-encode (``b"".join(enc.buffers)`` is
        byte-identical to ``encode_delta`` of the object packer's
        result; the differential fuzz suite pins it across every
        mutation kind and MTU-exact truncation boundaries).

        ``shared`` (a SharedPayloadCache) lets k peers catching up on
        the same (node, floor) window in one round cost ONE assembly:
        only UNTRUNCATED node payloads are shared (truncation depends
        on this frame's remaining budget), and a cached payload is only
        used when it fits the remaining budget whole — otherwise the
        truncating walk runs, exactly as the oracle would.

        ``collect_kvs`` additionally records (owner, key, version) refs
        for provenance emission; it bypasses the shared cache (shared
        entries carry no refs)."""
        sizes = _DeltaSizeModel()
        buffers: list[bytes] = []
        wire_len = 0
        kv_total = 0
        node_count = 0
        kv_refs: list[tuple[str, list[tuple[str, int]]]] | None = (
            [] if collect_kvs else None
        )
        for ns, floor in self._stale_candidates(digest, scheduled_for_deletion):
            shared_key = None
            if shared is not None and not collect_kvs:
                shared_key = (ns.node, ns.content_epoch, floor)
                ent = shared.get(shared_key)
                if ent is not None:
                    if sizes.delta_total_with(ent.accounted_body) <= mtu:
                        buffers.extend(ent.buffers)
                        wire_len += ent.wire_len
                        kv_total += ent.kv_count
                        node_count += 1
                        sizes.commit(ent.accounted_body)
                        if sizes.total() >= mtu:
                            break
                        continue
                    # Whole payload no longer fits this frame's budget:
                    # fall through to the truncating walk below.
            body = sizes.node_delta_base(
                ns.node, floor, ns.last_gc_version, ns.max_version
            )
            segs: list[bytes] = []
            refs: list[tuple[str, int]] | None = [] if collect_kvs else None
            truncated = False
            for key, vv in ns.stale_key_values(floor):
                seg = segments.segment(ns.node, key, vv)
                grown = body + sizes.kv_increment_from_segment(seg)
                if sizes.delta_total_with(grown) > mtu:
                    truncated = True
                    break
                body = grown
                segs.append(seg)
                if refs is not None:
                    refs.append((key, vv.version))
            if segs:
                nd_bufs, nd_len = node_delta_parts(
                    ns.node,
                    floor,
                    ns.last_gc_version,
                    segs,
                    None if truncated else ns.max_version,
                )
                buffers.extend(nd_bufs)
                wire_len += nd_len
                kv_total += len(segs)
                node_count += 1
                if kv_refs is not None:
                    kv_refs.append((ns.node.name, refs))
                sizes.commit(body)
                if not truncated and shared_key is not None:
                    shared.store(
                        shared_key,
                        SharedNodePayload(
                            tuple(nd_bufs), body, nd_len, len(segs)
                        ),
                    )
            if sizes.total() >= mtu:
                break
        if node_count == 0:
            return EMPTY_ENCODED_DELTA
        return EncodedDelta(buffers, wire_len, kv_total, node_count, kv_refs)
