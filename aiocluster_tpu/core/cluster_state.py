"""Cluster-wide replicated state and ScuttleButt reconciliation.

Parity: reference state.py:290-433 (``ClusterState``, ``staleness_score``).

The interesting method is ``compute_partial_delta_respecting_mtu``: given a
peer's digest, build the delta of everything the peer is missing, greedily
packed under a byte MTU. Two deliberate improvements over the reference:

1. **O(total kvs) packing.** The reference re-serialises the entire delta
   protobuf after every appended key-value to test the MTU
   (state.py:392-398) — quadratic in delta size. We account encoded sizes
   incrementally with exact proto3 arithmetic (wire/sizes.py), so packing
   is linear while selecting the *same* key-values byte-for-byte (the
   ``max_version`` field is reserved in the accounting regardless of
   whether it is finally emitted).
2. **No lost updates on truncation.** The reference always stamps the
   delta with the owner's full ``max_version`` (state.py:389); a receiver
   of an MTU-truncated delta then advertises versions it never received
   and the gap is never retransmitted. We only stamp ``max_version`` when
   every stale key-value fit — the chitchat-correct rule — so truncated
   ranges are re-requested on the next round.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from dataclasses import dataclass
from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .identity import Address, NodeId
from .kvstate import KeyChangeFn, NodeState
from .messages import Delta, Digest, KeyValueUpdate, NodeDelta, NodeDigest


@dataclass(frozen=True, slots=True)
class Staleness:
    """How far behind a peer is on one node's keyspace."""

    is_unknown: bool
    max_version: int
    num_stale_key_values: int


def staleness_score(node_state: NodeState, floor_version: int) -> Staleness | None:
    """None when the peer is up to date; otherwise a score (parity:
    reference state.py:425-433)."""
    if node_state.max_version <= floor_version:
        return None
    is_unknown = floor_version == 0
    if is_unknown:
        num_stale = len(node_state.key_values)
    else:
        num_stale = sum(1 for _ in node_state.stale_key_values(floor_version))
    return Staleness(is_unknown, node_state.max_version, num_stale)


class ClusterState:
    """All node keyspaces known to this process, keyed by NodeId.

    Digest computation is incrementally cached: every NodeState created
    here carries a change hook that adds its node to a dirty-set when a
    digest field (heartbeat / max_version / last_gc_version) moves, so
    ``compute_digest`` rebuilds O(dirty) per-node entries — and when
    nothing moved at all, returns the previously assembled ``Digest``
    object outright. ``digest_cache_stats`` exposes plain counters
    (rebuilds / hits / reuses) that the runtime exports as metrics and
    tests assert on; ``digest_epoch`` is a monotonic generation the
    engine keys its encoded-Syn cache on.
    """

    def __init__(self, seed_addrs: set[Address] | None = None) -> None:
        self._node_states: dict[NodeId, NodeState] = {}
        self._seed_addrs: set[Address] = seed_addrs or set()
        self._digest_cache: dict[NodeId, NodeDigest] = {}
        self._dirty: set[NodeId] = set()
        self._epoch = 0
        self._assembled: Digest | None = None
        self._assembled_key: tuple[int, frozenset[NodeId]] | None = None
        self.digest_cache_stats: dict[str, int] = {
            "rebuilds": 0,  # per-node NodeDigest reconstructions
            "hits": 0,      # per-node entries served from cache
            "reuses": 0,    # whole assembled Digests served unchanged
        }

    # -- membership -----------------------------------------------------------

    def node_state(self, node_id: NodeId) -> NodeState | None:
        return self._node_states.get(node_id)

    def node_state_or_default(self, node_id: NodeId) -> NodeState:
        ns = self._node_states.get(node_id)
        if ns is None:
            ns = NodeState(node_id)
            ns._on_change = lambda: self.mark_dirty(node_id)
            self._node_states[node_id] = ns
            self.mark_dirty(node_id)
        return ns

    def install_node_state(self, ns: NodeState) -> None:
        """Install a fully-built NodeState — the persistence layer's
        recovery path (runtime/persist.py): a restored keyspace (own or
        peer hint) enters through the same hook-wiring and dirty-marking
        as ``node_state_or_default``, so digest caching stays sound.
        Replaces any existing state for the node."""
        ns._on_change = lambda: self.mark_dirty(ns.node)
        self._node_states[ns.node] = ns
        self.mark_dirty(ns.node)

    def mark_dirty(self, node_id: NodeId) -> None:
        """Invalidate the cached digest entry for ``node_id``. Fired
        automatically by every NodeState mutator; call it manually after
        white-box direct field writes."""
        self._dirty.add(node_id)
        self._epoch += 1

    @property
    def digest_epoch(self) -> int:
        """Monotonic generation: bumps whenever any digest field changes
        (or membership does). Equal epochs ⇒ identical digests."""
        return self._epoch

    def nodes(self) -> Sequence[NodeId]:
        return tuple(self._node_states)

    def node_states(self) -> dict[NodeId, NodeState]:
        """Shallow copy of the per-node state map (live NodeState refs) —
        for synchronous O(changes) readers, so they never hold the live
        dict while gossip mutates it."""
        return dict(self._node_states)

    def node_states_copy(self) -> dict[NodeId, NodeState]:
        """Detached deep copy of every node's state — the snapshot
        surface (Cluster.snapshot): mutating the fleet afterwards can
        never retroactively mutate a taken snapshot (delete/TTL rewrite
        VersionedValues in place, so sharing refs would leak future
        mutations into it)."""
        return {nid: ns.copy() for nid, ns in self._node_states.items()}

    def seed_addrs(self) -> Sequence[Address]:
        return tuple(self._seed_addrs)

    def remove_node(self, node_id: NodeId) -> None:
        self._node_states.pop(node_id, None)
        self._digest_cache.pop(node_id, None)
        self._dirty.discard(node_id)
        self._epoch += 1

    # -- reconciliation -------------------------------------------------------

    def apply_delta(
        self,
        delta: Delta,
        ts: datetime | None = None,
        on_key_change: KeyChangeFn | None = None,
    ) -> None:
        now = ts if ts is not None else utc_now()
        for nd in delta.node_deltas:
            ns = self.node_state_or_default(nd.node_id)
            ns.apply_delta(nd, ts=now, on_key_change=on_key_change)

    def compute_digest(self, scheduled_for_deletion: set[NodeId]) -> Digest:
        """Digest of every known node except those scheduled for deletion
        (excluding them stops their state re-propagating before GC).

        Incremental: only dirty nodes rebuild their NodeDigest; a fully
        quiescent call returns the previously assembled Digest object
        (callers treat digests as read-only — the wire layer only
        encodes them)."""
        stats = self.digest_cache_stats
        if self._dirty:
            rebuilt = 0
            for node_id in self._dirty:
                ns = self._node_states.get(node_id)
                if ns is not None:
                    self._digest_cache[node_id] = ns.digest()
                    rebuilt += 1
            self._dirty.clear()
            stats["rebuilds"] += rebuilt
        key = (self._epoch, frozenset(scheduled_for_deletion))
        if self._assembled is not None and self._assembled_key == key:
            stats["reuses"] += 1
            return self._assembled
        cache = self._digest_cache
        # Iterate _node_states (not the cache) so entry order — and
        # therefore encoded bytes — matches the uncached implementation.
        # A state injected behind the API (white-box tests) has no cache
        # entry yet; build it here rather than KeyError.
        entries: dict[NodeId, NodeDigest] = {}
        for node_id, ns in self._node_states.items():
            if node_id in scheduled_for_deletion:
                continue
            nd = cache.get(node_id)
            if nd is None:
                nd = ns.digest()
                cache[node_id] = nd
                stats["rebuilds"] += 1
            else:
                stats["hits"] += 1
            entries[node_id] = nd
        digest = Digest(entries)
        self._assembled = digest
        self._assembled_key = key
        return digest

    def gc_marked_for_deletion(self, grace_period: timedelta) -> None:
        for ns in self._node_states.values():
            ns.gc_marked_for_deletion(grace_period)

    def compute_partial_delta_respecting_mtu(
        self,
        digest: Digest,
        mtu: int,
        scheduled_for_deletion: set[NodeId],
        size_model: Callable[..., object] | None = None,
    ) -> Delta:
        """Build the delta a peer (described by ``digest``) is missing,
        packed under ``mtu`` encoded bytes.

        For each node the peer is stale on, key-values above the peer's
        floor version are sent in increasing version order, so a replica's
        knowledge of any owner is always a *version-prefix* of the owner's
        history — the invariant the TPU sim backend exploits by collapsing
        per-replica knowledge to a single watermark integer.
        """
        if size_model is None:
            from ..wire.sizes import DeltaSizeModel

            size_model = DeltaSizeModel
        sizes = size_model()

        candidates: list[tuple[NodeState, int]] = []
        for node_id, ns in self._node_states.items():
            if node_id in scheduled_for_deletion:
                continue
            peer = digest.node_digests.get(node_id)
            peer_gc = peer.last_gc_version if peer is not None else 0
            peer_max = peer.max_version if peer is not None else 0
            if ns.max_version <= peer_max:
                continue
            # If the peer is so far behind that our GC watermark has passed
            # everything it knows, restart it from scratch (version floor 0).
            reset = peer_gc < ns.last_gc_version and peer_max < ns.last_gc_version
            floor = 0 if reset else peer_max
            # ns.max_version > peer_max >= floor always holds here, so the
            # node is stale by construction (no need to score it).
            candidates.append((ns, floor))

        node_deltas: list[NodeDelta] = []
        for ns, floor in candidates:
            # Reserve max_version bytes up front so packing decisions match
            # the reference's accounting; emit it only if nothing truncates.
            body = sizes.node_delta_base(ns.node, floor, ns.last_gc_version,
                                         ns.max_version)
            selected: list[KeyValueUpdate] = []
            truncated = False
            # stale_key_values yields in increasing version order straight
            # off the node's version index, so packing consumes it lazily:
            # an MTU-truncated node stops scanning at the cutoff instead
            # of materialising (and sorting) its whole stale range.
            for key, vv in ns.stale_key_values(floor):
                kv = KeyValueUpdate(key, vv.value, vv.version, vv.status)
                grown = body + sizes.kv_increment(kv)
                if sizes.delta_total_with(grown) > mtu:
                    truncated = True
                    break
                body = grown
                selected.append(kv)

            if selected:
                node_deltas.append(
                    NodeDelta(
                        node_id=ns.node,
                        from_version_excluded=floor,
                        last_gc_version=ns.last_gc_version,
                        key_values=selected,
                        max_version=None if truncated else ns.max_version,
                    )
                )
                sizes.commit(body)

            if sizes.total() >= mtu:
                break

        return Delta(node_deltas=node_deltas)
