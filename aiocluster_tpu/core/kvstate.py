"""One node's replicated keyspace.

Parity: reference state.py:106-287 (``NodeState``). Semantics preserved:

- Only the owner mutates its keyspace; replicas converge via deltas.
- ``max_version`` is a per-owner monotonic counter; every local mutation
  claims the next version.
- Deletes are in-place tombstones (value cleared, version bumped) so the
  deletion itself replicates; ``DELETE_AFTER_TTL`` keeps the value but
  schedules GC eligibility.
- ``last_gc_version`` is the GC watermark: once tombstones/TTL keys older
  than the grace period are purged, the watermark advances and replicas
  drop the same keys when they observe it in a delta.
- A heartbeat's *first* observation only records it — one heartbeat is not
  evidence of liveness (reference state.py:280-287).

All time-dependent methods accept ``ts`` for deterministic tests.
"""

from __future__ import annotations

from collections.abc import Callable, Iterator
from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .identity import NodeId
from .messages import NodeDelta, NodeDigest
from .values import KeyStatus, VersionedValue

KeyChangeFn = Callable[[NodeId, str, VersionedValue | None, VersionedValue], None]


class NodeState:
    """Versioned key-value state for a single node (owner or replica)."""

    __slots__ = ("key_values", "heartbeat", "max_version", "last_gc_version", "node")

    def __init__(
        self,
        node: NodeId,
        heartbeat: int = 0,
        key_values: dict[str, VersionedValue] | None = None,
        max_version: int = 0,
        last_gc_version: int = 0,
    ) -> None:
        self.node = node
        self.heartbeat = heartbeat
        self.key_values: dict[str, VersionedValue] = key_values or {}
        self.max_version = max_version
        self.last_gc_version = last_gc_version

    # -- reads --------------------------------------------------------------

    def get(self, key: str) -> VersionedValue | None:
        """Visible value: hides tombstones and TTL-scheduled keys."""
        vv = self.key_values.get(key)
        if vv is None or vv.is_deleted():
            return None
        return vv

    def get_versioned(self, key: str) -> VersionedValue | None:
        """Raw value including tombstones."""
        return self.key_values.get(key)

    def stale_key_values(
        self, floor_version: int
    ) -> Iterator[tuple[str, VersionedValue]]:
        """Keys with versions strictly above ``floor_version``."""
        for key, vv in self.key_values.items():
            if vv.version > floor_version:
                yield key, vv

    def digest(self) -> NodeDigest:
        return NodeDigest(
            self.node, self.heartbeat, self.last_gc_version, self.max_version
        )

    # -- owner-side writes ---------------------------------------------------

    def set(self, key: str, value: str, ts: datetime | None = None) -> None:
        """Idempotent set: writing the current live value is a no-op."""
        current = self.key_values.get(key)
        if (
            current is not None
            and current.status is KeyStatus.SET
            and current.value == value
        ):
            return
        self.set_with_version(key, value, self.max_version + 1, ts=ts)

    def set_with_version(
        self, key: str, value: str, version: int, ts: datetime | None = None
    ) -> None:
        now = ts if ts is not None else utc_now()
        self.set_versioned(key, VersionedValue(value, version, KeyStatus.SET, now))

    def set_versioned(self, key: str, vv: VersionedValue) -> None:
        """Install ``vv`` unless we already hold an equal-or-newer version.
        Always advances ``max_version`` (the owner has *seen* this version
        even when the key itself is stale)."""
        self.max_version = max(self.max_version, vv.version)
        current = self.key_values.get(key)
        if current is not None and current.version >= vv.version:
            return
        self.key_values[key] = vv

    def set_with_ttl(self, key: str, value: str, ts: datetime | None = None) -> None:
        """Set a value that becomes GC-eligible after the grace period."""
        current = self.key_values.get(key)
        if (
            current is not None
            and current.status is KeyStatus.DELETE_AFTER_TTL
            and current.value == value
        ):
            return
        now = ts if ts is not None else utc_now()
        self.set_versioned(
            key,
            VersionedValue(value, self.max_version + 1, KeyStatus.DELETE_AFTER_TTL, now),
        )

    def delete(self, key: str, ts: datetime | None = None) -> None:
        """Tombstone ``key`` in place; no-op for unknown keys."""
        vv = self.key_values.get(key)
        if vv is None:
            return
        self.max_version += 1
        vv.status = KeyStatus.DELETED
        vv.version = self.max_version
        vv.value = ""
        vv.status_change_ts = ts if ts is not None else utc_now()

    def delete_after_ttl(self, key: str, ts: datetime | None = None) -> None:
        """Schedule ``key`` for TTL deletion, keeping its value readable via
        ``get_versioned`` until GC."""
        vv = self.key_values.get(key)
        if vv is None:
            return
        self.max_version += 1
        vv.status = KeyStatus.DELETE_AFTER_TTL
        vv.version = self.max_version
        vv.status_change_ts = ts if ts is not None else utc_now()

    # -- replica-side reconciliation ----------------------------------------

    def apply_delta(
        self,
        node_delta: NodeDelta,
        ts: datetime | None = None,
        on_key_change: KeyChangeFn | None = None,
    ) -> None:
        """Merge a peer's delta for this node's keyspace.

        Rules (parity: reference state.py:190-233, with one correctness
        divergence documented below):
        1. A *reset* delta (``from_version_excluded == 0`` with a GC
           watermark ahead of ours) means the sender judged us staler than
           the owner's GC horizon and is resending the keyspace from
           scratch: wipe our copy and rebuild.
        2. Otherwise, adopting a higher GC watermark purges only
           *tombstoned* keys at or below it. Because deltas are
           version-ordered prefixes, knowing ``max_version >= watermark``
           means we already saw every tombstone the owner GC'd — live SET
           keys with old versions are still live at the owner and must
           survive. (The reference drops *all* keys at or below the
           watermark, state.py:200-207, permanently losing live keys on
           replicas; found by review, regression-tested.)
        3. Skip updates not newer than our recorded ``max_version``.
        4. Skip updates older than what we hold for that key.
        5. Skip deleted/TTL updates already covered by the GC watermark.
        6. ``max_version`` fast-forwards only when the sender marked the
           delta complete (``max_version is not None``).
        """
        now = ts if ts is not None else utc_now()
        if (
            node_delta.from_version_excluded == 0
            and node_delta.last_gc_version > self.last_gc_version
        ):
            self.key_values = {}
            self.max_version = 0
            self.last_gc_version = node_delta.last_gc_version
        elif node_delta.last_gc_version > self.last_gc_version:
            self.last_gc_version = node_delta.last_gc_version
            self.key_values = {
                k: v
                for k, v in self.key_values.items()
                if v.version > self.last_gc_version or not v.is_deleted()
            }
        for kv in node_delta.key_values:
            if kv.version <= self.max_version:
                continue
            existing = self.key_values.get(kv.key)
            if existing is not None and existing.version >= kv.version:
                continue
            if (
                kv.status in (KeyStatus.DELETED, KeyStatus.DELETE_AFTER_TTL)
                and kv.version <= self.last_gc_version
            ):
                continue
            vv = VersionedValue(kv.value, kv.version, kv.status, now)
            self.set_versioned(kv.key, vv)
            if on_key_change is not None:
                on_key_change(self.node, kv.key, existing, vv)
        if node_delta.max_version is not None:
            self.max_version = max(self.max_version, node_delta.max_version)

    # -- garbage collection ---------------------------------------------------

    def gc_marked_for_deletion(
        self, grace_period: timedelta, ts: datetime | None = None
    ) -> None:
        """Purge tombstones and TTL keys older than ``grace_period`` and
        advance the GC watermark to the highest purged version."""
        now = ts if ts is not None else utc_now()
        watermark = self.last_gc_version
        survivors: dict[str, VersionedValue] = {}
        for key, vv in self.key_values.items():
            if vv.status is KeyStatus.SET or now < vv.status_change_ts + grace_period:
                survivors[key] = vv
            else:
                watermark = max(watermark, vv.version)
        self.key_values = survivors
        self.last_gc_version = watermark

    # -- heartbeats -----------------------------------------------------------

    def inc_heartbeat(self) -> int:
        self.heartbeat += 1
        return self.heartbeat

    def apply_heartbeat(self, value: int) -> bool:
        """Record an observed heartbeat. Returns True only for a genuine
        *increase* — the first observation just initialises the counter."""
        if self.heartbeat == 0:
            self.heartbeat = value
            return False
        if value > self.heartbeat:
            self.heartbeat = value
            return True
        return False
