"""One node's replicated keyspace.

Parity: reference state.py:106-287 (``NodeState``). Semantics preserved:

- Only the owner mutates its keyspace; replicas converge via deltas.
- ``max_version`` is a per-owner monotonic counter; every local mutation
  claims the next version.
- Deletes are in-place tombstones (value cleared, version bumped) so the
  deletion itself replicates; ``DELETE_AFTER_TTL`` keeps the value but
  schedules GC eligibility.
- ``last_gc_version`` is the GC watermark: once tombstones/TTL keys older
  than the grace period are purged, the watermark advances and replicas
  drop the same keys when they observe it in a delta.
- A heartbeat's *first* observation only records it — one heartbeat is not
  evidence of liveness (reference state.py:280-287).

All time-dependent methods accept ``ts`` for deterministic tests.

Two performance structures ride along (both invisible to the semantics
above):

- A **version index** — ``(version, key)`` pairs in increasing version
  order. Versions are monotonic (the owner claims ``max_version + 1``
  per write; replicas receive version-ordered delta prefixes), so writes
  append in order and ``stale_key_values(floor)`` is a bisect plus a
  tail walk instead of a full keyspace scan. Entries for re-written or
  GC'd keys go stale in place and are filtered lazily; an out-of-order
  install or wholesale ``key_values`` replacement just marks the index
  dirty for a lazy rebuild.
- A **digest-change hook** (``_on_change``, wired by ClusterState):
  fired whenever one of the three digest fields (heartbeat,
  max_version, last_gc_version) changes, so the container can cache
  per-node digests and rebuild only what moved. Direct field writes
  (white-box tests) bypass the hook — pair them with
  ``ClusterState.mark_dirty`` when a digest is computed afterwards.
"""

from __future__ import annotations

from bisect import bisect_right
from collections.abc import Callable, Iterator
from datetime import datetime, timedelta

from ..utils.clock import utc_now
from .identity import NodeId
from .messages import NodeDelta, NodeDigest
from .values import KeyStatus, VersionedValue

KeyChangeFn = Callable[[NodeId, str, VersionedValue | None, VersionedValue], None]


class NodeState:
    """Versioned key-value state for a single node (owner or replica)."""

    __slots__ = (
        "key_values",
        "heartbeat",
        "max_version",
        "last_gc_version",
        "node",
        "content_epoch",
        "_vindex",
        "_vindex_dirty",
        "_on_change",
    )

    def __init__(
        self,
        node: NodeId,
        heartbeat: int = 0,
        key_values: dict[str, VersionedValue] | None = None,
        max_version: int = 0,
        last_gc_version: int = 0,
    ) -> None:
        self.node = node
        self.heartbeat = heartbeat
        self.key_values: dict[str, VersionedValue] = key_values or {}
        self.max_version = max_version
        self.last_gc_version = last_gc_version
        # Monotonic content generation: bumps whenever what a delta for
        # this node could carry changes (key-value installs, tombstones,
        # TTL marks, GC purges, resets, max_version fast-forwards) —
        # but NOT on heartbeats. Equal epochs ⇒ identical stale-scan
        # output at any floor, which is what the wire fast path keys
        # its shared per-round delta payloads on (wire/segments.py).
        self.content_epoch = 0
        self._vindex: list[tuple[int, str]] = []
        self._vindex_dirty = bool(self.key_values)
        self._on_change: Callable[[], None] | None = None

    def _touch(self) -> None:
        """One of the digest fields changed; tell the container (if any)."""
        cb = self._on_change
        if cb is not None:
            cb()

    def _content_touch(self) -> None:
        """A kv-content mutation: bump the content generation and fire
        the digest hook (content mutations conservatively fire the
        container's dirty-marking exactly like ``_touch`` always did)."""
        self.content_epoch += 1
        cb = self._on_change
        if cb is not None:
            cb()

    def _index_add(self, version: int, key: str) -> None:
        """Record an installed key version. Appends in O(1) on the
        monotonic fast path; anything out of order defers to a rebuild."""
        if self._vindex_dirty:
            return
        if not self._vindex or version >= self._vindex[-1][0]:
            self._vindex.append((version, key))
        else:
            self._vindex_dirty = True

    def _rebuild_index(self) -> None:
        self._vindex = sorted(
            (vv.version, k) for k, vv in self.key_values.items()
        )
        self._vindex_dirty = False

    # -- reads --------------------------------------------------------------

    def get(self, key: str) -> VersionedValue | None:
        """Visible value: hides tombstones and TTL-scheduled keys."""
        vv = self.key_values.get(key)
        if vv is None or vv.is_deleted():
            return None
        return vv

    def get_versioned(self, key: str) -> VersionedValue | None:
        """Raw value including tombstones."""
        return self.key_values.get(key)

    def stale_key_values(
        self, floor_version: int
    ) -> Iterator[tuple[str, VersionedValue]]:
        """Keys with versions strictly above ``floor_version``, in
        increasing version order (bisect + tail walk over the version
        index — O(log K + stale), not O(K))."""
        # Rebuild when dirty, or when stale entries (re-written / GC'd
        # keys left in place) outnumber the live keyspace — the lazy
        # compaction that keeps the tail walk proportional to real work.
        if self._vindex_dirty or len(self._vindex) > 2 * len(self.key_values) + 16:
            self._rebuild_index()
        idx = self._vindex
        kvs = self.key_values
        start = bisect_right(idx, floor_version, key=lambda e: e[0])
        for i in range(start, len(idx)):
            version, key = idx[i]
            vv = kvs.get(key)
            if vv is not None and vv.version == version:
                yield key, vv

    def digest(self) -> NodeDigest:
        return NodeDigest(
            self.node, self.heartbeat, self.last_gc_version, self.max_version
        )

    def copy(self) -> "NodeState":
        """A detached copy: scalars plus per-key VersionedValue copies
        (``delete``/``delete_after_ttl`` mutate values IN PLACE, so
        sharing refs would leak future mutations into snapshots). The
        copy carries no change hook; its version index rebuilds lazily
        on first stale scan."""
        return NodeState(
            self.node,
            heartbeat=self.heartbeat,
            key_values={
                k: VersionedValue(
                    vv.value, vv.version, vv.status, vv.status_change_ts
                )
                for k, vv in self.key_values.items()
            },
            max_version=self.max_version,
            last_gc_version=self.last_gc_version,
        )

    # -- owner-side writes ---------------------------------------------------

    def set(self, key: str, value: str, ts: datetime | None = None) -> None:
        """Idempotent set: writing the current live value is a no-op."""
        current = self.key_values.get(key)
        if (
            current is not None
            and current.status is KeyStatus.SET
            and current.value == value
        ):
            return
        self.set_with_version(key, value, self.max_version + 1, ts=ts)

    def set_with_version(
        self, key: str, value: str, version: int, ts: datetime | None = None
    ) -> None:
        now = ts if ts is not None else utc_now()
        self.set_versioned(key, VersionedValue(value, version, KeyStatus.SET, now))

    def set_versioned(self, key: str, vv: VersionedValue) -> None:
        """Install ``vv`` unless we already hold an equal-or-newer version.
        Always advances ``max_version`` (the owner has *seen* this version
        even when the key itself is stale)."""
        bumped = False
        if vv.version > self.max_version:
            self.max_version = vv.version
            self._content_touch()
            bumped = True
        current = self.key_values.get(key)
        if current is not None and current.version >= vv.version:
            return
        self.key_values[key] = vv
        self._index_add(vv.version, key)
        if not bumped:
            # Install BELOW the max_version watermark (a new key at an
            # old version via set_with_version): the stale scan changed
            # even though the watermark did not — the content epoch
            # must move or a shared delta payload cached before this
            # install would be served missing it (wire/segments.py).
            self._content_touch()

    def set_with_ttl(self, key: str, value: str, ts: datetime | None = None) -> None:
        """Set a value that becomes GC-eligible after the grace period."""
        current = self.key_values.get(key)
        if (
            current is not None
            and current.status is KeyStatus.DELETE_AFTER_TTL
            and current.value == value
        ):
            return
        now = ts if ts is not None else utc_now()
        self.set_versioned(
            key,
            VersionedValue(value, self.max_version + 1, KeyStatus.DELETE_AFTER_TTL, now),
        )

    def delete(self, key: str, ts: datetime | None = None) -> None:
        """Tombstone ``key`` in place; no-op for unknown keys."""
        vv = self.key_values.get(key)
        if vv is None:
            return
        self.max_version += 1
        vv.status = KeyStatus.DELETED
        vv.version = self.max_version
        vv.value = ""
        vv.status_change_ts = ts if ts is not None else utc_now()
        self._index_add(vv.version, key)
        self._content_touch()

    def delete_after_ttl(self, key: str, ts: datetime | None = None) -> None:
        """Schedule ``key`` for TTL deletion, keeping its value readable via
        ``get_versioned`` until GC."""
        vv = self.key_values.get(key)
        if vv is None:
            return
        self.max_version += 1
        vv.status = KeyStatus.DELETE_AFTER_TTL
        vv.version = self.max_version
        vv.status_change_ts = ts if ts is not None else utc_now()
        self._index_add(vv.version, key)
        self._content_touch()

    # -- replica-side reconciliation ----------------------------------------

    def apply_delta(
        self,
        node_delta: NodeDelta,
        ts: datetime | None = None,
        on_key_change: KeyChangeFn | None = None,
    ) -> None:
        """Merge a peer's delta for this node's keyspace.

        Rules (parity: reference state.py:190-233, with one correctness
        divergence documented below):
        1. A *reset* delta (``from_version_excluded == 0`` with a GC
           watermark ahead of ours) means the sender judged us staler than
           the owner's GC horizon and is resending the keyspace from
           scratch: wipe our copy and rebuild.
        2. Otherwise, adopting a higher GC watermark purges only
           *tombstoned* keys at or below it. Because deltas are
           version-ordered prefixes, knowing ``max_version >= watermark``
           means we already saw every tombstone the owner GC'd — live SET
           keys with old versions are still live at the owner and must
           survive. (The reference drops *all* keys at or below the
           watermark, state.py:200-207, permanently losing live keys on
           replicas; found by review, regression-tested.)
        3. Skip updates not newer than our recorded ``max_version``.
        4. Skip updates older than what we hold for that key.
        5. Skip deleted/TTL updates already covered by the GC watermark.
        6. ``max_version`` fast-forwards only when the sender marked the
           delta complete (``max_version is not None``).
        """
        now = ts if ts is not None else utc_now()
        if (
            node_delta.from_version_excluded == 0
            and node_delta.last_gc_version > self.last_gc_version
        ):
            self.key_values = {}
            self.max_version = 0
            self.last_gc_version = node_delta.last_gc_version
            # Wholesale replacement: the old index orders versions the
            # rebuilt keyspace no longer follows — start empty so the
            # reset delta's installs append monotonically again.
            self._vindex = []
            self._vindex_dirty = False
            self._content_touch()
        elif node_delta.last_gc_version > self.last_gc_version:
            self.last_gc_version = node_delta.last_gc_version
            self.key_values = {
                k: v
                for k, v in self.key_values.items()
                if v.version > self.last_gc_version or not v.is_deleted()
            }
            self._content_touch()
        for kv in node_delta.key_values:
            if kv.version <= self.max_version:
                continue
            existing = self.key_values.get(kv.key)
            if existing is not None and existing.version >= kv.version:
                continue
            if (
                kv.status in (KeyStatus.DELETED, KeyStatus.DELETE_AFTER_TTL)
                and kv.version <= self.last_gc_version
            ):
                continue
            vv = VersionedValue(kv.value, kv.version, kv.status, now)
            self.set_versioned(kv.key, vv)
            if on_key_change is not None:
                on_key_change(self.node, kv.key, existing, vv)
        if node_delta.max_version is not None and (
            node_delta.max_version > self.max_version
        ):
            self.max_version = node_delta.max_version
            self._content_touch()

    # -- garbage collection ---------------------------------------------------

    def gc_marked_for_deletion(
        self, grace_period: timedelta, ts: datetime | None = None
    ) -> None:
        """Purge tombstones and TTL keys older than ``grace_period`` and
        advance the GC watermark to the highest purged version."""
        now = ts if ts is not None else utc_now()
        watermark = self.last_gc_version
        survivors: dict[str, VersionedValue] = {}
        for key, vv in self.key_values.items():
            if vv.status is KeyStatus.SET or now < vv.status_change_ts + grace_period:
                survivors[key] = vv
            else:
                watermark = max(watermark, vv.version)
        if len(survivors) != len(self.key_values) or (
            watermark != self.last_gc_version
        ):
            # Purged keys leave stale index entries behind; the lazy
            # filter in stale_key_values skips them and compaction
            # reclaims them, so relative order stays valid.
            self.key_values = survivors
            self.last_gc_version = watermark
            self._content_touch()

    # -- heartbeats -----------------------------------------------------------

    def inc_heartbeat(self) -> int:
        self.heartbeat += 1
        self._touch()
        return self.heartbeat

    def apply_heartbeat(self, value: int) -> bool:
        """Record an observed heartbeat. Returns True only for a genuine
        *increase* — the first observation just initialises the counter."""
        if self.heartbeat == 0:
            self.heartbeat = value
            if value:
                self._touch()
            return False
        if value > self.heartbeat:
            self.heartbeat = value
            self._touch()
            return True
        return False
