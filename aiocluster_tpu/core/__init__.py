"""Pure, clock-injectable core: identity, values, replicated state,
reconciliation, and failure detection. No I/O, no concurrency — the seam
that lets the asyncio socket backend and the JAX sim backend share one
source of truth (SURVEY.md §7)."""

from .cluster_state import ClusterState, Staleness, staleness_score
from .config import (DEFAULT_MAX_PAYLOAD_SIZE, Config,
                     FailureDetectorConfig, PersistenceConfig)
from .failure import BoundedWindow, FailureDetector, HeartbeatWindow
from .identity import Address, NodeId
from .kvstate import NodeState
from .messages import (
    Ack,
    BadCluster,
    Delta,
    Digest,
    KeyValueUpdate,
    Leave,
    NodeDelta,
    NodeDigest,
    Packet,
    Syn,
    SynAck,
)
from .values import KeyStatus, VersionedValue, VersionStatusEnum

__all__ = (
    "Ack",
    "Address",
    "BadCluster",
    "BoundedWindow",
    "ClusterState",
    "FailureDetector",
    "HeartbeatWindow",
    "Config",
    "DEFAULT_MAX_PAYLOAD_SIZE",
    "Delta",
    "Digest",
    "FailureDetectorConfig",
    "KeyStatus",
    "KeyValueUpdate",
    "Leave",
    "NodeDelta",
    "NodeDigest",
    "NodeId",
    "NodeState",
    "Packet",
    "PersistenceConfig",
    "Staleness",
    "Syn",
    "SynAck",
    "VersionStatusEnum",
    "VersionedValue",
    "staleness_score",
)
