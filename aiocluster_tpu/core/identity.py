"""Node identity.

Parity: reference entities.py:52-82 (``Address``, ``NodeId``). A node is
identified by a human name plus a ``generation_id`` that defaults to the boot
monotonic clock, so a restarted node is a *new* cluster member and stale
replicas of its old incarnation age out instead of shadowing fresh state.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

Address = tuple[str, int]


@dataclass(frozen=True, slots=True, eq=True)
class NodeId:
    """Unique identity of one cluster member."""

    name: str
    generation_id: int = field(default_factory=time.monotonic_ns)
    gossip_advertise_addr: Address = ("localhost", 7001)
    tls_name: str | None = None

    def long_name(self) -> str:
        host, port = self.gossip_advertise_addr
        return f"{self.name}-{self.generation_id}-{host}:{port}"
