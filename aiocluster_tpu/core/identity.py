"""Node identity.

Parity: reference entities.py:52-82 (``Address``, ``NodeId``). A node is
identified by a human name plus a ``generation_id`` that defaults to the
boot wall-clock, so a restarted node is a *new* cluster member and stale
replicas of its old incarnation age out instead of shadowing fresh state.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

Address = tuple[str, int]

# Highest generation handed out by this process, guarded for the
# multi-threaded spawn case (servers booting clusters off-loop).
_generation_lock = threading.Lock()
_last_generation = 0


def next_generation_id() -> int:
    """A fresh, strictly increasing generation.

    Wall-clock (``time.time_ns``, the reference's semantics), NOT
    ``time.monotonic_ns``: the monotonic clock restarts at an arbitrary
    (typically small) value on host reboot, so a rebooted node could come
    back with a *lower* generation than its previous incarnation and lose
    the newer-generation-wins rule — its fresh state would be shadowed by
    stale replicas for up to the dead-node grace period. The guard below
    additionally pins the value strictly above every generation this
    process has issued, so in-process restarts (and a backwards-stepping
    wall clock) still bump the generation.
    """
    global _last_generation
    with _generation_lock:
        generation = time.time_ns()  # noqa: ACT044 -- wall-clock BY CONTRACT: generations order incarnations across process death, which no virtual/seam clock survives (docstring above; vtime soaks bypass via ChaosHarness._next_generation)
        if generation <= _last_generation:
            generation = _last_generation + 1
        _last_generation = generation
        return generation


def observe_generation(generation: int) -> None:
    """Raise the strictly-increasing guard's floor to a generation issued
    OUTSIDE this process — the durable half of the promise. The in-memory
    ``_last_generation`` dies with the process, so a rebooted node under
    a regressed wall clock could reissue a generation at or below its
    previous incarnation's and lose newer-generation-wins; the
    persistence layer (runtime/persist.py) records the last generation it
    saw and replays it here at boot, making ``next_generation_id()``
    return ``max(persisted + 1, time_ns)`` no matter what the clock says.
    """
    global _last_generation
    with _generation_lock:
        if generation > _last_generation:
            _last_generation = generation


@dataclass(frozen=True, slots=True, eq=True)
class NodeId:
    """Unique identity of one cluster member."""

    name: str
    generation_id: int = field(default_factory=next_generation_id)
    gossip_advertise_addr: Address = ("localhost", 7001)
    tls_name: str | None = None

    def long_name(self) -> str:
        host, port = self.gossip_advertise_addr
        return f"{self.name}-{self.generation_id}-{host}:{port}"
