"""Reconciliation and handshake message DTOs.

These are the *logical* messages of the ScuttleButt anti-entropy protocol
(parity: reference state.py:22-103 for digest/delta DTOs and
messages.proto:3-26 for the handshake envelope). Encoding lives entirely in
``aiocluster_tpu.wire``; these types are plain data.

Protocol recap: the initiator sends ``Syn(digest)`` — a per-node summary
(heartbeat, gc watermark, max version) of everything it knows. The responder
answers ``SynAck(its own digest, delta)`` where the delta carries exactly the
key-value updates the initiator is missing, and the initiator closes with
``Ack(delta)`` carrying what the responder is missing. State converges
bidirectionally in a single handshake.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .identity import NodeId
from .values import VersionStatusEnum


@dataclass(frozen=True, slots=True, eq=True)
class KeyValueUpdate:
    """One replicated write: a key with its owner-assigned version/status."""

    key: str
    value: str
    version: int
    status: VersionStatusEnum


@dataclass(frozen=True, slots=True, eq=True)
class NodeDigest:
    """Summary of one node's keyspace as known to the digest's sender."""

    node_id: NodeId
    heartbeat: int
    last_gc_version: int
    max_version: int


@dataclass(slots=True)
class Digest:
    """Per-node summaries for every node the sender knows about."""

    node_digests: dict[NodeId, NodeDigest] = field(default_factory=dict)

    def add_node(
        self,
        node_id: NodeId,
        heartbeat: int,
        last_gc_version: int,
        max_version: int,
    ) -> None:
        self.node_digests[node_id] = NodeDigest(
            node_id, heartbeat, last_gc_version, max_version
        )


@dataclass(slots=True)
class NodeDelta:
    """Updates for one owner's keyspace, covering versions strictly above
    ``from_version_excluded``.

    ``max_version`` is only populated when the delta is *complete* (no MTU
    truncation); receivers may then fast-forward their recorded max version.
    The reference always populated it (state.py:389), which silently loses
    truncated updates — see ClusterState.compute_partial_delta_respecting_mtu
    for the fix rationale.
    """

    node_id: NodeId
    from_version_excluded: int
    last_gc_version: int
    key_values: list[KeyValueUpdate]
    max_version: int | None = None


@dataclass(slots=True)
class Delta:
    """A bundle of per-node deltas; the unit bounded by the MTU."""

    node_deltas: list[NodeDelta] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Handshake envelope (wire parity: messages.proto:3-26)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Syn:
    digest: Digest


@dataclass(slots=True)
class SynAck:
    digest: Digest
    delta: Delta


@dataclass(slots=True)
class Ack:
    delta: Delta


@dataclass(slots=True)
class BadCluster:
    """Reply sent when the peer's cluster_id does not match ours."""


@dataclass(slots=True)
class Leave:
    """Graceful-departure announcement (docs/robustness.md "Durability &
    lifecycle"): the sender is shutting down ON PURPOSE. ``delta``
    carries the sender's final flush of its OWN keyspace (guarded on
    apply like any delta); receivers move the node to dead-with-reason
    immediately instead of waiting out the phi window. ``heartbeat`` is
    the sender's FINAL heartbeat (it stops responding before
    announcing, so no higher value can ever exist for this
    incarnation): receivers hold the death until they see evidence
    ABOVE it — in-flight digests of older heartbeats can never
    resurrect a drained node, while a genuine rejoin (which resumes
    past the final value) lifts the hold immediately. Fire-and-forget:
    no reply is expected. New beyond the reference schema (envelope
    field 6) — reference peers skip unknown fields and at worst see a
    message-less packet, which they drop like any malformed frame."""

    node_id: NodeId
    delta: Delta
    reason: str = "leave"
    heartbeat: int = 0


@dataclass(slots=True)
class TraceContext:
    """Wire-level span context riding Syn/SynAck/Ack when
    ``Config.trace_context`` is on (docs/observability.md "Fleet
    telemetry"). ``node`` names the packet's SENDER; ``handshake_id``
    is chosen by the handshake's initiator and echoed by the responder,
    correlating all three packets of one exchange across both nodes'
    flight recorders. It closes the provenance collector's one blind
    spot: a responder applying an Ack delta can name ``from_peer``
    exactly instead of relying on the 30s closest-preceding-send
    heuristic. New beyond the reference schema (envelope field 7) —
    reference peers skip unknown fields, and the context only ever
    rides WITH a handshake message, so they decode the same packet
    minus the context."""

    node: str
    handshake_id: int


@dataclass(slots=True)
class Packet:
    """Top-level envelope: cluster id + exactly one handshake message.

    ``trace`` is the optional wire-level span context (envelope field
    7, see :class:`TraceContext`); ``None`` — the default and the
    ``Config.trace_context=False`` state — keeps frames byte-identical
    to the reference."""

    cluster_id: str
    msg: Syn | SynAck | Ack | BadCluster | Leave
    trace: TraceContext | None = None
