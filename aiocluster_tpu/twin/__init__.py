"""Digital twin: trace-driven calibration and SLO-driven autotuning.

The runtime and the TPU sim stopped being parallel artifacts here
(ROADMAP item 5, docs/twin.md): a recorded runtime trace
(``Cluster.trace_rounds`` / ``ChaosHarness(trace=...)``) is lifted into
a deterministic simulation and replayed round-for-round (``replay``),
the residual between the two is fitted as a transfer function with
stated error bars and persisted as a versioned ``CalibrationRecord``
(``calibrate``), and an operator SLO is then evaluated over a
``SweepSimulator`` lane ensemble — every candidate under ONE compile —
to emit a recommended ``Config`` + ``SimConfig`` pair with the evidence
attached (``autotune``).
"""

from .autotune import (
    SLO,
    AutotuneInfeasible,
    Recommendation,
    autotune,
)
from .calibrate import (
    CALIBRATION_SCHEMA,
    CalibrationError,
    CalibrationRecord,
    CalibrationSchemaError,
    fit_calibration,
    load_calibration,
    save_calibration,
)
from .drift import AxisDrift, DriftVerdict, check_drift, export_drift
from .replay import (
    ReplayReport,
    RoundRow,
    RuntimeTrace,
    TraceSchemaError,
    lift_sim_config,
    load_runtime_trace,
    replay,
    wavefront_prediction,
)

__all__ = (
    "CALIBRATION_SCHEMA",
    "SLO",
    "AutotuneInfeasible",
    "AxisDrift",
    "CalibrationError",
    "CalibrationRecord",
    "CalibrationSchemaError",
    "DriftVerdict",
    "Recommendation",
    "ReplayReport",
    "RoundRow",
    "RuntimeTrace",
    "TraceSchemaError",
    "autotune",
    "check_drift",
    "export_drift",
    "fit_calibration",
    "lift_sim_config",
    "load_calibration",
    "load_runtime_trace",
    "replay",
    "save_calibration",
    "wavefront_prediction",
)
