"""Drift monitor: does the stored calibration still describe reality?

ROADMAP item 5's residual — "continuous recalibration (a cron-shaped
drift monitor over rolling traces)". A ``CalibrationRecord`` is a
snapshot of a deployment's transfer function; the deployment keeps
changing under it (hardware contention, fleet growth, interval tuning).
``check_drift`` re-fits the measurable axes on a ROLLING WINDOW of a
fresh trace and verdicts each against the stored record's own
tolerance — the cheap recurring check an operator crons between full
recalibrations (``python -m aiocluster_tpu twin --trace fresh.jsonl
--check-drift stored.json``).

Axes checked:

- ``rounds_per_sec`` — the wall-clock axis, re-measured directly from
  the window's per-node round timestamps (no sim needed). THE axis
  that drifts in practice: a slower machine, a retuned interval, a
  bigger fleet.
- ``round_duration_s`` — the per-round work floor.
- ``kv_scale`` — the volume axis — ONLY when the window reaches back
  to the trace's round 0: kv_scale is runtime-kv per *sim*-kv, and the
  sim it is measured against cold-starts at round 0, so a mid-flight
  (usually quiescent) window has no comparable sim volume. Skipped
  windows are reported as such, never silently verdicted.

A drifted verdict means "refit and redeploy the calibration", not
"the system is broken" — the magnitude says how stale the stored
numbers are. Exported as the ``aiocluster_twin_drift`` gauge (1 =
drifted) when a registry is passed.
"""

from __future__ import annotations

import statistics
from dataclasses import dataclass
from pathlib import Path

from ..obs.registry import MetricsRegistry
from .calibrate import CalibrationRecord
from .replay import RuntimeTrace, load_runtime_trace


@dataclass(frozen=True)
class AxisDrift:
    """One re-fitted axis vs its stored value."""

    axis: str
    fitted: float
    stored: float
    rel_err: float  # |fitted - stored| / |stored|
    tolerance: float
    drifted: bool

    def to_dict(self) -> dict:
        return {
            "axis": self.axis,
            "fitted": round(self.fitted, 6),
            "stored": round(self.stored, 6),
            "rel_err": round(self.rel_err, 6),
            "tolerance": self.tolerance,
            "drifted": self.drifted,
        }


@dataclass(frozen=True)
class DriftVerdict:
    """The monitor's answer: ok, or drifted with axis + magnitude."""

    ok: bool
    axes: tuple[AxisDrift, ...]
    skipped_axes: tuple[str, ...]  # axes the window could not re-fit
    window_rounds: int
    window_start: int
    trace_rounds: int
    tolerance: float

    @property
    def drifted_axes(self) -> tuple[AxisDrift, ...]:
        return tuple(a for a in self.axes if a.drifted)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "axes": [a.to_dict() for a in self.axes],
            "skipped_axes": list(self.skipped_axes),
            "window_rounds": self.window_rounds,
            "window_start": self.window_start,
            "trace_rounds": self.trace_rounds,
            "tolerance": self.tolerance,
        }


def export_drift(
    verdict: DriftVerdict, registry: MetricsRegistry
) -> None:
    """Mirror a verdict into the registry: ``aiocluster_twin_drift``
    (1 drifted / 0 ok) plus the per-axis relative error as
    ``aiocluster_twin_drift_rel_err{axis=}`` — the alertable shape of
    the cron loop (docs/twin.md)."""
    registry.gauge(
        "aiocluster_twin_drift",
        "Twin calibration drift verdict: 1 = a re-fitted axis left the "
        "stored CalibrationRecord's tolerance (refit and redeploy), "
        "0 = the stored transfer function still describes the fleet",
    ).set(0.0 if verdict.ok else 1.0)
    rel = registry.gauge(
        "aiocluster_twin_drift_rel_err",
        "Per-axis relative error of the rolling re-fit vs the stored "
        "calibration (the drift magnitude behind aiocluster_twin_drift)",
        labels=("axis",),
    )
    for a in verdict.axes:
        rel.labels(a.axis).set(a.rel_err)


def check_drift(
    calibration: CalibrationRecord,
    trace: RuntimeTrace | str | Path,
    *,
    window: int | None = None,
    tolerance: float | None = None,
    seed: int = 0,
    registry: MetricsRegistry | None = None,
) -> DriftVerdict:
    """Re-fit the transfer function's axes on the LAST ``window``
    rounds of ``trace`` and verdict each against ``calibration``
    (module docstring). ``window`` defaults to the stored record's own
    fit window; ``tolerance`` to the stored record's. Raises
    ``ValueError`` when the window holds fewer than two rounds (nothing
    to rate-fit — record longer)."""
    if isinstance(trace, (str, Path)):
        trace = load_runtime_trace(trace)
    tol = calibration.tolerance if tolerance is None else tolerance
    if tol <= 0:
        raise ValueError("drift tolerance must be > 0")
    rows = trace.rounds
    if not rows:
        raise ValueError(f"{trace.path}: trace aligned to zero rounds")
    last_round = rows[-1].round
    w = calibration.fit_rounds if window is None else int(window)
    if w < 2:
        raise ValueError("drift window must span at least 2 rounds")
    start = max(0, last_round + 1 - w)
    window_rows = [r for r in rows if r.round >= start]
    if len(window_rows) < 2:
        raise ValueError(
            f"{trace.path}: only {len(window_rows)} aligned round(s) in "
            f"the [{start}, {last_round}] window — record a longer trace"
        )

    axes: list[AxisDrift] = []
    skipped: list[str] = []

    def axis(name: str, fitted: float, stored: float) -> None:
        denom = max(abs(stored), 1e-12)
        rel = abs(fitted - stored) / denom
        axes.append(
            AxisDrift(
                axis=name,
                fitted=fitted,
                stored=stored,
                rel_err=rel,
                tolerance=tol,
                drifted=rel > tol,
            )
        )

    # Wall-clock axis: the window's measured per-node rate.
    rate, _rate_std = trace.rounds_per_sec(start, None)
    axis("rounds_per_sec", rate, calibration.rounds_per_sec)
    # Work-floor axis.
    duration = statistics.fmean(r.duration_s for r in window_rows)
    axis("round_duration_s", duration, calibration.round_duration_s)

    # Volume axis: only a window anchored at round 0 is comparable to
    # the cold-start sim kv_scale is defined against (module docstring).
    if calibration.kv_scale is not None and start == 0:
        from .calibrate import CalibrationError, fit_calibration
        from .replay import replay

        try:
            refit = fit_calibration(
                replay(trace, seed=seed), tolerance=tol
            )
        except CalibrationError:
            skipped.append("kv_scale")
        else:
            if refit.kv_scale is not None:
                axis("kv_scale", refit.kv_scale, calibration.kv_scale)
            else:
                skipped.append("kv_scale")
    elif calibration.kv_scale is not None:
        skipped.append("kv_scale")

    verdict = DriftVerdict(
        ok=not any(a.drifted for a in axes),
        axes=tuple(axes),
        skipped_axes=tuple(skipped),
        window_rounds=len(window_rows),
        window_start=start,
        trace_rounds=len(rows),
        tolerance=tol,
    )
    if registry is not None:
        export_drift(verdict, registry)
    return verdict
