"""Autotune: evaluate an operator SLO over one sweep, emit a Config.

ScuttleButt reconciliation and phi-accrual detection both ship
operator-facing knobs (fanout, phi threshold, write cadence) whose safe
settings the papers leave to folklore. This module answers them for
*this* cluster: declare the SLO (``convergence_deadline_s``, an FD
false-positive budget, optionally a chaos ``FaultPlan`` the tuning must
survive), hand over a fitted ``CalibrationRecord`` (twin/calibrate.py),
and ``autotune`` drives every candidate as one ``SweepSimulator`` lane
ensemble — ONE jit compile for the whole grid, no per-candidate retrace
(tests/test_twin.py counts the jit cache entries) — scores each lane's
rounds-to-convergence through the transfer function into wall-clock
with error bars, and emits the best feasible lane as a recommended
``Config`` + ``SimConfig`` pair with the evidence attached.

Feasibility is conservative: a lane qualifies only if the UPPER error
bar of its predicted convergence time meets the deadline (and its FD
false-positive fraction fits the budget, when one is declared); among
feasible lanes the lowest predicted time wins, ties breaking toward the
earlier (cheaper — grids are built cheapest-first) lane.
"""

from __future__ import annotations

import dataclasses
import itertools

from ..core.config import Config
from ..faults.plan import FaultPlan
from ..sim.checkpoint import _config_from_meta
from ..sim.config import SimConfig
from .calibrate import CalibrationRecord

RECOMMENDATION_SCHEMA = "aiocluster-twin-recommendation/1"


class AutotuneInfeasible(RuntimeError):
    """No candidate lane satisfied the SLO — the evidence table rides
    along so the operator sees how far each lane missed."""

    def __init__(self, message: str, lanes: list[dict]):
        super().__init__(message)
        self.lanes = lanes


@dataclasses.dataclass(frozen=True)
class SLO:
    """The operator's service-level objective for gossip tuning."""

    # The fleet must (re)converge within this wall-clock budget.
    convergence_deadline_s: float
    # Tolerable fraction of alive observer/peer pairs wrongly believed
    # dead (the sim's fd_false_positive_fraction metric). None = no FD
    # constraint (or FD untracked in the sim config).
    fd_false_positive_budget: float | None = None
    # Chaos conditioning: when set, every candidate lane is evaluated
    # UNDER this plan (docs/faults.md) — the recommendation then answers
    # "which knobs meet the deadline through this failure", not just in
    # fair weather.
    fault_plan: FaultPlan | None = None

    def __post_init__(self) -> None:
        if self.convergence_deadline_s <= 0:
            raise ValueError("convergence_deadline_s must be > 0")
        if (
            self.fd_false_positive_budget is not None
            and not 0.0 <= self.fd_false_positive_budget <= 1.0
        ):
            raise ValueError("fd_false_positive_budget must be in [0, 1]")

    def to_dict(self) -> dict:
        return {
            "convergence_deadline_s": self.convergence_deadline_s,
            "fd_false_positive_budget": self.fd_false_positive_budget,
            "fault_plan": (
                None if self.fault_plan is None else self.fault_plan.to_dict()
            ),
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "SLO":
        plan = raw.get("fault_plan")
        return cls(
            convergence_deadline_s=raw["convergence_deadline_s"],
            fd_false_positive_budget=raw.get("fd_false_positive_budget"),
            fault_plan=None if plan is None else FaultPlan.from_dict(plan),
        )


@dataclasses.dataclass
class Recommendation:
    """One recommended (Config, SimConfig) pair plus its evidence."""

    config: Config
    sim_config: SimConfig
    lane: int
    predicted: dict  # predict_wall_seconds of the winning lane
    evidence: dict  # slo + calibration + per-lane scored table

    @property
    def predicted_rounds_per_sec(self) -> float:
        return self.evidence["calibration"]["rounds_per_sec"]

    def to_dict(self) -> dict:
        """JSON-ready form. The runtime ``Config`` serializes as the
        TUNABLE fields the sweep actually explored (identity, TLS and
        transport knobs belong to the deployment, not the tuner);
        ``from_dict`` re-applies them over the same base config."""
        return {
            "schema": RECOMMENDATION_SCHEMA,
            "tunables": {
                "gossip_count": self.config.gossip_count,
                "phi_threshhold": (
                    self.config.failure_detector.phi_threshhold
                ),
            },
            "sim_config": dataclasses.asdict(self.sim_config),
            "lane": self.lane,
            "predicted": dict(self.predicted),
            "evidence": self.evidence,
        }

    @classmethod
    def from_dict(cls, raw: dict, base_config: Config) -> "Recommendation":
        if raw.get("schema") != RECOMMENDATION_SCHEMA:
            raise ValueError(
                f"recommendation schema {raw.get('schema')!r} is not "
                f"the supported {RECOMMENDATION_SCHEMA!r}"
            )
        tun = raw["tunables"]
        config = dataclasses.replace(
            base_config,
            gossip_count=int(tun["gossip_count"]),
            failure_detector=dataclasses.replace(
                base_config.failure_detector,
                phi_threshhold=float(tun["phi_threshhold"]),
            ),
        )
        return cls(
            config=config,
            sim_config=_config_from_meta(dict(raw["sim_config"])),
            lane=int(raw["lane"]),
            predicted=dict(raw["predicted"]),
            evidence=dict(raw["evidence"]),
        )


def _candidate_grid(
    sim_config: SimConfig,
    fanout,
    phi_threshold,
    writes_per_round,
) -> list[dict]:
    """The lane grid, cheapest-first: fanout ascending outermost (a
    lower fanout is less traffic per round), then phi descending (a
    higher threshold is fewer false positives), then writes ascending.
    Each axis defaults to the base config's current value."""
    fanouts = sorted(set(fanout)) if fanout else [sim_config.fanout]
    phis = (
        sorted(set(phi_threshold), reverse=True)
        if phi_threshold
        else [sim_config.phi_threshold]
    )
    wprs = (
        sorted(set(writes_per_round))
        if writes_per_round
        else [sim_config.writes_per_round]
    )
    return [
        {"fanout": f, "phi_threshold": p, "writes_per_round": w}
        for f, p, w in itertools.product(fanouts, phis, wprs)
    ]


def autotune(
    slo: "SLO",
    calibration: CalibrationRecord,
    base_config: Config,
    sim_config: SimConfig,
    *,
    fanout=None,
    phi_threshold=None,
    writes_per_round=None,
    seed: int = 0,
    max_rounds: int = 1024,
    chunk: int = 8,
) -> Recommendation:
    """Evaluate the candidate grid under ONE SweepSimulator compile and
    return the best feasible lane as a Recommendation (module
    docstring). Candidate axes not supplied stay at ``sim_config``'s
    current value; every lane shares ``seed`` so candidates differ only
    in the swept knobs."""
    from ..sim.sweep import SweepSimulator

    grid = _candidate_grid(sim_config, fanout, phi_threshold, writes_per_round)
    if len(grid) < 2:
        raise ValueError(
            "autotune needs at least two candidate lanes — pass "
            "fanout=/phi_threshold=/writes_per_round= candidate lists"
        )
    cfg = sim_config
    if slo.fault_plan is not None:
        cfg = dataclasses.replace(cfg, fault_plan=slo.fault_plan)
    if (
        slo.fd_false_positive_budget is not None
        and not cfg.track_failure_detector
    ):
        raise ValueError(
            "SLO declares an FD false-positive budget but the sim "
            "config does not track the failure detector"
        )
    lane_fanout = [g["fanout"] for g in grid]
    # The static config's fanout is the sweep's sub-exchange BOUND.
    cfg = dataclasses.replace(cfg, fanout=max(lane_fanout))
    sweep = SweepSimulator(
        cfg,
        seeds=[seed] * len(grid),
        fanout=lane_fanout if fanout else None,
        phi_threshold=[g["phi_threshold"] for g in grid]
        if phi_threshold
        else None,
        writes_per_round=[g["writes_per_round"] for g in grid]
        if writes_per_round
        else None,
        chunk=chunk,
    )
    sweep.run_until_converged(max_rounds=max_rounds)
    result = sweep.result()

    def objective(row: dict):
        rounds = row["rounds_to_convergence"]
        if rounds is None:
            return None  # never converged inside max_rounds
        pred = calibration.predict_wall_seconds(rounds)
        if pred["hi"] > slo.convergence_deadline_s:
            return None  # even the optimistic operator can't sign this
        if slo.fd_false_positive_budget is not None:
            fp = row.get("fd_false_positive_fraction")
            if fp is not None and fp > slo.fd_false_positive_budget:
                return None
        return pred["seconds"]

    # Evidence first: the scored table rides the result either way.
    scores = result.evaluate(objective)
    lanes_evidence = []
    for lane, (row, score) in enumerate(zip(result.rows(), scores)):
        entry = dict(row)
        entry.update(grid[lane])
        entry["feasible"] = score is not None
        if row["rounds_to_convergence"] is not None:
            entry["predicted"] = calibration.predict_wall_seconds(
                row["rounds_to_convergence"]
            )
        lanes_evidence.append(entry)

    best = result.best_lane(objective)
    if best is None:
        raise AutotuneInfeasible(
            f"no candidate lane meets the SLO (deadline "
            f"{slo.convergence_deadline_s}s, fd budget "
            f"{slo.fd_false_positive_budget}) — see .lanes for how far "
            "each missed",
            lanes_evidence,
        )
    lane, _score = best
    winner = grid[lane]
    rec_config = dataclasses.replace(
        base_config,
        gossip_count=winner["fanout"],
        failure_detector=dataclasses.replace(
            base_config.failure_detector,
            phi_threshhold=winner["phi_threshold"],
        ),
    )
    rec_sim = dataclasses.replace(
        cfg,
        fanout=winner["fanout"],
        phi_threshold=winner["phi_threshold"],
        writes_per_round=winner["writes_per_round"],
    )
    evidence = {
        "slo": slo.to_dict(),
        "calibration": calibration.to_dict(),
        "lanes": lanes_evidence,
        "swept": sorted(
            k for k, v in (
                ("fanout", fanout),
                ("phi_threshold", phi_threshold),
                ("writes_per_round", writes_per_round),
            ) if v
        ),
    }
    return Recommendation(
        config=rec_config,
        sim_config=rec_sim,
        lane=lane,
        predicted=calibration.predict_wall_seconds(
            result.rounds_to_convergence[lane]
        ),
        evidence=evidence,
    )
