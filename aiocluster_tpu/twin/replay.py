"""Replay: lift a recorded runtime trace into the deterministic sim.

The recording side is ``Cluster.trace_rounds`` (one ``twin_node``
record per member, one ``twin_round`` record per initiated round —
docs/twin.md spells the contract); this module is the consuming side:

- ``load_runtime_trace`` reads the JSONL tolerantly (``skip_invalid``
  semantics — a trace from a crashed process has a torn tail, and that
  trace is the one most worth replaying), checks the ``trace_header``
  schema loudly, groups ``twin_round`` events by node, and aligns them
  into a fleet-wide per-round table by each node's own round index.
- ``lift_sim_config`` derives the ``SimConfig`` the trace implies:
  fleet size from the ``twin_node`` records, fanout from the advertised
  ``gossip_count``, phi from the FD config — one tick per gossip round,
  the same mapping docs/sim.md documents for the reference knobs.
- ``replay`` runs that config through the deterministic ``Simulator``
  (chunk=1, stride-1 sampling: one metrics row per round) and returns
  the aligned (runtime, sim) round-by-round comparison table the
  calibrator fits (twin/calibrate.py).

Alignment is by ROUND INDEX, not wall-clock: one sim tick models one
fleet-wide gossip round, while runtime members tick on their own
(jittered) intervals — so round r of the table aggregates every node's
r-th initiated round against the sim state after r+1 ticks.
"""

from __future__ import annotations

import statistics
from dataclasses import dataclass, field
from pathlib import Path

from ..obs.trace import TRACE_SCHEMA, scan_trace
from ..sim.config import SimConfig


class TraceSchemaError(ValueError):
    """The trace does not carry a compatible ``trace_header`` — written
    by an incompatible version (or not by TraceWriter at all). Refused
    loudly instead of mis-fit silently (twin/calibrate.py)."""


@dataclass
class RoundRow:
    """One fleet-wide round of the aligned table: means/totals over the
    nodes that reported this round index."""

    round: int
    ts: float  # mean wall-clock timestamp of the round across nodes
    duration_s: float  # mean per-node round work time (excludes the interval)
    kv_sent: int  # fleet total key-versions sent this round
    kv_applied: int  # fleet total key-versions applied this round
    live: float  # mean live-peer count observed
    phi_max: float  # worst phi sample any node recorded this round
    nodes: int  # how many nodes reported this round index


@dataclass
class RuntimeTrace:
    """A loaded twin-grade runtime trace (see module docstring)."""

    path: str
    header: dict
    nodes: dict[str, dict]  # node name -> its (latest) twin_node record
    node_rounds: dict[str, list[dict]]  # node name -> twin_round records
    rounds: list[RoundRow] = field(default_factory=list)
    transitions: list[dict] = field(default_factory=list)
    skipped: int = 0  # malformed lines the tolerant read skipped

    @property
    def n_nodes(self) -> int:
        return len(self.nodes)

    def node_rates(
        self, start: int = 0, end: int | None = None
    ) -> dict[str, float]:
        """Per-node measured rounds/s over the [start, end) round-index
        window: (rounds - 1) / (last ts - first ts). Nodes with fewer
        than two rounds in the window are omitted."""
        rates: dict[str, float] = {}
        for name, recs in self.node_rounds.items():
            window = [
                r for r in recs
                if r["round"] >= start and (end is None or r["round"] < end)
            ]
            if len(window) < 2:
                continue
            span = window[-1]["ts"] - window[0]["ts"]
            if span > 0:
                rates[name] = (len(window) - 1) / span
        return rates

    def rounds_per_sec(
        self, start: int = 0, end: int | None = None
    ) -> tuple[float, float]:
        """Fleet (mean, std) of the per-node measured round rates — the
        transfer function's wall-clock axis, with its error bar."""
        rates = sorted(self.node_rates(start, end).values())
        if not rates:
            raise ValueError(
                f"trace {self.path} carries no node with two rounds in "
                f"[{start}, {end}) — nothing to rate-fit"
            )
        mean = statistics.fmean(rates)
        std = statistics.pstdev(rates) if len(rates) > 1 else 0.0
        return mean, std


def load_runtime_trace(
    path: str | Path, *, require_header: bool = True
) -> RuntimeTrace:
    """Read a twin-grade trace tolerantly and align it (module
    docstring). ``require_header=False`` admits headerless traces
    (hand-built fixtures) — calibration refuses those unless forced."""
    scan = scan_trace(path)
    header = scan.header
    if header is not None and header.get("schema") != TRACE_SCHEMA:
        raise TraceSchemaError(
            f"{path}: trace schema {header.get('schema')!r} is not the "
            f"supported {TRACE_SCHEMA!r}; refusing to mis-read records "
            "recorded under a different vocabulary"
        )
    if header is None and require_header:
        raise TraceSchemaError(
            f"{path}: no trace_header record — not a TraceWriter trace "
            "(or its first line was lost); pass require_header=False "
            "only for hand-built fixtures"
        )
    nodes: dict[str, dict] = {}
    node_rounds: dict[str, list[dict]] = {}
    transitions: list[dict] = []
    for rec in scan.records:
        event = rec.get("event")
        if event == "twin_node":
            # Latest wins: a restarted member re-describes itself.
            nodes[rec["node"]] = rec
        elif event == "twin_round":
            node_rounds.setdefault(rec["node"], []).append(rec)
        elif event == "node_transition":
            transitions.append(rec)
    trace = RuntimeTrace(
        path=str(path),
        header=header or {},
        nodes=nodes,
        node_rounds=node_rounds,
        transitions=transitions,
        skipped=len(scan.skipped),
    )
    if not node_rounds:
        raise ValueError(
            f"{path}: no twin_round records — record the fleet with "
            "Cluster.trace_rounds / ChaosHarness(trace=...) first "
            "(a plain trace= constructor trace has no twin events)"
        )
    # Align by round index. A restarted member restarts its own round
    # counter at 0 — its post-restart rounds fold into the early rows
    # (documented; calibration fits want restart-free windows anyway).
    by_round: dict[int, list[dict]] = {}
    for recs in node_rounds.values():
        for rec in recs:
            by_round.setdefault(int(rec["round"]), []).append(rec)
    for rnd in sorted(by_round):
        recs = by_round[rnd]
        trace.rounds.append(
            RoundRow(
                round=rnd,
                ts=statistics.fmean(r["ts"] for r in recs),
                duration_s=statistics.fmean(r["duration_s"] for r in recs),
                kv_sent=sum(int(r["kv_sent"]) for r in recs),
                kv_applied=sum(int(r["kv_applied"]) for r in recs),
                live=statistics.fmean(r["live"] for r in recs),
                phi_max=max(float(r.get("phi_max", 0.0)) for r in recs),
                nodes=len(recs),
            )
        )
    return trace


def lift_sim_config(trace: RuntimeTrace, **overrides) -> SimConfig:
    """The ``SimConfig`` this trace implies — one tick per gossip round,
    fleet shape and tuning knobs from the ``twin_node`` records
    (majority value where members disagree). Keyword overrides replace
    any derived field (e.g. ``budget=...`` to model a narrower MTU)."""
    if trace.n_nodes < 2:
        raise ValueError(
            f"trace describes {trace.n_nodes} node(s); a cluster sim "
            "needs at least 2 (were twin_node records recorded?)"
        )

    def majority(key, default=None):
        values = [n[key] for n in trace.nodes.values() if key in n]
        if not values:
            return default
        return statistics.mode(values)

    derived = {
        "n_nodes": trace.n_nodes,
        "keys_per_node": max(1, int(majority("n_own_keys", 1))),
        "fanout": min(int(majority("gossip_count", 3)), trace.n_nodes - 1),
        "phi_threshold": float(majority("phi_threshold", 8.0)),
        # The reference's paired 3-way handshake maps to the matching
        # pairing (docs/sim.md); matching also keeps the fanout axis
        # sweepable, which is what the autotuner needs this config for.
        "pairing": "matching",
    }
    derived.update(overrides)
    return SimConfig(**derived)


def wavefront_prediction(
    trace: RuntimeTrace,
    *,
    threshold: float = 0.99,
    seed: int = 0,
    max_rounds: int = 512,
    **overrides,
) -> dict:
    """The sim's predicted epidemic wavefront for THIS deployment: lift
    the trace's implied SimConfig and run one marked write through it
    from a converged fleet (obs.sim.wavefront_series). This is what the
    propagation benchmark lines up against the MEASURED write→visible
    curve from the provenance tracer — the twin comparing propagation
    *curves*, not just convergence round counts. Returns the wavefront
    dict plus the lifted config's shape for provenance."""
    import dataclasses

    from ..obs.sim import wavefront_series

    cfg = lift_sim_config(trace, **overrides)
    wf = wavefront_series(
        cfg, seed=seed, max_rounds=max_rounds, threshold=threshold
    )
    wf["sim_config"] = dataclasses.asdict(cfg)
    return wf


@dataclass
class ReplayReport:
    """The aligned (runtime, sim) comparison the calibrator fits."""

    trace: RuntimeTrace
    cfg: SimConfig
    seed: int
    sim_converged_round: int | None
    rows: list[dict]  # one aligned dict per runtime round
    sim_series: list[dict]  # full stride-1 sim metric series

    def to_dict(self) -> dict:
        """Evidence form (JSON-ready): the aligned table plus the run's
        shape — the full raw series stays out (it can be regenerated
        from the seed; evidence records should stay compact)."""
        import dataclasses

        return {
            "trace_path": self.trace.path,
            "trace_skipped_lines": self.trace.skipped,
            "n_nodes": self.trace.n_nodes,
            "sim_config": dataclasses.asdict(self.cfg),
            "seed": self.seed,
            "sim_converged_round": self.sim_converged_round,
            "rounds": self.rows,
        }


def replay(
    trace: RuntimeTrace,
    cfg: SimConfig | None = None,
    *,
    seed: int = 0,
    max_rounds: int = 4096,
) -> ReplayReport:
    """Run the trace's implied (or given) config through the
    deterministic sim and align the two series round-for-round.

    The sim runs at stride-1 sampling for at least as many ticks as the
    trace has rounds (so every runtime round has a sim row) and keeps
    going to its exact convergence round up to ``max_rounds`` — the
    figure autotune predictions are made of."""
    from ..obs.registry import MetricsRegistry
    from ..sim.simulator import Simulator

    if cfg is None:
        cfg = lift_sim_config(trace)
    n_trace_rounds = len(trace.rounds)
    sim = Simulator(
        cfg,
        seed=seed,
        chunk=1,
        metrics=MetricsRegistry(),  # private registry: replay is a study
        metrics_stride=1,
    )
    converged = sim.run_until_converged(
        max_rounds=max(max_rounds, n_trace_rounds)
    )
    if sim.tick < n_trace_rounds:
        # Converged before the trace ended: keep stepping so every
        # recorded runtime round has an aligned sim row.
        sim.run(n_trace_rounds - sim.tick)
    series = sim.flush_metrics()
    by_tick = {int(s["tick"]): s for s in series}
    initial_kv = cfg.n_nodes * cfg.keys_per_node  # every owner knows itself
    rows: list[dict] = []
    for row in trace.rounds:
        s = by_tick.get(row.round + 1)  # sim state after r+1 ticks
        prev = by_tick.get(row.round)
        prev_kv = prev["kv_known"] if prev is not None else float(initial_kv)
        rows.append(
            {
                "round": row.round,
                "ts": row.ts,
                "rt_duration_s": row.duration_s,
                "rt_kv_sent": row.kv_sent,
                "rt_kv_applied": row.kv_applied,
                "rt_live": row.live,
                "rt_phi_max": row.phi_max,
                "rt_nodes": row.nodes,
                "sim_kv_moved": (
                    None if s is None else max(s["kv_known"] - prev_kv, 0.0)
                ),
                "sim_mean_fraction": None if s is None else s["mean_fraction"],
                "sim_version_spread": (
                    None if s is None else s["version_spread"]
                ),
                "sim_alive": None if s is None else s["alive_count"],
            }
        )
    return ReplayReport(
        trace=trace,
        cfg=cfg,
        seed=seed,
        sim_converged_round=converged,
        rows=rows,
        sim_series=series,
    )
