"""Calibration: fit the runtime↔sim residual as a transfer function.

The sim predicts *rounds*; an operator's SLO is written in *seconds*.
What connects them is measured, not assumed: ``fit_calibration`` takes a
replay report (twin/replay.py), fits the transfer on the FIRST half of
the trace, and validates it against the HELD-OUT second half — the
closed-loop differential gate (tests/test_twin.py, benchmarks/
twin_bench.py) pins that the prediction lands within the record's
stated tolerance before anyone tunes against it.

The fitted quantities:

- ``rounds_per_sec`` (± std over per-node rates) — wall-clock per
  gossip round, the r03-style "reference rounds/s" figure measured for
  THIS deployment rather than quoted from a bench table. Turns any
  sim rounds-to-X into a wall-clock prediction with error bars
  (``predict_wall_seconds``).
- ``kv_scale`` (± std) — runtime key-versions applied per sim
  key-version moved: the reconciliation-volume bias between the
  byte-exact packer and the sim's budget model.
- ``round_duration_s`` — mean measured per-round work time (the
  interval-independent floor a shorter gossip_interval would hit).

Records persist as versioned JSON (``CALIBRATION_SCHEMA``) and load
with the same loud schema refusal discipline as ``sim/checkpoint.py``:
a record written under a different vocabulary is refused by name, never
silently mis-fit.
"""

from __future__ import annotations

import json
import os
import statistics
import warnings
from dataclasses import asdict, dataclass, fields
from pathlib import Path

from .replay import ReplayReport

CALIBRATION_SCHEMA = "aiocluster-twin-calibration/1"


class CalibrationError(ValueError):
    """The replay report cannot support a fit (too short, rate-less)."""


class CalibrationSchemaError(ValueError):
    """A persisted record under an incompatible schema — refused loudly
    instead of mis-fit silently (the sim/checkpoint.py discipline)."""


@dataclass(frozen=True)
class CalibrationRecord:
    """One fitted transfer function with its held-out validation."""

    schema: str
    source: str  # trace path the fit came from
    n_nodes: int
    trace_rounds: int
    fit_rounds: int  # rounds the fit consumed (first window)
    holdout_rounds: int  # rounds the validation consumed (second window)
    rounds_per_sec: float
    rounds_per_sec_std: float
    round_duration_s: float
    kv_scale: float | None
    kv_scale_std: float | None
    sim_converged_round: int | None
    # Held-out validation: relative error of the transfer's predictions
    # over the second window, against the stated tolerance.
    holdout_wall_rel_err: float
    holdout_kv_rel_err: float | None
    tolerance: float
    holdout_ok: bool

    # -- prediction -----------------------------------------------------------

    def predict_wall_seconds(self, rounds: int) -> dict:
        """Wall-clock prediction for ``rounds`` gossip rounds, with the
        error bars the fitted rate spread implies (±2 std on the rate;
        the ``hi`` bound uses the slowest plausible rate)."""
        if rounds < 0:
            raise ValueError("rounds must be >= 0")
        rate = self.rounds_per_sec
        lo_rate = rate + 2 * self.rounds_per_sec_std
        hi_rate = max(rate - 2 * self.rounds_per_sec_std, rate * 0.1, 1e-9)
        return {
            "rounds": int(rounds),
            "seconds": rounds / rate,
            "lo": rounds / lo_rate,
            "hi": rounds / hi_rate,
        }

    # -- (de)serialization ----------------------------------------------------

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, raw: dict) -> "CalibrationRecord":
        schema = raw.get("schema")
        if schema != CALIBRATION_SCHEMA:
            raise CalibrationSchemaError(
                f"calibration schema {schema!r} is not the supported "
                f"{CALIBRATION_SCHEMA!r}; refusing to fit predictions "
                "from a record written under a different vocabulary"
            )
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(raw) - known)
        if unknown:
            # A NEWER same-major writer's additions cannot change the
            # meaning of the fields this build reads (that would bump
            # the schema); tolerate them like checkpoint configs do.
            warnings.warn(
                f"calibration record has unknown keys {unknown} "
                "(written by a newer version?); ignoring them",
                stacklevel=2,
            )
        missing = sorted(known - set(raw))
        if missing:
            raise CalibrationSchemaError(
                f"calibration record is missing required fields "
                f"{missing}; refusing a partial transfer function"
            )
        return cls(**{k: raw[k] for k in known})


def save_calibration(path: str | Path, record: CalibrationRecord) -> None:
    """Persist one record as JSON (atomic tmp + replace, like every
    other durable artifact in this repo)."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(record.to_dict(), indent=2) + "\n")
    os.replace(tmp, path)


def load_calibration(path: str | Path) -> CalibrationRecord:
    with open(path, encoding="utf-8") as fh:
        try:
            raw = json.load(fh)
        except json.JSONDecodeError as exc:
            raise CalibrationSchemaError(
                f"{path}: not a JSON calibration record: {exc}"
            ) from None
    if not isinstance(raw, dict):
        raise CalibrationSchemaError(f"{path}: calibration record must "
                                     "be a JSON object")
    return CalibrationRecord.from_dict(raw)


def fit_calibration(
    report: ReplayReport,
    *,
    holdout_frac: float = 0.5,
    tolerance: float = 0.35,
) -> CalibrationRecord:
    """Fit the transfer on the first ``1 - holdout_frac`` of the trace
    and validate it on the held-out rest (module docstring). Raises
    ``CalibrationError`` when the trace is too short to split."""
    rows = report.rows
    n = len(rows)
    if not 0.0 < holdout_frac < 1.0:
        raise ValueError("holdout_frac must be in (0, 1)")
    fit_end = int(n * (1.0 - holdout_frac))
    if fit_end < 2 or n - fit_end < 2:
        raise CalibrationError(
            f"trace has {n} aligned rounds; need at least 2 on each "
            f"side of the {holdout_frac:.0%} holdout split to fit and "
            "validate — record a longer run"
        )
    trace = report.trace

    # Wall-clock axis: per-node rates over the fit window only.
    rate, rate_std = trace.rounds_per_sec(0, fit_end)
    round_duration = statistics.fmean(r["rt_duration_s"] for r in rows[:fit_end])

    # Volume axis: fleet totals over the fit window (per-round ratios
    # are 0/0 for most quiescent rounds; window totals are the stable
    # estimator, per-round ratios give the spread where defined).
    fit_rt_kv = sum(r["rt_kv_applied"] for r in rows[:fit_end])
    fit_sim_kv = sum(
        r["sim_kv_moved"] for r in rows[:fit_end]
        if r["sim_kv_moved"] is not None
    )
    kv_scale = kv_scale_std = None
    if fit_sim_kv > 0:
        kv_scale = fit_rt_kv / fit_sim_kv
        ratios = [
            r["rt_kv_applied"] / r["sim_kv_moved"]
            for r in rows[:fit_end]
            if r["sim_kv_moved"]
        ]
        kv_scale_std = (
            statistics.pstdev(ratios) if len(ratios) > 1 else 0.0
        )

    # Held-out validation. Wall-clock: the measured span of the holdout
    # rounds vs the fitted rate's prediction for the same round count.
    holdout_rounds = n - fit_end
    actual_span = rows[-1]["ts"] - rows[fit_end - 1]["ts"]
    predicted_span = holdout_rounds / rate
    if actual_span <= 0:
        raise CalibrationError(
            "holdout window spans no wall-clock time (timestamps not "
            "monotone?) — cannot validate the rate fit"
        )
    wall_rel_err = abs(predicted_span - actual_span) / actual_span
    # Volume: predicted vs measured holdout totals. Both sides go
    # quiescent after convergence, so the denominator is floored at one
    # fleet's worth of keys — a 0-vs-0 holdout validates at 0 error
    # instead of dividing by zero.
    kv_rel_err = None
    if kv_scale is not None:
        hold_rt_kv = sum(r["rt_kv_applied"] for r in rows[fit_end:])
        hold_sim_kv = sum(
            r["sim_kv_moved"] for r in rows[fit_end:]
            if r["sim_kv_moved"] is not None
        )
        floor = max(trace.n_nodes, 1)
        kv_rel_err = abs(kv_scale * hold_sim_kv - hold_rt_kv) / max(
            hold_rt_kv, floor
        )

    return CalibrationRecord(
        schema=CALIBRATION_SCHEMA,
        source=trace.path,
        n_nodes=trace.n_nodes,
        trace_rounds=n,
        fit_rounds=fit_end,
        holdout_rounds=holdout_rounds,
        rounds_per_sec=rate,
        rounds_per_sec_std=rate_std,
        round_duration_s=round_duration,
        kv_scale=kv_scale,
        kv_scale_std=kv_scale_std,
        sim_converged_round=report.sim_converged_round,
        holdout_wall_rel_err=wall_rel_err,
        holdout_kv_rel_err=kv_rel_err,
        tolerance=tolerance,
        holdout_ok=wall_rel_err <= tolerance,
    )
