"""Minimal proto3 wire codec for the gossip protocol.

Hand-written, dependency-free encoder/decoder producing bytes **identical**
to the reference's generated protobuf stubs for the schema in
messages.proto:1-74 (same field numbers, same proto3 emission rules:
zero-valued scalars omitted, message fields emitted when present, the
``optional`` max_version field emitted whenever set). Byte-for-byte
compatibility means a node of this framework can gossip with a node running
the reference library.

Only the two wire types the schema needs are implemented: varint (0) and
length-delimited (2). Unknown fields are skipped on decode, so schema
evolution by either side does not break the handshake.
"""

from __future__ import annotations

import functools

from . import native as _native
from ..core.identity import NodeId
from ..core.messages import (
    Ack,
    BadCluster,
    Delta,
    Digest,
    KeyValueUpdate,
    Leave,
    NodeDelta,
    NodeDigest,
    Packet,
    Syn,
    SynAck,
    TraceContext,
)
from ..core.values import VersionStatusEnum

__all__ = (
    "WireError",
    "decode_packet",
    "encode_packet",
    "encode_digest",
    "decode_digest",
    "encode_delta",
    "decode_delta",
    "encode_trace_context",
    "varint_size",
)

_VARINT = 0
_LEN = 2

# Plain process-wide encode-call accounting (cheap int bumps, exported
# nowhere by default): every key-value BODY encode — whether for real
# emission, for a size walk (wire/sizes.py prices by encoding), or for
# a segment-cache miss (wire/segments.py) — counts here, so the
# handshake benchmark can measure the encode-per-peer-per-round
# collapse the segment cache buys as a hard number instead of a claim.
ENCODE_STATS = {"kv_encodes": 0}


class WireError(ValueError):
    """Malformed or unsupported wire data."""


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def varint_size(value: int) -> int:
    """Encoded size in bytes of an unsigned varint."""
    if value < 0:
        raise WireError(f"negative varint: {value}")
    size = 1
    while value >= 0x80:
        value >>= 7
        size += 1
    return size


def _uvarint(value: int) -> bytes:
    if value < 0:
        raise WireError(f"negative varint: {value}")
    out = bytearray()
    while value >= 0x80:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def _field_varint(out: bytearray, field: int, value: int) -> None:
    """Emit a varint field, skipping proto3 default zero."""
    if value == 0:
        return
    out.append(field << 3 | _VARINT)
    out += _uvarint(value)


def _field_varint_present(out: bytearray, field: int, value: int) -> None:
    """Emit a varint field unconditionally (explicit-presence fields)."""
    out.append(field << 3 | _VARINT)
    out += _uvarint(value)


def _field_str(out: bytearray, field: int, value: str) -> None:
    if not value:
        return
    raw = value.encode("utf-8")
    out.append(field << 3 | _LEN)
    out += _uvarint(len(raw))
    out += raw


def _field_msg(out: bytearray, field: int, body: bytes) -> None:
    """Emit a submessage field (always, matching set-message presence)."""
    out.append(field << 3 | _LEN)
    out += _uvarint(len(body))
    out += body


class _Reader:
    """Streaming field reader over ``bytes`` OR a read-only
    ``memoryview`` (the zero-copy read path: ``chunk()`` on a
    memoryview yields sub-views, so nested submessages decode without
    intermediate slice copies; anything that must outlive the frame —
    strings, cache keys — materializes at the leaf)."""

    __slots__ = ("buf", "pos", "end")

    def __init__(
        self,
        buf: bytes | memoryview,
        start: int = 0,
        end: int | None = None,
    ) -> None:
        self.buf = buf
        self.pos = start
        self.end = len(buf) if end is None else end

    def at_end(self) -> bool:
        return self.pos >= self.end

    def varint(self) -> int:
        result = 0
        shift = 0
        while True:
            if self.pos >= self.end:
                raise WireError("truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                # Truncate to u64 like protobuf (and the native decoder):
                # a 10-byte varint's final byte may set bits above 63.
                return result & 0xFFFFFFFFFFFFFFFF
            shift += 7
            if shift > 63:
                raise WireError("varint too long")

    def chunk(self) -> bytes:
        n = self.varint()
        if self.pos + n > self.end:
            raise WireError("truncated length-delimited field")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def field(self) -> tuple[int, int]:
        tag = self.varint()
        return tag >> 3, tag & 0x7

    def skip(self, wire_type: int) -> None:
        if wire_type == _VARINT:
            self.varint()
        elif wire_type == _LEN:
            self.chunk()
        elif wire_type == 5:  # fixed32
            self.pos += 4
        elif wire_type == 1:  # fixed64
            self.pos += 8
        else:
            raise WireError(f"unsupported wire type {wire_type}")
        if self.pos > self.end:
            raise WireError("truncated field")


def _utf8(raw: bytes | memoryview) -> str:
    try:
        if type(raw) is bytes:
            return raw.decode("utf-8")
        # memoryview span: str() decodes straight off the buffer — the
        # leaf materialization of the zero-copy read path.
        return str(raw, "utf-8")
    except UnicodeDecodeError as exc:
        raise WireError(f"invalid utf-8 string field: {exc}") from exc


# ---------------------------------------------------------------------------
# Message bodies (field numbers per reference messages.proto:28-74)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=65536)
def encode_node_id(node: NodeId) -> bytes:
    """NodeId is a frozen, hashable dataclass and its encoding is pure,
    so the bytes are memoized: every digest and delta a node sends
    re-serializes the same ~N node ids each round (the asyncio
    backend's profiled hot path — the cache turns that into dict
    hits). The cap must sit ABOVE any plausible cluster population: a
    per-round sequential sweep over more ids than the cap is the
    classic LRU-thrash pattern (every call misses AND pays an
    eviction). 65,536 entries ≈ a few MB; beyond that the cache
    degrades to the uncached cost plus a dict probe, never worse."""
    addr = bytearray()
    host, port = node.gossip_advertise_addr
    _field_str(addr, 1, host)
    _field_varint(addr, 2, port)

    out = bytearray()
    _field_str(out, 1, node.name)
    _field_varint(out, 2, node.generation_id)
    _field_msg(out, 3, bytes(addr))
    _field_str(out, 4, node.tls_name or "")
    return bytes(out)


# Only small bodies are cache-eligible: the decode cache is keyed on
# PEER-CONTROLLED bytes (the codec interoperates with untrusted
# reference nodes), and unknown fields mean infinitely many distinct
# encodings can map to one NodeId. Honest node-id submessages are tens
# of bytes; the bound caps worst-case pinned memory at
# 65,536 x ~(256 + object) ≈ tens of MB, and junk traffic can at worst
# evict entries — degrading to the uncached baseline, never beyond.
_NODE_ID_CACHE_MAX_BODY = 256


def decode_node_id(body: bytes) -> NodeId:
    """Memoized for small bodies (see _NODE_ID_CACHE_MAX_BODY): the
    same node-id byte strings arrive in every digest/delta from every
    peer, every round; NodeId is immutable so sharing one object per
    distinct encoding is safe (and makes snapshot dict lookups cheaper
    via pointer-equal keys)."""
    if len(body) <= _NODE_ID_CACHE_MAX_BODY:
        return _decode_node_id_cached(bytes(body))  # noqa: ACT042 -- bounded (<=256B) cache-key materialization; a view key would pin the frame
    return _decode_node_id(body)


@functools.lru_cache(maxsize=65536)
def _decode_node_id_cached(body: bytes) -> NodeId:
    return _decode_node_id(body)


def _decode_node_id(body: bytes) -> NodeId:
    r = _Reader(body)
    name = ""
    generation_id = 0
    host, port = "", 0
    tls_name: str | None = None
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            name = _utf8(r.chunk())
        elif field == 2 and wt == _VARINT:
            generation_id = r.varint()
        elif field == 3 and wt == _LEN:
            ar = _Reader(r.chunk())
            while not ar.at_end():
                af, awt = ar.field()
                if af == 1 and awt == _LEN:
                    host = _utf8(ar.chunk())
                elif af == 2 and awt == _VARINT:
                    port = ar.varint()
                else:
                    ar.skip(awt)
        elif field == 4 and wt == _LEN:
            tls_name = _utf8(r.chunk()) or None
        else:
            r.skip(wt)
    return NodeId(name, generation_id, (host, port), tls_name)


def encode_node_digest(nd: NodeDigest) -> bytes:
    out = bytearray()
    _field_msg(out, 1, encode_node_id(nd.node_id))
    _field_varint(out, 2, nd.heartbeat)
    _field_varint(out, 3, nd.last_gc_version)
    _field_varint(out, 4, nd.max_version)
    return bytes(out)


@functools.lru_cache(maxsize=65536)
def _encode_digest_entry(nd: NodeDigest) -> bytes:
    """One complete digest entry — field-1 tag + length + NodeDigest body
    — memoized on the (frozen, hashable) NodeDigest. ClusterState's
    incremental digest cache hands back the same per-node entries until
    a node's heartbeat/version moves, so a population-sized digest
    encode is ~all dict hits with one real encode per changed node.
    Changed entries churn through the LRU (heartbeats are monotonic),
    but the stable majority stays hot; eviction degrades to the uncached
    cost, never beyond. Byte-identical to the encode_node_digest framing
    (differential-tested)."""
    nid = encode_node_id(nd.node_id)  # memoized bytes
    hb, lgc, mv = nd.heartbeat, nd.last_gc_version, nd.max_version
    body_len = 1 + varint_size(len(nid)) + len(nid)
    if hb:
        body_len += 1 + varint_size(hb)
    if lgc:
        body_len += 1 + varint_size(lgc)
    if mv:
        body_len += 1 + varint_size(mv)
    out = bytearray()
    out.append(1 << 3 | _LEN)
    out += _uvarint(body_len)
    _field_msg(out, 1, nid)
    _field_varint(out, 2, hb)
    _field_varint(out, 3, lgc)
    _field_varint(out, 4, mv)
    return bytes(out)


def decode_node_digest(body: bytes) -> NodeDigest:
    r = _Reader(body)
    node_id = NodeId("", 0, ("", 0))
    heartbeat = last_gc = max_version = 0
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            node_id = decode_node_id(r.chunk())
        elif field == 2 and wt == _VARINT:
            heartbeat = r.varint()
        elif field == 3 and wt == _VARINT:
            last_gc = r.varint()
        elif field == 4 and wt == _VARINT:
            max_version = r.varint()
        else:
            r.skip(wt)
    return NodeDigest(node_id, heartbeat, last_gc, max_version)


def encode_kv_body(key: str, value: str, version: int, status: int) -> bytes:
    """The KeyValueUpdatePb submessage body from bare fields — THE one
    kv encoder: ``encode_kv_update`` (the DTO oracle) and the segment
    cache (wire/segments.py) both delegate here, so the two can never
    drift byte-wise. Every call is one real encode (ENCODE_STATS)."""
    ENCODE_STATS["kv_encodes"] += 1
    out = bytearray()
    _field_str(out, 1, key)
    _field_str(out, 2, value)
    _field_varint(out, 3, version)
    _field_varint(out, 4, status)
    return bytes(out)


def encode_kv_update(kv: KeyValueUpdate) -> bytes:
    return encode_kv_body(kv.key, kv.value, kv.version, int(kv.status))


def decode_kv_update(body: bytes) -> KeyValueUpdate:
    r = _Reader(body)
    key = value = ""
    version = 0
    status = 0
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            key = _utf8(r.chunk())
        elif field == 2 and wt == _LEN:
            value = _utf8(r.chunk())
        elif field == 3 and wt == _VARINT:
            version = r.varint()
        elif field == 4 and wt == _VARINT:
            status = r.varint()
        else:
            r.skip(wt)
    try:
        st = VersionStatusEnum(status)
    except ValueError as exc:
        raise WireError(f"unknown version status {status}") from exc
    return KeyValueUpdate(key, value, version, st)


def encode_node_delta(nd: NodeDelta) -> bytes:
    out = bytearray()
    _field_msg(out, 1, encode_node_id(nd.node_id))
    _field_varint(out, 2, nd.from_version_excluded)
    _field_varint(out, 3, nd.last_gc_version)
    bulk = (
        _native.encode_kv_updates(nd.key_values)
        if len(nd.key_values) >= _native.NATIVE_THRESHOLD
        else None
    )
    if bulk is not None:
        # The C side encoded one body per kv: same accounting currency
        # as encode_kv_body, so the bench's encode-call collapse figure
        # is honest whichever path engaged.
        ENCODE_STATS["kv_encodes"] += len(nd.key_values)
        out += bulk
    else:
        for kv in nd.key_values:
            _field_msg(out, 4, encode_kv_update(kv))
    if nd.max_version is not None:
        _field_varint_present(out, 5, nd.max_version)
    return bytes(out)


def decode_node_delta(body: bytes) -> NodeDelta:
    # Large bodies (MTU-full deltas, ~2000 kvs at 64KB) take the native
    # bulk parser; output is identical to the Python loop below. The
    # native side needs contiguous bytes (ctypes c_char_p) — the ONE
    # materialization of a memoryview-span delta, after which every kv
    # string decodes from it directly.
    if len(body) >= 512:
        if type(body) is not bytes:
            body = bytes(body)  # noqa: ACT042 -- the ONE materialization of a memoryview delta: ctypes c_char_p needs contiguous bytes
        try:
            parsed = _native.decode_node_delta_raw(body)
        except _native.NativeDecodeError as exc:
            raise WireError(str(exc)) from exc
        except UnicodeDecodeError as exc:
            raise WireError(f"invalid utf-8 string field: {exc}") from exc
        if parsed is not None:
            (fve, lgc, maxv, has_max), node_id_bytes, raw_kvs = parsed
            node_id = (
                decode_node_id(node_id_bytes)
                if node_id_bytes is not None
                else NodeId("", 0, ("", 0))
            )
            kvs = []
            for key, value, version, status in raw_kvs:
                try:
                    st = VersionStatusEnum(status)
                except ValueError as exc:
                    raise WireError(f"unknown version status {status}") from exc
                kvs.append(KeyValueUpdate(key, value, version, st))
            return NodeDelta(
                node_id, fve, lgc, kvs, maxv if has_max else None
            )
    r = _Reader(body)
    node_id = NodeId("", 0, ("", 0))
    fve = lgc = 0
    kvs: list[KeyValueUpdate] = []
    max_version: int | None = None
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            node_id = decode_node_id(r.chunk())
        elif field == 2 and wt == _VARINT:
            fve = r.varint()
        elif field == 3 and wt == _VARINT:
            lgc = r.varint()
        elif field == 4 and wt == _LEN:
            kvs.append(decode_kv_update(r.chunk()))
        elif field == 5 and wt == _VARINT:
            max_version = r.varint()
        else:
            r.skip(wt)
    return NodeDelta(node_id, fve, lgc, kvs, max_version)


def encode_digest(digest: Digest) -> bytes:
    """Hot path: one memoized entry-bytes lookup per node (see
    _encode_digest_entry) concatenated into one buffer. Emission is
    byte-identical to _field_msg(out, 1, encode_node_digest(nd)), which
    remains the single-entry oracle (differential-tested)."""
    out = bytearray()
    for nd in digest.node_digests.values():
        out += _encode_digest_entry(nd)
    return bytes(out)


# Shared default for digest entries that carry no node_id submessage
# (degenerate but legal); NodeId is frozen, so one instance is safe.
_EMPTY_NODE_ID = NodeId("", 0, ("", 0))

# Only small entry bodies are cache-eligible — the same reasoning (and
# the same bound) as _NODE_ID_CACHE_MAX_BODY: the key is PEER-CONTROLLED
# bytes, honest entries are tens of bytes, and junk can at worst evict
# down to the uncached baseline.
_DIGEST_ENTRY_CACHE_MAX_BODY = 256


@functools.lru_cache(maxsize=65536)
def _decode_digest_entry_cached(body: bytes) -> NodeDigest:
    """Memoized single-entry decode: a peer's digest entry for a node
    repeats byte-for-byte every handshake until that node's heartbeat or
    versions move, so steady-state digest decodes are ~all dict hits.
    NodeDigest is frozen; sharing one object per distinct encoding is
    safe. Mirrors decode_node_digest exactly (the oracle)."""
    r = _Reader(body)
    node_id = _EMPTY_NODE_ID
    heartbeat = last_gc = max_version = 0
    while not r.at_end():
        ef, ewt = r.field()
        if ef == 1 and ewt == _LEN:
            node_id = decode_node_id(r.chunk())
        elif ef == 2 and ewt == _VARINT:
            heartbeat = r.varint()
        elif ef == 3 and ewt == _VARINT:
            last_gc = r.varint()
        elif ef == 4 and ewt == _VARINT:
            max_version = r.varint()
        else:
            r.skip(ewt)
    return NodeDigest(node_id, heartbeat, last_gc, max_version)


def decode_digest(body: bytes | memoryview) -> Digest:
    """Hot path: every handshake carries one or two digests with an
    entry per known node. Small entries (every honest one) go through
    the memoized single-entry decode above — one bytes-slice + dict hit
    per unchanged entry; oversized entries are parsed in a WINDOW of
    the one top-level reader. Both mirror decode_node_digest exactly
    (same varint semantics, same WireError cases; decode_node_digest
    remains the single-entry API and the differential-test oracle).

    The entry loop is hand-flattened: an honest digest is a run of
    ``0x0a <len> <body>`` entries with single-byte tags and (for
    entries under 128 bytes — all of them) single-byte lengths, so the
    population-sized per-handshake decode costs one byte compare, one
    slice and one dict probe per entry instead of a reader-object
    varint walk. Anything else — multi-byte lengths, foreign fields,
    non-minimal tag encodings — falls back to the generic _Reader
    path with identical semantics."""
    digests: dict[NodeId, NodeDigest] = {}
    buf = body
    pos = 0
    end = len(body)
    while pos < end:
        if buf[pos] == 0x0A:  # field 1, LEN — minimally encoded
            pos += 1
            if pos >= end:
                raise WireError("truncated varint")
            n = buf[pos]
            pos += 1
            if n >= 0x80:
                # Multi-byte length varint (entries over 127 bytes).
                n &= 0x7F
                shift = 7
                while True:
                    if pos >= end:
                        raise WireError("truncated varint")
                    b = buf[pos]
                    pos += 1
                    n |= (b & 0x7F) << shift
                    if not b & 0x80:
                        n &= 0xFFFFFFFFFFFFFFFF
                        break
                    shift += 7
                    if shift > 63:
                        raise WireError("varint too long")
            entry_end = pos + n
            if entry_end > end:
                raise WireError("truncated length-delimited field")
            nd = _decode_digest_entry_at(buf, pos, entry_end, n)
            digests[nd.node_id] = nd
            pos = entry_end
        else:
            # Generic arm: multi-byte/non-minimal tags, unknown fields.
            r = _Reader(buf, pos, end)
            field, wt = r.field()
            if field == 1 and wt == _LEN:
                n = r.varint()
                entry_end = r.pos + n
                if entry_end > end:
                    raise WireError("truncated length-delimited field")
                nd = _decode_digest_entry_at(buf, r.pos, entry_end, n)
                digests[nd.node_id] = nd
                pos = entry_end
            else:
                r.skip(wt)
                pos = r.pos
    return Digest(digests)


def _decode_digest_entry_at(
    buf: bytes | memoryview, start: int, end: int, n: int
) -> NodeDigest:
    """THE entry dispatch both decode_digest arms share: cache-eligible
    bodies go through the memoized decode (bytes() is the cache-key
    materialization — a no-op on a bytes buffer; a memoryview key would
    pin the whole frame), oversized ones parse in a window."""
    if n <= _DIGEST_ENTRY_CACHE_MAX_BODY:
        return _decode_digest_entry_cached(bytes(buf[start:end]))  # noqa: ACT042 -- bounded (<=256B) cache-key materialization; a view key would pin the frame
    return _decode_digest_entry_window(buf, start, end)


def _decode_digest_entry_window(
    buf: bytes | memoryview, start: int, end: int
) -> NodeDigest:
    """Oversized (cache-ineligible) digest entry, parsed in a window of
    the shared buffer — mirrors decode_node_digest exactly."""
    r = _Reader(buf, start, end)
    node_id = _EMPTY_NODE_ID
    heartbeat = last_gc = max_version = 0
    while r.pos < end:
        ef, ewt = r.field()
        if ef == 1 and ewt == _LEN:
            node_id = decode_node_id(r.chunk())
        elif ef == 2 and ewt == _VARINT:
            heartbeat = r.varint()
        elif ef == 3 and ewt == _VARINT:
            last_gc = r.varint()
        elif ef == 4 and ewt == _VARINT:
            max_version = r.varint()
        else:
            r.skip(ewt)
    return NodeDigest(node_id, heartbeat, last_gc, max_version)


def encode_delta(delta: Delta) -> bytes:
    out = bytearray()
    for nd in delta.node_deltas:
        _field_msg(out, 1, encode_node_delta(nd))
    return bytes(out)


def decode_delta(body: bytes) -> Delta:
    r = _Reader(body)
    nds: list[NodeDelta] = []
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            nds.append(decode_node_delta(r.chunk()))
        else:
            r.skip(wt)
    return Delta(nds)


# ---------------------------------------------------------------------------
# Handshake envelope (field numbers per messages.proto:3-26)
# ---------------------------------------------------------------------------


def encode_packet(packet: Packet) -> bytes:
    out = bytearray()
    _field_str(out, 1, packet.cluster_id)
    msg = packet.msg
    if isinstance(msg, Syn):
        body = bytearray()
        _field_msg(body, 2, encode_digest(msg.digest))
        _field_msg(out, 2, bytes(body))
    elif isinstance(msg, SynAck):
        body = bytearray()
        _field_msg(body, 2, encode_digest(msg.digest))
        _field_msg(body, 3, encode_delta(msg.delta))
        _field_msg(out, 3, bytes(body))
    elif isinstance(msg, Ack):
        body = bytearray()
        _field_msg(body, 3, encode_delta(msg.delta))
        _field_msg(out, 4, bytes(body))
    elif isinstance(msg, BadCluster):
        _field_msg(out, 5, b"")
    elif isinstance(msg, Leave):
        # New beyond the reference schema (field 6, skipped by its
        # decoders): graceful-departure announcement + final flush.
        body = bytearray()
        _field_msg(body, 1, encode_node_id(msg.node_id))
        _field_msg(body, 2, encode_delta(msg.delta))
        _field_str(body, 3, msg.reason)
        _field_varint(body, 4, msg.heartbeat)
        _field_msg(out, 6, bytes(body))
    else:  # pragma: no cover - exhaustiveness guard
        raise WireError(f"unknown packet message type: {type(msg)!r}")
    if packet.trace is not None:
        # New beyond the reference schema (field 7, skipped by its
        # decoders): span context — sender name + handshake id.
        out += encode_trace_context(packet.trace)
    return bytes(out)


def encode_trace_context(trace: TraceContext) -> bytes:
    """The complete envelope field 7 (tag + length + body) for a span
    context — standalone so the zero-copy parts path can APPEND it as a
    trailing buffer after the cached Syn/SynAck/Ack parts (proto3 field
    order is insignificant on decode; the per-digest-epoch caches never
    see the per-handshake bytes)."""
    body = bytearray()
    _field_str(body, 1, trace.node)
    _field_varint(body, 2, trace.handshake_id)
    out = bytearray()
    _field_msg(out, 7, bytes(body))
    return bytes(out)


def _decode_trace_context(body: bytes) -> TraceContext:
    r = _Reader(body)
    node = ""
    handshake_id = 0
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            node = _utf8(r.chunk())
        elif field == 2 and wt == _VARINT:
            handshake_id = r.varint()
        else:
            r.skip(wt)
    return TraceContext(node, handshake_id)


def _decode_syn(body: bytes) -> Syn:
    r = _Reader(body)
    digest = Digest()
    while not r.at_end():
        field, wt = r.field()
        if field == 2 and wt == _LEN:
            digest = decode_digest(r.chunk())
        else:
            r.skip(wt)
    return Syn(digest)


def _decode_synack(body: bytes) -> SynAck:
    r = _Reader(body)
    digest = Digest()
    delta = Delta()
    while not r.at_end():
        field, wt = r.field()
        if field == 2 and wt == _LEN:
            digest = decode_digest(r.chunk())
        elif field == 3 and wt == _LEN:
            delta = decode_delta(r.chunk())
        else:
            r.skip(wt)
    return SynAck(digest, delta)


def _decode_leave(body: bytes) -> Leave:
    r = _Reader(body)
    node_id = _EMPTY_NODE_ID
    delta = Delta()
    reason = "leave"
    heartbeat = 0
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            node_id = decode_node_id(r.chunk())
        elif field == 2 and wt == _LEN:
            delta = decode_delta(r.chunk())
        elif field == 3 and wt == _LEN:
            reason = _utf8(r.chunk()) or "leave"
        elif field == 4 and wt == _VARINT:
            heartbeat = r.varint()
        else:
            r.skip(wt)
    return Leave(node_id, delta, reason, heartbeat)


def _decode_ack(body: bytes) -> Ack:
    r = _Reader(body)
    delta = Delta()
    while not r.at_end():
        field, wt = r.field()
        if field == 3 and wt == _LEN:
            delta = decode_delta(r.chunk())
        else:
            r.skip(wt)
    return Ack(delta)


def decode_packet(data: bytes | memoryview) -> Packet:
    r = _Reader(data)
    cluster_id = ""
    msg: Syn | SynAck | Ack | BadCluster | Leave | None = None
    trace: TraceContext | None = None
    while not r.at_end():
        field, wt = r.field()
        if field == 1 and wt == _LEN:
            cluster_id = _utf8(r.chunk())
        elif field == 2 and wt == _LEN:
            msg = _decode_syn(r.chunk())
        elif field == 3 and wt == _LEN:
            msg = _decode_synack(r.chunk())
        elif field == 4 and wt == _LEN:
            msg = _decode_ack(r.chunk())
        elif field == 5 and wt == _LEN:
            r.chunk()
            msg = BadCluster()
        elif field == 6 and wt == _LEN:
            msg = _decode_leave(r.chunk())
        elif field == 7 and wt == _LEN:
            trace = _decode_trace_context(r.chunk())
        else:
            r.skip(wt)
    if msg is None:
        raise WireError("packet carries no handshake message")
    return Packet(cluster_id, msg, trace)
