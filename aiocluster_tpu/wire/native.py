"""ctypes loader + wrappers for the native bulk wire codec.

Compiles ``_native.cpp`` with g++ on first use (cached beside a content
hash under ``~/.cache/aiocluster_tpu``), loads it via ctypes, and exposes
bulk encode/decode for the repeated-kv hot path of NodeDeltaPb. When the
toolchain or binary is unavailable — or ``AIOCLUSTER_TPU_NO_NATIVE`` is
set — everything degrades to the pure-Python codec in proto.py.

The native path only engages for deltas with >= ``NATIVE_THRESHOLD`` kv
updates; below that, ctypes marshaling costs more than it saves.
"""

from __future__ import annotations

import ctypes
import os
import threading
from pathlib import Path

from ..utils.cbuild import build_and_load

_SRC = Path(__file__).with_name("_native.cpp")
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _lib() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("AIOCLUSTER_TPU_NO_NATIVE"):
        return None
    lib = build_and_load(_SRC)  # shared cache policy (utils/cbuild.py)
    if lib is not None:
        lib.acg_enc_kv_updates.restype = ctypes.c_long
        lib.acg_enc_kv_updates.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
            ctypes.c_long, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.acg_dec_node_delta.restype = ctypes.c_long
        lib.acg_dec_node_delta.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_long,
        ]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _lib() is not None


def warmup() -> bool:
    """Compile/load the native codec now. The first build shells out to
    g++ (seconds); call this off the event loop (Cluster.start does, via a
    thread) so the first MTU-full delta never stalls the gossip loop."""
    return available()


NATIVE_THRESHOLD = 16  # kv updates; below this ctypes overhead dominates


def encode_kv_updates(kvs) -> bytes | None:
    """Bulk-encode the repeated field-4 kv updates of a NodeDelta.
    Returns None when the native path is unavailable (caller falls back)."""
    lib = _lib()
    if lib is None:
        return None
    n = len(kvs)
    keys_b = [kv.key.encode("utf-8") for kv in kvs]
    vals_b = [kv.value.encode("utf-8") for kv in kvs]
    koff = (ctypes.c_long * (n + 1))()
    voff = (ctypes.c_long * (n + 1))()
    for i in range(n):
        koff[i + 1] = koff[i] + len(keys_b[i])
        voff[i + 1] = voff[i] + len(vals_b[i])
    keys = b"".join(keys_b)
    vals = b"".join(vals_b)
    versions = (ctypes.c_longlong * n)(*(kv.version for kv in kvs))
    statuses = (ctypes.c_int * n)(*(int(kv.status) for kv in kvs))
    # Worst case per kv: 2 tag+len headers (<=11B each) + payloads + 2
    # varint fields (<=11B each); 44 covers all header bytes.
    cap = koff[n] + voff[n] + 44 * n + 16
    out = ctypes.create_string_buffer(cap)
    written = lib.acg_enc_kv_updates(
        keys, koff, vals, voff, versions, statuses, n, out, cap
    )
    if written < 0:  # pragma: no cover - cap math guarantees fit
        return None
    return out.raw[:written]


class NativeDecodeError(ValueError):
    pass


_U64 = (1 << 64) - 1


class _Scratch(threading.local):
    """Grow-only per-thread decode buffers: a 64KB MTU delta would
    otherwise allocate ~1.4MB of zeroed ctypes arrays per handshake."""

    def __init__(self) -> None:
        self.cap = 0

    def ensure(self, max_kvs: int):
        if max_kvs > self.cap:
            self.cap = max(max_kvs, 2 * self.cap)
            self.kv_spans = (ctypes.c_long * (4 * self.cap))()
            self.versions = (ctypes.c_longlong * self.cap)()
            self.statuses = (ctypes.c_longlong * self.cap)()
        return self.kv_spans, self.versions, self.statuses


_scratch = _Scratch()


def decode_node_delta_raw(body: bytes):
    """Parse a NodeDelta body natively.

    Returns (scalars, node_id_bytes | None, kv_tuples) where kv_tuples is
    a list of (key, value, version, status_int); or None when the native
    path is unavailable. Raises NativeDecodeError on malformed input
    (the caller maps it to WireError).
    """
    lib = _lib()
    if lib is None:
        return None
    blen = len(body)
    # Every kv costs >= 2 bytes on the wire; +1 guards the empty body.
    max_kvs = blen // 2 + 1
    scalars = (ctypes.c_longlong * 4)()
    node_span = (ctypes.c_long * 2)()
    kv_spans, versions, statuses = _scratch.ensure(max_kvs)
    nkv = lib.acg_dec_node_delta(
        body, blen, scalars, node_span, kv_spans, versions, statuses, max_kvs
    )
    if nkv == -3:
        raise NativeDecodeError("unsupported wire type")
    if nkv < 0:
        raise NativeDecodeError("truncated or malformed NodeDelta")
    kvs = []
    for i in range(nkv):
        ko, kl, vo, vl = kv_spans[4 * i : 4 * i + 4]
        key = body[ko : ko + kl].decode("utf-8") if ko >= 0 else ""
        value = body[vo : vo + vl].decode("utf-8") if vo >= 0 else ""
        # The C side carries u64 varints as int64 bit patterns; mask back
        # to the unsigned values the pure-Python decoder produces.
        kvs.append((key, value, versions[i] & _U64, statuses[i] & _U64))
    node_id_bytes = (
        body[node_span[0] : node_span[1]] if node_span[0] >= 0 else None
    )
    return (
        (scalars[0] & _U64, scalars[1] & _U64, scalars[2] & _U64,
         bool(scalars[3])),
        node_id_bytes,
        kvs,
    )
