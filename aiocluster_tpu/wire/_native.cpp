// Native bulk codec for the gossip wire format's hot path.
//
// The asyncio backend's per-handshake cost is dominated by the repeated
// KeyValueUpdatePb loop of NodeDeltaPb (reference messages.proto:55-66):
// a full 64KB MTU delta carries ~2000 kv updates. These two functions
// move that loop into C++ — encoding from flat offset arrays and
// decoding into span/scalar arrays — with byte-identical output to
// wire/proto.py's pure-Python implementation (same proto3 emission
// rules; parity-tested in tests/test_wire_native.py).
//
// Plain C ABI, loaded via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>

namespace {

inline long uvarint_size(unsigned long long v) {
  long n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

inline long put_uvarint(unsigned char* out, unsigned long long v) {
  long n = 0;
  while (v >= 0x80) {
    out[n++] = static_cast<unsigned char>(v) | 0x80;
    v >>= 7;
  }
  out[n++] = static_cast<unsigned char>(v);
  return n;
}

// Full-range u64 varint read via out-param (values with bit 63 set are
// legitimate); returns false on truncation/overlong. Advances *pos.
inline bool get_uvarint(const unsigned char* buf, long len, long* pos,
                        unsigned long long* out) {
  unsigned long long result = 0;
  int shift = 0;
  while (*pos < len) {
    unsigned char b = buf[(*pos)++];
    result |= static_cast<unsigned long long>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

}  // namespace

extern "C" {

// Encode n KeyValueUpdate submessages (field 4 of NodeDeltaPb) into out.
// keys/vals are concatenated UTF-8 with (n+1)-element byte-offset arrays.
// Emission matches proto3 rules: empty strings / zero varints omitted,
// field order key(1), value(2), version(3), status(4).
// Returns bytes written, or -1 if cap is too small.
long acg_enc_kv_updates(const unsigned char* keys, const long* koff,
                        const unsigned char* vals, const long* voff,
                        const long long* versions, const int* statuses,
                        long n, unsigned char* out, long cap) {
  long w = 0;
  for (long i = 0; i < n; ++i) {
    long klen = koff[i + 1] - koff[i];
    long vlen = voff[i + 1] - voff[i];
    unsigned long long ver = static_cast<unsigned long long>(versions[i]);
    unsigned long long st = static_cast<unsigned long long>(statuses[i]);

    long body = 0;
    if (klen > 0) body += 1 + uvarint_size(klen) + klen;
    if (vlen > 0) body += 1 + uvarint_size(vlen) + vlen;
    if (ver) body += 1 + uvarint_size(ver);
    if (st) body += 1 + uvarint_size(st);

    long need = 1 + uvarint_size(body) + body;
    if (w + need > cap) return -1;

    out[w++] = (4 << 3) | 2;  // NodeDeltaPb.key_values, length-delimited
    w += put_uvarint(out + w, body);
    if (klen > 0) {
      out[w++] = (1 << 3) | 2;
      w += put_uvarint(out + w, klen);
      std::memcpy(out + w, keys + koff[i], klen);
      w += klen;
    }
    if (vlen > 0) {
      out[w++] = (2 << 3) | 2;
      w += put_uvarint(out + w, vlen);
      std::memcpy(out + w, vals + voff[i], vlen);
      w += vlen;
    }
    if (ver) {
      out[w++] = (3 << 3) | 0;
      w += put_uvarint(out + w, ver);
    }
    if (st) {
      out[w++] = (4 << 3) | 0;
      w += put_uvarint(out + w, st);
    }
  }
  return w;
}

// Parse a whole NodeDeltaPb body (reference messages.proto:55-66).
//
// Outputs:
//   scalars[0..3] = from_version_excluded, last_gc_version, max_version,
//                   has_max_version
//   node_span[0..1] = [start, end) of the NodeIdPb submessage bytes
//                     (or -1,-1 if absent)
//   kv_spans: 4 longs per kv = key_off, key_len, val_off, val_len
//             (offsets into buf; strings are substrings of the input)
//   versions / statuses: per-kv, full u64 bit patterns in long long
//             slots (the Python side masks back to unsigned)
// Unknown fields are skipped (forward compatibility), matching the
// Python decoder.
// Returns kv count, -1 on truncation/overflow, -2 if max_kvs exceeded,
// -3 on unsupported wire type.
long acg_dec_node_delta(const unsigned char* buf, long len,
                        long long* scalars, long* node_span, long* kv_spans,
                        long long* versions, long long* statuses,
                        long max_kvs) {
  scalars[0] = scalars[1] = scalars[2] = 0;
  scalars[3] = 0;
  node_span[0] = node_span[1] = -1;
  long nkv = 0;
  long pos = 0;
  while (pos < len) {
    unsigned long long tag;
    if (!get_uvarint(buf, len, &pos, &tag)) return -1;
    unsigned long long field = tag >> 3;
    int wt = static_cast<int>(tag & 0x7);
    if (wt == 2) {  // length-delimited
      unsigned long long n;
      if (!get_uvarint(buf, len, &pos, &n)) return -1;
      // Unsigned compare against the REMAINING bytes: a huge declared
      // length must not wrap the position arithmetic.
      if (n > static_cast<unsigned long long>(len - pos)) return -1;
      if (field == 1) {
        node_span[0] = pos;
        node_span[1] = pos + static_cast<long>(n);
      } else if (field == 4) {
        if (nkv >= max_kvs) return -2;
        // Parse the kv submessage in place.
        long kend = pos + static_cast<long>(n);
        long kp = pos;
        long ko = -1, kl = 0, vo = -1, vl = 0;
        unsigned long long ver = 0, st = 0;
        while (kp < kend) {
          unsigned long long ktag;
          if (!get_uvarint(buf, kend, &kp, &ktag)) return -1;
          unsigned long long kf = ktag >> 3;
          int kwt = static_cast<int>(ktag & 0x7);
          if (kwt == 2) {
            unsigned long long sn;
            if (!get_uvarint(buf, kend, &kp, &sn)) return -1;
            if (sn > static_cast<unsigned long long>(kend - kp)) return -1;
            if (kf == 1) {
              ko = kp;
              kl = static_cast<long>(sn);
            } else if (kf == 2) {
              vo = kp;
              vl = static_cast<long>(sn);
            }
            kp += static_cast<long>(sn);
          } else if (kwt == 0) {
            unsigned long long v;
            if (!get_uvarint(buf, kend, &kp, &v)) return -1;
            if (kf == 3)
              ver = v;
            else if (kf == 4)
              st = v;
          } else if (kwt == 5) {
            if (kend - kp < 4) return -1;
            kp += 4;
          } else if (kwt == 1) {
            if (kend - kp < 8) return -1;
            kp += 8;
          } else {
            return -3;
          }
        }
        kv_spans[4 * nkv + 0] = ko;
        kv_spans[4 * nkv + 1] = kl;
        kv_spans[4 * nkv + 2] = vo;
        kv_spans[4 * nkv + 3] = vl;
        versions[nkv] = static_cast<long long>(ver);
        statuses[nkv] = static_cast<long long>(st);
        ++nkv;
      }
      pos += static_cast<long>(n);
    } else if (wt == 0) {  // varint
      unsigned long long v;
      if (!get_uvarint(buf, len, &pos, &v)) return -1;
      if (field == 2) {
        scalars[0] = static_cast<long long>(v);
      } else if (field == 3) {
        scalars[1] = static_cast<long long>(v);
      } else if (field == 5) {
        scalars[2] = static_cast<long long>(v);
        scalars[3] = 1;
      }
    } else if (wt == 5) {
      if (len - pos < 4) return -1;
      pos += 4;
    } else if (wt == 1) {
      if (len - pos < 8) return -1;
      pos += 8;
    } else {
      return -3;
    }
  }
  return nkv;
}

}  // extern "C"
