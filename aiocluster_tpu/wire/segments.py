"""Zero-copy wire data plane: segment-cached kv encoding, shared delta
payloads, and scatter-gather packet assembly.

The reference (and this repo before ``Config.wire_fastpath``) re-encodes
every stale key-value **per peer per round**: once to size it against
the MTU (wire/sizes.py prices by encoding) and once to emit it, then
copies the assembled payload at least twice more (proto envelope
concat → frame prefix concat → writer). This module removes all of
that while staying byte-identical to the oracle codec in proto.py:

- :class:`SegmentStore` — each (node, key, version) key-value encodes
  ONCE into an immutable *segment*: the complete field-4 submessage
  (tag + length varint + body), so a delta body is a concatenation of
  segments and the MTU packer can price by ``len(segment)``
  (``DeltaSizeModel.kv_increment_from_segment``) with zero encode work.
  Entries self-validate on use — a lookup whose cached
  (version, status) no longer matches the live value re-encodes and
  counts an ``invalidate`` — so a stale segment can never outlive a
  mutation, whatever path mutated the state.
- :class:`SharedPayloadCache` — one node's fully-assembled,
  untruncated delta payload for a given catch-up window, keyed by
  (node, content_epoch, floor): k peers requesting the same window in
  one round cost ONE assembly, not k. Truncated payloads are never
  shared (truncation depends on the requesting frame's remaining
  budget).
- :class:`EncodedDelta` + the ``*_packet_parts`` helpers — an encoded
  DeltaPb as a list of buffer refs plus exact envelope arithmetic, so
  the transport can ``writelines([header, *parts])`` without ever
  materializing the payload (``b"".join``-free by construction; the
  analyzer's ACT042 rule enforces that discipline across wire/ and the
  transport).

Everything here must stay byte-for-byte equal to
``encode_packet(Packet(...))`` over the same logical messages — the
differential fuzz suite (tests/test_wire_fastpath.py) pins that,
including MTU-exact truncation boundaries and invalidation after every
mutation kind.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

from ..core.identity import NodeId
from ..core.values import VersionedValue
from .proto import _uvarint, encode_kv_body, encode_node_id

__all__ = (
    "SegmentStore",
    "SharedPayloadCache",
    "EncodedDelta",
    "EMPTY_ENCODED_DELTA",
    "node_delta_parts",
    "syn_packet_parts",
    "synack_packet_parts",
    "ack_packet_parts",
    "cluster_id_field",
)

# Single-byte proto3 tags for the schema's fields (field << 3 | wire_type).
_TAG_DELTA_ENTRY = b"\x0a"   # Delta.node_deltas      (field 1, LEN)
_TAG_ND_NODE_ID = b"\x0a"    # NodeDelta.node_id      (field 1, LEN)
_TAG_ND_FVE = 0x10           # NodeDelta.from_version_excluded (2, VARINT)
_TAG_ND_LGC = 0x18           # NodeDelta.last_gc_version       (3, VARINT)
_TAG_ND_KV = b"\x22"         # NodeDelta.key_values            (4, LEN)
_TAG_ND_MAXV = b"\x28"       # NodeDelta.max_version           (5, VARINT)
_TAG_SYN = b"\x12"           # Packet.syn             (field 2, LEN)
_TAG_SYNACK = b"\x1a"        # Packet.syn_ack         (field 3, LEN)
_TAG_ACK = b"\x22"           # Packet.ack             (field 4, LEN)
_TAG_DIGEST = b"\x12"        # Syn/SynAck.digest      (field 2, LEN)
_TAG_DELTA = b"\x1a"         # SynAck/Ack.delta       (field 3, LEN)
_TAG_CLUSTER_ID = b"\x0a"    # Packet.cluster_id      (field 1, LEN)


class SegmentStore:
    """Bounded LRU of encoded key-value segments, keyed (node, key).

    A hit requires the cached (version, status) to match the live
    ``VersionedValue`` — versions are owner-monotonic and every
    sanctioned mutation (owner writes, tombstones, TTL marks, replica
    installs) moves the version, so validation-on-use makes stale
    segments structurally impossible rather than relying on an
    invalidation callback firing. ``stats`` are plain ints (core-style;
    the engine exports them as
    ``aiocluster_wire_segment_events_total{event}``).
    """

    __slots__ = ("_cache", "_max_entries", "stats")

    def __init__(self, max_entries: int = 65536) -> None:
        self._cache: OrderedDict[
            tuple[NodeId, str], tuple[int, int, bytes]
        ] = OrderedDict()
        self._max_entries = max_entries
        self.stats = {"hit": 0, "miss": 0, "invalidate": 0, "evict": 0}

    def segment(self, node_id: NodeId, key: str, vv: VersionedValue) -> bytes:
        """The complete field-4 submessage for (key, vv) — encoded once
        per (version, status), then served as the same immutable bytes
        to every peer and every sizing pass."""
        ck = (node_id, key)
        cache = self._cache
        entry = cache.get(ck)
        status = int(vv.status)
        if entry is not None:
            if entry[0] == vv.version and entry[1] == status:
                self.stats["hit"] += 1
                cache.move_to_end(ck)
                return entry[2]
            self.stats["invalidate"] += 1
        self.stats["miss"] += 1
        body = encode_kv_body(key, vv.value, vv.version, status)
        seg = _TAG_ND_KV + _uvarint(len(body)) + body
        cache[ck] = (vv.version, status, seg)
        if entry is not None:
            # Replacing an invalidated entry keeps its (stale) LRU slot
            # on plain assignment — a hot, frequently-rewritten key
            # must land at the MRU end like any other fresh use.
            cache.move_to_end(ck)
        if len(cache) > self._max_entries:
            cache.popitem(last=False)
            self.stats["evict"] += 1
        return seg

    def invalidate_node(self, node_id: NodeId) -> None:
        """Drop every segment for ``node_id`` (membership removal).
        Purely a memory courtesy: validation-on-use already makes the
        entries harmless, but a departed node's segments would
        otherwise linger until LRU pressure."""
        dead = [ck for ck in self._cache if ck[0] == node_id]
        for ck in dead:
            del self._cache[ck]
        if dead:
            self.stats["invalidate"] += len(dead)

    def __len__(self) -> int:
        return len(self._cache)


@dataclass(slots=True)
class EncodedDelta:
    """An encoded DeltaPb as buffer refs: ``b"".join(buffers)`` equals
    ``encode_delta(delta)`` for the logical delta it represents, but no
    caller ever performs that join — the transport writes the list.

    ``kv_refs`` is per-node ``(owner_name, [(key, version), ...])`` and
    is only collected when the caller asked (provenance tracing);
    otherwise None.
    """

    buffers: tuple[bytes, ...] | list[bytes]
    wire_len: int
    kv_count: int
    node_count: int
    kv_refs: list[tuple[str, list[tuple[str, int]]]] | None = None


# Shared empty result: an empty DeltaPb encodes to zero bytes, so every
# empty-delta handshake reuses this one object — no Delta/NodeDelta
# construction, no encode (the "empty both ways" fast resolution).
EMPTY_ENCODED_DELTA = EncodedDelta((), 0, 0, 0, None)


@dataclass(slots=True)
class SharedNodePayload:
    """One node's untruncated delta payload for one catch-up window."""

    buffers: tuple[bytes, ...]
    accounted_body: int  # DeltaSizeModel body (max_version reserved)
    wire_len: int        # actual framed bytes (sum of buffer lengths)
    kv_count: int


class SharedPayloadCache:
    """Bounded LRU of :class:`SharedNodePayload`, keyed
    (node, content_epoch, floor). The content epoch moves on every
    kv-content mutation (core/kvstate.py), so equal keys imply an
    identical stale scan — the payload is reusable verbatim for every
    peer catching up on the same window within the same state."""

    __slots__ = ("_cache", "_max_entries", "stats")

    def __init__(self, max_entries: int = 128) -> None:
        self._cache: OrderedDict[
            tuple[NodeId, int, int], SharedNodePayload
        ] = OrderedDict()
        self._max_entries = max_entries
        self.stats = {"hit": 0, "store": 0, "evict": 0}

    def get(self, key: tuple[NodeId, int, int]) -> SharedNodePayload | None:
        ent = self._cache.get(key)
        if ent is not None:
            self.stats["hit"] += 1
            self._cache.move_to_end(key)
        return ent

    def store(
        self, key: tuple[NodeId, int, int], payload: SharedNodePayload
    ) -> None:
        self._cache[key] = payload
        self.stats["store"] += 1
        if len(self._cache) > self._max_entries:
            self._cache.popitem(last=False)
            self.stats["evict"] += 1

    def invalidate_node(self, node_id: NodeId) -> None:
        """Drop every payload for ``node_id``. REQUIRED on membership
        removal (unlike the segment store's validation-on-use, these
        entries are keyed by the node's ``content_epoch`` — a re-added
        NodeState restarts that counter at 0, so a stale entry could
        collide with a fresh (epoch, floor) pair and serve a
        pre-removal window)."""
        dead = [k for k in self._cache if k[0] == node_id]
        for k in dead:
            del self._cache[k]
        if dead:
            self.stats["evict"] += len(dead)

    def __len__(self) -> int:
        return len(self._cache)


def node_delta_parts(
    node_id: NodeId,
    from_version_excluded: int,
    last_gc_version: int,
    segments: list[bytes],
    max_version: int | None,
) -> tuple[list[bytes], int]:
    """Assemble one NodeDelta's buffers: the field-1 entry prefix (tag +
    body length + node_id/floor/gc fields), the kv segments by
    reference, and the trailing ``max_version`` field when the delta is
    complete. Returns (buffers, framed_length). Byte-identical to
    ``_field_msg(out, 1, encode_node_delta(nd))``."""
    head = bytearray()
    nid = encode_node_id(node_id)  # memoized bytes
    head += _TAG_ND_NODE_ID
    head += _uvarint(len(nid))
    head += nid
    if from_version_excluded:
        head.append(_TAG_ND_FVE)
        head += _uvarint(from_version_excluded)
    if last_gc_version:
        head.append(_TAG_ND_LGC)
        head += _uvarint(last_gc_version)
    kv_len = 0
    for seg in segments:
        kv_len += len(seg)
    trailer = None
    if max_version is not None:
        # Explicit-presence field: emitted even when 0 (the oracle's
        # _field_varint_present).
        trailer = _TAG_ND_MAXV + _uvarint(max_version)
    body_len = len(head) + kv_len + (len(trailer) if trailer else 0)
    prefix = _TAG_DELTA_ENTRY + _uvarint(body_len) + bytes(head)
    buffers = [prefix, *segments]
    if trailer is not None:
        buffers.append(trailer)
    return buffers, len(prefix) + kv_len + (len(trailer) if trailer else 0)


def cluster_id_field(cluster_id: str) -> bytes:
    """The packet's field-1 cluster_id bytes (empty string omitted,
    proto3 zero-skip — matches ``_field_str``)."""
    if not cluster_id:
        return b""
    raw = cluster_id.encode("utf-8")
    return _TAG_CLUSTER_ID + _uvarint(len(raw)) + raw


def _len_prefixed(tag: bytes, body_len: int) -> tuple[bytes, int]:
    """(tag + length varint, total field size including body)."""
    head = tag + _uvarint(body_len)
    return head, len(head) + body_len


def syn_packet_parts(
    cid_field: bytes, digest_parts: list[bytes], digest_len: int
) -> list[bytes]:
    """Encoded Syn packet as buffers: byte-identical to
    ``encode_packet(Packet(cluster_id, Syn(digest)))``."""
    dig_head, dig_total = _len_prefixed(_TAG_DIGEST, digest_len)
    body_head, _ = _len_prefixed(_TAG_SYN, dig_total)
    return [cid_field + body_head + dig_head, *digest_parts]


def synack_packet_parts(
    cid_field: bytes,
    digest_parts: list[bytes],
    digest_len: int,
    enc: EncodedDelta,
) -> list[bytes]:
    """Encoded SynAck packet as buffers: byte-identical to
    ``encode_packet(Packet(cluster_id, SynAck(digest, delta)))``."""
    dig_head, dig_total = _len_prefixed(_TAG_DIGEST, digest_len)
    dl_head, dl_total = _len_prefixed(_TAG_DELTA, enc.wire_len)
    body_head, _ = _len_prefixed(_TAG_SYNACK, dig_total + dl_total)
    return [
        cid_field + body_head + dig_head,
        *digest_parts,
        dl_head,
        *enc.buffers,
    ]


def ack_packet_parts(cid_field: bytes, enc: EncodedDelta) -> list[bytes]:
    """Encoded Ack packet as buffers: byte-identical to
    ``encode_packet(Packet(cluster_id, Ack(delta)))``."""
    dl_head, dl_total = _len_prefixed(_TAG_DELTA, enc.wire_len)
    body_head, _ = _len_prefixed(_TAG_ACK, dl_total)
    return [cid_field + body_head + dl_head, *enc.buffers]
