"""Exact encoded-size accounting for MTU-bounded delta packing.

The packer (core/cluster_state.py) must answer "would this delta exceed the
MTU if I add one more key-value?" The reference answered by re-serialising
the whole delta per key-value (reference state.py:392-398, quadratic). Here
each size is computed once and totals advance with O(1) arithmetic, while
remaining byte-exact with the proto3 encoding in wire/proto.py.
"""

from __future__ import annotations

from ..core.identity import NodeId
from ..core.messages import KeyValueUpdate
from .proto import encode_kv_update, encode_node_id, varint_size

__all__ = ("DeltaSizeModel",)

_TAG_SIZE = 1  # all fields in the schema have single-byte tags


def _len_field_size(body_size: int) -> int:
    """Bytes for a length-delimited field holding ``body_size`` bytes."""
    return _TAG_SIZE + varint_size(body_size) + body_size


def _varint_field_size(value: int) -> int:
    """Bytes for a varint field, honouring proto3 zero-skipping."""
    return 0 if value == 0 else _TAG_SIZE + varint_size(value)


class DeltaSizeModel:
    """Incremental size of one DeltaPb under construction.

    ``node_delta_base``/``kv_increment`` price the parts; the caller tracks
    a candidate node-delta body size, tests it with ``delta_total_with``,
    and ``commit``s it once the node's key-values are chosen.
    """

    def __init__(self) -> None:
        self._committed = 0

    def node_delta_base(
        self,
        node_id: NodeId,
        from_version_excluded: int,
        last_gc_version: int,
        max_version: int,
    ) -> int:
        """Body size of a NodeDeltaPb before any key-values, with the
        ``max_version`` presence-tracked field reserved (always costed,
        matching the reference's accounting even though we may omit it on
        the wire for truncated deltas)."""
        return (
            _len_field_size(len(encode_node_id(node_id)))
            + _varint_field_size(from_version_excluded)
            + _varint_field_size(last_gc_version)
            + _TAG_SIZE
            + varint_size(max_version)  # optional field: emitted even when 0
        )

    def kv_increment(self, kv: KeyValueUpdate) -> int:
        """Bytes added to a node-delta body by appending ``kv``."""
        return _len_field_size(len(encode_kv_update(kv)))

    @staticmethod
    def kv_increment_from_segment(segment: bytes) -> int:
        """``kv_increment`` priced off a cached wire segment: a segment
        (wire/segments.py) is the COMPLETE field-4 submessage — tag +
        length varint + body — so its length IS the body increment.
        This is how the fast packer sizes by cached lengths with zero
        encode work; ``kv_increment`` (which encodes to measure)
        remains the oracle the differential fuzz suite checks against."""
        return len(segment)

    def delta_total_with(self, node_delta_body: int) -> int:
        """Total DeltaPb size if a node delta of ``node_delta_body`` bytes
        were appended to what is already committed."""
        return self._committed + _len_field_size(node_delta_body)

    def commit(self, node_delta_body: int) -> None:
        self._committed += _len_field_size(node_delta_body)

    def total(self) -> int:
        return self._committed
