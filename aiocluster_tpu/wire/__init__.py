"""Wire format: hand-written proto3-compatible codec + size accounting.

Byte-compatible with the reference's messages.proto schema (field numbers
preserved) so this framework and the reference can gossip in one cluster.
"""

from .proto import (
    WireError,
    decode_delta,
    decode_digest,
    decode_packet,
    encode_delta,
    encode_digest,
    encode_packet,
)
from .sizes import DeltaSizeModel

__all__ = (
    "DeltaSizeModel",
    "WireError",
    "decode_delta",
    "decode_digest",
    "decode_packet",
    "encode_delta",
    "encode_digest",
    "encode_packet",
)
