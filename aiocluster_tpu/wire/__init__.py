"""Wire format: hand-written proto3-compatible codec + size accounting.

Byte-compatible with the reference's messages.proto schema (field numbers
preserved) so this framework and the reference can gossip in one cluster.
"""

from .proto import (
    ENCODE_STATS,
    WireError,
    decode_delta,
    decode_digest,
    decode_packet,
    encode_delta,
    encode_digest,
    encode_packet,
    encode_trace_context,
)
from .segments import (
    EMPTY_ENCODED_DELTA,
    EncodedDelta,
    SegmentStore,
    SharedPayloadCache,
)
from .sizes import DeltaSizeModel

__all__ = (
    "DeltaSizeModel",
    "EMPTY_ENCODED_DELTA",
    "ENCODE_STATS",
    "EncodedDelta",
    "SegmentStore",
    "SharedPayloadCache",
    "WireError",
    "decode_delta",
    "decode_digest",
    "decode_packet",
    "encode_delta",
    "encode_digest",
    "encode_packet",
    "encode_trace_context",
)
