"""Watch fan-out hub: one epoch bump wakes every watcher with one encode.

Design contract (docs/serving.md):

- **Level-triggered.** The hub never trusts an event payload; a kick
  (from the cluster's membership/key-change hooks) or a poll tick just
  makes the pump compare ``SnapshotCache.epoch_now()`` against the last
  published epoch. Hook events ride the runtime's bounded
  ``HookDispatcher`` queue and may legitimately be DROPPED under load —
  a drop costs wake latency (bounded by ``poll_interval``), never a
  missed epoch.
- **Coalescing.** Any number of kicks between two pump iterations
  collapse into one publish; a publish encodes once (via the cache) and
  hands the *same* ``EncodedSnapshot`` to every parked long-poller and
  every stream watcher.
- **Backpressure.** Long-pollers are client-paced by construction (one
  future per request). Stream watchers hold a bounded queue; when it
  overflows the publish is dropped *and counted* and the watcher is
  marked lagged — its next read resyncs from the current snapshot
  instead of replaying missed epochs, so serve-side memory is bounded
  by ``watchers * queue_maxsize`` payload references, always.
"""

from __future__ import annotations

import asyncio
from contextlib import suppress

from ..obs.registry import MetricsRegistry
from .cache import EncodedSnapshot, SnapshotCache

# A stream watcher that has fallen `queue_maxsize` publishes behind is
# lagging; 2 keeps worst-case hub memory at ~two shared payload refs per
# watcher while riding out one slow read.
DEFAULT_QUEUE_MAXSIZE = 2

# Liveness fallback for dropped hook events: the pump re-checks the
# epoch this often even with no kicks. Latency floor for a watcher whose
# wake-up hook was dropped; pure-int compare when nothing changed.
DEFAULT_POLL_INTERVAL = 0.25


class StreamWatcher:
    """One subscribed streaming client: a bounded queue of shared
    payloads plus the lagged→resync escape hatch."""

    __slots__ = ("_hub", "_queue", "lagged", "closed")

    def __init__(self, hub: "WatchHub", maxsize: int) -> None:
        self._hub = hub
        # None is the close sentinel (hub shutdown / unsubscribe).
        self._queue: asyncio.Queue[EncodedSnapshot | None] = asyncio.Queue(
            maxsize=maxsize
        )
        self.lagged = False
        self.closed = False

    def _offer(self, encoded: EncodedSnapshot) -> bool:
        """Hub-side delivery; False (and lagged) when the queue is full."""
        try:
            self._queue.put_nowait(encoded)
            return True
        except asyncio.QueueFull:
            self.lagged = True
            return False

    def _wake_closed(self) -> None:
        """Unblock a parked ``next()`` after close (sentinel delivery;
        a full queue is drained first — the reader is gone anyway)."""
        self.closed = True
        try:
            self._queue.put_nowait(None)
        except asyncio.QueueFull:
            while not self._queue.empty():
                self._queue.get_nowait()
            self._queue.put_nowait(None)

    async def next(self, timeout: float | None = None) -> EncodedSnapshot | None:
        """The next payload for this watcher, or None on timeout/close.

        A lagged watcher drains its stale queue and is served the
        *current* snapshot (one shared cache encode) — it resynchronises
        instead of silently missing the dropped epochs.
        """
        if self.closed:
            return None
        if self.lagged:
            self.lagged = False
            while not self._queue.empty():
                self._queue.get_nowait()
            self._hub.count_watch("resync")
            return self._hub.cache.get()
        if timeout is None:
            return await self._queue.get()
        try:
            return await asyncio.wait_for(self._queue.get(), timeout)
        except (TimeoutError, asyncio.TimeoutError):
            return None

    def close(self) -> None:
        self._hub._unsubscribe(self)


class WatchHub:
    """Fan-out of epoch bumps to long-pollers and stream watchers."""

    def __init__(
        self,
        cache: SnapshotCache,
        *,
        metrics: MetricsRegistry | None = None,
        poll_interval: float = DEFAULT_POLL_INTERVAL,
        queue_maxsize: int = DEFAULT_QUEUE_MAXSIZE,
    ) -> None:
        self.cache = cache
        self._poll_interval = poll_interval
        self._queue_maxsize = max(1, queue_maxsize)
        self._kick = asyncio.Event()
        self._pump_task: asyncio.Task | None = None
        self._stopping = False
        self._published_epoch: int | None = None
        # fut -> the client's `since` epoch: a publish only wakes the
        # futures it is actually NEWER than (a waiter parked at the
        # current epoch must sleep through the pump's first iteration).
        self._parked: dict[asyncio.Future[EncodedSnapshot], int] = {}
        self._stream: set[StreamWatcher] = set()
        self._watch_events = None
        self._hub_events = None
        self._watchers_gauge = None
        if metrics is not None:
            self._watch_events = metrics.counter(
                "aiocluster_serve_watch_events_total",
                "Watcher outcomes: immediate (long-poll answered without "
                "parking), wake (parked long-poll answered by a publish), "
                "timeout (long-poll expired empty), stream (payload "
                "queued to a stream watcher), drop (stream queue full; "
                "publish dropped, watcher marked lagged), resync (lagged "
                "watcher served the current snapshot)",
                labels=("event",),
            )
            self._hub_events = metrics.counter(
                "aiocluster_serve_hub_events_total",
                "Pump activity: kick (hook-driven wakeups), publish "
                "(epoch bumps fanned out), idle (pump woke to an "
                "unchanged epoch)",
                labels=("event",),
            )
            self._watchers_gauge = metrics.gauge(
                "aiocluster_serve_watchers",
                "Currently connected watchers (parked long-polls + "
                "stream subscriptions)",
            )

    def count_watch(self, event: str) -> None:
        if self._watch_events is not None:
            self._watch_events.labels(event).inc()

    def _count_hub(self, event: str) -> None:
        if self._hub_events is not None:
            self._hub_events.labels(event).inc()

    def _sync_gauge(self) -> None:
        if self._watchers_gauge is not None:
            self._watchers_gauge.set(len(self._parked) + len(self._stream))

    @property
    def published_epoch(self) -> int | None:
        return self._published_epoch

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._pump_task is None:
            # Anchor at the current epoch so the pump's first iteration
            # is an idle compare, not a spurious publish/encode.
            self._published_epoch = self.cache.epoch_now()
            self._stopping = False
            self._pump_task = asyncio.create_task(self._pump())

    async def stop(self) -> None:
        # Swap-to-local before the join suspends: a second stop() racing
        # this one must see None at the guard, not cancel/await a pump
        # another stopper already owns.
        pump, self._pump_task = self._pump_task, None
        if pump is not None:
            # Belt AND suspenders: on 3.10, ``asyncio.wait_for`` can
            # swallow a cancellation that races the awaited future's
            # completion (bpo-42130) — and the pump's kick.wait()
            # COMPLETES CONSTANTLY under a live gossiping fleet. The
            # flag (checked every loop) ends the pump even when the
            # CancelledError delivery is eaten; the cancel + kick cover
            # the parked waits.
            self._stopping = True
            self._kick.set()
            pump.cancel()
            with suppress(asyncio.CancelledError):  # noqa: ACT013 -- joining our own cancelled pump at shutdown
                await pump
        for fut in self._parked:
            if not fut.done():
                fut.cancel()
        self._parked.clear()
        for watcher in list(self._stream):
            watcher._wake_closed()
        self._stream.clear()
        self._sync_gauge()

    # -- producers ------------------------------------------------------------

    def kick(self) -> None:
        """Hint that the epoch may have moved (hook callbacks call this;
        any number of kicks coalesce into the pump's next iteration)."""
        self._count_hub("kick")
        self._kick.set()

    async def _pump(self) -> None:
        while not self._stopping:
            try:
                await asyncio.wait_for(
                    self._kick.wait(), timeout=self._poll_interval
                )
            except (TimeoutError, asyncio.TimeoutError):
                pass  # poll tick: liveness through dropped hook events
            if self._stopping:
                return
            self._kick.clear()
            if self.cache.epoch_now() == self._published_epoch:
                self._count_hub("idle")  # pure int compare, no walk
                continue
            encoded = self.cache.get()  # ONE encode, shared below
            if (
                self._published_epoch is not None
                and encoded.epoch <= self._published_epoch
            ):
                # Heartbeat-only epoch bump: the cache deduped it to the
                # already-published content. Nobody wakes.
                self._count_hub("idle")
                continue
            self._published_epoch = encoded.epoch
            self._count_hub("publish")
            parked, self._parked = self._parked, {}
            for fut, since in parked.items():
                if fut.done():
                    continue
                if encoded.epoch > since:
                    fut.set_result(encoded)
                else:
                    self._parked[fut] = since  # still not newer: re-park
            for watcher in self._stream:
                if watcher._offer(encoded):
                    self.count_watch("stream")
                else:
                    self.count_watch("drop")
            self._sync_gauge()

    # -- consumers ------------------------------------------------------------

    async def wait_newer(
        self, since: int, timeout: float
    ) -> EncodedSnapshot | None:
        """Long-poll: the current payload immediately when the *content*
        is already past ``since``, otherwise the next publish (shared
        object), or None when ``timeout`` elapses first. Heartbeat-only
        epoch bumps dedup in the cache and park the caller — a live
        fleet's long-polls stay long, not busy-polls."""
        if self.cache.epoch_now() > since:
            encoded = self.cache.get()
            if encoded.epoch > since:
                self.count_watch("immediate")
                return encoded
        fut: asyncio.Future[EncodedSnapshot] = (
            asyncio.get_running_loop().create_future()
        )
        self._parked[fut] = since
        self._sync_gauge()
        try:
            encoded = await asyncio.wait_for(fut, timeout)
            self.count_watch("wake")
            return encoded
        except (TimeoutError, asyncio.TimeoutError):
            self.count_watch("timeout")
            return None
        finally:
            self._parked.pop(fut, None)
            self._sync_gauge()

    def subscribe(self) -> StreamWatcher:
        watcher = StreamWatcher(self, self._queue_maxsize)
        self._stream.add(watcher)
        self._sync_gauge()
        return watcher

    def _unsubscribe(self, watcher: StreamWatcher) -> None:
        watcher._wake_closed()
        self._stream.discard(watcher)
        self._sync_gauge()
