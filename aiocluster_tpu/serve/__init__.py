"""Serve tier: the service-discovery read path over a running Cluster.

ScuttleButt replication (the runtime) answers "how does every node
learn the state"; this package answers "how do a fleet's *clients* read
it" — and the answer must be O(changes), not O(state), per client:

- :mod:`cache` — ``SnapshotCache``: one canonical JSON encode per
  state epoch, shared as the same ``bytes`` object by every concurrent
  reader and watcher; epoch-floor history powers ``GET /state?since=E``
  delta reads off the version-indexed stale scans.
- :mod:`hub` — ``WatchHub``: the fan-out point. Membership and
  key-change hooks kick it; bursts coalesce into one publish per epoch;
  parked long-pollers and bounded-queue stream watchers all receive the
  single shared encoded payload. Slow stream watchers drop to a counted
  "resync from snapshot" instead of growing unbounded queues.
- :mod:`http` — ``ServeApp``: the stdlib-asyncio HTTP surface
  (``/state`` with ETag/304 and ``?since=`` deltas, ``/watch``
  long-poll + chunked streaming, the reference example's KV endpoints,
  ``/metrics``, ``/healthz``), fronted by ``OverloadPolicy`` admission
  control — event-loop-lag + in-flight shedding with ``429`` +
  ``Retry-After``, and a real degraded-state ``/healthz``
  (docs/robustness.md).

See docs/serving.md for the endpoint contract and bench methodology
(benchmarks/serve_bench.py is the 10k-watcher load generator).
"""

from .cache import EncodedSnapshot, SnapshotCache, encode_snapshot
from .http import OverloadPolicy, ServeApp
from .hub import StreamWatcher, WatchHub

__all__ = [
    "EncodedSnapshot",
    "OverloadPolicy",
    "ServeApp",
    "SnapshotCache",
    "StreamWatcher",
    "WatchHub",
    "encode_snapshot",
]
