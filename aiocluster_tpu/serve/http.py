"""``ServeApp``: the stdlib-asyncio HTTP API over one cluster member.

Endpoints (full contract in docs/serving.md):

  GET  /state                 full snapshot; ``ETag: "<epoch>"``; an
                              ``If-None-Match`` naming the CURRENT epoch
                              short-circuits to 304 with zero encodes
  GET  /state?since=E         delta read: only key-versions above the
                              client's epoch-E floors (O(changes));
                              floors aged out of history → full payload
                              (the counted resync path)
  GET  /watch?since=E         long-poll: responds the moment the epoch
                              passes E (or immediately when it already
                              has), 204 after ``timeout`` seconds idle
  GET  /watch?since=E&stream=1  chunked stream: one JSON payload chunk
                              per epoch bump until the client leaves
  GET  /kv/<key>              this node's visible value
  PUT  /kv/<key>?v=...[&ttl=1]  owner write (replicates via gossip)
  DELETE /kv/<key>            owner tombstone
  POST /kv_mark/<key>         delete-after-TTL mark (reference parity)
  GET  /metrics               Prometheus text (the cluster's registry)
  GET  /healthz               liveness
  GET  /debug/flightrec       the node's flight recorder dump (the
                              always-on bounded ring of recent events,
                              obs/flightrec.py) — operator endpoint,
                              never shed, like /healthz
  GET  /fleet                 any-member fleet view from gossip-borne
                              telemetry (obs/fleet.py): per-node health
                              digests with staleness annotations;
                              ``ETag: "<epoch>"`` + If-None-Match 304,
                              cached per digest-epoch; ``?stale_s=``
                              filters to fresh entries (uncached path);
                              operator endpoint, never shed

The hot path does zero redundant work per client: every 200 ``/state``
and every watch wake serves the SnapshotCache's per-epoch ``bytes``;
``cache_enabled=False`` keeps the naive re-walk-and-re-encode-per-
request behavior as the benchmark's control arm (and the reference
example's semantics).
"""

from __future__ import annotations

import asyncio
import json
import math
from contextlib import suppress
from dataclasses import dataclass
from urllib.parse import parse_qs, unquote, urlparse

from ..obs.expo import render_prometheus
from ..obs.registry import MetricsRegistry
from ..runtime.cluster import Cluster
from ..utils.clock import sleep as clock_sleep
from .cache import SnapshotCache, encode_snapshot, parse_etag
from .hub import WatchHub

# Long-poll parking ceiling: a client asking for more still gets its
# 204 heartbeat by then (idle connections stay bounded server-side).
MAX_LONG_POLL_S = 300.0
DEFAULT_LONG_POLL_S = 30.0

# Request bodies are read-and-discarded (values ride query params), so
# there is no reason to buffer more than this before dropping the
# connection as abusive.
MAX_BODY_BYTES = 1 << 20

# Header-count ceiling per request: no endpoint needs more, and an
# uncapped header dict is per-connection unbounded memory (the same
# discipline ACT026 enforces for queues).
MAX_HEADERS = 100

_JSON = "application/json"
_TEXT = "text/plain"

# Loop-lag hysteresis: one saturated probe decays over a few intervals
# instead of flapping the shed decision per probe.
_LAG_DECAY = 0.5


@dataclass(frozen=True, slots=True)
class OverloadPolicy:
    """Serve-tier admission control (docs/robustness.md).

    Past either threshold a request is shed with ``429`` +
    ``Retry-After`` instead of joining a queue that has already lost —
    an overloaded tier that answers *some* requests on time degrades;
    one that answers *all* of them late collapses:

    - ``max_inflight`` bounds concurrently *executing* requests
      (parked ``/watch`` long-polls are excluded — they cost a future,
      not CPU; their backpressure is the hub's bounded queues with
      counted drop→resync).
    - ``shed_lag_s`` sheds on measured event-loop lag — the signal
      that the process (gossip rounds included) is past saturation;
      applies to every endpoint including ``/watch``.
    - ``/healthz``, ``/metrics``, ``/debug/flightrec`` and ``/fleet``
      are never shed: the operator's view must survive the storm it is
      diagnosing.

    ``enabled=False`` restores the accept-everything behavior (the
    overload benchmark's control arm).
    """

    enabled: bool = True
    max_inflight: int = 256
    shed_lag_s: float = 1.0
    probe_interval_s: float = 0.1
    retry_after_s: float = 1.0


class _Request:
    __slots__ = ("method", "path", "query", "headers")

    def __init__(
        self,
        method: str,
        path: str,
        query: dict[str, list[str]],
        headers: dict[str, str],
    ) -> None:
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers

    def q1(self, name: str) -> str | None:
        values = self.query.get(name)
        return values[0] if values else None


class ServeApp:
    """The serve tier for one Cluster: cache + hub + HTTP front."""

    def __init__(
        self,
        cluster: Cluster,
        *,
        metrics: MetricsRegistry | None = None,
        cache_enabled: bool = True,
        watch_queue_maxsize: int = 2,
        hub_poll_interval: float = 0.25,
        floor_history: int = 1024,
        overload: OverloadPolicy | None = None,
    ) -> None:
        self._cluster = cluster
        self._metrics = (
            metrics if metrics is not None else cluster.metrics_registry()
        )
        self.cache_enabled = cache_enabled
        self.overload = overload if overload is not None else OverloadPolicy()
        self.cache = SnapshotCache(
            cluster, metrics=self._metrics, floor_history=floor_history
        )
        self.hub = WatchHub(
            self.cache,
            metrics=self._metrics,
            poll_interval=hub_poll_interval,
            queue_maxsize=watch_queue_maxsize,
        )
        self._requests = self._metrics.counter(
            "aiocluster_serve_requests_total",
            "HTTP requests served, by endpoint and status code",
            labels=("endpoint", "status"),
        )
        self._sheds = self._metrics.counter(
            "aiocluster_serve_shed_total",
            "Requests shed by admission control (429), by reason",
            labels=("reason",),
        )
        self._lag_gauge = self._metrics.gauge(
            "aiocluster_loop_lag_seconds",
            "Measured event-loop lag (decayed max over recent probes)",
        )
        self._inflight_gauge = self._metrics.gauge(
            "aiocluster_serve_inflight",
            "Requests currently executing (parked watches excluded)",
        )
        self._lag = 0.0
        self._inflight = 0
        self._shed_total = 0
        # /fleet payload cached per digest-epoch (same dedup signal the
        # snapshot cache keys on): (epoch, encoded bytes).
        self._fleet_cache: tuple[int, bytes] | None = None
        self._lag_task: asyncio.Task | None = None
        self._server: asyncio.Server | None = None
        self._conns: set[asyncio.StreamWriter] = set()
        self.port: int | None = None

    # -- lifecycle ------------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Bind, register the hub's hook feeds, start the pump; returns
        the bound port."""
        # Membership and key-change hooks kick the hub (dispatched
        # through the runtime's bounded hook queue — drops cost latency
        # only; the hub's poll fallback guarantees liveness).
        self._cluster.on_key_change(self._on_key_change)
        self._cluster.on_node_join(self._on_membership)
        self._cluster.on_node_leave(self._on_membership)
        self.hub.start()
        # The loop-lag probe runs regardless of the shed policy —
        # /healthz reports the lag either way.
        if self._lag_task is None:
            self._lag_task = asyncio.create_task(self._lag_probe())
        self._server = await asyncio.start_server(self._handle, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _lag_probe(self) -> None:
        """Measure event-loop lag: sleep a fixed interval and see how
        late the wakeup lands. A decayed max (not the raw sample) feeds
        the shed decision, so one saturated probe holds the degraded
        state for a few intervals instead of flapping."""
        loop = asyncio.get_running_loop()
        interval = self.overload.probe_interval_s
        while True:
            t0 = loop.time()
            await clock_sleep(interval)
            lag = max(0.0, loop.time() - t0 - interval)
            self._lag = max(lag, self._lag * _LAG_DECAY)
            self._lag_gauge.set(self._lag)

    async def stop(self) -> None:
        # Detach from the cluster's hook feeds: a stopped app must not
        # keep receiving kick dispatches (crowding the bounded hook
        # queue) or pin its cache/payloads via the registered closures.
        self._cluster.remove_on_key_change(self._on_key_change)
        self._cluster.remove_on_node_join(self._on_membership)
        self._cluster.remove_on_node_leave(self._on_membership)
        # Swap both handles to locals before any await: stop() can race
        # a second stop() (app teardown vs test cleanup), and the second
        # caller must see None at the guards instead of re-cancelling
        # tasks or re-closing a server the first already owns.
        lag_task, self._lag_task = self._lag_task, None
        if lag_task is not None:
            lag_task.cancel()
            try:
                await lag_task
            except asyncio.CancelledError:  # noqa: ACT013 -- absorbing the cancel we just issued at app teardown
                pass
        await self.hub.stop()
        server, self._server = self._server, None
        if server is not None:
            server.close()
            # Parked watch handlers hold open connections; close them so
            # their tasks finish now instead of at client timeout.
            for writer in list(self._conns):
                writer.close()
                with suppress(Exception):
                    await writer.wait_closed()
            await server.wait_closed()

    async def __aenter__(self) -> "ServeApp":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    async def _on_key_change(self, *_args) -> None:
        self.hub.kick()

    async def _on_membership(self, *_args) -> None:
        self.hub.kick()

    # -- HTTP plumbing --------------------------------------------------------

    async def _read_request(
        self, reader: asyncio.StreamReader
    ) -> _Request | None:
        line = await reader.readline()
        if not line.strip():
            return None
        try:
            method, target, _version = line.decode("latin-1").split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            raw = await reader.readline()
            if raw in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= MAX_HEADERS:
                return None  # header flood: bounded memory, drop it
            name, _, value = raw.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            return None  # malformed Content-Length: drop the connection
        if not 0 <= length <= MAX_BODY_BYTES:
            return None  # refuse to buffer arbitrary client-claimed sizes
        if length:
            await reader.readexactly(length)  # read and discard bodies
        url = urlparse(target)
        return _Request(method, url.path, parse_qs(url.query), headers)

    @staticmethod
    def _response(
        status: str,
        body: bytes,
        content_type: str = _TEXT,
        extra_headers: tuple[tuple[str, str], ...] = (),
        keep_alive: bool = True,
    ) -> bytes:
        headers = [
            f"HTTP/1.1 {status}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
            "Connection: " + ("keep-alive" if keep_alive else "close"),
        ]
        headers.extend(f"{k}: {v}" for k, v in extra_headers)
        return ("\r\n".join(headers) + "\r\n\r\n").encode() + body

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Serve requests on one connection (HTTP/1.1 keep-alive) until
        the client leaves — watcher fleets reconnect-storm without it."""
        self._conns.add(writer)
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    return
                close = request.headers.get("connection", "").lower() == "close"
                # Admission control (docs/robustness.md): past the
                # thresholds the request is answered 429 + Retry-After
                # immediately — cheap for the server, honest to the
                # client — instead of joining a doomed queue. The
                # connection stays usable (clients retry on it).
                reason = self._shed_reason(request.path)
                if reason is not None:
                    self._shed_total += 1
                    self._sheds.labels(reason).inc()
                    self._requests.labels("shed", "429").inc()
                    writer.write(
                        self._response(
                            "429 Too Many Requests",
                            b"overloaded\n",
                            _TEXT,
                            (
                                (
                                    "Retry-After",
                                    str(
                                        max(
                                            1,
                                            math.ceil(
                                                self.overload.retry_after_s
                                            ),
                                        )
                                    ),
                                ),
                            ),
                            keep_alive=not close,
                        )
                    )
                    await writer.drain()
                    if close:
                        return
                    continue
                if request.path == "/watch" and request.q1("stream"):
                    await self._stream_watch(request, writer)
                    return  # stream ends with the connection
                is_watch = request.path == "/watch"
                if not is_watch:
                    # Parked long-polls are excluded: they hold a
                    # future, not the CPU — counting them would shed
                    # /state the moment a watcher fleet connects.
                    self._inflight += 1
                    self._inflight_gauge.set(self._inflight)
                try:
                    if not is_watch:
                        # Yield once before routing: synchronous
                        # endpoint bodies (the /state encode) otherwise
                        # run to completion inside one task step, the
                        # gauge never observes real concurrency, and a
                        # queued wave of requests would ALL pass the
                        # in-flight check before the first encode runs
                        # — the cap must bound the admitted wave.
                        await asyncio.sleep(0)
                    endpoint, status, payload = await self._route(request)
                finally:
                    if not is_watch:
                        self._inflight -= 1
                        self._inflight_gauge.set(self._inflight)
                self._requests.labels(endpoint, status.split()[0]).inc()
                writer.write(
                    self._response(
                        status,
                        payload[0],
                        payload[1],
                        payload[2],
                        keep_alive=not close,
                    )
                )
                await writer.drain()
                if close:
                    return
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            TimeoutError,
            asyncio.TimeoutError,
            OSError,
            # StreamReader.readline raises ValueError (wrapping
            # LimitOverrunError) past its 64 KB line limit — an
            # over-long request/header line is malformed input, not an
            # unhandled-task-exception event.
            ValueError,
        ):
            pass  # client went away or sent garbage; drop the connection
        finally:
            self._conns.discard(writer)
            writer.close()
            with suppress(Exception):
                await writer.wait_closed()

    # -- routing --------------------------------------------------------------

    async def _route(
        self, request: _Request
    ) -> tuple[str, str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        """(endpoint label, status line, (body, content type, headers))."""
        method, path = request.method, request.path
        if path == "/state" and method == "GET":
            return ("state",) + self._handle_state(request)
        if path == "/watch" and method == "GET":
            return ("watch",) + await self._handle_watch(request)
        if path == "/metrics" and method == "GET":
            body = render_prometheus(self._metrics).encode()
            return (
                "metrics",
                "200 OK",
                (body, "text/plain; version=0.0.4; charset=utf-8", ()),
            )
        if path == "/healthz" and method == "GET":
            return self._handle_healthz()
        if path == "/debug/flightrec" and method == "GET":
            # Post-mortem ring dump (obs/flightrec.py): bounded by
            # construction, so encoding it is O(capacity), not O(state).
            body = (
                json.dumps(
                    {"events": self._cluster.flight_record()},
                    sort_keys=True,
                ).encode()
                + b"\n"
            )
            return ("flightrec", "200 OK", (body, _JSON, ()))
        if path == "/fleet" and method == "GET":
            return ("fleet",) + self._handle_fleet(request)
        parts = [p for p in path.split("/") if p]
        if len(parts) == 2 and parts[0] == "kv":
            return ("kv",) + self._handle_kv(request, unquote(parts[1]))
        if len(parts) == 2 and parts[0] == "kv_mark" and method == "POST":
            key = unquote(parts[1])
            if self._cluster.get(key) is not None:
                self._cluster.delete_after_ttl(key)
                return ("kv_mark", "200 OK", (b"ok", _TEXT, ()))
            return ("kv_mark", "404 Not Found", (b"not found", _TEXT, ()))
        return ("other", "404 Not Found", (b"not found", _TEXT, ()))

    def _shed_reason(self, path: str) -> str | None:
        """Why this request should be shed right now, or None to admit
        it (see OverloadPolicy). Lag sheds everything; the in-flight
        bound spares /watch (parked long-polls are not executing)."""
        pol = self.overload
        if not pol.enabled or path in (
            "/healthz", "/metrics", "/debug/flightrec", "/fleet",
        ):
            return None
        if self._lag > pol.shed_lag_s:
            return "lag"
        if path != "/watch" and self._inflight >= pol.max_inflight:
            return "inflight"
        return None

    def _shedding(self) -> bool:
        # One source of truth with the admission check: would a plain
        # executing request be shed right now?
        return self._shed_reason("/") is not None

    def _handle_healthz(
        self,
    ) -> tuple[str, str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        """The real degraded-state report (docs/robustness.md): 503
        once the cluster is closed, otherwise 200 with
        ok/degraded status plus loop lag, shed counts, open breakers
        and the FD's phi summary — not the static "ok" the reference
        example serves regardless of cluster state."""
        summary = self._cluster.health_summary()
        closed = self._cluster.is_closed
        degraded = self._shedding() or bool(summary.get("breaker_open_peers"))
        status = "closed" if closed else ("degraded" if degraded else "ok")
        body = (
            json.dumps(
                {
                    "status": status,
                    "loop_lag_s": round(self._lag, 4),
                    "inflight": self._inflight,
                    "shed_total": self._shed_total,
                    **summary,
                },
                sort_keys=True,
            ).encode()
            + b"\n"
        )
        http_status = "503 Service Unavailable" if closed else "200 OK"
        return ("healthz", http_status, (body, _JSON, ()))

    def _handle_fleet(
        self, request: _Request
    ) -> tuple[str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        """Any-member fleet view (obs/fleet.py). The unfiltered payload
        is cached per digest-epoch — a watcher fleet polling /fleet
        costs one assemble+encode per epoch, not per request — with the
        same ETag/If-None-Match contract as /state. ``?stale_s=``
        re-assembles at request time (the filter depends on the client's
        threshold, not just the epoch)."""
        stale_raw = request.q1("stale_s")
        if stale_raw is not None:
            try:
                stale_s = float(stale_raw)
            except ValueError:
                return "400 Bad Request", (b"bad stale_s", _TEXT, ())
            if not math.isfinite(stale_s) or stale_s < 0:
                return "400 Bad Request", (b"bad stale_s", _TEXT, ())
            body = (
                json.dumps(
                    self._cluster.fleet_view(stale_s=stale_s), sort_keys=True
                ).encode()
                + b"\n"
            )
            return "200 OK", (body, _JSON, ())
        epoch = self._cluster.state_epoch()
        client_epoch = parse_etag(request.headers.get("if-none-match"))
        if client_epoch is not None and client_epoch == epoch:
            return "304 Not Modified", (
                b"",
                _JSON,
                (("ETag", f'"{epoch}"'),),
            )
        cached = self._fleet_cache
        if cached is not None and cached[0] == epoch:
            body = cached[1]
        else:
            body = (
                json.dumps(self._cluster.fleet_view(), sort_keys=True).encode()
                + b"\n"
            )
            self._fleet_cache = (epoch, body)
        return "200 OK", (body, _JSON, (("ETag", f'"{epoch}"'),))

    def _handle_state(
        self, request: _Request
    ) -> tuple[str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        if not self.cache_enabled:
            # Control arm (and the pre-serve example's behavior): walk
            # and encode the full state on every request.
            body = encode_snapshot(self._cluster.snapshot())
            return "200 OK", (body, _JSON, ())
        since_raw = request.q1("since")
        if since_raw is not None:
            try:
                since = int(since_raw)
            except ValueError:
                return "400 Bad Request", (b"bad since", _TEXT, ())
            delta = self.cache.delta_since(since)
            if delta is not None:
                return "200 OK", (
                    delta,
                    _JSON,
                    (("ETag", f'"{self.cache.epoch_now()}"'), ("X-Delta", "1")),
                )
            # Floors aged out: resync the client with the full payload.
            encoded = self.cache.get()
            return "200 OK", (
                encoded.payload,
                _JSON,
                (("ETag", encoded.etag), ("X-Resync", "1")),
            )
        client_epoch = parse_etag(request.headers.get("if-none-match"))
        if client_epoch is not None and client_epoch == self.cache.epoch_now():
            # Zero encodes on this path: the epoch compare is an int read.
            self.cache.note_not_modified()
            return "304 Not Modified", (
                b"",
                _JSON,
                (("ETag", f'"{client_epoch}"'),),
            )
        encoded = self.cache.get()
        if client_epoch is not None and client_epoch == encoded.epoch:
            # The raw epoch moved (heartbeats) but the cache deduped to
            # the same content epoch the client already holds.
            self.cache.note_not_modified()
            return "304 Not Modified", (b"", _JSON, (("ETag", encoded.etag),))
        return "200 OK", (
            encoded.payload,
            _JSON,
            (("ETag", encoded.etag),),
        )

    async def _handle_watch(
        self, request: _Request
    ) -> tuple[str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        try:
            since = int(request.q1("since") or self.cache.epoch_now())
        except ValueError:
            return "400 Bad Request", (b"bad since", _TEXT, ())
        try:
            timeout = float(request.q1("timeout") or DEFAULT_LONG_POLL_S)
        except ValueError:
            return "400 Bad Request", (b"bad timeout", _TEXT, ())
        if not math.isfinite(timeout):
            # nan survives min() and makes wait_for never fire — a
            # ?timeout=nan client would park forever past the ceiling.
            return "400 Bad Request", (b"bad timeout", _TEXT, ())
        timeout = min(timeout, MAX_LONG_POLL_S)
        if since > self.cache.epoch_now():
            # Epoch discontinuity: epochs never regress within one boot,
            # so a `since` ahead of now is a resume token from a
            # PREVIOUS incarnation (the member rebooted and its epoch
            # counter restarted low) — or garbage. Either way no future
            # publish can ever exceed it honestly; parking would strand
            # the client until timeout and an empty 204 would strand it
            # forever. Serve the full payload NOW, flagged X-Resync
            # (counted), so the client realigns to this boot's epochs.
            self.cache.note_resync_full()
            encoded = self.cache.get()
            return "200 OK", (
                encoded.payload,
                _JSON,
                (("ETag", encoded.etag), ("X-Resync", "1")),
            )
        encoded = await self.hub.wait_newer(since, timeout)
        if encoded is None:
            # Timed out ⇒ no content newer than `since` was published.
            # The resume token must not be the raw epoch_now(): that can
            # cover a content change the pump has not published yet, and
            # a client resuming from it would never be woken for that
            # change. `since` is always safe; cap it at the raw epoch so
            # a client that overshot (bogus future `since`) realigns.
            resume = min(since, self.cache.epoch_now())
            return "204 No Content", (b"", _JSON, (("ETag", f'"{resume}"'),))
        return "200 OK", (
            encoded.payload,
            _JSON,
            (("ETag", encoded.etag),),
        )

    async def _stream_watch(
        self, request: _Request, writer: asyncio.StreamWriter
    ) -> None:
        """Chunked streaming watch: one JSON payload chunk per epoch
        bump. A slow consumer overflows its bounded queue and receives
        a full resync payload instead of the missed epochs."""
        self._requests.labels("watch_stream", "200").inc()
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
            b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        watcher = self.hub.subscribe()
        try:
            since_raw = request.q1("since")
            if since_raw is not None:
                try:
                    since = int(since_raw)
                except ValueError:
                    since = None
                # Catch the client up first when it is behind (content
                # epoch: heartbeat-only bumps owe it nothing).
                if since is not None and since < self.cache.epoch_now():
                    encoded = self.cache.get()
                    if since < encoded.epoch:
                        await self._write_chunk(writer, encoded.payload)
                elif since is not None and since > self.cache.epoch_now():
                    # Epoch discontinuity (see _handle_watch): a resume
                    # token from a previous boot — realign the stream
                    # with a full payload now rather than leaving the
                    # client silent until the next bump.
                    self.cache.note_resync_full()
                    await self._write_chunk(writer, self.cache.get().payload)
            while True:
                encoded = await watcher.next()
                if encoded is None or watcher.closed:
                    break
                await self._write_chunk(writer, encoded.payload)
        finally:
            watcher.close()
            with suppress(Exception):
                await self._write_chunk(writer, b"")  # terminal chunk

    @staticmethod
    async def _write_chunk(writer: asyncio.StreamWriter, data: bytes) -> None:
        writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        await writer.drain()

    def _handle_kv(
        self, request: _Request, key: str
    ) -> tuple[str, tuple[bytes, str, tuple[tuple[str, str], ...]]]:
        method = request.method
        if method == "GET":
            value = self._cluster.get(key)
            if value is None:
                return "404 Not Found", (b"not found", _TEXT, ())
            return "200 OK", (value.encode(), _TEXT, ())
        if method == "PUT":
            value = request.q1("v") or ""
            if (request.q1("ttl") or "0") in ("1", "true"):
                self._cluster.set_with_ttl(key, value)
            else:
                self._cluster.set(key, value)
            return "200 OK", (b"ok", _TEXT, ())
        if method == "DELETE":
            self._cluster.delete(key)
            return "200 OK", (b"ok", _TEXT, ())
        return "405 Method Not Allowed", (b"method not allowed", _TEXT, ())
