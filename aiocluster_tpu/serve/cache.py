"""Epoch-keyed snapshot encoding: pay the O(state) walk once, share it.

``ClusterState.digest_epoch`` is a monotonic generation that bumps on
every digest-field or membership change — equal epochs imply identical
cluster state. ``SnapshotCache`` keys the canonical JSON encoding of
``Cluster.snapshot()`` on it (the ``make_syn_bytes`` caching pattern
from the gossip engine, applied at the serving layer): the first reader
of a new epoch pays one snapshot + one ``json.dumps``; every other
concurrent reader — and every watcher the hub wakes — gets the same
``bytes`` object. All methods are synchronous (no awaits), so under
asyncio a second encode of the same epoch cannot even race in.

In a LIVE fleet the digest epoch also bumps on every gossip heartbeat,
so raw-epoch caching alone would re-encode per round and make watch
long-polls degenerate into busy-polls (``epoch_now() > since`` is true
within one round of any reply). The cache therefore dedups on CONTENT,
in two tiers: an O(nodes) fingerprint (live/dead membership + every
node's ``max_version``/``last_gc_version`` — visible state cannot
change without one of these moving) filters heartbeat-only bumps
without walking or encoding anything, and when the fingerprint DID
move but the fresh encode is byte-identical (a same-value rewrite) the
previous epoch's ``EncodedSnapshot`` keeps serving — identical bytes
mean identical visible state, so the older validator stays correct.
Both tiers count as ``dedup`` events and remember the newest cluster
epoch verified. Watchers and ETags key on the *content* epoch;
heartbeat-only bumps wake nobody and cost no walk.

Delta reads ride the raw epoch currency: every encode (full, delta, or
dedup check) records the per-node ``max_version`` floors at that epoch
in a bounded history, and ``delta_since(E)`` replays only key-versions
above the client's floors via the version-indexed ``stale_key_values``
scans — O(changes), never O(state). A floor set that has aged out of
the history makes ``delta_since`` return None and the caller resyncs
the client from the full snapshot (counted, by design).
"""

from __future__ import annotations

import json
from collections import OrderedDict
from dataclasses import dataclass

from ..obs.registry import MetricsRegistry
from ..runtime.cluster import Cluster, ClusterSnapshot

# How many epochs of per-node version floors delta_since keeps. Bounded
# so a hot fleet cannot grow serve-side memory: one floor set is
# O(nodes) ints, and a client older than the window just resyncs.
DEFAULT_FLOOR_HISTORY = 1024


def _content_dict(snap: ClusterSnapshot) -> dict:
    """The epoch-free content of the ``GET /state`` payload: visible
    key-values only (tombstones and TTL-scheduled keys hidden). Equal
    *visible* cluster states produce equal dicts regardless of how many
    heartbeat-only digest-epoch bumps separate them — this is what the
    cache's dedup compares.

    The per-key value is bound ONCE — the reference example evaluated
    ``s.get(k)`` twice per key (guard, then value) and a GC between the
    two evaluations turned a tombstone into ``AttributeError``.
    """
    nodes: dict[str, dict[str, str]] = {}
    for node_id, ns in snap.node_states.items():
        visible: dict[str, str] = {}
        for key, vv in ns.key_values.items():
            if not vv.is_deleted():
                visible[key] = vv.value
        nodes[node_id.name] = visible
    return {
        "cluster_id": snap.cluster_id,
        "self": snap.self_node_id.name,
        "live": sorted(n.name for n in snap.live_nodes),
        "dead": sorted(n.name for n in snap.dead_nodes),
        "nodes": nodes,
    }


def _dumps(payload: dict) -> bytes:
    return json.dumps(payload, separators=(",", ":"), sort_keys=True).encode()


def encode_snapshot(snap: ClusterSnapshot) -> bytes:
    """The canonical ``GET /state`` payload: the content dict plus the
    snapshot's epoch (the body-level resume token; the ETag carries the
    same value), deterministic key order so equal states encode to
    equal bytes."""
    return _dumps({**_content_dict(snap), "epoch": snap.epoch})


@dataclass(frozen=True, slots=True)
class EncodedSnapshot:
    """One epoch's encoded payload — the unit the cache shares."""

    epoch: int
    payload: bytes
    etag: str  # '"<epoch>"', the HTTP validator form


def parse_etag(value: str | None) -> int | None:
    """The epoch inside an ``If-None-Match`` header value (weak
    validators and quoting tolerated), or None when absent/garbage."""
    if not value:
        return None
    token = value.strip()
    if token.startswith(("W/", "w/")):
        token = token[2:]
    token = token.strip().strip('"')
    try:
        return int(token)
    except ValueError:
        return None


class SnapshotCache:
    """Encode-once-per-epoch snapshot fan-out for one serving Cluster."""

    def __init__(
        self,
        cluster: Cluster,
        *,
        metrics: MetricsRegistry | None = None,
        floor_history: int = DEFAULT_FLOOR_HISTORY,
    ) -> None:
        self._cluster = cluster
        self._current: EncodedSnapshot | None = None
        # _current.payload minus its epoch field: the content bytes the
        # dedup compares. The payload itself embeds the (raw) epoch, so
        # comparing payloads would never match across epochs and every
        # heartbeat bump would re-encode + wake every watcher.
        self._current_content: bytes | None = None
        # The content fingerprint at _current's encode: the O(nodes)
        # first-tier dedup check (see module docstring).
        self._current_fp: tuple | None = None
        # Newest cluster epoch verified content-identical to _current
        # (heartbeat-only bumps): epochs in
        # [_current.epoch, _checked_epoch] all serve _current.
        self._checked_epoch: int = -1
        # epoch -> {node name: max_version at that epoch}; insertion
        # order is ascending epoch, popped FIFO at the bound.
        self._floors: OrderedDict[int, dict[str, int]] = OrderedDict()
        self._floor_history = max(1, floor_history)
        self._events = None
        self._bytes_gauge = None
        if metrics is not None:
            self._events = metrics.counter(
                "aiocluster_serve_snapshot_events_total",
                "Snapshot cache activity: encode (one per served epoch), "
                "hit (reader shared an existing encode), dedup (newer "
                "epoch verified content-identical — fingerprint or "
                "byte compare; previous payload kept), "
                "not_modified (ETag short-circuit), delta (since= reply "
                "built), delta_empty (client already current), "
                "resync_full (since= floor aged out; full payload served)",
                labels=("event",),
            )
            self._bytes_gauge = metrics.gauge(
                "aiocluster_serve_snapshot_bytes",
                "Size of the most recently encoded snapshot payload",
            )

    def _count(self, event: str) -> None:
        if self._events is not None:
            self._events.labels(event).inc()

    # -- full snapshots -------------------------------------------------------

    def epoch_now(self) -> int:
        """The cluster's current state epoch — a cheap int read, the
        zero-encode short-circuit for ``If-None-Match`` checks."""
        return self._cluster.state_epoch()

    def note_not_modified(self) -> None:
        """Count an ETag short-circuit (the 304 path encodes nothing)."""
        self._count("not_modified")

    def note_resync_full(self) -> None:
        """Count a full-payload resync decided OUTSIDE delta_since — the
        /watch epoch-discontinuity path (a rebooted member's epoch
        counter restarted below the client's resume token)."""
        self._count("resync_full")

    def _fingerprint(self) -> tuple:
        """O(nodes) content-change pre-check: live/dead membership plus
        every node's version watermarks. Visible content cannot change
        through the sanctioned mutators without a key write (bumps that
        node's ``max_version``), a GC pass (``last_gc_version``), or a
        membership/liveness transition — so an unchanged fingerprint
        proves a raw-epoch bump was heartbeat-only, with no state walk
        and no encode."""
        states = self._cluster.node_states_view()
        return (
            tuple(sorted(n.name for n in self._cluster.live_nodes())),
            tuple(sorted(n.name for n in self._cluster.dead_nodes())),
            tuple(
                sorted(
                    (nid.name, ns.max_version, ns.last_gc_version)
                    for nid, ns in states.items()
                )
            ),
        )

    def get(self) -> EncodedSnapshot:
        """The current state's encoded snapshot; walks + encodes only
        when the epoch moved since the last call, and dedups
        heartbeat-only bumps (fingerprint tier — no walk) and
        byte-identical re-encodes (same-value rewrites) to the previous
        ``EncodedSnapshot``, so churn never invalidates every client's
        validator."""
        epoch = self._cluster.state_epoch()
        current = self._current
        if current is not None and (
            current.epoch == epoch or self._checked_epoch == epoch
        ):
            self._count("hit")
            return current
        fp = self._fingerprint()
        if current is not None and fp == self._current_fp:
            # Heartbeat-only bump: no walk, no encode, no floor entry —
            # a pump polling through churn costs O(nodes) per check and
            # cannot evict the content epoch's floors from the history.
            self._checked_epoch = epoch
            if current.epoch in self._floors:
                self._floors.move_to_end(current.epoch)
            self._count("dedup")
            return current
        snap = self._cluster.snapshot()
        content = _content_dict(snap)
        content_bytes = _dumps(content)
        self._record_floors(
            snap.epoch,
            {n.name: ns.max_version for n, ns in snap.node_states.items()},
        )
        self._checked_epoch = snap.epoch
        self._current_fp = fp
        if current is not None and content_bytes == self._current_content:
            self._count("dedup")
            return current
        encoded = EncodedSnapshot(
            epoch=snap.epoch,
            payload=_dumps({**content, "epoch": snap.epoch}),
            etag=f'"{snap.epoch}"',
        )
        self._current_content = content_bytes
        self._count("encode")
        if self._bytes_gauge is not None:
            self._bytes_gauge.set(len(encoded.payload))
        self._current = encoded
        return encoded

    def _record_floors(self, epoch: int, floors: dict[str, int]) -> None:
        if epoch in self._floors:
            self._floors.move_to_end(epoch)
            return
        self._floors[epoch] = floors
        while len(self._floors) > self._floor_history:
            self._floors.popitem(last=False)

    # -- delta reads ----------------------------------------------------------

    def delta_since(self, since: int) -> bytes | None:
        """The ``GET /state?since=E`` payload: per node, only key-values
        with versions above the client's floor at epoch ``E`` (straight
        off the version-indexed stale scans — tombstones included, so
        deletes replicate to clients too), plus nodes that departed.

        Returns None when ``E`` is not in the floor history (client too
        far behind, or a made-up epoch): the caller serves the full
        snapshot instead — the counted "resync" path.
        """
        epoch = self._cluster.state_epoch()
        floors = self._floors.get(since)
        if floors is None:
            self._count("resync_full")
            return None
        if since >= epoch:
            self._count("delta_empty")
            return json.dumps(
                {"epoch": epoch, "since": since, "delta": {}, "departed": []},
                separators=(",", ":"),
                sort_keys=True,
            ).encode()
        states = self._cluster.node_states_view()
        delta: dict[str, dict] = {}
        new_floors: dict[str, int] = {}
        present: set[str] = set()
        for node_id, ns in states.items():
            name = node_id.name
            present.add(name)
            new_floors[name] = ns.max_version
            floor = floors.get(name, 0)
            if ns.last_gc_version > floor:
                # The GC horizon passed the client's knowledge: purged
                # tombstones can no longer be replayed, so resend this
                # node's keyspace from scratch (the gossip reset rule,
                # applied to serve clients).
                floor = 0
            if ns.max_version <= floor:
                continue
            key_values = {
                key: {
                    "value": vv.value,
                    "version": vv.version,
                    "status": int(vv.status),
                }
                for key, vv in ns.stale_key_values(floor)
            }
            delta[name] = {
                "floor": floor,
                "max_version": ns.max_version,
                "last_gc_version": ns.last_gc_version,
                "key_values": key_values,
            }
        departed = sorted(name for name in floors if name not in present)
        # The reply advertises `epoch`, so the NEXT `since=epoch` request
        # must find floors for it — record them at build time (O(nodes)).
        self._record_floors(epoch, new_floors)
        self._count("delta")
        return json.dumps(
            {
                "epoch": epoch,
                "since": since,
                "delta": delta,
                "departed": departed,
            },
            separators=(",", ":"),
            sort_keys=True,
        ).encode()
