"""Prometheus text exposition + optional asyncio ``/metrics`` endpoint.

``render_prometheus`` emits text format 0.0.4 (the format every scraper
accepts): ``# HELP``/``# TYPE`` headers, one line per sample, histograms
as cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.

``MetricsHTTPServer`` is a stdlib-only asyncio HTTP/1.0 responder for the
two paths a scraper needs (``/metrics``, ``/healthz``). It runs either on
the caller's event loop (``start``) or on a daemon thread with its own
loop (``start_in_thread``) so the synchronous sim/bench drivers can be
scraped mid-run.
"""

from __future__ import annotations

import asyncio
import threading
from asyncio import StreamReader, StreamWriter

from .registry import Histogram, MetricsRegistry, default_registry


def _fmt_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value).is_integer() and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_text(names: tuple[str, ...], values: tuple[str, ...],
                 extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """The registry as Prometheus text format 0.0.4 (trailing newline
    included — scrapers require it)."""
    registry = registry if registry is not None else default_registry()
    lines: list[str] = []
    for family in registry.families():
        lines.append(f"# HELP {family.name} {family.help}")
        lines.append(f"# TYPE {family.name} {family.kind}")
        for values, child in family.samples():
            if isinstance(family, Histogram):
                buckets, total_sum, total_count = child.stats()
                for bound, cum in buckets:
                    le = _labels_text(
                        family.label_names, values,
                        extra=(("le", _fmt_value(bound)),),
                    )
                    lines.append(f"{family.name}_bucket{le} {cum}")
                base = _labels_text(family.label_names, values)
                lines.append(
                    f"{family.name}_sum{base} {_fmt_value(total_sum)}"
                )
                lines.append(f"{family.name}_count{base} {total_count}")
            else:
                base = _labels_text(family.label_names, values)
                lines.append(
                    f"{family.name}{base} {_fmt_value(child.value)}"
                )
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Tiny asyncio HTTP endpoint serving ``/metrics`` (and ``/healthz``)."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self._registry = registry
        self._host = host
        self._requested_port = port
        self._server: asyncio.Server | None = None
        self._thread: threading.Thread | None = None
        self._thread_loop: asyncio.AbstractEventLoop | None = None
        self.port: int | None = None  # bound port once started

    async def _handle(self, reader: StreamReader, writer: StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else ""
            # Drain (and ignore) the header block.
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            if path.split("?")[0] == "/metrics":
                body = render_prometheus(self._registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = "200 OK"
            elif path.split("?")[0] == "/healthz":
                body, ctype, status = b"ok\n", "text/plain", "200 OK"
            else:
                body, ctype, status = b"not found\n", "text/plain", "404 Not Found"
            writer.write(
                f"HTTP/1.0 {status}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
                + body
            )
            await writer.drain()
        except (TimeoutError, asyncio.TimeoutError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def start(self) -> int:
        """Bind on the caller's loop; returns the bound port."""
        self._server = await asyncio.start_server(
            self._handle, self._host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        # Swap-to-local before wait_closed suspends so a concurrent
        # stop() sees None at the guard instead of double-closing.
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()

    # -- thread mode (synchronous drivers: sim CLI, bench.py) ---------------

    def start_in_thread(self) -> int:
        """Serve from a daemon thread running its own event loop; returns
        the bound port. For drivers that aren't themselves async. A bind
        failure (port in use, privileged port) re-raises HERE, in the
        caller's thread, with the original OSError."""
        started = threading.Event()
        failure: list[BaseException] = []

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._thread_loop = loop
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.start())
            except BaseException as exc:  # surface bind errors to caller
                failure.append(exc)
                started.set()
                loop.close()
                return
            started.set()
            loop.run_forever()
            # stop_thread() stops the loop; close the server here, on its
            # own loop, then tear the loop down.
            loop.run_until_complete(self.stop())
            loop.close()

        self._thread = threading.Thread(
            target=run, name="metrics-http", daemon=True
        )
        self._thread.start()
        if not started.wait(timeout=10.0):
            raise RuntimeError("metrics HTTP server failed to start")
        if failure:
            self._thread = None
            self._thread_loop = None
            raise failure[0]
        assert self.port is not None
        return self.port

    def stop_thread(self) -> None:
        if self._thread_loop is not None:
            self._thread_loop.call_soon_threadsafe(self._thread_loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
            self._thread_loop = None
