"""JSONL trace writer: one JSON object per line, one line per event.

The schema is deliberately open — every record carries ``event`` (the
record type) and ``ts`` (wall seconds via the clock seam), and the emitter adds
whatever scalar fields describe the event (docs/observability.md lists
the event types both backends emit). JSONL keeps the file greppable,
streamable, and loadable with one ``read_trace`` call or a pandas
``read_json(lines=True)``.

The FIRST record of every fresh trace is a self-describing header
(``event == "trace_header"`` carrying ``schema``): offline consumers —
above all the digital twin's calibrator (docs/twin.md), which fits
numbers against these records — refuse an incompatible schema loudly
instead of mis-fitting silently. Appending to an existing file never
injects a second header mid-stream.

Writes are line-buffered under a lock (safe from asyncio callbacks and
worker threads) and flushed per line so a crash mid-run loses at most the
line being written — a trace that dies with the process is the one you
need most. ``scan_trace``/``read_trace(skip_invalid=True)`` recover
every complete record from exactly such a torn file.
"""

from __future__ import annotations

import io
import json
import threading
from dataclasses import dataclass, field
from pathlib import Path

from ..utils.clock import Clock, resolve_clock

# Version of the trace record vocabulary. Bump ONLY on a change that
# would make an old consumer mis-read new records (renamed fields,
# changed units); added event kinds and added fields are
# forward-compatible and do not bump it.
TRACE_SCHEMA = "aiocluster-trace/1"


class TraceWriter:
    """Append-only JSONL event sink. Usable as a context manager."""

    def __init__(self, path: str | Path, *, clock: Clock | None = None) -> None:
        self.path = Path(path)
        self._fh: io.TextIOBase | None = self.path.open("a", encoding="utf-8")
        self._lock = threading.Lock()
        # ``ts`` comes from the clock seam: real wall time by default,
        # the virtual wall under a vtime loop (docs/virtual-time.md) —
        # which is what makes twin traces replay bit-identically there.
        self._clock = resolve_clock(clock)
        self.events_written = 0
        # A fresh (empty) file self-describes before any event lands;
        # appending to a non-empty trace keeps its original header.
        if self._fh.tell() == 0:
            self.emit("trace_header", kind="trace_header", schema=TRACE_SCHEMA)

    def emit(self, event: str, **fields: object) -> None:
        """Write one record; silently drops events after close() (late
        callbacks during shutdown must not raise into the event loop)."""
        record = {"event": event, "ts": round(self._clock.wall(), 6), **fields}
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            self.events_written += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "TraceWriter":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


@dataclass
class TraceScan:
    """Result of a tolerant trace read: the complete records plus an
    account of what was skipped (line number, reason) — a crashed
    process leaves at most a torn final line, but the scan tolerates
    (and counts) any malformed line so the caller can decide whether
    the damage is a tail or the whole file."""

    records: list[dict] = field(default_factory=list)
    skipped: list[tuple[int, str]] = field(default_factory=list)

    @property
    def first_invalid(self) -> tuple[int, str] | None:
        """(lineno, reason) of the FIRST malformed line, or None."""
        return self.skipped[0] if self.skipped else None

    @property
    def header(self) -> dict | None:
        """The trace_header record, if the trace carries one."""
        if self.records and self.records[0].get("event") == "trace_header":
            return self.records[0]
        return None


def _iter_trace(path: str | Path):
    """Stream (lineno, record, reason) triples: ``record`` is the
    parsed dict for a valid line (reason None), None for a malformed
    one (reason set). Blank lines are skipped entirely."""
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                yield lineno, None, f"invalid JSONL: {exc}"
                continue
            if not isinstance(rec, dict) or "event" not in rec:
                yield (
                    lineno,
                    None,
                    "trace records must be objects with an 'event' field",
                )
                continue
            yield lineno, rec, None


def scan_trace(path: str | Path) -> TraceScan:
    """Tolerant trace read: never raises on malformed lines. Complete
    records (valid JSON objects with an ``event`` field) are collected;
    everything else — above all the torn final line of a crashed
    writer — is counted with its line number and reason."""
    scan = TraceScan()
    for lineno, rec, reason in _iter_trace(path):
        if rec is None:
            scan.skipped.append((lineno, reason))
        else:
            scan.records.append(rec)
    return scan


def read_trace(path: str | Path, *, skip_invalid: bool = False) -> list[dict]:
    """Load a JSONL trace back into a list of dicts.

    Strict by default: raises ValueError naming the FIRST malformed
    line, failing fast at that line without reading the rest (the
    obs-demo CI target uses this as the validity check — and "first"
    matters, because the first tear is where the evidence of what went
    wrong lives; later lines are usually collateral).

    ``skip_invalid=True`` recovers instead of raising: malformed lines
    are skipped and every complete record is returned — the mode for
    traces from crashed processes (a torn final line would otherwise
    make the whole file unreadable, and the trace that died with its
    process is exactly the one the twin most needs to replay). Use
    :func:`scan_trace` when the skip accounting itself is needed.
    """
    records: list[dict] = []
    for lineno, rec, reason in _iter_trace(path):
        if rec is None:
            if not skip_invalid:
                raise ValueError(f"{path}:{lineno}: {reason}")
            continue
        records.append(rec)
    return records
