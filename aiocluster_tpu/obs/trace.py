"""JSONL trace writer: one JSON object per line, one line per event.

The schema is deliberately open — every record carries ``event`` (the
record type) and ``ts`` (seconds, ``time.time()``), and the emitter adds
whatever scalar fields describe the event (docs/observability.md lists
the event types both backends emit). JSONL keeps the file greppable,
streamable, and loadable with one ``read_trace`` call or a pandas
``read_json(lines=True)``.

Writes are line-buffered under a lock (safe from asyncio callbacks and
worker threads) and flushed per line so a crash mid-run loses at most the
line being written — a trace that dies with the process is the one you
need most.
"""

from __future__ import annotations

import io
import json
import threading
import time
from pathlib import Path


class TraceWriter:
    """Append-only JSONL event sink. Usable as a context manager."""

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)
        self._fh: io.TextIOBase | None = self.path.open("a", encoding="utf-8")
        self._lock = threading.Lock()
        self.events_written = 0

    def emit(self, event: str, **fields: object) -> None:
        """Write one record; silently drops events after close() (late
        callbacks during shutdown must not raise into the event loop)."""
        record = {"event": event, "ts": round(time.time(), 6), **fields}
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            self.events_written += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "TraceWriter":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


def read_trace(path: str | Path) -> list[dict]:
    """Load a JSONL trace back into a list of dicts. Raises ValueError
    (with the line number) on a corrupt line — the obs-demo CI target
    uses this as the validity check."""
    records: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: invalid JSONL: {exc}") from None
            if not isinstance(rec, dict) or "event" not in rec:
                raise ValueError(
                    f"{path}:{lineno}: trace records must be objects with "
                    "an 'event' field"
                )
            records.append(rec)
    return records
